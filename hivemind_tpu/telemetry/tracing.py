"""Distributed tracing (ISSUE 4 tentpole): cross-peer spans, an always-on
flight recorder, and Chrome-trace/Perfetto export.

PR 2's metrics answer "how much / how often"; this module answers *why was this
round slow, and which peer stalled it*. The pieces:

- :class:`Span` — one timed operation: ``trace_id``/``span_id``/``parent_id``
  (64-bit), monotonic start/end, attributes, and a list of timestamped events
  (chaos injections, breaker trips, retries land here — see
  ``resilience/chaos.py``, ``resilience/breaker.py``, ``resilience/policy.py``).
- :func:`trace` — contextvar-scoped span context manager; :func:`current_span`
  reads the active one. Works across ``await`` (tasks inherit contextvars).
- :class:`SpanRecorder` — the flight recorder: a bounded per-process ring
  buffer of *finished* spans. Always on, fixed memory, oldest-evicted. Spans
  whose duration crosses :func:`set_slow_span_threshold` are additionally kept
  in a small side ring and logged with their event chain.
- :func:`render_chrome_trace` — Chrome trace-event JSON (loads directly in
  Perfetto / ``chrome://tracing``). Each distinct ``peer`` attribute becomes
  one pid row, so multi-peer-in-one-process tests and real swarm dumps both
  read as one row per peer. Served at ``GET /trace`` by
  :class:`~hivemind_tpu.telemetry.exporter.MetricsExporter`.

Cross-peer propagation: ``p2p/p2p.py`` piggybacks the active span's
``(trace_id, span_id)`` on the mux OPEN frame (16 bytes, only when a span is
active), so a server-side handler span becomes a child of the remote caller's
span; :func:`pack_context` / :func:`unpack_context` define the wire form.

Cost discipline (acceptance criterion): with tracing disabled
(``HIVEMIND_TRACE=0``) an instrumented site costs one module-bool check and
one contextvar read; with it enabled (the default) a span is one small object
plus a ring-buffer append at exit — no serialization happens anywhere off the
export path.
"""

from __future__ import annotations

import contextvars
import json
import os
import random
import struct
import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_CTX_STRUCT = struct.Struct(">QQ")  # (trace_id, span_id) — the wire context

# ------------------------------------------------------------- telemetry clock
#
# Spans are timed with :func:`telemetry_time` — ``time.perf_counter`` by
# default (monotonic, immune to NTP steps). The simulator swaps it for the
# virtual loop clock via :func:`set_telemetry_time_source` (mirroring
# ``set_dht_time_source``: a module-global function pointer, NOT a
# monkeypatch, because callers across the tree bind these functions at
# import). Export adds the wall anchor from :func:`wall_anchor` so timelines
# from different peers align on the wall clock.
#
# The anchor used to be computed ONCE at import (ISSUE 17 satellite): over a
# long run perf_counter and the wall clock drift apart (and an NTP step moves
# the wall clock outright), so an import-time anchor skews cross-peer merges
# by however much the clocks diverged since startup. It is now re-computed
# when older than _ANCHOR_MAX_AGE_S, and the spool segment headers record the
# anchor plus the drift observed at the last re-anchor (wall_anchor_info) so
# post-mortem merges can bound the residual skew.

_ANCHOR_MAX_AGE_S = 60.0
# {"anchor": wall - perf at last re-anchor, "at": monotonic re-anchor time,
#  "drift_s": anchor movement observed at the last re-anchor} — dict ops are
# GIL-atomic; a racing re-anchor just recomputes the same values.
_anchor_state: Dict[str, float] = {
    "anchor": time.time() - time.perf_counter(), "at": time.monotonic(), "drift_s": 0.0
}

_time_source = None  # swapped by the sim; None = time.perf_counter
_wall_source = None  # paired wall clock; None = time.time


def set_telemetry_time_source(source=None, wall_source=None) -> None:
    """Swap the clock spans/ledgers/watchdogs are timed with (None restores
    the defaults). ``source`` replaces ``perf_counter`` for span timing;
    ``wall_source`` replaces ``time.time`` for record timestamps and defaults
    to ``source`` — the virtual loop clock starts at an epoch-magnitude value,
    so it serves as both, and the wall anchor is then exactly 0.0 (per-peer
    spools from one sim merge without skew correction)."""
    global _time_source, _wall_source
    _time_source = source
    _wall_source = wall_source if wall_source is not None else source


def telemetry_time() -> float:
    """The span clock: ``perf_counter`` unless the sim swapped it."""
    if _time_source is not None:
        return _time_source()
    return time.perf_counter()


def wall_time() -> float:
    """Wall-clock timestamps for ledger/watchdog records: ``time.time``
    unless the sim swapped the clock (virtual time is epoch-magnitude)."""
    if _wall_source is not None:
        return _wall_source()
    return time.time()


def _reanchor() -> None:
    state = _anchor_state
    new_anchor = time.time() - time.perf_counter()
    state["drift_s"] = round(new_anchor - state["anchor"], 6)
    state["anchor"] = new_anchor
    state["at"] = time.monotonic()


def wall_anchor() -> float:
    """Offset such that ``telemetry_time() + wall_anchor() ≈ wall_time()``.
    Re-anchored when stale; exactly 0.0 under a virtual clock."""
    if _time_source is not None:
        return 0.0
    state = _anchor_state
    if time.monotonic() - state["at"] > _ANCHOR_MAX_AGE_S:
        _reanchor()
    return state["anchor"]


def wall_anchor_info() -> Dict[str, Any]:
    """Anchor + drift estimate for spool segment headers: ``{"anchor",
    "drift_s", "age_s", "clock"}`` where drift_s is how far the anchor moved
    at the last re-anchor (≈ clock divergence per _ANCHOR_MAX_AGE_S window)."""
    if _time_source is not None:
        return {"anchor": 0.0, "drift_s": 0.0, "age_s": 0.0, "clock": "virtual"}
    anchor = wall_anchor()
    state = _anchor_state
    return {
        "anchor": round(anchor, 6),
        "drift_s": state["drift_s"],
        "age_s": round(time.monotonic() - state["at"], 3),
        "clock": "wall",
    }

_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "hivemind_current_span", default=None
)

# best-effort per-THREAD view of the innermost open `trace` block, for observers
# that cannot read another thread's contextvars (the event-loop watchdog wants
# "which span was executing when the loop stalled"). Only `trace` blocks update
# it. While a thread is synchronously blocked INSIDE a trace block, the entry is
# the blocking span; if the blocker runs outside any trace block (a bare loop
# callback), the entry may be a suspended task's still-open span — the watchdog's
# stall event then carries the accurate blocking FRAME but an approximate span
# association. Dict ops are GIL-atomic.
_THREAD_SPANS: Dict[int, "Span"] = {}


def thread_current_span(thread_id: int) -> Optional["Span"]:
    """The innermost `trace` block open on the given thread (best-effort)."""
    return _THREAD_SPANS.get(thread_id)

# one rng for id generation; seeded from the OS so forked peers diverge.
# random.Random methods are atomic under the GIL — no lock needed.
_ids = random.Random(int.from_bytes(os.urandom(8), "big") ^ os.getpid())


def seed_trace_ids(seed: int) -> None:
    """Reseed the trace/span id rng. The rng is OS-seeded so forked peers
    diverge — which also means two same-seed sim runs produce different ids;
    sim scenarios call this so spool contents are bit-identical per seed."""
    global _ids
    _ids = random.Random(seed)

enabled = os.environ.get("HIVEMIND_TRACE", "1") != "0"


def _new_id() -> int:
    return _ids.getrandbits(64) or 1  # 0 is reserved for "no id"


class Span:
    """One timed operation. Created via :func:`trace` / :func:`start_span`;
    finished spans land in the flight recorder."""

    __slots__ = (
        "name", "trace_id", "span_id", "parent_id", "start", "end",
        "attributes", "events", "thread_id",
    )

    def __init__(
        self,
        name: str,
        trace_id: Optional[int] = None,
        parent_id: Optional[int] = None,
        attributes: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.trace_id = trace_id if trace_id else _new_id()
        self.span_id = _new_id()
        self.parent_id = parent_id
        self.start = telemetry_time()
        self.end: Optional[float] = None
        self.attributes = attributes
        self.events: Optional[List[Tuple[float, str, Optional[Dict[str, Any]]]]] = None
        self.thread_id = threading.get_ident()

    # ------------------------------------------------------------------ recording

    def set(self, key: str, value: Any) -> None:
        if self.attributes is None:
            self.attributes = {}
        self.attributes[key] = value

    def add_event(self, name: str, **attributes: Any) -> None:
        """Record a timestamped event on this span (chaos injection, breaker
        trip, retry attempt, ...). Cheap: one tuple append."""
        if self.events is None:
            self.events = []
        self.events.append((telemetry_time(), name, attributes or None))

    @property
    def duration(self) -> float:
        return (self.end if self.end is not None else telemetry_time()) - self.start

    def context_bytes(self) -> bytes:
        """The 16-byte wire context piggybacked on RPC envelopes."""
        return _CTX_STRUCT.pack(self.trace_id, self.span_id)

    # ------------------------------------------------------------------ export

    def summary(self) -> Dict[str, Any]:
        """Compact JSON-able view (DHT peer snapshots, monitor timelines)."""
        out: Dict[str, Any] = {
            "name": self.name,
            "trace": f"{self.trace_id:016x}",
            "span": f"{self.span_id:016x}",
            "start": round(self.start + wall_anchor(), 6),
            "dur_ms": round(self.duration * 1e3, 3),
        }
        if self.parent_id:
            out["parent"] = f"{self.parent_id:016x}"
        if self.attributes:
            out.update({k: v for k, v in self.attributes.items() if isinstance(v, (str, int, float, bool))})
        if self.events:
            out["events"] = [name for _t, name, _a in self.events]
        return out

    def __repr__(self) -> str:
        state = f"{self.duration * 1e3:.2f}ms" if self.end is not None else "open"
        return f"Span({self.name!r}, trace={self.trace_id:016x}, {state})"


def pack_context(span: Optional[Span]) -> Optional[bytes]:
    """Wire context of a span (None when there is nothing to propagate)."""
    return None if span is None else span.context_bytes()


def unpack_context(raw: Optional[bytes]) -> Optional[Tuple[int, int]]:
    """Parse a remote peer's 16-byte context; None when absent or malformed
    (a peer must not be able to crash a handler with a bad envelope)."""
    if raw is None or len(raw) != _CTX_STRUCT.size:
        return None
    try:
        trace_id, span_id = _CTX_STRUCT.unpack(raw)
    except struct.error:  # pragma: no cover - length is checked above
        return None
    return (trace_id, span_id) if trace_id and span_id else None


# ---------------------------------------------------------------------- recorder


class SpanRecorder:
    """The flight recorder: a fixed-capacity ring of finished spans. Appends
    are one deque op (GIL-atomic); the oldest span is evicted at capacity, so
    memory is bounded no matter how long the process runs."""

    def __init__(self, capacity: int = 4096, slow_capacity: int = 32):
        self._ring: "deque[Span]" = deque(maxlen=capacity)
        self._slow: "deque[Span]" = deque(maxlen=slow_capacity)
        self.slow_threshold = float(os.environ.get("HIVEMIND_SLOW_SPAN_S", "10.0"))
        self.dropped = 0  # spans evicted so far (diagnosing undersized rings)

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def record(self, span: Span) -> None:
        ring = self._ring
        if len(ring) == ring.maxlen:
            self.dropped += 1
        ring.append(span)
        if span.end is not None and span.end - span.start >= self.slow_threshold:
            self._slow.append(span)
            chain = [name for _t, name, _a in span.events] if span.events else []
            logger.warning(
                f"slow span {span.name!r}: {span.duration:.3f}s "
                f"(threshold {self.slow_threshold}s), events={chain}, "
                f"trace={span.trace_id:016x}"
            )

    def snapshot(self) -> List[Span]:
        return list(self._ring)

    def slow_spans(self) -> List[Span]:
        return list(self._slow)

    def summaries(self, limit: int = 30) -> List[Dict[str, Any]]:
        """The most recent ``limit`` finished spans, compact (peer snapshots)."""
        ring = self._ring
        spans = list(ring)[-limit:] if limit else list(ring)
        return [span.summary() for span in spans]

    def clear(self) -> None:
        self._ring.clear()
        self._slow.clear()
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._ring)


RECORDER = SpanRecorder()


def set_slow_span_threshold(seconds: float) -> None:
    """Spans at least this long are kept in the slow ring and logged with
    their event chain (the "why was this round slow" log line)."""
    RECORDER.slow_threshold = float(seconds)


# ---------------------------------------------------------------------- creation


def current_span() -> Optional[Span]:
    """The span active in this task/thread, or None."""
    return _current_span.get()


def install_span(span: Optional[Span]):
    """Make ``span`` current WITHOUT a context manager (returns the reset token
    for :func:`uninstall_span`). For operations whose span outlives the block
    that created it — e.g. futures-mode DHT gets, where the span is finished
    from a done-callback after the creating coroutine returned."""
    return _current_span.set(span)


def uninstall_span(token) -> None:
    _current_span.reset(token)


def start_span(
    name: str,
    parent: Optional[Span] = None,
    remote_context: Optional[Tuple[int, int]] = None,
    **attributes: Any,
) -> Optional[Span]:
    """Create a span WITHOUT installing it as current (for code that cannot
    hold a context manager open, e.g. async generators — a generator's body
    runs in its consumer's context, so installing would leak). Finish with
    :func:`finish_span`. Returns None when tracing is disabled."""
    if not enabled:
        return None
    if parent is None and remote_context is None:
        parent = _current_span.get()
    if remote_context is not None:
        trace_id, parent_id = remote_context
    else:
        trace_id = parent.trace_id if parent is not None else None
        parent_id = parent.span_id if parent is not None else None
    span = Span(name, trace_id=trace_id, parent_id=parent_id, attributes=attributes or None)
    for listener in _SPAN_START_LISTENERS:
        try:
            listener(span)
        except Exception as e:  # pragma: no cover - listeners must stay harmless
            logger.debug(f"span start listener failed on {span.name!r}: {e!r}")
    return span


# finished-span listeners (the round ledger subscribes here): called after the
# recorder append, exceptions swallowed — attribution must never fail the
# operation it observes. Kept as a plain list read without a lock (GIL-atomic);
# registration happens at import/startup time.
_SPAN_LISTENERS: List = []

# span-START listeners (the black-box spool subscribes here): a crash-killed
# peer's last operation never reaches finish_span, so post-mortem needs the
# open span on disk BEFORE the work runs. Every code path creating a span goes
# through start_span (trace.__enter__ included), so this is the one hook.
_SPAN_START_LISTENERS: List = []


def add_span_listener(listener) -> None:
    """Register ``listener(span)`` to run on every finished span."""
    if listener not in _SPAN_LISTENERS:
        _SPAN_LISTENERS.append(listener)


def remove_span_listener(listener) -> None:
    try:
        _SPAN_LISTENERS.remove(listener)
    except ValueError:
        pass


def add_span_start_listener(listener) -> None:
    """Register ``listener(span)`` to run on every span CREATION (the span is
    still open — its ``end`` is None and attributes may still grow)."""
    if listener not in _SPAN_START_LISTENERS:
        _SPAN_START_LISTENERS.append(listener)


def remove_span_start_listener(listener) -> None:
    try:
        _SPAN_START_LISTENERS.remove(listener)
    except ValueError:
        pass


def finish_span(span: Optional[Span], recorder: Optional[SpanRecorder] = None) -> None:
    """Stamp the end time and append to the flight recorder. None-safe so call
    sites need no enabled-check of their own."""
    if span is None:
        return
    span.end = telemetry_time()
    (recorder if recorder is not None else RECORDER).record(span)
    for listener in _SPAN_LISTENERS:
        try:
            listener(span)
        except Exception as e:  # pragma: no cover - listeners must stay harmless
            logger.debug(f"span listener failed on {span.name!r}: {e!r}")


class trace:
    """``with trace("dht.store", peer=...) as span:`` — create a child of the
    current span, install it for the block, record it at exit. The standard
    way to instrument a code path; use :func:`start_span` only where a context
    manager cannot wrap the operation."""

    __slots__ = ("_name", "_attributes", "_remote", "_parent", "span", "_token", "_thread_prev")

    def __init__(
        self,
        name: str,
        remote_context: Optional[Tuple[int, int]] = None,
        parent: Optional[Span] = None,
        **attributes: Any,
    ):
        self._name = name
        self._attributes = attributes
        self._remote = remote_context
        self._parent = parent
        self.span: Optional[Span] = None
        self._token = None
        self._thread_prev: Optional[Span] = None

    def __enter__(self) -> Optional[Span]:
        if not enabled:
            return None
        self.span = start_span(
            self._name, parent=self._parent, remote_context=self._remote, **self._attributes
        )
        self._token = _current_span.set(self.span)
        tid = threading.get_ident()
        self._thread_prev = _THREAD_SPANS.get(tid)
        _THREAD_SPANS[tid] = self.span
        return self.span

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
            tid = threading.get_ident()
            # interleaved asyncio tasks enter/exit in non-stack order: only
            # restore when the table still points at US (otherwise a later
            # task's live entry would be clobbered), and never reinstall a
            # span that already finished while we were suspended
            if _THREAD_SPANS.get(tid) is self.span:
                if self._thread_prev is not None and self._thread_prev.end is None:
                    _THREAD_SPANS[tid] = self._thread_prev
                else:
                    _THREAD_SPANS.pop(tid, None)
            self._thread_prev = None
        if self.span is not None:
            if exc_type is not None:
                self.span.add_event("error", type=exc_type.__name__)
            finish_span(self.span)
        return False


# ---------------------------------------------------------------------- export


# fixed tids for the compute-vs-comm lanes (ISSUE 19); real thread ids are
# huge, so small constants cannot collide in practice
_LANE_TIDS = {"compute": 1, "comm": 2}


def _span_lane(name: str) -> Optional[str]:
    """Lazy bridge to device.span_lane — device.py imports tracing, so tracing
    must not import it back at module scope."""
    try:
        from hivemind_tpu.telemetry.device import span_lane

        return span_lane(name)
    except Exception:
        return None


def render_chrome_trace(
    spans: Optional[Iterable[Span]] = None, default_peer: str = "local"
) -> Dict[str, Any]:
    """Spans as a Chrome trace-event JSON object (the ``{"traceEvents": [...]}``
    form; opens directly in Perfetto / ``chrome://tracing``).

    pid/tid mapping: each distinct ``peer`` span attribute becomes one pid row
    (named via ``process_name`` metadata); tids are the recording threads,
    EXCEPT comm/compute spans (ISSUE 19): those land on two fixed named lanes
    per peer — ``compute`` (tid 1) and ``comm`` (tid 2) — so the overlap the
    StepTimeline scores is visible as two stacked rows in Perfetto. Span
    events render as instant events on the same row, and every event carries
    its trace/span/parent ids in ``args`` so traces remain greppable."""
    spans = RECORDER.snapshot() if spans is None else list(spans)
    anchor = wall_anchor()
    peers: Dict[str, int] = {}
    lanes_used: set = set()  # (pid, lane)
    events: List[Dict[str, Any]] = []
    for span in spans:
        peer = default_peer
        if span.attributes is not None:
            peer = str(span.attributes.get("peer", default_peer))
        pid = peers.get(peer)
        if pid is None:
            pid = peers[peer] = len(peers) + 1
        ts_us = (span.start + anchor) * 1e6
        dur_us = max(span.duration * 1e6, 0.001)
        args: Dict[str, Any] = {
            "trace_id": f"{span.trace_id:016x}",
            "span_id": f"{span.span_id:016x}",
        }
        if span.parent_id:
            args["parent_id"] = f"{span.parent_id:016x}"
        if span.attributes:
            args.update(
                {k: v for k, v in span.attributes.items() if isinstance(v, (str, int, float, bool))}
            )
        lane = _span_lane(span.name)
        if lane is not None:
            tid = _LANE_TIDS[lane]
            args["lane"] = lane
            lanes_used.add((pid, lane))
        else:
            tid = span.thread_id % 2**31
        events.append(
            {
                "name": span.name, "cat": "span", "ph": "X",
                "ts": round(ts_us, 3), "dur": round(dur_us, 3),
                "pid": pid, "tid": tid, "args": args,
            }
        )
        for when, event_name, event_attrs in span.events or ():
            instant_args = {"span_id": f"{span.span_id:016x}"}
            if event_attrs:
                instant_args.update(event_attrs)
            events.append(
                {
                    "name": event_name, "cat": "event", "ph": "i", "s": "t",
                    "ts": round((when + anchor) * 1e6, 3),
                    "pid": pid, "tid": tid, "args": instant_args,
                }
            )
    for peer, pid in peers.items():
        events.append(
            {
                "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                "args": {"name": f"peer {peer}"},
            }
        )
    for pid, lane in sorted(lanes_used):
        events.append(
            {
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": _LANE_TIDS[lane], "args": {"name": lane},
            }
        )
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def render_chrome_trace_json(spans: Optional[Iterable[Span]] = None) -> str:
    return json.dumps(render_chrome_trace(spans), default=str)
