"""Per-round performance attribution (ISSUE 8 tentpole): the round ledger.

Metrics (PR 2) answer "how much", traces (PR 4) answer "why was THIS operation
slow" — this module answers the operator's question in between: *where did
epoch N's wall time go, and which peer caused it*. A :class:`RoundLedger`
assembles **one structured record per averaging round** (and one per optimizer
epoch transition) from signals that already exist:

- **span boundaries** — it subscribes to finished spans
  (:func:`~hivemind_tpu.telemetry.tracing.add_span_listener`) and folds
  ``averaging.matchmaking`` / ``allreduce.local_reduce`` /
  ``allreduce.peer_exchange`` / ``allreduce.round`` into per-round phase
  durations, keyed by the round span's id so concurrent averagers (grad +
  state) cannot cross-contaminate;
- **registry counters** — bytes in/out, retries, sender bans, breaker trips,
  chaos injections and state-sync bytes are read as deltas at round close, so
  each record carries the traffic and resilience activity of its window;
- **per-peer attribution** — the slowest ``peer_exchange`` partner of each
  round is named in the record and accumulated into a per-peer *straggler
  score* (times-slowest count + excess seconds over the round's median
  exchange), the paper's one-slow-peer-taxes-everyone failure mode made
  directly readable.

Records are bounded rings (fixed memory, oldest evicted), ride the existing
DHT peer snapshot compact and size-budgeted like span summaries
(monitor.py), and are served raw at ``GET /ledger`` on the MetricsExporter.
Cost discipline: the listener does a dict lookup per finished span and a few
dict ops per *round* — nothing runs per tensor part, and nothing serializes
off the export path.
"""

from __future__ import annotations

import math
import threading
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from hivemind_tpu.telemetry.registry import REGISTRY, MetricsRegistry
from hivemind_tpu.telemetry.tracing import Span, add_span_listener, wall_anchor, wall_time
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

# counter families whose per-round deltas ride each record (absent families — a
# layer that never loaded — simply contribute nothing)
_DELTA_COUNTERS = {
    "bytes_sent": "hivemind_averaging_bytes_sent_total",
    "bytes_received": "hivemind_averaging_bytes_received_total",
    "retries": "hivemind_resilience_retries_total",
    "banned_senders": "hivemind_averaging_banned_senders_total",
    "breaker_trips": "hivemind_breaker_trips_total",
    "chaos_injections": "hivemind_chaos_injections_total",
    "state_sync_bytes": "hivemind_state_sync_bytes_total",
}

# how many open rounds may buffer child phases at once: far above any real
# concurrency (grad + state + powersgd = 3-4), small enough that a leak from
# rounds that never close cannot grow without bound
_MAX_PENDING_ROUNDS = 64

# recently-closed rounds kept addressable for LATE exchange spans. The slowest
# partner's exchange systematically finishes AFTER its round record closes:
# its delta resolves last, which completes the round's output iterator (ending
# the round span) while the exchange task still awaits the stream close — so
# without retro-attachment the ledger would tend to drop exactly the exchange
# it exists to attribute.
_MAX_CLOSED_ROUNDS = 16


def _percentile(values: List[float], fraction: float) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    index = min(len(ordered) - 1, max(0, math.ceil(fraction * len(ordered)) - 1))
    return ordered[index]


class RoundLedger:
    """See module docstring. One process-wide instance (:data:`LEDGER`) is fed
    by the span listener; tests may build private instances and call
    :meth:`on_span` directly."""

    def __init__(
        self,
        capacity: int = 256,
        epoch_capacity: int = 128,
        registry: MetricsRegistry = REGISTRY,
    ):
        self._lock = threading.Lock()
        self._registry = registry
        self._records: "deque[Dict[str, Any]]" = deque(maxlen=capacity)
        self._epochs: "deque[Dict[str, Any]]" = deque(maxlen=epoch_capacity)
        self._straggler: Dict[str, Dict[str, float]] = {}
        # adaptive link-codec demote/promote decisions (ISSUE 11), fed by the
        # averager's LinkCodecPolicy — bounded ring, shown in hivemind-top
        self._codec_events: "deque[Dict[str, Any]]" = deque(maxlen=64)
        # open-round buffers keyed by the allreduce.round span id
        self._pending_exchanges: Dict[int, List[Dict[str, Any]]] = {}
        self._pending_local: Dict[int, float] = {}
        # recently-closed rounds (span id -> live record) for late exchanges,
        # plus the straggler-score contribution each record currently holds so
        # a late slower exchange can re-attribute the round
        self._closed_rounds: Dict[int, Dict[str, Any]] = {}
        self._round_contrib: Dict[int, Tuple[str, float]] = {}
        # most recent finished matchmaking per PEER id, consumed by that
        # peer's next round close
        self._last_matchmaking: Dict[str, Dict[str, Any]] = {}
        # delta baselines: empty until the first round SEEDS them (that round
        # reports no counters — attributing bootstrap traffic, e.g. a 2 GB
        # state download, to round 1 would be fiction). clear() re-anchors at
        # clear time, so post-clear round 1 gets a true window.
        self._counter_baseline: Dict[str, float] = {}
        self._round_index = 0
        # per-PEER epoch rolling windows: several optimizers share one process
        # (and this singleton) in tests and soaks, and peer A's transition must
        # not consume peer B's rounds
        self._epoch_window: Dict[str, Dict[str, Any]] = {}
        # record listeners (the black-box spool subscribes): called with
        # ("round"|"epoch", copied record) OUTSIDE the lock — a listener doing
        # file I/O must not serialize the span hot path. A round retro-updated
        # by a late exchange is re-emitted; spool readers keep the last copy
        # per (peer, round).
        self._record_listeners: List = []

    def add_record_listener(self, listener) -> None:
        if listener not in self._record_listeners:
            self._record_listeners.append(listener)

    def remove_record_listener(self, listener) -> None:
        try:
            self._record_listeners.remove(listener)
        except ValueError:
            pass

    def _notify_record(self, kind: str, record: Dict[str, Any]) -> None:
        for listener in self._record_listeners:
            try:
                listener(kind, record)
            except Exception as e:  # pragma: no cover - listeners must stay harmless
                logger.debug(f"ledger record listener failed: {e!r}")

    # ------------------------------------------------------------------ feeding

    def on_span(self, span: Span) -> None:
        """Span listener: cheap name dispatch; everything else is per round."""
        name = span.name
        if name == "allreduce.peer_exchange":
            parent = span.parent_id
            if parent:
                attrs = span.attributes or {}
                info = {
                    "remote": str(attrs.get("remote", "?")),
                    "dur_s": round(span.duration, 6),
                    "events": [n for _t, n, _a in span.events] if span.events else [],
                }
                if attrs.get("codec") is not None:
                    # the negotiated wire tier of this link (ISSUE 11) — rides
                    # the record so demotions are visible per round
                    info["codec"] = str(attrs["codec"])
                updated: Optional[Dict[str, Any]] = None
                with self._lock:
                    if parent in self._closed_rounds:
                        self._attach_late_exchange(parent, info)
                        updated = self._copy_record(self._closed_rounds[parent])
                    else:
                        self._pending_exchanges.setdefault(parent, []).append(info)
                if updated is not None:
                    self._notify_record("round", updated)
        elif name == "allreduce.local_reduce":
            if span.parent_id:
                with self._lock:
                    self._pending_local[span.parent_id] = round(span.duration, 6)
        elif name == "averaging.matchmaking":
            attrs = span.attributes or {}
            with self._lock:
                # keyed by peer id so multi-peer-in-one-process rounds cannot
                # swap waits; two averagers of the SAME peer (grad + state)
                # overlap only in DPU mode, where this stays best-effort
                self._last_matchmaking[str(attrs.get("peer", "?"))] = {
                    "wait_s": round(span.duration, 6),
                    "outcome": attrs.get("outcome"),
                }
        elif name == "allreduce.round":
            self._close_round(span)

    def _counter_total(self, metric_name: str) -> float:
        metric = self._registry.get(metric_name)
        if metric is None:
            return 0.0
        total = 0.0
        for _key, child in metric.series():
            total += child.value  # type: ignore[union-attr]
        return total

    def _close_round(self, span: Span) -> None:
        attrs = span.attributes or {}
        with self._lock:
            exchanges = self._pending_exchanges.pop(span.span_id, [])
            local_reduce = self._pending_local.pop(span.span_id, None)
            matchmaking = self._last_matchmaking.pop(str(attrs.get("peer", "?")), None)
            self._round_index += 1
            record: Dict[str, Any] = {
                "round": self._round_index,
                "time": round(span.start + span.duration + wall_anchor(), 3),
                "peer": str(attrs.get("peer", "?")),
                "group_size": attrs.get("group_size"),
                "rank": attrs.get("rank"),
                "total_s": round(span.duration, 6),
            }
            if matchmaking is not None:
                record["matchmaking_wait_s"] = matchmaking["wait_s"]
                record["matchmaking_outcome"] = matchmaking["outcome"]
            if local_reduce is not None:
                record["local_reduce_s"] = local_reduce
            if exchanges:
                record["exchanges"] = exchanges
                for exchange in exchanges:
                    other = self._score(exchange["remote"])
                    other["total_s"] = round(other["total_s"] + exchange["dur_s"], 6)
                link_codecs = {
                    exchange["remote"]: exchange["codec"]
                    for exchange in exchanges
                    if "codec" in exchange
                }
                if link_codecs:
                    record["link_codecs"] = link_codecs
            events = [n for _t, n, _a in span.events] if span.events else []
            for exchange in exchanges:
                events.extend(exchange["events"])
            if events:
                counts: Dict[str, int] = {}
                for event in events:
                    counts[event] = counts.get(event, 0) + 1
                record["events"] = counts
            # counter deltas since the previous record: this round's window. A
            # metric with no recorded baseline (first round after init/clear)
            # only SEEDS it — attributing process-lifetime totals to round 1
            # would be fiction, not attribution
            counters: Dict[str, float] = {}
            for field, metric_name in _DELTA_COUNTERS.items():
                total = self._counter_total(metric_name)
                baseline = self._counter_baseline.get(metric_name)
                self._counter_baseline[metric_name] = total
                if baseline is None:
                    continue
                delta = total - baseline
                if delta:
                    counters[field] = round(delta, 6)
            if counters:
                record["counters"] = counters
            # the epoch window opens BEFORE attribution runs: _apply_round_
            # attribution only updates an EXISTING window, so a late retro-
            # attribution after record_epoch popped it cannot resurrect the
            # previous epoch's straggler into the next epoch's record
            window = self._peer_epoch_window(record["peer"])
            window["rounds"] += 1
            window["round_s"] += span.duration
            self._apply_round_attribution(span.span_id, record)
            self._records.append(record)
            # the record stays addressable for late exchange spans (see
            # _MAX_CLOSED_ROUNDS): the slowest partner usually lands here
            self._closed_rounds[span.span_id] = record
            while len(self._closed_rounds) > _MAX_CLOSED_ROUNDS:
                oldest = next(iter(self._closed_rounds))
                self._closed_rounds.pop(oldest, None)
                self._round_contrib.pop(oldest, None)
            # prune leaked buffers from rounds that never closed (crashed peers)
            if len(self._pending_exchanges) > _MAX_PENDING_ROUNDS:
                for key in list(self._pending_exchanges)[: -_MAX_PENDING_ROUNDS // 2]:
                    self._pending_exchanges.pop(key, None)
                    self._pending_local.pop(key, None)
            published = self._copy_record(record) if self._record_listeners else None
        if published is not None:
            self._notify_record("round", published)

    def _score(self, remote: str) -> Dict[str, float]:
        return self._straggler.setdefault(
            remote, {"rounds_slowest": 0, "excess_s": 0.0, "total_s": 0.0}
        )

    def _peer_epoch_window(self, peer: str) -> Dict[str, Any]:
        return self._epoch_window.setdefault(
            str(peer),
            {"rounds": 0, "round_s": 0.0, "straggler": None,
             "overlap_sum": 0.0, "overlap_n": 0},
        )

    def note_overlap(self, peer: str, ratio: float) -> None:
        """Stamp a comm round's overlap efficiency (ISSUE 19: fraction of the
        round's wall time hidden under compute, computed by the device
        StepTimeline) onto this peer's newest round record and accrue it into
        the rolling epoch window, so record_epoch rolls up a per-epoch mean."""
        ratio = float(ratio)
        with self._lock:
            for record in reversed(self._records):
                if record.get("peer") == str(peer):
                    record["overlap_efficiency"] = round(ratio, 4)
                    break
            window = self._peer_epoch_window(str(peer))
            window["overlap_sum"] += ratio
            window["overlap_n"] += 1

    def _apply_round_attribution(self, round_id: int, record: Dict[str, Any]) -> None:
        """(Re)derive slowest/spread from ``record['exchanges']`` and move the
        round's straggler-score contribution to the current slowest partner
        (idempotent per round: a previous attribution is retracted first)."""
        exchanges = record.get("exchanges")
        if not exchanges:
            return
        exchanges.sort(key=lambda e: -e["dur_s"])
        durations = [e["dur_s"] for e in exchanges]
        slowest = exchanges[0]
        record["slowest_peer"] = slowest["remote"]
        record["slowest_s"] = slowest["dur_s"]
        record["exchange_spread_s"] = round(durations[0] - durations[-1], 6)
        excess = (
            max(0.0, slowest["dur_s"] - _percentile(durations, 0.5))
            if len(durations) > 1
            else 0.0
        )
        previous = self._round_contrib.get(round_id)
        if previous is not None:
            prev_remote, prev_excess = previous
            prev_score = self._score(prev_remote)
            prev_score["rounds_slowest"] -= 1
            prev_score["excess_s"] = round(prev_score["excess_s"] - prev_excess, 6)
        score = self._score(slowest["remote"])
        score["rounds_slowest"] += 1
        score["excess_s"] = round(score["excess_s"] + excess, 6)
        self._round_contrib[round_id] = (slowest["remote"], excess)
        window = self._epoch_window.get(str(record.get("peer", "?")))
        if window is not None:  # popped by record_epoch: a late attach must not resurrect it
            window["straggler"] = slowest["remote"]

    def _attach_late_exchange(self, round_id: int, info: Dict[str, Any]) -> None:
        """An exchange span that outlived its round (the slowest one usually
        does — its delta completes the round's output, ending the round span
        while the exchange still awaits the stream close): fold it into the
        already-assembled record and re-attribute the round. Lock held."""
        record = self._closed_rounds[round_id]
        record.setdefault("exchanges", []).append(info)
        if "codec" in info:
            record.setdefault("link_codecs", {})[info["remote"]] = info["codec"]
        score = self._score(info["remote"])
        score["total_s"] = round(score["total_s"] + info["dur_s"], 6)
        if info["events"]:
            counts = record.setdefault("events", {})
            for event in info["events"]:
                counts[event] = counts.get(event, 0) + 1
        self._apply_round_attribution(round_id, record)

    def record_codec_event(self, peer: str, action: str, tier: Optional[str] = None) -> None:
        """One adaptive link-codec decision (demote/promote, from the averager's
        straggler policy): who, what, and to which tier."""
        with self._lock:
            self._codec_events.append(
                {
                    "time": round(wall_time(), 3),
                    "peer": str(peer),
                    "action": str(action),
                    "tier": tier,
                }
            )

    def codec_events(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            events = list(self._codec_events)
            if limit:
                events = events[-limit:]
            return [dict(event) for event in events]

    def record_epoch(
        self,
        epoch: int,
        peer: str = "?",
        averaged_ok: Optional[bool] = None,
        num_peers: Optional[int] = None,
        **extra: Any,
    ) -> Dict[str, Any]:
        """One epoch-transition record (called by the optimizer): carries the
        averaging rounds that happened since the previous transition, so the
        per-epoch swarm timeline can attribute epoch wall time to rounds and
        rounds to peers."""
        with self._lock:
            # consume THIS peer's rolling window only (see _epoch_window)
            window = self._epoch_window.pop(str(peer), None) or {
                "rounds": 0, "round_s": 0.0, "straggler": None,
                "overlap_sum": 0.0, "overlap_n": 0,
            }
            entry: Dict[str, Any] = {
                "epoch": int(epoch),
                "peer": str(peer),
                "time": round(wall_time(), 3),
                "rounds": window["rounds"],
                "round_s": round(window["round_s"], 6),
            }
            if window.get("overlap_n"):
                entry["overlap_efficiency"] = round(
                    window["overlap_sum"] / window["overlap_n"], 4
                )
            if averaged_ok is not None:
                entry["averaged_ok"] = bool(averaged_ok)
            if num_peers is not None:
                entry["num_peers"] = int(num_peers)
            if window["straggler"] is not None:
                entry["straggler"] = window["straggler"]
            entry.update(extra)
            self._epochs.append(entry)
        self._notify_record("epoch", dict(entry))
        return dict(entry)

    # ------------------------------------------------------------------ reading

    @staticmethod
    def _copy_record(record: Dict[str, Any]) -> Dict[str, Any]:
        """Records stay LIVE after publication (_attach_late_exchange mutates
        them under the lock), so every read hands out copies deep enough that
        a concurrent retro-attachment cannot change a dict/list mid-serialize."""
        out = dict(record)
        if "exchanges" in out:
            out["exchanges"] = [dict(exchange) for exchange in out["exchanges"]]
        for nested in ("events", "counters", "link_codecs"):
            if nested in out:
                out[nested] = dict(out[nested])
        return out

    def records(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self._records)
            if limit:
                records = records[-limit:]
            return [self._copy_record(record) for record in records]

    def epochs(self, limit: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            epochs = list(self._epochs)
            if limit:
                epochs = epochs[-limit:]
            return [dict(entry) for entry in epochs]

    def straggler_scores(self, limit: Optional[int] = None) -> Dict[str, Dict[str, float]]:
        """Per-peer straggler scores, worst first (by times-slowest, then excess)."""
        with self._lock:
            items = sorted(
                ((peer, dict(score)) for peer, score in self._straggler.items()),
                # peer name breaks ties: without it, tied peers rank by dict
                # insertion order — i.e. span completion order — and a limited
                # listing's MEMBERSHIP would vary run to run (the sim hashes
                # these summaries into its determinism digest)
                key=lambda kv: (-kv[1]["rounds_slowest"], -kv[1]["excess_s"], kv[0]),
            )
        return dict(items[:limit] if limit else items)

    def summary(self) -> Dict[str, Any]:
        """Compact rollup for BENCH artifacts and the dashboard header: round
        count plus mean/p95 of each phase — a perf regression's artifact then
        says WHERE the regression lives, not just the headline number."""
        records = self.records()
        out: Dict[str, Any] = {"rounds": len(records), "epochs": len(self._epochs)}
        for field in ("total_s", "matchmaking_wait_s", "local_reduce_s", "slowest_s"):
            values = [r[field] for r in records if field in r]
            if values:
                out[field] = {
                    "mean": round(sum(values) / len(values), 6),
                    "p95": round(_percentile(values, 0.95), 6),
                }
        stragglers = self.straggler_scores(limit=5)
        if stragglers:
            out["stragglers"] = stragglers
        return out

    def snapshot(self, max_records: int = 8, max_stragglers: int = 5) -> Dict[str, Any]:
        """The compact view that rides the DHT peer snapshot: most recent
        records without their full exchange lists, top straggler scores, and
        recent epoch transitions. Size-budgeted by monitor._shrink_to_fit."""
        records = []
        for record in self.records(limit=max_records):
            compact = {k: v for k, v in record.items() if k != "exchanges"}
            records.append(compact)
        out: Dict[str, Any] = {}
        if records:
            out["records"] = records
        stragglers = self.straggler_scores(limit=max_stragglers)
        if stragglers:
            out["stragglers"] = stragglers
        epochs = self.epochs(limit=max_records)
        if epochs:
            out["epochs"] = epochs
        codec_events = self.codec_events(limit=max_stragglers)
        if codec_events:
            out["codec_events"] = codec_events
        return out

    def export(self) -> Dict[str, Any]:
        """Everything, raw — the ``GET /ledger`` response body."""
        return {
            "records": self.records(),
            "epochs": self.epochs(),
            "straggler_scores": self.straggler_scores(),
            "codec_events": self.codec_events(),
            "summary": self.summary(),
        }

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._epochs.clear()
            self._straggler.clear()
            self._codec_events.clear()
            self._pending_exchanges.clear()
            self._pending_local.clear()
            self._closed_rounds.clear()
            self._round_contrib.clear()
            self._last_matchmaking.clear()
            # re-anchor the delta baselines NOW: registry counters are
            # monotonic and survive a ledger clear, and the first post-clear
            # record must cover its own window, not everything since import
            self._counter_baseline = {
                metric_name: self._counter_total(metric_name)
                for metric_name in _DELTA_COUNTERS.values()
            }
            self._round_index = 0
            self._epoch_window.clear()

    def __len__(self) -> int:
        return len(self._records)


LEDGER = RoundLedger()
add_span_listener(LEDGER.on_span)
