"""Scenario harness for the in-process swarm simulator (ISSUE 12).

Each scenario builds a seeded :class:`SimNetwork` on a
:class:`VirtualClockEventLoop`, runs real DHT / matchmaking / beam-search logic
over it, and returns a :class:`ScenarioResult` whose ``summary`` is
**deterministic**: every value derives from virtual time, seeded RNG streams
and message contents — never from wall clocks or memory addresses — so two
runs with the same seed produce byte-identical canonical JSON (asserted by
``benchmark_swarm_sim.py --smoke`` and tests/test_swarm_sim.py). Wall-time
facts (how fast the sim ran) live in ``diagnostics``, outside the digest.

Scenarios:

- ``dht_churn`` — N-peer DHT: bootstrap, bulk publish, seeded crash churn +
  replacements, republish, store/get fan-out probes; optional matchmaking
  cohort (the 1k-peer ROADMAP soak is this scenario at ``peers=1000``).
- ``beam_routing`` — a full expert grid declared through the real prefix
  encoding; MoEBeamSearcher recall@beam vs a brute-force oracle (ROADMAP: 10k
  experts).
- ``matchmaking_partition`` — two regions, a timed WAN partition: groups must
  keep forming inside each island (no cross-region groups while severed) and
  mix again after heal.
- ``smoke`` — small composite of all three plus a link-scoped chaos rule,
  tier-1-safe.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
import json
import random
import statistics
import time
import zlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.moe.client.beam_search import MoEBeamSearcher
from hivemind_tpu.moe.server.dht_handler import declare_experts
from hivemind_tpu.resilience import CHAOS
from hivemind_tpu.sim.clock import VirtualClockEventLoop, install_virtual_time, uninstall_virtual_time
from hivemind_tpu.sim.network import LinkMatrix, LinkProfile, Partition, SimNetwork
from hivemind_tpu.sim.peer import SimPeer
from hivemind_tpu.telemetry.blackbox import BlackBox
from hivemind_tpu.telemetry.ledger import RoundLedger
from hivemind_tpu.telemetry.registry import MetricsRegistry
from hivemind_tpu.telemetry.tracing import (
    add_span_listener,
    remove_span_listener,
    seed_trace_ids,
    trace,
)
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)


@dataclass
class ScenarioResult:
    name: str
    seed: int
    summary: dict
    diagnostics: dict = field(default_factory=dict)

    def canonical(self) -> str:
        """Canonical JSON of the deterministic summary (digest input)."""
        return json.dumps(self.summary, sort_keys=True, separators=(",", ":"))

    def digest(self) -> str:
        return hashlib.sha256(self.canonical().encode()).hexdigest()


def run_scenario(name: str, seed: int = 0, **params) -> ScenarioResult:
    """Run one scenario to completion on a fresh virtual-clock loop.

    Installs the virtual swarm-time source and seeds every RNG stream the
    scenario touches; both are restored/irrelevant after return, so scenarios
    compose with the rest of a test process.
    """
    scenario = _SCENARIOS.get(name)
    if scenario is None:
        raise ValueError(f"unknown scenario {name!r} (choose from {sorted(_SCENARIOS)})")
    loop = VirtualClockEventLoop()
    install_virtual_time(loop)
    rng_state = random.getstate()
    random.seed(zlib.crc32(f"{name}|{seed}".encode()))
    # trace/span ids are OS-seeded by default (forked peers must diverge);
    # inside a scenario they come from the scenario seed so per-peer black-box
    # spools are bit-identical across same-seed runs (ISSUE 17)
    seed_trace_ids(zlib.crc32(f"{name}|{seed}|trace".encode()))
    if CHAOS.enabled:
        CHAOS.reseed(seed)  # replaying the same seed must replay the same faults
    wall_started = time.perf_counter()
    try:
        asyncio.set_event_loop(loop)
        vtime_started = loop.time()
        summary = loop.run_until_complete(scenario(seed=seed, **params))
        sim_seconds = loop.time() - vtime_started
    finally:
        uninstall_virtual_time()
        random.setstate(rng_state)  # the process's global stream is not ours to keep
        seed_trace_ids(None)  # back to OS entropy: live peers must diverge again
        with contextlib.suppress(Exception):
            _drain_loop(loop)
        asyncio.set_event_loop(None)
        loop.close()
    wall_seconds = time.perf_counter() - wall_started
    return ScenarioResult(
        name=name,
        seed=seed,
        summary=summary,
        diagnostics={
            "wall_seconds": round(wall_seconds, 3),
            "sim_seconds": round(sim_seconds, 3),
            "sim_seconds_per_wall_second": round(sim_seconds / max(wall_seconds, 1e-9), 2),
            "chaos_injections": CHAOS.stats(),
        },
    )


def _drain_loop(loop: asyncio.AbstractEventLoop) -> None:
    """Cancel and reap whatever the scenario left behind so loop.close() is quiet."""
    pending = [t for t in asyncio.all_tasks(loop) if not t.done()]
    for task in pending:
        task.cancel()
    if pending:
        loop.run_until_complete(asyncio.gather(*pending, return_exceptions=True))


# ---------------------------------------------------------------------- helpers


def _region_name(index: int, regions: int) -> str:
    return f"r{index % max(regions, 1)}"


async def _build_swarm(
    network: SimNetwork,
    count: int,
    *,
    seed: int,
    regions: int,
    name_prefix: str = "p",
    start_index: int = 0,
    existing: Sequence[SimPeer] = (),
    batch: int = 32,
    **dht_kwargs,
) -> List[SimPeer]:
    """Spawn ``count`` peers in deterministic batches; each bootstraps off up to
    3 peers created strictly before its batch (so batch concurrency cannot race
    a peer against its own bootstrap target)."""
    rng = random.Random(zlib.crc32(f"{seed}|bootstrap|{name_prefix}|{start_index}".encode()))
    peers: List[SimPeer] = list(existing)
    created: List[SimPeer] = []
    index = start_index
    while len(created) < count:
        # the very first peer seeds the swarm alone; everyone after bootstraps
        # off peers created in strictly earlier batches
        n_batch = 1 if not peers else min(batch, count - len(created))
        known = list(peers)  # bootstrap pool: everyone from earlier batches
        coros = []
        for _ in range(n_batch):
            name = f"{name_prefix}{index:05d}"
            region = _region_name(index, regions)
            if known:
                targets = rng.sample(known, k=min(3, len(known)))
                bootstrap = [maddr for t in targets for maddr in t.bootstrap_maddrs()]
            else:
                bootstrap = []
            coros.append(
                SimPeer.create(network, name, region, bootstrap=bootstrap, **dht_kwargs)
            )
            index += 1
        batch_peers = await asyncio.gather(*coros)
        created.extend(batch_peers)
        peers.extend(batch_peers)
    return created


def _routing_table_stats(peers: Sequence[SimPeer]) -> dict:
    sizes = sorted(len(p.node.protocol.routing_table) for p in peers if p.p2p.alive)
    if not sizes:
        return {"min": 0, "median": 0, "max": 0}
    return {
        "min": sizes[0],
        "median": int(statistics.median(sizes)),
        "max": sizes[-1],
    }


# ---------------------------------------------------------------------- dht_churn


async def _scenario_dht_churn(
    seed: int,
    *,
    peers: int = 1000,
    regions: int = 4,
    keys: int = 1000,
    churn_fraction: float = 0.10,
    replacements: Optional[int] = None,
    probe_samples: int = 200,
    matchmaking_peers: int = 0,
    matchmaking_rounds: int = 2,
    min_matchmaking_time: float = 4.0,
    blackbox_root: Optional[str] = None,
) -> dict:
    network = SimNetwork(LinkMatrix(seed=seed), seed=seed)
    rng = random.Random(zlib.crc32(f"{seed}|churn".encode()))
    swarm = await _build_swarm(network, peers, seed=seed, regions=regions)

    # --- bulk publish: each key belongs to one owner; owners store in ONE
    # store_many call so the shared-traversal batching (dht/node.py) is on the
    # hot path exactly like a republish storm
    owners: Dict[str, SimPeer] = {}
    per_owner: Dict[int, List[str]] = {}
    for key_index in range(keys):
        owner_index = key_index % len(swarm)
        key = f"key-{key_index:05d}"
        owners[key] = swarm[owner_index]
        per_owner.setdefault(owner_index, []).append(key)

    async def _publish(owner_index: int, owned_keys: List[str]) -> int:
        owner = swarm[owner_index]
        expiration = get_dht_time() + 600.0
        result = await owner.node.store_many(
            owned_keys,
            [{"owner": owner.name, "k": k} for k in owned_keys],
            expiration,
        )
        return sum(bool(v) for v in result.values())

    publish_started_msgs = network.counters["messages"]
    publish_ok = 0
    owner_items = sorted(per_owner.items())
    for start in range(0, len(owner_items), 64):
        chunk = owner_items[start : start + 64]
        publish_ok += sum(await asyncio.gather(*(_publish(i, ks) for i, ks in chunk)))
    publish_msgs = network.counters["messages"] - publish_started_msgs

    # --- churn: seeded crash-kills, then replacements bootstrapping off survivors
    n_kill = int(len(swarm) * churn_fraction)
    victims = sorted(rng.sample(range(len(swarm)), k=n_kill))
    for index in victims:
        swarm[index].crash()
    survivors = [p for p in swarm if p.p2p.alive]
    n_replace = n_kill if replacements is None else replacements
    replacement_peers = await _build_swarm(
        network,
        n_replace,
        seed=seed,
        regions=regions,
        name_prefix="q",
        start_index=len(swarm),
        existing=survivors,
    )
    live = survivors + replacement_peers

    # --- republish: surviving owners re-store with fresh expirations; the
    # message delta is the republish load the satellite batching targets
    republish_started_msgs = network.counters["messages"]
    republish_ok = 0
    live_owner_items = [(i, ks) for i, ks in owner_items if swarm[i].p2p.alive]
    for start in range(0, len(live_owner_items), 64):
        chunk = live_owner_items[start : start + 64]
        republish_ok += sum(await asyncio.gather(*(_publish(i, ks) for i, ks in chunk)))
    republish_msgs = network.counters["messages"] - republish_started_msgs

    # --- optional matchmaking cohort riding the same churned swarm
    matchmaking_summary = None
    if matchmaking_peers > 0:
        cohort = live[: min(matchmaking_peers, len(live))]
        for peer in cohort:
            await peer.enable_matchmaking(
                "sim_soak", target_group_size=4, min_matchmaking_time=min_matchmaking_time
            )
        matchmaking_summary = await _run_matchmaking_rounds(
            network,
            cohort,
            rounds=matchmaking_rounds,
            window=min_matchmaking_time * 6,
            blackbox_root=blackbox_root,
        )

    # --- probes: seeded sample of keys, each read from a seeded live reader
    probe_keys = sorted(rng.sample(sorted(owners), k=min(probe_samples, len(owners))))
    hits = 0
    for key in probe_keys:
        reader = live[rng.randrange(len(live))]
        found = await reader.node.get(key)
        if found is not None and isinstance(found.value, dict) and found.value.get("k") == key:
            hits += 1
    get_success_rate = hits / max(len(probe_keys), 1)

    summary = {
        "scenario": "dht_churn",
        "peers": peers,
        "regions": regions,
        "keys": keys,
        "publish_ok": publish_ok,
        "publish_messages": publish_msgs,
        "churn_killed": n_kill,
        "replacements": n_replace,
        "republish_ok": republish_ok,
        "republish_messages": republish_msgs,
        "probes": len(probe_keys),
        "probe_hits": hits,
        "get_success_rate": round(get_success_rate, 4),
        "routing_table": _routing_table_stats(live),
        "network": dict(sorted(network.counters.items())),
        "sim_seconds": round(network.rel_time(), 3),
    }
    if matchmaking_summary is not None:
        summary["matchmaking"] = matchmaking_summary
    await _teardown(network, swarm + replacement_peers)
    return summary


# ---------------------------------------------------------------------- beam_routing


def _expert_uid(prefix: str, coords: Tuple[int, ...]) -> str:
    return prefix + ".".join(str(c) for c in coords)


async def _scenario_beam_routing(
    seed: int,
    *,
    peers: int = 100,
    servers: int = 50,
    grid: Tuple[int, ...] = (10, 10, 100),
    beam_size: int = 8,
    trials: int = 16,
    regions: int = 2,
) -> dict:
    network = SimNetwork(LinkMatrix(seed=seed), seed=seed)
    swarm = await _build_swarm(network, peers, seed=seed, regions=regions)
    server_peers = swarm[: min(servers, len(swarm))]
    client = swarm[-1]
    prefix = "ffn."

    # full grid coverage, experts spread over servers by seeded hash — at the
    # default grid this is the ROADMAP's 10k-expert declaration load
    coords_list: List[Tuple[int, ...]] = [()]
    for dim_size in grid:
        coords_list = [c + (i,) for c in coords_list for i in range(dim_size)]
    assignments: Dict[int, List[str]] = {}
    for coords in coords_list:
        uid = _expert_uid(prefix, coords)
        owner = zlib.crc32(f"{seed}|expert|{uid}".encode()) % len(server_peers)
        assignments.setdefault(owner, []).append(uid)

    declare_started_msgs = network.counters["messages"]
    expiration = get_dht_time() + 1200.0

    async def _declare(owner: int) -> None:
        peer = server_peers[owner]
        await declare_experts(peer.dht, assignments[owner], expiration, wait=False)

    owners_sorted = sorted(assignments)
    for start in range(0, len(owners_sorted), 16):
        await asyncio.gather(*(_declare(o) for o in owners_sorted[start : start + 16]))
    declare_msgs = network.counters["messages"] - declare_started_msgs

    searcher = MoEBeamSearcher(client.dht, prefix, grid_size=grid)
    recalls: List[float] = []
    for trial in range(trials):
        trial_rng = np.random.default_rng(seed * 100_003 + trial)
        scores = [trial_rng.standard_normal(dim_size).astype(np.float32) for dim_size in grid]
        # oracle: brute-force top-k over the (separable) full grid
        total = scores[0]
        for dim_scores in scores[1:]:
            total = total[..., None] + dim_scores
        flat = total.reshape(-1)
        top = np.argsort(-flat, kind="stable")[:beam_size]
        oracle = {
            _expert_uid(prefix, tuple(int(c) for c in np.unravel_index(int(ix), grid)))
            for ix in top
        }
        found = await searcher._find_best_experts_async(
            client.node, [s[None] for s in scores], beam_size
        )
        found_uids = {info.uid for info in found[0]}
        recalls.append(len(found_uids & oracle) / beam_size)

    summary = {
        "scenario": "beam_routing",
        "peers": peers,
        "servers": len(server_peers),
        "experts": len(coords_list),
        "grid": list(grid),
        "beam_size": beam_size,
        "trials": trials,
        "declare_messages": declare_msgs,
        "recall_at_beam": round(float(np.mean(recalls)), 6),
        "min_recall": round(float(np.min(recalls)), 6),
        "network": dict(sorted(network.counters.items())),
        "sim_seconds": round(network.rel_time(), 3),
    }
    await _teardown(network, swarm)
    return summary


# ---------------------------------------------------------------------- matchmaking_partition


def _peer_stagger(seed: int, name: str, spread: float) -> float:
    """Deterministic per-peer start offset. Virtual time is perfectly
    synchronized, so peers launched by one ``gather`` would all declare the
    SAME matchmaking expiration and nobody could ever lead anybody (the
    earliest-expiration-leads DAG needs distinct deadlines). Real swarms are
    desynchronized by wall-clock jitter; the sim makes that jitter seeded."""
    return (zlib.crc32(f"{seed}|stagger|{name}".encode()) % 10_000) / 10_000 * spread


async def _match_loop(
    network: SimNetwork,
    peer: SimPeer,
    name_of: Dict,
    records: List[Tuple[float, Tuple[str, ...]]],
    *,
    rounds: Optional[int] = None,
    window: Optional[float] = None,
    deadline: Optional[float] = None,
    min_lead: float = 0.0,
    poll: float = 0.25,
    simulate_allreduce: bool = False,
) -> None:
    """One peer's matchmaking driver, shared by every scenario: staggered start,
    repeated ``look_for_group`` bounded by ``rounds`` attempts and/or a
    virtual-time ``deadline`` (stop when less than ``min_lead`` remains; with a
    deadline a timed-out attempt ends the loop), appending deterministic
    ``(rel_time, sorted_member_names)`` records. Each attempt is traced as an
    ``averaging.matchmaking`` span (the round ledger's wait-time signal), and
    with ``simulate_allreduce`` a formed group runs one synthesized
    :meth:`SimPeer.simulate_allreduce_round` so virtual-time ledger records
    with straggler attribution exist (ISSUE 17)."""
    await asyncio.sleep(_peer_stagger(network.seed, peer.name, spread=2.0))
    attempts = 0
    while rounds is None or attempts < rounds:
        if not peer.p2p.alive:
            return
        timeout = window
        if deadline is not None:
            remaining = deadline - network.rel_time()
            if remaining <= min_lead:
                return
            timeout = remaining if window is None else min(window, remaining)
        attempts += 1
        timed_out = False
        with trace("averaging.matchmaking", peer=peer.name) as mm_span:
            try:
                group = await asyncio.wait_for(peer.look_for_group(), timeout=timeout)
            except asyncio.TimeoutError:
                group, timed_out = None, True
            except Exception:
                group = None
            if mm_span is not None:
                mm_span.set(
                    "outcome",
                    "timeout" if timed_out else ("matched" if group is not None else "failed"),
                )
        if timed_out and deadline is not None:
            return
        if group is not None:
            members = tuple(sorted(name_of.get(pid, str(pid)) for pid in group.peer_ids))
            records.append((round(network.rel_time(), 3), members))
            if simulate_allreduce:
                await peer.simulate_allreduce_round(group)
        await asyncio.sleep(poll)


def _dedupe_groups(records: List[Tuple[float, Tuple[str, ...]]]) -> Dict[Tuple[str, ...], float]:
    """One group assembles once but is recorded by every member: dedupe on the
    member set, keep the earliest formation time (deterministic)."""
    groups: Dict[Tuple[str, ...], float] = {}
    for formed_at, members in records:
        if members not in groups or formed_at < groups[members]:
            groups[members] = formed_at
    return groups


async def _run_matchmaking_rounds(
    network: SimNetwork,
    cohort: Sequence[SimPeer],
    *,
    rounds: int,
    window: float,
    simulate_allreduce: bool = True,
    blackbox_root: Optional[str] = None,
) -> dict:
    """Every cohort peer repeatedly looks for a group for ``rounds`` attempts
    (bounded by ``window`` sim-seconds each); returns deterministic group facts.

    With ``simulate_allreduce`` (the default) every formed group also runs a
    synthesized all-reduce round, attributed by a PRIVATE :class:`RoundLedger`
    on a private empty registry — the process-wide registry's counters are
    cross-test noise and would poison the deterministic digest. The resulting
    virtual-time ledger summary (rounds, phase quantiles, straggler scores)
    rides the returned dict. ``blackbox_root`` additionally arms one
    :class:`BlackBox` spool per cohort peer under ``<root>/<peer name>``,
    subscribed to the same private ledger — per-peer spools bit-identical
    across same-seed runs."""
    name_of = {peer.peer_id: peer.name for peer in cohort}
    records: List[Tuple[float, Tuple[str, ...]]] = []
    ledger: Optional[RoundLedger] = None
    boxes: List[BlackBox] = []
    if simulate_allreduce:
        ledger = RoundLedger(registry=MetricsRegistry())
        add_span_listener(ledger.on_span)
        if blackbox_root is not None:
            for peer in cohort:
                boxes.append(
                    BlackBox(
                        Path(blackbox_root) / peer.name,
                        peer=peer.name,
                        peer_filter=peer.name,
                        ledger=ledger,
                        metrics_interval=None,
                    )
                )
    try:
        await asyncio.gather(
            *(
                _match_loop(
                    network, peer, name_of, records,
                    rounds=rounds, window=window, simulate_allreduce=simulate_allreduce,
                )
                for peer in cohort
            )
        )
    finally:
        for box in boxes:
            box.close()
        if ledger is not None:
            remove_span_listener(ledger.on_span)
    groups = _dedupe_groups(records)
    matched = {name for members in groups for name in members}
    summary = {
        "cohort": len(cohort),
        "rounds_per_peer": rounds,
        "groups": sorted([t, list(m)] for m, t in groups.items()),
        "groups_formed": len(groups),
        "peers_matched": len(matched),
        "group_sizes": sorted(len(m) for m in groups),
    }
    if ledger is not None:
        summary["ledger"] = ledger.summary()
    return summary


async def _scenario_matchmaking_partition(
    seed: int,
    *,
    peers: int = 16,
    target_group_size: int = 4,
    min_matchmaking_time: float = 4.0,
    request_timeout: float = 3.0,
    partition_delay: float = 10.0,
    partition_length: float = 60.0,
    post_heal: float = 60.0,
) -> dict:
    regions = ("east", "west")
    links = LinkMatrix(
        seed=seed,
        intra=LinkProfile(delay=0.004, bandwidth=125e6, jitter=0.1),
        inter=LinkProfile(delay=0.08, bandwidth=12.5e6, jitter=0.25),
    )
    network = SimNetwork(links, seed=seed)
    swarm = await _build_swarm(network, peers, seed=seed, regions=2)
    region_of = {}
    for index, peer in enumerate(swarm):
        region_of[peer.name] = regions[index % 2]
    # NB: _region_name gave peers regions "r0"/"r1"; relabel to east/west for
    # the partition (the matrix matches on the SimP2P region tag)
    for peer in swarm:
        peer.p2p.region = region_of[peer.name]

    for peer in swarm:
        await peer.enable_matchmaking(
            "sim_partition",
            target_group_size=target_group_size,
            min_matchmaking_time=min_matchmaking_time,
            request_timeout=request_timeout,
        )

    # schedule the partition relative to NOW (bootstrap already consumed sim time)
    partition_start = network.rel_time() + partition_delay
    partition_end = partition_start + partition_length
    links.partitions = (Partition.between("east", "west", partition_start, partition_end),)
    scenario_end = partition_end + post_heal

    name_of = {peer.peer_id: peer.name for peer in swarm}
    records: List[Tuple[float, Tuple[str, ...]]] = []
    await asyncio.gather(
        *(
            _match_loop(
                network, peer, name_of, records,
                deadline=scenario_end, min_lead=min_matchmaking_time, poll=0.5,
            )
            for peer in swarm
        )
    )
    groups = _dedupe_groups(records)

    def _phase(formed_at: float) -> str:
        if formed_at < partition_start:
            return "pre"
        if formed_at < partition_end:
            return "during"
        return "post"

    phases = {"pre": [], "during": [], "post": []}
    for members, formed_at in groups.items():
        regions_in_group = {region_of[name] for name in members}
        phases[_phase(formed_at)].append(
            {"t": formed_at, "members": list(members), "cross_region": len(regions_in_group) > 1}
        )
    for phase_groups in phases.values():
        phase_groups.sort(key=lambda g: (g["t"], g["members"]))
    matched_during = {
        name for g in phases["during"] for name in g["members"]
    }
    # groups assembled moments after the cut may have courted cross-region
    # BEFORE it: the settled window excludes in-flight state, so an assertion
    # "no cross-region groups while severed" has a principled boundary
    settle_margin = min_matchmaking_time + 2.0 * request_timeout  # lead time + 2 RPC timeouts
    cross_region_during_settled = sum(
        g["cross_region"] for g in phases["during"] if g["t"] >= partition_start + settle_margin
    )

    summary = {
        "scenario": "matchmaking_partition",
        "peers": peers,
        "target_group_size": target_group_size,
        "partition": [round(partition_start, 3), round(partition_end, 3)],
        "groups_pre": len(phases["pre"]),
        "groups_during": len(phases["during"]),
        "groups_post": len(phases["post"]),
        "cross_region_during": sum(g["cross_region"] for g in phases["during"]),
        "cross_region_during_settled": cross_region_during_settled,
        "cross_region_post": sum(g["cross_region"] for g in phases["post"]),
        "peers_matched_during": len(matched_during),
        "convergence_during": round(len(matched_during) / peers, 4),
        "groups": phases,
        "network": dict(sorted(network.counters.items())),
        "sim_seconds": round(network.rel_time(), 3),
    }
    await _teardown(network, swarm)
    return summary


# ---------------------------------------------------------------------- smoke composite


async def _scenario_smoke(
    seed: int,
    *,
    dht_peers: int = 60,
    beam_peers: int = 24,
    matchmaking_peers: int = 12,
) -> dict:
    """Small composite of all three scenarios under one loop — plus a
    link-scoped chaos rule, proving the 14-point catalog composes with the
    sim's directional link scoping."""
    rule = CHAOS.add_rule(
        "p2p.unary.send", "delay", delay=0.05, times=200, scope="link:*->*"
    )
    try:
        dht_summary = await _scenario_dht_churn(
            seed,
            peers=dht_peers,
            regions=2,
            keys=90,
            churn_fraction=0.15,
            probe_samples=60,
        )
        chaos_hits = rule.hits
    finally:
        CHAOS.remove_rule(rule)
    beam_summary = await _scenario_beam_routing(
        seed, peers=beam_peers, servers=12, grid=(4, 4, 8), beam_size=4, trials=4
    )
    matchmaking_summary = await _scenario_matchmaking_partition(
        seed,
        peers=matchmaking_peers,
        partition_delay=6.0,
        partition_length=40.0,
        post_heal=40.0,
    )
    return {
        "scenario": "smoke",
        "chaos_link_rule_hits": chaos_hits,
        "dht": dht_summary,
        "beam": beam_summary,
        "matchmaking": matchmaking_summary,
    }


# ---------------------------------------------------------------------- plumbing


async def _teardown(network: SimNetwork, peers: Sequence[SimPeer]) -> None:
    for peer in peers:
        with contextlib.suppress(Exception):
            await peer.shutdown()
    await network.shutdown()


_SCENARIOS = {
    "dht_churn": _scenario_dht_churn,
    "beam_routing": _scenario_beam_routing,
    "matchmaking_partition": _scenario_matchmaking_partition,
    "smoke": _scenario_smoke,
}


def scenario_names() -> List[str]:
    return sorted(_SCENARIOS)
