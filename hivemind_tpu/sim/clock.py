"""Virtual time for the in-process swarm simulator (ISSUE 12).

The simulator runs hundreds-to-thousands of peers whose protocols are paced by
timers — matchmaking windows, DHT expirations, republish cadences, WAN link
delays. Sleeping those out in wall time would make a 1k-peer scenario take
hours and make every run racy. :class:`VirtualClockEventLoop` makes time
*event-driven* instead: ``loop.time()`` is a simulated clock, and whenever the
loop would block waiting for its next timer it jumps the clock straight to
that timer's deadline. A scenario that simulates 600 seconds of swarm time
completes in however long its CPU work actually takes, and — because callback
order is decided by the timer heap and FIFO ready queue, never by the host's
scheduler — the same seed replays the exact same execution.

``get_dht_time`` (utils/timed_storage.py) must track the same clock so DHT
expirations, declaration windows and blacklist backoffs live in simulated
time; :func:`install_virtual_time` wires both and restores wall time on exit.
"""

from __future__ import annotations

import asyncio
import math
from typing import Optional

from hivemind_tpu.telemetry.tracing import set_telemetry_time_source
from hivemind_tpu.utils.timed_storage import set_dht_time_source

# consecutive selector polls with nothing scheduled and nothing ready before the
# loop declares the simulation deadlocked (a real deadlock, e.g. awaiting a
# future nobody will ever resolve, would otherwise spin silently forever)
_MAX_IDLE_POLLS = 500


class SimDeadlockError(RuntimeError):
    """The virtual-clock loop has no timers, no ready callbacks and no I/O:
    nothing can ever make progress again."""


class VirtualClockEventLoop(asyncio.SelectorEventLoop):
    """An asyncio loop whose clock is simulated (see module docstring).

    All waits must be timer- or callback-driven (pure in-process simulation).
    Real file descriptors still poll (zero-timeout), so a stray
    ``call_soon_threadsafe`` from another thread is delivered rather than
    deadlocking — but anything thread-timed breaks determinism and has no
    place in a scenario.
    """

    def __init__(self, start_time: float = 1_000_000_000.0):
        super().__init__()
        self._vtime = float(start_time)
        self._idle_polls = 0
        self._real_select = self._selector.select
        self._selector.select = self._virtual_select  # type: ignore[method-assign]
        # virtual time is epoch-magnitude (~1e9) where a double's ulp is ~1.2e-7:
        # with the host's nanosecond clock resolution, a timer landing within one
        # ulp of "now" would never satisfy `when < time() + resolution` and the
        # loop would spin forever on a sub-ulp timeout. One microsecond of sim
        # granularity makes those timers fire; nothing in the swarm is sub-µs.
        self._clock_resolution = max(self._clock_resolution, 1e-6)

    def time(self) -> float:
        return self._vtime

    def _virtual_select(self, timeout: Optional[float] = None):
        events = self._real_select(0)
        if events:
            self._idle_polls = 0
            return events
        if timeout is None:
            # no timer scheduled: only a cross-thread wakeup could help. Poll
            # briefly on the real clock (without advancing virtual time) so a
            # threadsafe callback still lands; a deterministic scenario never
            # reaches this branch, so a long stay here is a deadlocked sim.
            self._idle_polls += 1
            if self._idle_polls > _MAX_IDLE_POLLS:
                raise SimDeadlockError(
                    "virtual clock: no timers, no ready callbacks and no I/O — "
                    "the simulation is waiting on something that can never happen"
                )
            return self._real_select(0.02)
        self._idle_polls = 0
        if timeout > 0:
            # jump straight to the next timer deadline; a timeout below the
            # current ulp must still advance by one representable tick or the
            # loop would spin at a frozen clock
            advanced = self._vtime + timeout
            if advanced <= self._vtime:
                advanced = math.nextafter(self._vtime, math.inf)
            self._vtime = advanced
        return events


def install_virtual_time(loop: VirtualClockEventLoop) -> None:
    """Point ``get_dht_time`` AND the telemetry clock (spans, ledgers,
    watchdog stamps, black-box spools — ISSUE 17) at the loop's virtual
    clock. Virtual time starts at an epoch magnitude, so it serves as both
    the span clock and the wall clock; the wall anchor is exactly 0 and
    same-seed runs spool bit-identical telemetry."""
    set_dht_time_source(loop.time)
    set_telemetry_time_source(loop.time)


def uninstall_virtual_time() -> None:
    """Restore wall-clock swarm time (always call from a finally block)."""
    set_dht_time_source(None)
    set_telemetry_time_source(None)
