"""In-process swarm transport: a seeded WAN link matrix under the RPC seam (ISSUE 12).

:class:`SimP2P` implements the slice of the :class:`~hivemind_tpu.p2p.P2P`
surface that ``ServicerBase``/``StubBase`` and the DHT/matchmaking/MoE layers
actually touch — ``add_protobuf_handler`` / ``call_protobuf_handler`` /
``iterate_protobuf_handler`` plus identity and addressing — so the *logic*
layers (DHT routing/storage/validation, matchmaking, expert declarations and
beam search, breakers, ledgers) run **unmodified** over an in-process network.
Requests still round-trip through protobuf serialization (each side owns its
message objects, exactly like the wire), but instead of sockets every message
pays a seeded link cost:

- :class:`LinkMatrix` derives per-directed-link delay (with seeded jitter),
  bandwidth and loss from region tags, and severs region pairs on a
  :class:`Partition` schedule;
- faults beyond the baseline geometry come from the **chaos engine** via the
  directional ``scope=link:<src>-><dst>`` rule syntax (resilience/chaos.py) —
  the simulator tags every message with its link, so the existing 14-point
  catalog composes with per-link schedules instead of a parallel fault system.

Run under :class:`~hivemind_tpu.sim.clock.VirtualClockEventLoop`, link waits
cost no wall time and every delivery order is deterministic for a given seed.
"""

from __future__ import annotations

import asyncio
import hashlib
import zlib
from dataclasses import dataclass, field
from typing import AsyncIterator, Callable, Dict, List, Optional, Tuple, Type

from hivemind_tpu.p2p.p2p import P2PContext, P2PHandlerError, _parse, _serialize
from hivemind_tpu.p2p.peer_id import Multiaddr, PeerID
from hivemind_tpu.resilience import CHAOS as _CHAOS
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.streaming import WireParts

logger = get_logger(__name__)

# observability for simulated swarms (docs/observability.md, docs/simulation.md):
# the registry mirrors SimNetwork's deterministic internal counters so live sims
# are scrape-able; scenario summaries read the internal counters, not these.
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY

_SIM_MESSAGES = _TELEMETRY.counter(
    "hivemind_sim_messages_total", "messages carried by the simulated transport", ("kind",)
)
_SIM_BYTES = _TELEMETRY.counter(
    "hivemind_sim_bytes_total", "serialized payload bytes carried by the simulated transport"
)
_SIM_DROPS = _TELEMETRY.counter(
    "hivemind_sim_dropped_total", "messages the simulated network refused or lost", ("cause",)
)
_SIM_PEERS = _TELEMETRY.gauge("hivemind_sim_peers", "live peers in the simulated swarm")
_SIM_VTIME = _TELEMETRY.gauge(
    "hivemind_sim_virtual_time_seconds", "current virtual time of the running simulation"
)
_SIM_PARTITIONS = _TELEMETRY.gauge(
    "hivemind_sim_partitions_active", "partitions currently severing region pairs"
)


@dataclass(frozen=True)
class LinkProfile:
    """Base link geometry for a region pair (before per-link seeded jitter)."""

    delay: float = 0.02  # one-way propagation, seconds
    bandwidth: float = 12.5e6  # bytes/s (default ≈ 100 Mbps)
    loss: float = 0.0  # per-message loss probability
    jitter: float = 0.25  # ± fraction applied to delay, fixed per directed link


@dataclass(frozen=True)
class LinkSpec:
    """Resolved properties of one directed link."""

    delay: float
    bandwidth: float
    loss: float


@dataclass(frozen=True)
class Partition:
    """Severs every link between region sets ``a`` and ``b`` (both directions)
    during ``[start, end)`` seconds of virtual time since network creation."""

    start: float
    end: float
    a: frozenset
    b: frozenset

    @classmethod
    def between(cls, a, b, start: float, end: float) -> "Partition":
        a = frozenset([a] if isinstance(a, str) else a)
        b = frozenset([b] if isinstance(b, str) else b)
        return cls(start=float(start), end=float(end), a=a, b=b)

    def severs(self, region_a: str, region_b: str) -> bool:
        return (region_a in self.a and region_b in self.b) or (
            region_a in self.b and region_b in self.a
        )


_INTRA_DEFAULT = LinkProfile(delay=0.002, bandwidth=125e6, loss=0.0, jitter=0.1)
_INTER_DEFAULT = LinkProfile(delay=0.05, bandwidth=12.5e6, loss=0.0, jitter=0.25)


class LinkMatrix:
    """Seeded per-link WAN properties derived from region geometry.

    :param seed: jitter/loss seed — the same seed reproduces every link exactly
    :param intra: profile for links within one region
    :param inter: profile for links between different regions
    :param overrides: ``{(region_a, region_b): LinkProfile}`` — symmetric lookup
    :param partitions: schedule of :class:`Partition` windows
    """

    def __init__(
        self,
        seed: int = 0,
        intra: LinkProfile = _INTRA_DEFAULT,
        inter: LinkProfile = _INTER_DEFAULT,
        overrides: Optional[Dict[Tuple[str, str], LinkProfile]] = None,
        partitions: Tuple[Partition, ...] = (),
    ):
        self.seed = seed
        self.intra = intra
        self.inter = inter
        self.overrides = dict(overrides or {})
        self.partitions = tuple(partitions)
        self._spec_cache: Dict[Tuple[str, str], LinkSpec] = {}

    def profile(self, region_a: str, region_b: str) -> LinkProfile:
        hit = self.overrides.get((region_a, region_b))
        if hit is None:
            hit = self.overrides.get((region_b, region_a))
        if hit is not None:
            return hit
        return self.intra if region_a == region_b else self.inter

    def spec(self, src_name: str, dst_name: str, src_region: str, dst_region: str) -> LinkSpec:
        key = (src_name, dst_name)
        cached = self._spec_cache.get(key)
        if cached is not None:
            return cached
        profile = self.profile(src_region, dst_region)
        # fixed per-directed-link jitter: crc32 keeps it cheap and seed-stable
        unit = zlib.crc32(f"{self.seed}|{src_name}|{dst_name}".encode()) / 2**32
        delay = profile.delay * (1.0 + profile.jitter * (2.0 * unit - 1.0))
        spec = LinkSpec(delay=max(delay, 0.0), bandwidth=profile.bandwidth, loss=profile.loss)
        self._spec_cache[key] = spec
        return spec

    def partitioned(self, region_a: str, region_b: str, rel_time: float) -> bool:
        for partition in self.partitions:
            if partition.start <= rel_time < partition.end and partition.severs(region_a, region_b):
                return True
        return False

    def partitions_active(self, rel_time: float) -> int:
        return sum(1 for p in self.partitions if p.start <= rel_time < p.end)


@dataclass
class _SimHandler:
    fn: Callable
    request_type: Optional[Type]
    stream_input: bool
    stream_output: bool


def _material(payload) -> bytes:
    """Serialized payload as plain bytes (WireParts joined, memoryview copied)."""
    if isinstance(payload, WireParts):
        return payload.join()
    return bytes(payload)


class SimPeerDeadError(ConnectionError):
    """The target peer has been killed (or never existed)."""


class SimPartitionError(ConnectionError):
    """The link is severed by an active partition."""


class SimLossError(ConnectionError):
    """The message was lost by the link's seeded loss process."""


class SimNetwork:
    """The swarm: peer registry + link matrix + deterministic traffic counters.

    Create peers with :meth:`spawn`; the returned :class:`SimP2P` plugs
    directly into ``DHTNode.create(p2p=...)`` and every ``ServicerBase``.
    """

    def __init__(self, links: Optional[LinkMatrix] = None, seed: int = 0):
        self.seed = seed
        self.links = links if links is not None else LinkMatrix(seed=seed)
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = asyncio.get_event_loop()
        self._loop = loop
        self._epoch = loop.time()
        self._peers: Dict[PeerID, "SimP2P"] = {}
        self._by_addr: Dict[Tuple[str, int], PeerID] = {}
        self._busy: Dict[Tuple[PeerID, PeerID], float] = {}
        self._loss_rng: Dict[Tuple[PeerID, PeerID], "_Crc32Stream"] = {}
        self._tasks: set = set()
        self._next_index = 0
        # deterministic counters: scenario summaries read these (the telemetry
        # registry mirrors them but is process-global and wall-time-tainted)
        self.counters: Dict[str, int] = {
            "messages": 0,
            "bytes": 0,
            "dropped_partition": 0,
            "dropped_loss": 0,
            "dropped_dead": 0,
            "handler_errors": 0,
        }

    # ------------------------------------------------------------------ time

    def now(self) -> float:
        return self._loop.time()

    def rel_time(self) -> float:
        """Seconds of virtual time since the network was created."""
        return self._loop.time() - self._epoch

    # ------------------------------------------------------------------ peers

    def spawn(self, name: str, region: str = "default") -> "SimP2P":
        peer = SimP2P(self, name=name, region=region, index=self._next_index)
        self._next_index += 1
        if peer.peer_id in self._peers:
            raise ValueError(f"duplicate sim peer name {name!r} (ids are name-derived)")
        self._peers[peer.peer_id] = peer
        self._by_addr[(peer.maddr.host, peer.maddr.port)] = peer.peer_id
        _SIM_PEERS.set(self.live_peer_count())
        return peer

    def kill(self, peer: "SimP2P") -> None:
        """Crash semantics: the peer stops answering but nothing is cleaned up —
        its DHT declarations dangle exactly like a real dead process's."""
        peer.alive = False
        _SIM_PEERS.set(self.live_peer_count())

    def live_peer_count(self) -> int:
        return sum(1 for p in self._peers.values() if p.alive)

    def get_peer(self, peer_id: PeerID) -> Optional["SimP2P"]:
        return self._peers.get(peer_id)

    def resolve_maddr(self, maddr) -> PeerID:
        maddr = Multiaddr.parse(str(maddr))
        if maddr.peer_id is not None and maddr.peer_id in self._peers:
            return maddr.peer_id
        peer_id = self._by_addr.get((maddr.host, maddr.port))
        if peer_id is None:
            raise SimPeerDeadError(f"no sim peer at {maddr}")
        return peer_id

    async def shutdown(self) -> None:
        for task in list(self._tasks):
            task.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks.clear()

    # ------------------------------------------------------------------ links

    def _link_spec(self, src: "SimP2P", dst: "SimP2P") -> LinkSpec:
        return self.links.spec(src.name, dst.name, src.region, dst.region)

    def _check_link(self, src: "SimP2P", dst_id: PeerID) -> "SimP2P":
        dst = self._peers.get(dst_id)
        if dst is None or not dst.alive or not src.alive:
            self.counters["dropped_dead"] += 1
            _SIM_DROPS.inc(cause="dead")
            raise SimPeerDeadError(f"sim peer {dst_id} is unreachable (dead)")
        if self.links.partitioned(src.region, dst.region, self.rel_time()):
            self.counters["dropped_partition"] += 1
            _SIM_DROPS.inc(cause="partition")
            raise SimPartitionError(
                f"link {src.name}->{dst.name} severed by partition "
                f"({src.region}|{dst.region})"
            )
        return dst

    def _lost(self, src: "SimP2P", dst: "SimP2P", spec: LinkSpec) -> bool:
        if spec.loss <= 0.0:
            return False
        rng = self._loss_rng.get((src.peer_id, dst.peer_id))
        if rng is None:
            rng = _Crc32Stream(f"{self.seed}|loss|{src.name}|{dst.name}")
            self._loss_rng[(src.peer_id, dst.peer_id)] = rng
        return rng.next_unit() < spec.loss

    async def _transit(self, src: "SimP2P", dst: "SimP2P", nbytes: int, kind: str) -> None:
        """Pay one message's wire time: per-directed-link bandwidth serialization
        plus propagation delay. Raises on seeded loss (after the wire time, so
        rng consumption order == send order == deterministic)."""
        spec = self._link_spec(src, dst)
        now = self.now()
        start = max(now, self._busy.get((src.peer_id, dst.peer_id), now))
        finish = start + (nbytes / spec.bandwidth if spec.bandwidth > 0 else 0.0)
        self._busy[(src.peer_id, dst.peer_id)] = finish
        lost = self._lost(src, dst, spec)
        wait = (finish + spec.delay) - now
        if wait > 0:
            await asyncio.sleep(wait)
        self.counters["messages"] += 1
        self.counters["bytes"] += nbytes
        _SIM_MESSAGES.inc(kind=kind)
        _SIM_BYTES.inc(nbytes)
        _SIM_VTIME.set(self.now())
        _SIM_PARTITIONS.set(self.links.partitions_active(self.rel_time()))
        if lost:
            self.counters["dropped_loss"] += 1
            _SIM_DROPS.inc(cause="loss")
            raise SimLossError(f"message lost on link {src.name}->{dst.name}")
        # delivery-time checks: a message in flight when the link is severed (or
        # the receiver dies) is lost — long-lived streams opened before a
        # partition must NOT keep delivering across it
        if not dst.alive:
            self.counters["dropped_dead"] += 1
            _SIM_DROPS.inc(cause="dead")
            raise SimPeerDeadError(f"sim peer {dst.name} died before delivery")
        if self.links.partitioned(src.region, dst.region, self.rel_time()):
            self.counters["dropped_partition"] += 1
            _SIM_DROPS.inc(cause="partition")
            raise SimPartitionError(
                f"in-flight message lost: link {src.name}->{dst.name} severed"
            )

    def _spawn_task(self, coro) -> asyncio.Task:
        task = self._loop.create_task(coro)
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)
        return task

    # ------------------------------------------------------------------ unary

    async def unary_call(
        self, src: "SimP2P", dst_id: PeerID, name: str, request, response_type: Optional[Type]
    ):
        payload = _material(_serialize(request))
        scope = f"link:{src.peer_id}->{dst_id}"
        if _CHAOS.enabled:  # composes with scope=link:<src>-><dst> chaos rules
            payload = await _CHAOS.inject("p2p.unary.send", payload=payload, scope=scope)
        dst = self._check_link(src, dst_id)
        future = self._loop.create_future()
        # the handler runs in its OWN task: a caller that times out abandons the
        # future, but the server still executes (and its side effects apply),
        # matching real stream semantics
        self._spawn_task(self._serve_unary(src, dst, name, payload, response_type, future))
        future.add_done_callback(_retrieve_exception)
        return await future

    async def _serve_unary(
        self,
        src: "SimP2P",
        dst: "SimP2P",
        name: str,
        payload: bytes,
        response_type: Optional[Type],
        future: asyncio.Future,
    ) -> None:
        try:
            # _transit raises SimPeerDeadError itself if dst died while the
            # message was in flight, so the handler lookup can trust dst.alive
            await self._transit(src, dst, len(payload), kind="unary")
            handler = dst.handlers.get(name)
            if handler is None:
                raise P2PHandlerError(f"unknown handler {name!r}")
            context = P2PContext(name, dst.peer_id, src.peer_id)
            request = _parse(payload, handler.request_type)
            try:
                response = await handler.fn(request, context)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.counters["handler_errors"] += 1
                raise P2PHandlerError(f"{name} failed on {dst.name}: {e!r}") from e
            rpayload = _material(_serialize(response))
            rscope = f"link:{dst.peer_id}->{src.peer_id}"
            if _CHAOS.enabled:
                rpayload = await _CHAOS.inject("p2p.unary.recv", payload=rpayload, scope=rscope)
            await self._transit(dst, src, len(rpayload), kind="unary")
            if not future.done():
                future.set_result(_parse(rpayload, response_type))
        except asyncio.CancelledError:
            if not future.done():
                future.cancel()
            raise
        except Exception as e:
            if not future.done():
                future.set_exception(e)

    # ------------------------------------------------------------------ streaming

    async def stream_call(
        self, src: "SimP2P", dst_id: PeerID, name: str, requests, response_type: Optional[Type]
    ) -> AsyncIterator:
        """Async generator yielding parsed response messages (SimP2P delegates
        ``iterate_protobuf_handler`` here)."""
        out_queue: asyncio.Queue = asyncio.Queue()
        dst = self._check_link(src, dst_id)
        serve = self._spawn_task(self._serve_stream(src, dst, name, requests, response_type, out_queue))
        try:
            while True:
                kind, item = await out_queue.get()
                if kind == "msg":
                    yield item
                elif kind == "err":
                    raise item
                else:
                    return
        finally:
            # client closed/abandoned the stream: tear down the server handler
            # (its finally blocks run), like a stream reset on the wire
            serve.cancel()

    async def _serve_stream(
        self,
        src: "SimP2P",
        dst: "SimP2P",
        name: str,
        requests,
        response_type: Optional[Type],
        out_queue: asyncio.Queue,
    ) -> None:
        req_queue: asyncio.Queue = asyncio.Queue()
        feeder = self._spawn_task(self._feed_stream(src, dst, requests, req_queue))
        try:
            handler = dst.handlers.get(name)
            if handler is None:
                raise P2PHandlerError(f"unknown handler {name!r}")
            context = P2PContext(name, dst.peer_id, src.peer_id)

            if handler.stream_input:

                async def _request_iter():
                    while True:
                        kind, item = await req_queue.get()
                        if kind == "msg":
                            yield _parse(item, handler.request_type)
                        elif kind == "err":
                            raise item
                        else:
                            return

                request = _request_iter()
            else:
                kind, item = await req_queue.get()
                if kind == "err":
                    raise item
                if kind != "msg":
                    raise P2PHandlerError(f"{name}: request stream ended before a message")
                request = _parse(item, handler.request_type)

            try:
                if handler.stream_output:
                    result = handler.fn(request, context)
                    if asyncio.iscoroutine(result):
                        result = await result
                    async for response in result:
                        await self._ship_response(src, dst, response, response_type, out_queue)
                else:
                    response = await handler.fn(request, context)
                    await self._ship_response(src, dst, response, response_type, out_queue)
            except asyncio.CancelledError:
                raise
            except ConnectionError:
                raise  # transport loss on the response leg: not a handler fault
            except Exception as e:
                self.counters["handler_errors"] += 1
                raise P2PHandlerError(f"{name} failed on {dst.name}: {e!r}") from e
            out_queue.put_nowait(("end", None))
        except asyncio.CancelledError:
            # external teardown (network.shutdown) mid-stream: a consumer still
            # awaiting the queue must not hang forever — the common case (the
            # client itself closed the stream) has no reader, so this is inert
            out_queue.put_nowait(("err", SimPeerDeadError(f"stream {name} torn down")))
            raise
        except Exception as e:
            out_queue.put_nowait(("err", e))
        finally:
            feeder.cancel()

    async def _ship_response(
        self, src: "SimP2P", dst: "SimP2P", response, response_type: Optional[Type], out_queue
    ) -> None:
        rpayload = _material(_serialize(response))
        if _CHAOS.enabled:  # per streamed response message, dst->src direction
            rpayload = await _CHAOS.inject(
                "p2p.stream.recv", payload=rpayload, scope=f"link:{dst.peer_id}->{src.peer_id}"
            )
        await self._transit(dst, src, len(rpayload), kind="stream")
        out_queue.put_nowait(("msg", _parse(rpayload, response_type)))

    async def _feed_stream(self, src: "SimP2P", dst: "SimP2P", requests, req_queue: asyncio.Queue) -> None:
        scope = f"link:{src.peer_id}->{dst.peer_id}"
        try:
            if hasattr(requests, "__aiter__"):
                async for request in requests:
                    payload = _material(_serialize(request))
                    if _CHAOS.enabled:  # per streamed request message
                        payload = await _CHAOS.inject("p2p.stream.send", payload=payload, scope=scope)
                    await self._transit(src, dst, len(payload), kind="stream")
                    req_queue.put_nowait(("msg", payload))
            else:
                payload = _material(_serialize(requests))
                if _CHAOS.enabled:
                    payload = await _CHAOS.inject("p2p.stream.send", payload=payload, scope=scope)
                await self._transit(src, dst, len(payload), kind="stream")
                req_queue.put_nowait(("msg", payload))
            req_queue.put_nowait(("end", None))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            req_queue.put_nowait(("err", e))


class _Crc32Stream:
    """A tiny deterministic unit-interval stream (cheaper and more portable
    across runs than random.Random for per-link loss draws)."""

    __slots__ = ("_state",)

    def __init__(self, key: str):
        self._state = zlib.crc32(key.encode())

    def next_unit(self) -> float:
        self._state = zlib.crc32(self._state.to_bytes(4, "big"))
        return self._state / 2**32


def _retrieve_exception(future: asyncio.Future) -> None:
    # mark abandoned-call exceptions retrieved (the caller may have timed out)
    if not future.cancelled():
        future.exception()


class SimP2P:
    """The transport face one simulated peer presents to the real stack.

    Duck-types the ``P2P`` attributes/methods the DHT, matchmaking and MoE
    layers touch; everything routes through the owning :class:`SimNetwork`.
    """

    def __init__(self, network: SimNetwork, name: str, region: str, index: int):
        self.network = network
        self.name = name
        self.region = region
        self.alive = True
        digest = hashlib.sha256(f"{network.seed}|peer|{name}".encode()).digest()
        self.peer_id = PeerID(b"\x12\x20" + digest)
        host = f"10.{(index >> 16) & 255}.{(index >> 8) & 255}.{index & 255}"
        self.maddr = Multiaddr(host=host, port=4242, peer_id=self.peer_id)
        self.handlers: Dict[str, _SimHandler] = {}

    # ---------------------------------------------------------------- handlers

    async def add_protobuf_handler(
        self,
        name: str,
        handler: Callable,
        request_type: Optional[Type] = None,
        *,
        stream_input: bool = False,
        stream_output: bool = False,
    ) -> None:
        if name in self.handlers:
            raise P2PHandlerError(f"handler {name!r} is already registered")
        self.handlers[name] = _SimHandler(handler, request_type, stream_input, stream_output)

    async def remove_protobuf_handler(self, name: str) -> None:
        self.handlers.pop(name, None)

    # ---------------------------------------------------------------- calls

    async def call_protobuf_handler(
        self,
        peer_id: PeerID,
        name: str,
        request,
        response_type: Optional[Type] = None,
        *,
        idempotent: bool = False,
    ):
        return await self.network.unary_call(self, peer_id, name, request, response_type)

    def iterate_protobuf_handler(
        self, peer_id: PeerID, name: str, requests, response_type: Optional[Type] = None
    ) -> AsyncIterator:
        return self.network.stream_call(self, peer_id, name, requests, response_type)

    # ---------------------------------------------------------------- identity

    def get_visible_maddrs(self, latest: bool = False) -> List[Multiaddr]:
        return [self.maddr]

    def add_peer_addr(self, peer_id: PeerID, maddr) -> None:
        pass  # the network keeps a global registry; learned addresses are a no-op

    async def connect(self, maddr) -> PeerID:
        peer_id = self.network.resolve_maddr(maddr)
        self.network._check_link(self, peer_id)  # dead/partitioned targets refuse the dial
        return peer_id

    async def list_peers(self) -> List[PeerID]:
        return [pid for pid, p in self.network._peers.items() if p.alive and pid != self.peer_id]

    async def disconnect(self, peer_id: PeerID) -> None:
        pass

    async def shutdown(self) -> None:
        self.alive = False
        _SIM_PEERS.set(self.network.live_peer_count())

    def __repr__(self):
        return f"<SimP2P {self.name} region={self.region} {'up' if self.alive else 'DEAD'}>"
