"""hivemind_tpu.sim — the thousand-peer in-process swarm simulator (ISSUE 12).

Real logic layers (DHT, matchmaking, expert declarations + beam search,
breakers) over an in-process transport driven by a seeded WAN link matrix,
paced by a virtual clock so thousand-peer scenarios run in seconds and replay
deterministically. See docs/simulation.md.
"""

from hivemind_tpu.sim.clock import (
    SimDeadlockError,
    VirtualClockEventLoop,
    install_virtual_time,
    uninstall_virtual_time,
)
from hivemind_tpu.sim.network import (
    LinkMatrix,
    LinkProfile,
    LinkSpec,
    Partition,
    SimLossError,
    SimNetwork,
    SimP2P,
    SimPartitionError,
    SimPeerDeadError,
)
from hivemind_tpu.sim.peer import SimDHT, SimPeer, descriptor_schema_hash
from hivemind_tpu.sim.scenarios import ScenarioResult, run_scenario, scenario_names

__all__ = [
    "LinkMatrix",
    "LinkProfile",
    "LinkSpec",
    "Partition",
    "ScenarioResult",
    "SimDHT",
    "SimDeadlockError",
    "SimLossError",
    "SimNetwork",
    "SimP2P",
    "SimPartitionError",
    "SimPeer",
    "SimPeerDeadError",
    "VirtualClockEventLoop",
    "descriptor_schema_hash",
    "install_virtual_time",
    "run_scenario",
    "scenario_names",
    "uninstall_virtual_time",
]
