"""One lightweight simulated peer: real logic layers, descriptor-stubbed compute.

A :class:`SimPeer` owns a :class:`~hivemind_tpu.sim.network.SimP2P` transport
face and runs the **real** :class:`~hivemind_tpu.dht.node.DHTNode` (routing,
storage, validation, blacklist breakers) on it. Optional layers bolt on the
real implementations too: matchmaking runs the actual
:class:`~hivemind_tpu.averaging.matchmaking.Matchmaking` +
:class:`~hivemind_tpu.averaging.key_manager.GroupKeyManager` state machines
(the schema hash is computed from :class:`TensorDescriptor` placeholders the
same way the averager computes it from live tensors — no arrays are ever
allocated), and expert declarations ride the real
``moe.server.dht_handler.declare_experts`` prefix encoding so
:class:`~hivemind_tpu.moe.client.beam_search.MoEBeamSearcher` searches real
records. What never runs in the sim: tensor math, all-reduce data planes,
expert forward/backward — compute stays a descriptor.
"""

from __future__ import annotations

import asyncio
import contextlib
import hashlib
from typing import AsyncIterator, Optional, Sequence, Tuple

from hivemind_tpu.averaging.key_manager import GroupKeyManager
from hivemind_tpu.averaging.matchmaking import Matchmaking
from hivemind_tpu.dht.node import DHTNode
from hivemind_tpu.dht.routing import DHTID
from hivemind_tpu.p2p import P2PContext, PeerID
from hivemind_tpu.p2p.servicer import ServicerBase
from hivemind_tpu.proto import averaging_pb2
from hivemind_tpu.sim.network import SimNetwork, SimP2P
from hivemind_tpu.telemetry.tracing import trace
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.tensor_descr import TensorDescriptor

logger = get_logger(__name__)

DEFAULT_SIM_DESCRIPTORS = (TensorDescriptor(shape=(1024, 1024)), TensorDescriptor(shape=(1024,)))


def descriptor_schema_hash(descriptors: Sequence[TensorDescriptor]) -> str:
    """The same schema fingerprint DecentralizedAverager computes from live
    tensors (averager.py ``_compute_schema_hash``), derived from descriptors
    alone — sim peers with matching descriptors would interoperate with real
    averagers of the same schema."""
    schema = [[list(d.shape), str(d.dtype)] for d in descriptors]
    payload = MSGPackSerializer.dumps([schema, "NoCompression", "v1"])
    return hashlib.sha256(payload).hexdigest()[:32]


class SimDHT:
    """The thin slice of the :class:`~hivemind_tpu.dht.dht.DHT` facade that
    GroupKeyManager / declare_experts / beam search touch. Everything already
    runs on the sim loop, so ``run_coroutine`` schedules a task instead of
    bridging threads — callers inside the sim must pass ``return_future=True``
    (``wait=False`` at the declare_experts level) and await it."""

    def __init__(self, node: DHTNode):
        self.node = node

    @property
    def peer_id(self) -> PeerID:
        return self.node.peer_id

    def run_coroutine(self, coro, return_future: bool = False):
        task = asyncio.get_event_loop().create_task(coro(self, self.node))
        if return_future:
            return task
        raise RuntimeError(
            "SimDHT.run_coroutine cannot block inside the sim loop; "
            "call with return_future=True (declare_experts/get_experts: wait=False) and await the result"
        )

    async def replicate_p2p(self):
        return self.node.p2p


class _SimAveragerService(ServicerBase):
    """Bridges rpc_join_group onto the peer's Matchmaking instance — the same
    delegation DecentralizedAverager does, minus the data plane."""

    def __init__(self, matchmaking: Matchmaking):
        self._matchmaking = matchmaking

    async def rpc_join_group(
        self, request: averaging_pb2.JoinRequest, context: P2PContext
    ) -> AsyncIterator[averaging_pb2.MessageFromLeader]:
        async for message in self._matchmaking.rpc_join_group(request, context):
            yield message


class SimPeer:
    """A DHT participant in the simulated swarm; create with ``await SimPeer.create(...)``."""

    def __init__(self):
        raise RuntimeError("use `await SimPeer.create(...)`")

    @classmethod
    async def create(
        cls,
        network: SimNetwork,
        name: str,
        region: str = "default",
        *,
        bootstrap: Sequence[str] = (),
        **dht_kwargs,
    ) -> "SimPeer":
        self = object.__new__(cls)
        self.network = network
        self.name = name
        self.region = region
        self.p2p: SimP2P = network.spawn(name, region)
        node_id = DHTID.generate(source=f"{network.seed}|node|{name}".encode())
        self.node = await DHTNode.create(
            p2p=self.p2p,
            node_id=node_id,
            initial_peers=list(bootstrap),
            **dht_kwargs,
        )
        self.dht = SimDHT(self.node)
        self.matchmaking: Optional[Matchmaking] = None
        self._service: Optional[_SimAveragerService] = None
        return self

    @property
    def peer_id(self) -> PeerID:
        return self.p2p.peer_id

    def bootstrap_maddrs(self) -> Tuple[str, ...]:
        return (str(self.p2p.maddr),)

    # ------------------------------------------------------------------ matchmaking

    async def enable_matchmaking(
        self,
        prefix: str = "sim_averager",
        *,
        target_group_size: Optional[int] = 4,
        min_group_size: int = 2,
        min_matchmaking_time: float = 5.0,
        request_timeout: float = 3.0,
        initial_group_bits: str = "",
        descriptors: Sequence[TensorDescriptor] = DEFAULT_SIM_DESCRIPTORS,
    ) -> None:
        """Attach the real matchmaking state machine (leader + follower sides)
        over descriptor-stubbed tensors."""
        key_manager = GroupKeyManager(
            self.dht, prefix, initial_group_bits=initial_group_bits, target_group_size=target_group_size
        )
        self.matchmaking = Matchmaking(
            self.p2p,
            key_manager,
            get_stub=lambda peer_id: _SimAveragerService.get_stub(self.p2p, peer_id),
            schema_hash=descriptor_schema_hash(descriptors),
            target_group_size=target_group_size,
            min_group_size=min_group_size,
            min_matchmaking_time=min_matchmaking_time,
            request_timeout=request_timeout,
        )
        self._service = _SimAveragerService(self.matchmaking)
        await self._service.add_p2p_handlers(self.p2p)

    async def look_for_group(self, *, timeout: Optional[float] = None):
        assert self.matchmaking is not None, "call enable_matchmaking() first"
        return await self.matchmaking.look_for_group(data_for_gather=b"", timeout=timeout)

    async def simulate_allreduce_round(
        self,
        group,
        *,
        descriptors: Sequence[TensorDescriptor] = DEFAULT_SIM_DESCRIPTORS,
        reduce_throughput: float = 2e9,
    ) -> None:
        """Synthesize one butterfly all-reduce round as REAL telemetry spans
        (ISSUE 17): an ``allreduce.round`` span wrapping ``local_reduce`` plus
        one ``peer_exchange`` per partner, with durations derived from the
        seeded :class:`~hivemind_tpu.sim.network.LinkMatrix` — so the round
        ledger and the black-box spool see the same span shapes a live
        averager emits, in virtual time, bit-identically per seed. No tensor
        math runs: the sleeps ARE the data plane here.
        """
        peer_ids = list(group.peer_ids)
        # rank by canonical member order, NOT leader order: the leader shuffles
        # with an os.urandom group id (real protocol, deliberately unseeded),
        # and same-seed sim runs must spool bit-identical ledger records
        canonical = sorted(peer_ids, key=str)
        rank = canonical.index(self.peer_id) if self.peer_id in canonical else -1
        total_bytes = sum(d.nbytes for d in descriptors)
        # butterfly all-reduce: each peer owns 1/group_size of the vector and
        # exchanges its part with every partner
        part_bytes = total_bytes / max(1, len(peer_ids))

        async def _exchange(remote_id: PeerID) -> None:
            remote = self.network.get_peer(remote_id)
            remote_name = remote.name if remote is not None else str(remote_id)
            remote_region = remote.region if remote is not None else self.region
            spec = self.network.links.spec(self.name, remote_name, self.region, remote_region)
            with trace("allreduce.peer_exchange", peer=self.name, remote=remote_name):
                await asyncio.sleep(spec.delay + part_bytes / spec.bandwidth)

        with trace(
            "allreduce.round", peer=self.name, group_size=len(peer_ids), rank=rank
        ):
            with trace("allreduce.local_reduce", peer=self.name):
                await asyncio.sleep(total_bytes / reduce_throughput)
            remotes = [pid for pid in peer_ids if pid != self.peer_id]
            # sequential, not gathered: the task-interleave order of concurrent
            # sleeps would depend on sibling peers sharing the loop, and the
            # ledger's late-exchange path is already exercised by live tests.
            # Virtual time makes the sequential walk free.
            for remote_id in remotes:
                await _exchange(remote_id)

    # ------------------------------------------------------------------ lifecycle

    def crash(self) -> None:
        """Die without cleanup: declarations dangle, peers discover the corpse
        through failed RPCs and their blacklists — exactly like a killed
        process. Background tasks are cancelled (a dead process runs nothing)."""
        self.network.kill(self.p2p)
        if self.node._refresh_task is not None:
            self.node._refresh_task.cancel()
        for task in list(self.node.protocol._handoff_tasks):
            task.cancel()

    async def shutdown(self) -> None:
        with contextlib.suppress(Exception):
            await self.node.shutdown()
        await self.p2p.shutdown()

    def __repr__(self):
        return f"<SimPeer {self.name} region={self.region}>"
