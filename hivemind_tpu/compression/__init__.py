from hivemind_tpu.compression.adaptive import (
    PerTensorCompression,
    RoleAdaptiveCompression,
    SizeAdaptiveCompression,
)
from hivemind_tpu.compression.base import (
    CompressionBase,
    CompressionInfo,
    CompressionType,
    NoCompression,
    TensorRole,
)
from hivemind_tpu.compression.floating import Float16Compression, ScaledFloat16Compression
from hivemind_tpu.compression.quantization import (
    BlockwiseQuantization,
    Quantile8BitQuantization,
    Uniform8BitQuantization,
)
from hivemind_tpu.compression.serialization import (
    codec_name,
    deserialize_tensor,
    deserialize_tensor_stream,
    deserialize_to_jax,
    expert_request_parts,
    expert_response_parts,
    get_codec,
    resolve_activation_codec,
    serialize_tensor,
    split_response_for_wire,
    split_tensor_for_streaming,
)
