from hivemind_tpu.compression.adaptive import (
    PerTensorCompression,
    RoleAdaptiveCompression,
    SizeAdaptiveCompression,
)
from hivemind_tpu.compression.base import (
    CompressionBase,
    CompressionInfo,
    CompressionType,
    NoCompression,
    TensorRole,
)
from hivemind_tpu.compression.floating import Float16Compression, ScaledFloat16Compression
from hivemind_tpu.compression.quantization import (
    BlockwiseQuantization,
    Quantile8BitQuantization,
    Uniform8BitQuantization,
)
from hivemind_tpu.compression.serialization import (
    deserialize_tensor,
    deserialize_tensor_stream,
    deserialize_to_jax,
    get_codec,
    serialize_tensor,
    split_tensor_for_streaming,
)
