"""Serialization facade: one codec instance per CompressionType enum value
(capability parity: reference hivemind/compression/serialization.py:13-68)."""

from __future__ import annotations

from typing import Any, AsyncIterator, List, Optional

import numpy as np

from hivemind_tpu.compression.base import (
    CompressionBase,
    CompressionInfo,
    CompressionType,
    NoCompression,
)
from hivemind_tpu.compression.floating import Float16Compression, ScaledFloat16Compression
from hivemind_tpu.compression.quantization import (
    BlockwiseQuantization,
    Quantile8BitQuantization,
    Uniform8BitQuantization,
)
from hivemind_tpu.proto import runtime_pb2

_CODECS = {
    CompressionType.NONE: NoCompression(),
    CompressionType.FLOAT16: Float16Compression(),
    CompressionType.MEANSTD_16BIT: ScaledFloat16Compression(),
    CompressionType.UNIFORM_8BIT: Uniform8BitQuantization(),
    CompressionType.QUANTILE_8BIT: Quantile8BitQuantization(),
    CompressionType.BLOCKWISE_8BIT: BlockwiseQuantization(),
}

for _value in runtime_pb2.CompressionType.values():
    assert _value in _CODECS, f"no codec registered for CompressionType={_value}"


def get_codec(compression_type: int) -> CompressionBase:
    return _CODECS[compression_type]


def serialize_tensor(
    array: Any,
    compression: CompressionBase | int = CompressionType.NONE,
    info: Optional[CompressionInfo] = None,
    allow_inplace: bool = False,
) -> runtime_pb2.Tensor:
    if isinstance(compression, int):
        compression = _CODECS[compression]
    return compression.compress(array, info, allow_inplace)


def deserialize_tensor(serialized: runtime_pb2.Tensor) -> np.ndarray:
    return _CODECS[serialized.compression].extract(serialized)


def deserialize_to_jax(serialized: runtime_pb2.Tensor):
    import jax.numpy as jnp

    return jnp.asarray(deserialize_tensor(serialized))


def _clone_tensor_metadata(source: runtime_pb2.Tensor) -> runtime_pb2.Tensor:
    """A Tensor message carrying every field of ``source`` EXCEPT its (possibly
    multi-MiB) payload — chunking helpers must never duplicate the buffer just to
    replace it (ISSUE 6 satellite: the old CopyFrom+overwrite did exactly that)."""
    return runtime_pb2.Tensor(
        size=source.size,
        dtype=source.dtype,
        requires_grad=source.requires_grad,
        compression=source.compression,
    )


async def deserialize_tensor_stream(stream: AsyncIterator[List[runtime_pb2.Tensor]]) -> List[np.ndarray]:
    """Reassemble tensors from a stream of chunked parts: each tensor arrives as its
    first message (with ``chunks`` = total count) followed by buffer-only continuation
    messages (reference serialization.py deserialize_tensor_stream)."""
    tensors: List[np.ndarray] = []
    parts: List[runtime_pb2.Tensor] = []
    async for chunk_batch in stream:
        for chunk in chunk_batch:
            parts.append(chunk)
            total = parts[0].chunks or 1
            if len(parts) == total:
                combined = _clone_tensor_metadata(parts[0])
                combined.buffer = b"".join(p.buffer for p in parts)
                tensors.append(deserialize_tensor(combined))
                parts = []
    if parts:
        raise ValueError(f"stream ended mid-tensor: got {len(parts)}/{parts[0].chunks} chunks")
    return tensors


def split_tensor_for_streaming(serialized: runtime_pb2.Tensor, chunk_size_bytes: int) -> List[runtime_pb2.Tensor]:
    """Split one serialized tensor into wire-sized chunk messages (the inverse of
    deserialize_tensor_stream's reassembly)."""
    from hivemind_tpu.utils.streaming import split_for_streaming

    buffers = list(split_for_streaming(serialized.buffer, chunk_size_bytes))
    first = _clone_tensor_metadata(serialized)
    first.buffer = buffers[0]
    first.chunks = len(buffers)
    out = [first]
    for extra in buffers[1:]:
        out.append(runtime_pb2.Tensor(buffer=extra))
    return out
