"""Serialization facade: one codec instance per CompressionType enum value
(capability parity: reference hivemind/compression/serialization.py:13-68), plus
the serving-path wire splicers (ISSUE 10): hand-encoded ``ExpertRequest`` /
``ExpertResponse`` frames whose multi-MB tensor buffers ride as separate
scatter-gather buffers (:class:`~hivemind_tpu.utils.streaming.WireParts`)
instead of being copied into one ``SerializeToString`` blob. The encodings are
byte-identical to protobuf's own (asserted in tests/test_serving_compression.py),
so the receive side parses them with the stock generated classes."""

from __future__ import annotations

from typing import Any, AsyncIterator, List, Optional, Sequence

import numpy as np

from hivemind_tpu.compression.base import (
    CompressionBase,
    CompressionInfo,
    CompressionType,
    NoCompression,
)
from hivemind_tpu.compression.floating import Float16Compression, ScaledFloat16Compression
from hivemind_tpu.compression.quantization import (
    BlockwiseQuantization,
    Quantile8BitQuantization,
    Uniform8BitQuantization,
)
from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.utils.streaming import WireParts

_CODECS = {
    CompressionType.NONE: NoCompression(),
    CompressionType.FLOAT16: Float16Compression(),
    CompressionType.MEANSTD_16BIT: ScaledFloat16Compression(),
    CompressionType.UNIFORM_8BIT: Uniform8BitQuantization(),
    CompressionType.QUANTILE_8BIT: Quantile8BitQuantization(),
    CompressionType.BLOCKWISE_8BIT: BlockwiseQuantization(),
}

for _value in runtime_pb2.CompressionType.values():
    assert _value in _CODECS, f"no codec registered for CompressionType={_value}"


def get_codec(compression_type: int) -> CompressionBase:
    return _CODECS[compression_type]


def resolve_activation_codec(name: Optional[str]) -> CompressionBase:
    """The serving wire dtype by knob value ("none", "float16", "meanstd_16bit",
    … — any CompressionType name, case-insensitive; None/"" = NONE)."""
    if not name:
        return _CODECS[CompressionType.NONE]
    try:
        # Value() rejects anything that is not an enum member — a plain getattr
        # would let remote-supplied names hit real enum-wrapper attributes and
        # escape as KeyError past callers' ValueError guards
        value = runtime_pb2.CompressionType.Value(str(name).upper())
    except ValueError:
        valid = ", ".join(k.lower() for k in runtime_pb2.CompressionType.keys())
        raise ValueError(f"unknown activation compression {name!r}; expected one of: {valid}") from None
    return _CODECS[value]


def codec_name(codec: CompressionBase) -> str:
    """Canonical lowercase knob value for a codec ("float16", "none", …)."""
    return runtime_pb2.CompressionType.Name(codec.compression_type).lower()


def serialize_tensor(
    array: Any,
    compression: CompressionBase | int = CompressionType.NONE,
    info: Optional[CompressionInfo] = None,
    allow_inplace: bool = False,
) -> runtime_pb2.Tensor:
    if isinstance(compression, int):
        compression = _CODECS[compression]
    return compression.compress(array, info, allow_inplace)


def deserialize_tensor(serialized: runtime_pb2.Tensor) -> np.ndarray:
    return _CODECS[serialized.compression].extract(serialized)


def deserialize_to_jax(serialized: runtime_pb2.Tensor):
    import jax.numpy as jnp

    return jnp.asarray(deserialize_tensor(serialized))


def _clone_tensor_metadata(source: runtime_pb2.Tensor) -> runtime_pb2.Tensor:
    """A Tensor message carrying every field of ``source`` EXCEPT its (possibly
    multi-MiB) payload — chunking helpers must never duplicate the buffer just to
    replace it (ISSUE 6 satellite: the old CopyFrom+overwrite did exactly that)."""
    return runtime_pb2.Tensor(
        size=source.size,
        dtype=source.dtype,
        requires_grad=source.requires_grad,
        compression=source.compression,
    )


async def deserialize_tensor_stream(
    stream: AsyncIterator[List[runtime_pb2.Tensor]], off_loop: bool = False
) -> List[np.ndarray]:
    """Reassemble tensors from a stream of chunked parts: each tensor arrives as its
    first message (with ``chunks`` = total count) followed by buffer-only continuation
    messages (reference serialization.py deserialize_tensor_stream).

    ``off_loop=True`` runs each completed tensor's join+decode in the shared
    executor — server handlers use it so a multi-MB prefill chunk cannot stall
    the event loop (ISSUE 10); chunks still decode one tensor at a time, as
    they complete."""
    from hivemind_tpu.utils.asyncio_utils import run_in_executor

    def _combine(chunk_parts: List[runtime_pb2.Tensor]) -> np.ndarray:
        combined = _clone_tensor_metadata(chunk_parts[0])
        combined.buffer = b"".join(p.buffer for p in chunk_parts)
        return deserialize_tensor(combined)

    tensors: List[np.ndarray] = []
    parts: List[runtime_pb2.Tensor] = []
    async for chunk_batch in stream:
        for chunk in chunk_batch:
            parts.append(chunk)
            total = parts[0].chunks or 1
            if len(parts) == total:
                tensors.append(await run_in_executor(_combine, parts) if off_loop else _combine(parts))
                parts = []
    if parts:
        raise ValueError(f"stream ended mid-tensor: got {len(parts)}/{parts[0].chunks} chunks")
    return tensors


def split_tensor_for_streaming(serialized: runtime_pb2.Tensor, chunk_size_bytes: int) -> List[runtime_pb2.Tensor]:
    """Split one serialized tensor into wire-sized chunk messages (the inverse of
    deserialize_tensor_stream's reassembly)."""
    from hivemind_tpu.utils.streaming import split_for_streaming

    buffers = list(split_for_streaming(serialized.buffer, chunk_size_bytes))
    first = _clone_tensor_metadata(serialized)
    first.buffer = buffers[0]
    first.chunks = len(buffers)
    out = [first]
    for extra in buffers[1:]:
        out.append(runtime_pb2.Tensor(buffer=extra))
    return out


# ------------------------------------------------------------------ wire splicers
#
# Hand-rolled protobuf framing for the serving hot path: concatenating encoded
# fields in field-number order is exactly what SerializeToString emits, so a
# Tensor can be framed as [buffer-field header][the buffer object itself]
# [metadata fields] with the (possibly multi-MB) buffer riding as ITS OWN
# scatter-gather part — never copied into a materialized message. Field
# numbers/tags below mirror proto/runtime.proto; byte-identity with protobuf's
# own encoder is pinned by tests.

_TENSOR_BUFFER_TAG = b"\x0a"  # Tensor.buffer = 1, wire type 2
_REQUEST_UID_TAG = b"\x0a"  # ExpertRequest.uid = 1
_REQUEST_TENSOR_TAG = b"\x12"  # ExpertRequest.tensors = 2
_REQUEST_METADATA_TAG = b"\x1a"  # ExpertRequest.metadata = 3
_RESPONSE_TENSOR_TAG = b"\x0a"  # ExpertResponse.tensors = 1
_RESPONSE_METADATA_TAG = b"\x12"  # ExpertResponse.metadata = 2


def _varint(value: int) -> bytes:
    out = bytearray()
    while True:
        bits = value & 0x7F
        value >>= 7
        if value:
            out.append(bits | 0x80)
        else:
            out.append(bits)
            return bytes(out)


def _tensor_field_parts(serialized: runtime_pb2.Tensor, field_tag: bytes) -> List[bytes]:
    """Encode one Tensor as a length-delimited field of an outer message,
    splicing ``serialized.buffer`` in as a separate part (zero-copy)."""
    buffer = serialized.buffer
    meta = _clone_tensor_metadata(serialized)
    meta.chunks = serialized.chunks
    meta_bytes = meta.SerializeToString()
    if buffer:
        # protobuf emits fields in number order: buffer (field 1) precedes the
        # metadata fields (2..6), keeping the frame byte-identical to protobuf's
        inner = [_TENSOR_BUFFER_TAG + _varint(len(buffer)), buffer, meta_bytes]
    else:
        inner = [meta_bytes]
    inner_len = sum(len(part) for part in inner)
    return [field_tag + _varint(inner_len), *inner]


def expert_request_parts(
    uid: str, tensors: Sequence[runtime_pb2.Tensor], metadata: bytes = b""
) -> WireParts:
    """``ExpertRequest(uid=, tensors=, metadata=)`` as scatter-gather parts."""
    parts: List[Any] = []
    if uid:
        uid_bytes = uid.encode("utf-8")
        parts.append(_REQUEST_UID_TAG + _varint(len(uid_bytes)) + uid_bytes)
    for tensor in tensors:
        parts.extend(_tensor_field_parts(tensor, _REQUEST_TENSOR_TAG))
    if metadata:
        parts.append(_REQUEST_METADATA_TAG + _varint(len(metadata)) + metadata)
    return WireParts(*parts)


def expert_response_parts(
    tensors: Sequence[runtime_pb2.Tensor], metadata: bytes = b""
) -> WireParts:
    """``ExpertResponse(tensors=, metadata=)`` as scatter-gather parts."""
    parts: List[Any] = []
    for tensor in tensors:
        parts.extend(_tensor_field_parts(tensor, _RESPONSE_TENSOR_TAG))
    if metadata:
        parts.append(_RESPONSE_METADATA_TAG + _varint(len(metadata)) + metadata)
    return WireParts(*parts)


def split_response_for_wire(
    serialized: runtime_pb2.Tensor, chunk_size_bytes: int
) -> List[WireParts]:
    """One serialized tensor as a list of ``ExpertResponse`` stream-chunk frames
    (the wire-parts analog of ``split_tensor_for_streaming``): the buffer is
    sliced as zero-copy memoryviews, so a multi-hundred-MB streamed response is
    never re-materialized chunk by chunk."""
    view = memoryview(serialized.buffer)
    total_chunks = max(1, -(-len(view) // chunk_size_bytes)) if len(view) else 1
    first = _clone_tensor_metadata(serialized)
    first.chunks = total_chunks
    meta_bytes = first.SerializeToString()
    out: List[WireParts] = []
    for index in range(total_chunks):
        chunk = view[index * chunk_size_bytes : (index + 1) * chunk_size_bytes]
        inner: List[Any] = []
        if len(chunk):
            inner.extend([_TENSOR_BUFFER_TAG + _varint(len(chunk)), chunk])
        if index == 0:
            inner.append(meta_bytes)
        inner_len = sum(len(part) for part in inner)
        out.append(WireParts(_RESPONSE_TENSOR_TAG + _varint(inner_len), *inner))
    return out
