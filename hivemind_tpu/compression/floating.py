"""Float16 codecs (capability parity: reference hivemind/compression/floating.py)."""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

from hivemind_tpu.compression.base import (
    CompressionBase,
    CompressionInfo,
    CompressionType,
    as_numpy,
)
from hivemind_tpu.proto import runtime_pb2

FP16_MAX = 65504.0


class Float16Compression(CompressionBase):
    """Clamp to the fp16 range and cast (reference floating.py:10-40)."""

    compression_type = CompressionType.FLOAT16
    is_lossy = True

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        array = as_numpy(array)
        original_dtype = "bfloat16" if str(array.dtype) == "bfloat16" else array.dtype.name
        array32 = array.astype(np.float32, copy=False)
        # a dtype conversion already made array32 private; otherwise in-place needs
        # the caller's explicit permission (bit-identical either way — same values)
        private = True if array32 is not array else allow_inplace
        if private and array32.flags.writeable:
            clipped32 = np.clip(array32, -FP16_MAX, FP16_MAX, out=array32)
        else:
            clipped32 = np.clip(array32, -FP16_MAX, FP16_MAX)
        return runtime_pb2.Tensor(
            buffer=clipped32.astype(np.float16).tobytes(),
            size=array.shape,
            dtype=original_dtype,
            compression=self.compression_type,
        )

    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        from hivemind_tpu.utils.tensor_descr import numpy_dtype

        half = np.frombuffer(serialized.buffer, dtype=np.float16)
        return half.astype(numpy_dtype(serialized.dtype or "float32")).reshape(tuple(serialized.size))

    def estimate_compression_ratio(self, info: CompressionInfo) -> float:
        return 16.0 / (8 * (info.descriptor.itemsize if info.descriptor else 4))


class ScaledFloat16Compression(Float16Compression):
    """Normalize per last axis by mean/std, cast to fp16, and ship the fp32 stats
    alongside (reference floating.py:43-91, MEANSTD_16BIT)."""

    compression_type = CompressionType.MEANSTD_16BIT

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        array = as_numpy(array)
        original_dtype = "bfloat16" if str(array.dtype) == "bfloat16" else array.dtype.name
        array32 = array.astype(np.float32, copy=False)
        if array32.ndim == 0:
            array32 = array32.reshape(1)
            means = np.zeros(1, np.float32)
            stds = np.ones(1, np.float32)
            normalized = array32
        else:
            means = array32.mean(axis=-1, keepdims=True, dtype=np.float32)
            stds = array32.std(axis=-1, keepdims=True, dtype=np.float32) + 1e-6
            private = True if array32 is not array else allow_inplace
            if private and array32.flags.writeable:
                np.subtract(array32, means, out=array32)
                np.divide(array32, stds, out=array32)
                normalized = array32
            else:
                normalized = (array32 - means) / stds
        half = np.clip(normalized, -FP16_MAX, FP16_MAX).astype(np.float16)
        buffer = half.tobytes() + means.astype(np.float32).tobytes() + stds.astype(np.float32).tobytes()
        return runtime_pb2.Tensor(
            buffer=buffer,
            size=array.shape,
            dtype=original_dtype,
            compression=self.compression_type,
        )

    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        from hivemind_tpu.utils.tensor_descr import numpy_dtype

        shape = tuple(serialized.size)
        numel = int(np.prod(shape)) if shape else 1
        stats_shape = (*shape[:-1], 1) if shape else (1,)
        stats_count = int(np.prod(stats_shape))
        half_bytes = numel * 2
        half = np.frombuffer(serialized.buffer, dtype=np.float16, count=numel)
        means = np.frombuffer(serialized.buffer, dtype=np.float32, count=stats_count, offset=half_bytes)
        stds = np.frombuffer(
            serialized.buffer, dtype=np.float32, count=stats_count, offset=half_bytes + stats_count * 4
        )
        restored = half.astype(np.float32).reshape(shape or (1,))
        restored = restored * stds.reshape(stats_shape) + means.reshape(stats_shape)
        out = restored.astype(numpy_dtype(serialized.dtype or "float32"))
        return out.reshape(shape) if shape else out.reshape(())
