"""Compression framework (capability parity: reference hivemind/compression/base.py).

Codecs turn arrays (numpy or jax; bfloat16 is first-class) into ``runtime_pb2.Tensor``
messages and back. Unlike the reference, there is no legacy-bfloat16 env switch: TPU
tensors are bf16-native and serialize as raw bf16 bytes.
"""

from __future__ import annotations

import dataclasses
from abc import ABC, abstractmethod
from enum import Enum
from typing import Any, Optional

import numpy as np

from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.utils.tensor_descr import TensorDescriptor, numpy_dtype

CompressionType = runtime_pb2.CompressionType


class TensorRole(Enum):
    ACTIVATION = "activation"
    PARAMETER = "parameter"
    GRADIENT = "gradient"
    OPTIMIZER = "optimizer"
    UNSPECIFIED = "unspecified"


@dataclasses.dataclass(frozen=True)
class CompressionInfo:
    """Metadata a codec may use to decide how to compress
    (reference compression/base.py:30-45)."""

    key: Any = None
    descriptor: Optional[TensorDescriptor] = None
    role: TensorRole = TensorRole.UNSPECIFIED
    part_index: int = 0
    part_size: Optional[int] = None

    @classmethod
    def from_array(cls, array: Any, key: Any = None, role: TensorRole = TensorRole.UNSPECIFIED) -> "CompressionInfo":
        return cls(key=key, descriptor=TensorDescriptor.from_array(array), role=role)


def as_numpy(array: Any) -> np.ndarray:
    """View any array (numpy / jax, incl. bfloat16) as numpy without copying when
    possible. jax device arrays are fetched to host."""
    if isinstance(array, np.ndarray):
        return array
    return np.asarray(array)


def _dtype_name(array: np.ndarray) -> str:
    return "bfloat16" if str(array.dtype) == "bfloat16" else array.dtype.name


class CompressionBase(ABC):
    compression_type: int = CompressionType.NONE
    # True when extract(compress(x)) != x in general — the averaging wire layer
    # uses this to decide whether error-feedback residuals apply (ISSUE 11)
    is_lossy: bool = False

    @abstractmethod
    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        """Encode an array into a protobuf Tensor."""

    @abstractmethod
    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        """Decode a protobuf Tensor back into a numpy array."""

    def estimate_compression_ratio(self, info: CompressionInfo) -> float:
        """compressed size / original size (approximate)."""
        return 1.0

    def __repr__(self):
        return f"{type(self).__name__}()"


class NoCompression(CompressionBase):
    """Raw little-endian bytes; bfloat16 serialized natively
    (reference base.py:79-122 upcasts bf16 unless a legacy env is set — deviation noted)."""

    compression_type = CompressionType.NONE

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        array = as_numpy(array)
        return runtime_pb2.Tensor(
            buffer=array.tobytes(),
            size=array.shape,
            dtype=_dtype_name(array),
            requires_grad=bool(getattr(array, "requires_grad", False)),
            compression=self.compression_type,
        )

    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        dtype = numpy_dtype(serialized.dtype)
        array = np.frombuffer(serialized.buffer, dtype=dtype)
        return array.reshape(tuple(serialized.size)).copy()
