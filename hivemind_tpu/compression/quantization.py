"""8-bit quantization codecs (capability parity: reference
hivemind/compression/quantization.py).

ISSUE 11 rework: these codecs are now on the averaging WIRE hot path (the
butterfly all-reduce's reduce-scatter and all-gather legs run them per part in
the shared executor), so the compress/extract paths are pure numpy — no jit
dispatch, no host↔device hop — and copy-discipline matches the Float16 path
from ISSUE 6/10 (this file is covered by hivemind-lint's ``hotpath-copies`` rule):

- code assignment runs CHUNKED through one small reusable float scratch, so
  neither ``compress`` path materializes an input-sized temporary — the codecs
  accept ``allow_inplace`` for API parity but never need to mutate the input
  (a strictly stronger guarantee than in-place staging);
- wire buffers are assembled with ONE allocation + slice writes (no bytes
  concatenation of multi-MB payloads);
- the jitted jax equivalents remain in :mod:`hivemind_tpu.ops.quantization` /
  ``ops.pallas_quantization`` for callers that want the math on-device; the
  numpy and jax paths share formulas (6σ uniform buckets with bucket-mean
  codebooks, per-4096-block absmax int8) but are not bit-identical to each
  other — a codec instance is deterministic within a process, which is what
  the wire-equivalence suite pins.
"""

from __future__ import annotations

import struct
from typing import Any, Optional, Tuple

import numpy as np

from hivemind_tpu.compression.base import (
    CompressionBase,
    CompressionInfo,
    CompressionType,
    as_numpy,
)
from hivemind_tpu.ops.quantization import (
    BLOCKWISE_BLOCK_SIZE,
    UNIFORM_NUM_BUCKETS,
    UNIFORM_RANGE_IN_SIGMAS,
    hash_sample_indices,
    pad_to_block,
    quantile_quantize,
)
from hivemind_tpu.proto import runtime_pb2

# rint/clip/cast staging chunk: big enough that the python loop is noise
# (≤ a handful of iterations per 2 MiB part), small enough to stay cache-warm
_CODE_CHUNK = 1 << 18

# statistics (mean/std + bucket-mean codebook) come from a bounded
# layout-independent sample past this size — 512 samples per bucket keeps the
# bucket-mean standard error far inside one bucket width while the only
# full-array work left is code assignment (the weighted bincount the codebook
# used to need is ~8 ms per 2 MiB part — the dominant codec cost). Sampling is
# the same deterministic multiplicative hash the quantile codec uses, so wire
# bytes stay reproducible.
_STATS_SAMPLE = 1 << 17


def _stats_indices(size: int) -> Optional[np.ndarray]:
    """Hash-sample indices for codebook statistics, or None (use everything)."""
    if size <= _STATS_SAMPLE:
        return None
    return hash_sample_indices(size, _STATS_SAMPLE)


def _assemble_wire(header_struct: Tuple[str, Tuple[int, ...]], *arrays: np.ndarray) -> bytes:
    """One wire buffer from a packed header + raw array payloads with a single
    allocation and slice writes — the lint-enforced alternative to chaining
    ``struct.pack(...) + a.tobytes() + b.tobytes()`` (which copies the bulk
    payload once per ``+``)."""
    fmt, values = header_struct
    header_size = struct.calcsize(fmt)
    total = header_size + sum(a.nbytes for a in arrays)
    wire = np.empty(total, np.uint8)
    struct.pack_into(fmt, wire, 0, *values)
    offset = header_size
    for array in arrays:
        wire[offset : offset + array.nbytes] = np.ascontiguousarray(array).view(np.uint8).reshape(-1)
        offset += array.nbytes
    return wire.tobytes()


def _uniform_quantize_np(flat32: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Uniform 8-bit quantization over [mean − 6σ, mean + 6σ] with a bucket-mean
    codebook (same formula as ``ops.quantization.uniform_quantize``), computed
    without any input-sized temporary: codes are staged chunk-by-chunk through
    one small scratch, then a single ``bincount`` over the untouched input
    builds the codebook. The input is never mutated."""
    if flat32.size == 0:
        return np.zeros(0, np.uint8), np.zeros(UNIFORM_NUM_BUCKETS, np.float32)
    indices = _stats_indices(flat32.size)
    sample = flat32 if indices is None else flat32[indices]
    mean = float(np.mean(sample))
    std = float(np.std(sample)) + 1e-11
    lo = mean - UNIFORM_RANGE_IN_SIGMAS * std
    hi = mean + UNIFORM_RANGE_IN_SIGMAS * std
    scale = (UNIFORM_NUM_BUCKETS - 1) / (hi - lo)
    codes = np.empty(flat32.size, np.uint8)
    scratch = np.empty(min(flat32.size, _CODE_CHUNK), np.float32)
    for start in range(0, flat32.size, _CODE_CHUNK):
        view = flat32[start : start + _CODE_CHUNK]
        staged = scratch[: view.size]
        np.subtract(view, np.float32(lo), out=staged)
        np.multiply(staged, np.float32(scale), out=staged)
        np.rint(staged, out=staged)
        np.clip(staged, 0, UNIFORM_NUM_BUCKETS - 1, out=staged)
        codes[start : start + _CODE_CHUNK] = staged  # cast-assign into the output
    # bucket-mean codebook: average of the elements that landed in each bucket
    # (estimated from the same bounded sample), midpoint fallback for empties
    sample_codes = codes if indices is None else codes[indices]
    sums = np.bincount(sample_codes, weights=sample, minlength=UNIFORM_NUM_BUCKETS)
    counts = np.bincount(sample_codes, minlength=UNIFORM_NUM_BUCKETS)
    midpoints = lo + (np.arange(UNIFORM_NUM_BUCKETS, dtype=np.float64) + 0.5) / scale
    codebook = np.where(counts > 0, sums / np.maximum(counts, 1), midpoints)
    return codes, codebook.astype(np.float32, copy=False)


def _blockwise_quantize_np(padded32: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-4096-block absmax int8 (same formula as the jitted/pallas path),
    numpy-only and temp-free: absmax via max/−min reductions (no |x| temp),
    codes staged through one small row-chunk scratch. Input never mutated."""
    blocks = padded32.reshape(-1, BLOCKWISE_BLOCK_SIZE)
    absmax = np.maximum(blocks.max(axis=1), -blocks.min(axis=1))
    scale = np.where(absmax > 0, 127.0 / absmax, 0.0).astype(np.float32, copy=False)
    codes = np.empty(blocks.shape, np.int8)
    rows_per_chunk = max(1, _CODE_CHUNK // BLOCKWISE_BLOCK_SIZE)
    scratch = np.empty((min(blocks.shape[0], rows_per_chunk), BLOCKWISE_BLOCK_SIZE), np.float32)
    for start in range(0, blocks.shape[0], rows_per_chunk):
        view = blocks[start : start + rows_per_chunk]
        staged = scratch[: view.shape[0]]
        np.multiply(view, scale[start : start + rows_per_chunk, None], out=staged)
        np.rint(staged, out=staged)
        np.clip(staged, -127, 127, out=staged)
        codes[start : start + rows_per_chunk] = staged
    return codes, absmax.astype(np.float32, copy=False)


class _CodebookQuantization(CompressionBase):
    """Shared wire format: [u32 codebook_size][fp32 codebook][u8 codes]."""

    is_lossy = True

    def _quantize(self, flat32):
        raise NotImplementedError

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        array = as_numpy(array)
        original_dtype = "bfloat16" if str(array.dtype) == "bfloat16" else array.dtype.name
        flat = np.ascontiguousarray(array, dtype=np.float32).reshape(-1)
        codes, codebook = self._quantize(flat)
        codes = np.asarray(codes, dtype=np.uint8)
        codebook = np.asarray(codebook, dtype=np.float32)
        buffer = _assemble_wire(("<I", (codebook.size,)), codebook, codes)
        return runtime_pb2.Tensor(
            buffer=buffer, size=array.shape, dtype=original_dtype, compression=self.compression_type
        )

    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        from hivemind_tpu.utils.tensor_descr import numpy_dtype

        (codebook_size,) = struct.unpack_from("<I", serialized.buffer)
        codebook = np.frombuffer(serialized.buffer, dtype=np.float32, count=codebook_size, offset=4)
        codes = np.frombuffer(serialized.buffer, dtype=np.uint8, offset=4 + codebook_size * 4)
        restored = codebook[codes.astype(np.int64, copy=False)]
        return restored.astype(numpy_dtype(serialized.dtype or "float32"), copy=False).reshape(tuple(serialized.size))

    def estimate_compression_ratio(self, info: CompressionInfo) -> float:
        return 8.0 / (8 * (info.descriptor.itemsize if info.descriptor else 4))


class Uniform8BitQuantization(_CodebookQuantization):
    compression_type = CompressionType.UNIFORM_8BIT

    def _quantize(self, flat32):
        return _uniform_quantize_np(flat32)


class Quantile8BitQuantization(_CodebookQuantization):
    """Codebook = 256 empirical quantiles, estimated from a bounded hash-sampled
    subset past 2^20 elements so multi-M-element tensors never pay a full sort
    on the codec path (ops/quantization.quantile_quantize; runtime bounded by a
    regression test)."""

    compression_type = CompressionType.QUANTILE_8BIT

    def _quantize(self, flat32):
        codes, codebook = quantile_quantize(flat32)
        return codes, codebook


class BlockwiseQuantization(CompressionBase):
    """Per-4096-block absmax int8 (reference quantization.py:130-201 via bitsandbytes;
    numpy on the wire path, fused Pallas/jnp kernels in ops/ for device callers).
    Wire format: [u32 n_blocks][u32 true_size][fp32 absmax per block][i8 codes]."""

    compression_type = CompressionType.BLOCKWISE_8BIT
    is_lossy = True

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        array = as_numpy(array)
        original_dtype = "bfloat16" if str(array.dtype) == "bfloat16" else array.dtype.name
        flat = np.ascontiguousarray(array, dtype=np.float32).reshape(-1)
        padded, true_size = pad_to_block(flat)
        codes, absmax = _blockwise_quantize_np(padded)
        buffer = _assemble_wire(("<II", (absmax.size, true_size)), absmax, codes)
        return runtime_pb2.Tensor(
            buffer=buffer, size=array.shape, dtype=original_dtype, compression=self.compression_type
        )

    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        from hivemind_tpu.utils.tensor_descr import numpy_dtype

        n_blocks, true_size = struct.unpack_from("<II", serialized.buffer)
        absmax = np.frombuffer(serialized.buffer, dtype=np.float32, count=n_blocks, offset=8)
        codes = np.frombuffer(serialized.buffer, dtype=np.int8, offset=8 + n_blocks * 4)
        if n_blocks == 0:  # zero-element tensor: reshape(0, -1) would raise
            restored = np.zeros(0, np.float32)
        else:
            restored = codes.astype(np.float32, copy=True).reshape(n_blocks, -1)
            np.multiply(restored, (absmax / np.float32(127.0))[:, None], out=restored)
            restored = restored.reshape(-1)[:true_size]
        return restored.astype(numpy_dtype(serialized.dtype or "float32"), copy=False).reshape(tuple(serialized.size))

    def estimate_compression_ratio(self, info: CompressionInfo) -> float:
        return 8.25 / (8 * (info.descriptor.itemsize if info.descriptor else 4))
