"""8-bit quantization codecs (capability parity: reference
hivemind/compression/quantization.py). The math lives in hivemind_tpu.ops.quantization
as jitted jax functions — on TPU inputs it runs on device; numpy inputs go through the
CPU jax backend (same code, no thread-pool machinery needed)."""

from __future__ import annotations

import struct
from typing import Any, Optional

import numpy as np

from hivemind_tpu.compression.base import (
    CompressionBase,
    CompressionInfo,
    CompressionType,
    as_numpy,
)
from hivemind_tpu.ops.quantization import (
    BLOCKWISE_BLOCK_SIZE,
    blockwise_quantize,
    dequantize_with_codebook,
    pad_to_block,
    quantile_quantize,
    uniform_quantize,
)
from hivemind_tpu.proto import runtime_pb2


class _CodebookQuantization(CompressionBase):
    """Shared wire format: [u32 codebook_size][fp32 codebook][u8 codes]."""

    def _quantize(self, flat32):
        raise NotImplementedError

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        array = as_numpy(array)
        original_dtype = "bfloat16" if str(array.dtype) == "bfloat16" else array.dtype.name
        flat = np.ascontiguousarray(array, dtype=np.float32).reshape(-1)
        codes, codebook = self._quantize(flat)
        codes, codebook = np.asarray(codes), np.asarray(codebook)
        buffer = struct.pack("<I", codebook.size) + codebook.astype(np.float32).tobytes() + codes.tobytes()
        return runtime_pb2.Tensor(
            buffer=buffer, size=array.shape, dtype=original_dtype, compression=self.compression_type
        )

    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        from hivemind_tpu.utils.tensor_descr import numpy_dtype

        (codebook_size,) = struct.unpack_from("<I", serialized.buffer)
        codebook = np.frombuffer(serialized.buffer, dtype=np.float32, count=codebook_size, offset=4)
        codes = np.frombuffer(serialized.buffer, dtype=np.uint8, offset=4 + codebook_size * 4)
        restored = dequantize_with_codebook(codes, codebook)
        return restored.astype(numpy_dtype(serialized.dtype or "float32")).reshape(tuple(serialized.size))

    def estimate_compression_ratio(self, info: CompressionInfo) -> float:
        return 8.0 / (8 * (info.descriptor.itemsize if info.descriptor else 4))


class Uniform8BitQuantization(_CodebookQuantization):
    compression_type = CompressionType.UNIFORM_8BIT

    def _quantize(self, flat32):
        return uniform_quantize(flat32)


class Quantile8BitQuantization(_CodebookQuantization):
    compression_type = CompressionType.QUANTILE_8BIT

    def _quantize(self, flat32):
        return quantile_quantize(flat32)


class BlockwiseQuantization(CompressionBase):
    """Per-4096-block absmax int8 (reference quantization.py:130-201 via bitsandbytes;
    here a fused Pallas kernel on TPU / fused-jnp on host — see
    ops/pallas_quantization.py and ops/quantization.py for the deviation note).
    Wire format: [u32 n_blocks][u32 true_size][fp32 absmax per block][i8 codes]."""

    compression_type = CompressionType.BLOCKWISE_8BIT

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        array = as_numpy(array)
        original_dtype = "bfloat16" if str(array.dtype) == "bfloat16" else array.dtype.name
        flat = np.ascontiguousarray(array, dtype=np.float32).reshape(-1)
        padded, true_size = pad_to_block(flat)
        from hivemind_tpu.ops.pallas_quantization import blockwise_quantize_auto

        codes, absmax = blockwise_quantize_auto(padded)
        codes, absmax = np.asarray(codes), np.asarray(absmax)
        buffer = (
            struct.pack("<II", absmax.size, true_size)
            + absmax.astype(np.float32).tobytes()
            + codes.tobytes()
        )
        return runtime_pb2.Tensor(
            buffer=buffer, size=array.shape, dtype=original_dtype, compression=self.compression_type
        )

    def extract(self, serialized: runtime_pb2.Tensor) -> np.ndarray:
        from hivemind_tpu.ops.pallas_quantization import blockwise_dequantize_auto
        from hivemind_tpu.utils.tensor_descr import numpy_dtype

        n_blocks, true_size = struct.unpack_from("<II", serialized.buffer)
        absmax = np.frombuffer(serialized.buffer, dtype=np.float32, count=n_blocks, offset=8)
        codes = np.frombuffer(serialized.buffer, dtype=np.int8, offset=8 + n_blocks * 4)
        codes = codes.reshape(n_blocks, -1)
        restored = np.asarray(blockwise_dequantize_auto(codes, absmax))[:true_size]
        return restored.astype(numpy_dtype(serialized.dtype or "float32")).reshape(tuple(serialized.size))

    def estimate_compression_ratio(self, info: CompressionInfo) -> float:
        return 8.25 / (8 * (info.descriptor.itemsize if info.descriptor else 4))
