"""Adaptive codec selection (capability parity: reference hivemind/compression/adaptive.py:11-66)."""

from __future__ import annotations

from typing import Any, Mapping, Optional, Sequence

from hivemind_tpu.compression.base import CompressionBase, CompressionInfo, TensorRole
from hivemind_tpu.proto import runtime_pb2


class AdaptiveCompressionBase(CompressionBase):
    def choose_compression(self, info: CompressionInfo) -> CompressionBase:
        raise NotImplementedError

    @property
    def compression_type(self):  # type: ignore[override]
        raise AttributeError("adaptive codecs have no fixed compression type")

    def compress(self, array: Any, info: Optional[CompressionInfo] = None, allow_inplace: bool = False) -> runtime_pb2.Tensor:
        info = info if info is not None else CompressionInfo.from_array(array)
        return self.choose_compression(info).compress(array, info, allow_inplace)

    def extract(self, serialized: runtime_pb2.Tensor):
        from hivemind_tpu.compression.serialization import deserialize_tensor

        return deserialize_tensor(serialized)

    def estimate_compression_ratio(self, info: CompressionInfo) -> float:
        return self.choose_compression(info).estimate_compression_ratio(info)


class SizeAdaptiveCompression(AdaptiveCompressionBase):
    """Compress only tensors above a size threshold; small tensors aren't worth the
    precision loss (reference adaptive.py SizeAdaptiveCompression)."""

    def __init__(self, threshold: int, less: CompressionBase, greater_equal: CompressionBase):
        self.threshold, self.less, self.greater_equal = threshold, less, greater_equal

    def choose_compression(self, info: CompressionInfo) -> CompressionBase:
        numel = info.descriptor.numel if info.descriptor is not None else 0
        return self.greater_equal if numel >= self.threshold else self.less


class RoleAdaptiveCompression(AdaptiveCompressionBase):
    """Pick a codec by the tensor's role in training (reference adaptive.py
    RoleAdaptiveCompression)."""

    def __init__(
        self,
        *,
        activation: Optional[CompressionBase] = None,
        parameter: Optional[CompressionBase] = None,
        gradient: Optional[CompressionBase] = None,
        optimizer: Optional[CompressionBase] = None,
        default: CompressionBase,
    ):
        self.by_role: Mapping[TensorRole, Optional[CompressionBase]] = {
            TensorRole.ACTIVATION: activation,
            TensorRole.PARAMETER: parameter,
            TensorRole.GRADIENT: gradient,
            TensorRole.OPTIMIZER: optimizer,
        }
        self.default = default

    def choose_compression(self, info: CompressionInfo) -> CompressionBase:
        chosen = self.by_role.get(info.role)
        return chosen if chosen is not None else self.default


class PerTensorCompression(AdaptiveCompressionBase):
    """A fixed codec per tensor key (reference adaptive.py PerTensorCompression)."""

    def __init__(self, tensor_compressions: Sequence[CompressionBase] | Mapping[Any, CompressionBase]):
        self.tensor_compressions = tensor_compressions

    def choose_compression(self, info: CompressionInfo) -> CompressionBase:
        if isinstance(self.tensor_compressions, Mapping):
            return self.tensor_compressions[info.key]
        return self.tensor_compressions[info.key]
