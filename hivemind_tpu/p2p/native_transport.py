"""Zero-config native transport: spawn a PRIVATE relay daemon for this process
and wire the data-plane proxy through it — both directions.

The reference never runs without its native daemon (hivemind/p2p/p2p_daemon.py
spawns p2pd at startup and terminates the whole transport there, :84-147). Here
the native tier is optional — the pure-asyncio transport is complete — but
``P2P.create(native_transport=True)`` reproduces the reference's default
posture with one flag: a daemon child is spawned (building it from source if
needed), listening ONLY on a 0600 AF_UNIX socket (the key-handoff trust
boundary; no TCP control port is opened), and the P2P routes outbound dials
('X') and its public listener ('Y') through it, so ChaCha20-Poly1305 for both
directions runs in C++ outside the Python event loop.

The daemon's lifetime is tied to the P2P: `shutdown()` kills it, and if it dies
first the inbound watchdog falls back to direct listening (see
`P2P._watch_inbound_proxy`) while outbound dials degrade to plain sockets."""

from __future__ import annotations

import os
import subprocess
import tempfile
from pathlib import Path
from typing import Optional

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

NATIVE_DIR = Path(__file__).parent.parent / "native"


class NativeTransportDaemon:
    """A private relay daemon child serving the data-plane proxy over a 0600
    unix socket. Use :func:`spawn_native_transport`."""

    def __init__(
        self, process: subprocess.Popen, unix_path: str, port: int,
        workdir: str, owns_workdir: bool,
    ):
        self.process = process
        self.unix_path = unix_path
        self.port = port  # the daemon's TCP control port (relay/'Y' listeners ride it too)
        self._workdir = workdir
        self._owns_workdir = owns_workdir

    @property
    def alive(self) -> bool:
        return self.process.poll() is None

    def shutdown(self) -> None:
        if self.process.poll() is None:
            self.process.kill()
            self.process.wait()
        try:
            os.unlink(self.unix_path)
        except OSError:
            pass
        if self._owns_workdir:
            import shutil

            shutil.rmtree(self._workdir, ignore_errors=True)


def _die_with_parent():
    """Child pre-exec: SIGKILL on parent death (Linux PR_SET_PDEATHSIG), so an
    OOM-killed or SIGKILLed trainer cannot orphan a daemon with open listeners
    (the graceful path still reaps via shutdown())."""
    try:
        import ctypes
        import signal

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, signal.SIGKILL)  # 1 = PR_SET_PDEATHSIG
    except Exception:
        pass  # non-Linux / no libc: best effort only


def build_daemon_binary():
    """Build the relay daemon if the source is present (no-op when fresh).
    Returns ``(binary_path or None, error_text)``. The `make` is serialized
    with an flock: concurrent P2P.create calls from several processes must not
    race the same output binary. A missing toolchain is an error TEXT, not an
    exception — callers choose whether to degrade or raise."""
    import fcntl

    binary = NATIVE_DIR / "relay_daemon"
    if (NATIVE_DIR / "relay_daemon.cpp").exists():
        try:
            with open(NATIVE_DIR / ".build.lock", "w") as lock:
                fcntl.flock(lock, fcntl.LOCK_EX)
                build = subprocess.run(
                    ["make"], cwd=NATIVE_DIR, capture_output=True, text=True
                )
            if build.returncode != 0:
                return None, f"build failed:\n{build.stderr[-500:]}"
        except OSError as e:  # make not installed, unwritable dir, ...
            return None, f"native toolchain unavailable: {e!r}"
    if not binary.exists():
        return None, "no relay daemon binary or source"
    return binary, ""


def read_daemon_banner(process: subprocess.Popen, timeout: float):
    """Bounded read of the daemon's two startup lines (it emits exactly two, in
    one flush — see its main()). Returns ``(line1, line2)`` or None on timeout /
    early exit; a STALE binary predating the two-line protocol trips the bound
    instead of hanging the caller forever.

    Reads the RAW fd, not the buffered TextIOWrapper: both lines arrive in one
    flush, so after a buffered readline the second line sits in the Python-side
    buffer where select() on the fd would block until timeout."""
    import select
    import time

    fd = process.stdout.fileno()
    buf = b""
    deadline = time.monotonic() + timeout
    while buf.count(b"\n") < 2:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None
        ready, _, _ = select.select([fd], [], [], remaining)
        if not ready:
            return None
        chunk = os.read(fd, 4096)
        if not chunk:  # EOF: the child died before finishing its banner
            return None
        buf += chunk
    lines = buf.decode(errors="replace").splitlines()
    return lines[0].strip(), lines[1].strip()


def _listener_binds_any(port: int) -> Optional[bool]:
    """True if a LISTEN socket on ``port`` is bound to 0.0.0.0 (read from
    /proc/net/tcp); None when that table is unavailable or the port is absent."""
    try:
        with open("/proc/net/tcp") as table:
            next(table)  # header
            for line in table:
                fields = line.split()
                if len(fields) < 4 or fields[3] != "0A":  # 0A = TCP_LISTEN
                    continue
                addr_hex, _, port_hex = fields[1].partition(":")
                if int(port_hex, 16) == port:
                    return addr_hex == "00000000"
    except (OSError, ValueError, StopIteration):
        return None
    return None


def spawn_native_transport(
    workdir: Optional[str] = None, banner_timeout: float = 30.0
) -> Optional[NativeTransportDaemon]:
    """Build (if needed) and spawn the relay daemon with a fresh unix socket.
    Returns None — with a warning — when the native toolchain or binary is
    unavailable, so callers can degrade to the pure-asyncio transport.

    BLOCKING (the build can take tens of seconds on a slow host): async callers
    must run this in an executor — ``P2P.create`` does."""
    binary, error = build_daemon_binary()
    if binary is None:
        logger.warning(f"{error}; staying on the asyncio data plane")
        return None

    owns_workdir = workdir is None
    workdir = workdir or tempfile.mkdtemp(prefix="hivemind_native_")
    unix_path = os.path.join(workdir, "data_plane.sock")
    # 127.0.0.1: a PRIVATE daemon's control surface is the 0600 unix socket; its
    # TCP listener (relay/'Y' control) must not be reachable from off-host, so a
    # zero-config spawn exposes no remote relay surface (advisory at the old
    # INADDR_ANY spawn). Public relays are started explicitly, without this arg.
    process = subprocess.Popen(
        [str(binary), "0", "", unix_path, "127.0.0.1"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
        preexec_fn=_die_with_parent,
    )

    def _give_up(reason: str) -> None:
        process.kill()
        process.wait()
        if owns_workdir:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)
        logger.warning(f"{reason}; staying on the asyncio data plane")

    banner = read_daemon_banner(process, banner_timeout)
    if banner is None:
        _give_up(f"daemon produced no complete banner within {banner_timeout:.0f}s")
        return None
    try:
        port = int(banner[0].rsplit(" ", 1)[-1])
    except ValueError:
        _give_up(f"unexpected daemon banner {banner[0]!r}")
        return None
    if not os.path.exists(unix_path):
        _give_up("daemon did not create its unix socket")
        return None
    if _listener_binds_any(port):
        # a binary predating the bind-host argument ignores argv[4] and binds
        # INADDR_ANY — the loopback confinement silently fails open; say so
        logger.warning(
            "the private relay daemon bound its TCP listener to 0.0.0.0 (stale "
            "binary predating the bind-host argument?); rebuild hivemind_tpu/native "
            "with `make` to confine the relay surface to loopback"
        )
    logger.debug(f"private data-plane daemon up (pid {process.pid}, socket {unix_path})")
    return NativeTransportDaemon(process, unix_path, port, workdir, owns_workdir)
