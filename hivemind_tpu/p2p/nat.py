"""NAT traversal helpers: reachability probing and hole punching (capability parity:
reference hivemind/p2p/p2p_daemon.py:84-147, where the Go daemon's AutoNAT + AutoRelay
+ DCUtR flags provide the same three capabilities).

- :class:`NATTraversal` registers two P2P handlers:

  * ``nat.check`` — AutoNAT-style dial-back: a peer asks us to TCP-dial its
    advertised addresses and report which ones are reachable from the outside.
  * ``nat.punch`` — DCUtR-style coordination: two peers that can already exchange
    messages (e.g. through a relay) swap their direct endpoints and SIMULTANEOUSLY
    dial each other; whichever direction lands first becomes the direct connection
    and replaces the relayed one for future streams.

- :func:`RelayClient.whoami` (see relay.py) supplies the STUN-style observed
  endpoint a NATed peer advertises for punching.

Security note: real AutoNAT only dials back addresses that share the requester's
observed IP so a prober cannot be used to scan third parties. The in-process
transport does not expose per-connection remote addresses to handlers yet, so
``nat.check`` instead refuses to probe more than ``MAX_PROBE_ADDRS`` addresses and
never keeps the connection open beyond the TCP handshake."""

from __future__ import annotations

import asyncio
from typing import List, Optional, Sequence

from hivemind_tpu.p2p.peer_id import Multiaddr, PeerID
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)

MAX_PROBE_ADDRS = 4
PROBE_TIMEOUT = 3.0
# control RPCs ride a (possibly relayed) path to a peer that then dials N
# addresses at PROBE_TIMEOUT each — generous, but never infinite
CONTROL_RPC_TIMEOUT = 15.0
PUNCH_TIMEOUT = 10.0


class NATTraversal:
    """Attach reachability + hole-punching to a P2P node."""

    def __init__(self, p2p):
        self.p2p = p2p

    async def register_handlers(self) -> None:
        await self.p2p.add_protobuf_handler("nat.check", self._rpc_check)
        await self.p2p.add_protobuf_handler("nat.punch", self._rpc_punch)

    # ------------------------------------------------------------------ reachability

    async def _rpc_check(self, request: bytes, context) -> bytes:
        addrs = MSGPackSerializer.loads(request)[:MAX_PROBE_ADDRS]
        reachable = []
        for addr in addrs:
            try:
                maddr = Multiaddr.parse(addr)
                if maddr.host_proto not in self.p2p._DIALABLE_PROTOS:
                    continue  # unix/onion3 parse (codec parity) but cannot be
                    # probed over TCP — and must not burn PROBE_TIMEOUT each
                _reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(maddr.host, maddr.port), timeout=PROBE_TIMEOUT
                )
                writer.close()
                reachable.append(addr)
            except Exception:
                continue
        return MSGPackSerializer.dumps(reachable)

    async def check_reachability(
        self, via: PeerID, maddrs: Optional[Sequence] = None
    ) -> List[str]:
        """Ask ``via`` to dial our addresses back; returns the publicly-reachable
        subset. An empty result on a working path means we are NATed and should
        register at a relay (reference auto_relay, p2p_daemon.py:126-137)."""
        maddrs = maddrs if maddrs is not None else self.p2p.get_visible_maddrs()
        request = MSGPackSerializer.dumps([str(m) for m in maddrs])
        response = await asyncio.wait_for(
            self.p2p.call_protobuf_handler(via, "nat.check", request, idempotent=True),
            timeout=CONTROL_RPC_TIMEOUT,
        )
        return list(MSGPackSerializer.loads(response))

    # ------------------------------------------------------------------ hole punching

    async def _rpc_punch(self, request: bytes, context) -> bytes:
        """The passive side: reply with our direct endpoints and immediately start
        dialing the initiator's (TCP simultaneous open under real NATs)."""
        their_addrs = [Multiaddr.parse(a) for a in MSGPackSerializer.loads(request)]
        spawn(self._punch_dial(context.remote_id, their_addrs), name="nat.punch_dial")
        return MSGPackSerializer.dumps([str(m) for m in self.p2p.get_visible_maddrs()])

    async def _punch_dial(self, peer_id: PeerID, addrs: Sequence[Multiaddr]) -> bool:
        for maddr in addrs:
            try:
                await asyncio.wait_for(
                    self.p2p._dial(maddr.with_peer_id(peer_id), expected_peer=peer_id, replace_existing=True),
                    timeout=PUNCH_TIMEOUT,
                )
                return True
            except Exception as e:
                logger.debug(f"punch dial to {maddr} failed: {e!r}")
        return False

    async def hole_punch(self, peer_id: PeerID, direct_addrs: Optional[Sequence] = None) -> bool:
        """Coordinate a direct connection with a peer we can already message (through
        a relay): exchange endpoints over the existing path, then both sides dial.
        Returns True if a direct connection was established from our side (the
        peer's dial may land first; either way the connection map is upgraded)."""
        ours = direct_addrs if direct_addrs is not None else self.p2p.get_visible_maddrs()
        request = MSGPackSerializer.dumps([str(m) for m in ours])
        # punch is effectively idempotent (the handler's dial uses replace_existing),
        # so the ambiguous-loss retry is safe — and this call races connection churn
        # by construction
        response = await asyncio.wait_for(
            self.p2p.call_protobuf_handler(peer_id, "nat.punch", request, idempotent=True),
            timeout=CONTROL_RPC_TIMEOUT,
        )
        their_addrs = [Multiaddr.parse(a) for a in MSGPackSerializer.loads(response)]
        return await self._punch_dial(peer_id, their_addrs)
