"""Client for the native relay daemon (hivemind_tpu/native/relay_daemon.cpp) — the
circuit-relay capability: a firewalled peer registers over an OUTBOUND connection and
becomes dialable as ``/ip4/<relay>/tcp/<port>/p2p-circuit/p2p/<peer>`` (role parity:
reference p2p_daemon.py:114-137 auto-relay). The relay splices raw bytes; the normal
end-to-end Noise handshake runs straight through it, so the relay never sees
plaintext."""

from __future__ import annotations

import asyncio
import contextlib
import os
import struct
from typing import Optional, Tuple

from hivemind_tpu.p2p.crypto_channel import handshake
from hivemind_tpu.p2p.mux import MuxConnection
from hivemind_tpu.p2p.peer_id import PeerID
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


async def _send_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(struct.pack(">I", len(payload)) + payload)
    await writer.drain()


async def _recv_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    return await reader.readexactly(length)


async def register_control(
    reader: asyncio.StreamReader, writer: asyncio.StreamWriter, peer_id_bytes: bytes, identity
) -> bytes:
    """Run the relay REGISTER exchange, answering an Ed25519 challenge if the daemon
    issues one ('C' + 32B nonce → 'P' + raw pubkey + raw signature over
    ``"hivemind-relay-register:" + challenge + peer_id``). Returns the final frame
    ('O' on success). A valid proof also reclaims the peer_id from a stale control
    line — only the key owner can evict a registration."""
    import base64

    await _send_frame(writer, b"R" + peer_id_bytes)
    response = await _recv_frame(reader)
    if response[:1] == b"C":
        challenge = response[1:]
        message = b"hivemind-relay-register:" + challenge + peer_id_bytes
        signature = base64.b64decode(identity.sign(message))  # sign() returns base64
        pubkey = identity.get_public_key().to_bytes()
        await _send_frame(writer, b"P" + pubkey + signature)
        response = await _recv_frame(reader)
    return response


class RelayClient:
    """Attach a P2P node to a relay daemon.

    ``await RelayClient.create(p2p, host, port)`` registers the node; incoming
    relayed dials are accepted automatically and served like direct connections.
    ``dial(peer_id)`` connects to a registered peer through the relay."""

    def __init__(self, p2p, host: str, port: int):
        self.p2p = p2p
        self.host, self.port = host, port
        self._control_writer: Optional[asyncio.StreamWriter] = None
        self._control_task: Optional[asyncio.Task] = None

    @classmethod
    async def create(cls, p2p, host: str, port: int) -> "RelayClient":
        self = cls(p2p, host, port)
        await self._register()
        return self

    async def _register(self) -> None:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        response = await register_control(
            reader, writer, self.p2p.peer_id.to_bytes(), self.p2p.identity
        )
        if response != b"O":
            raise ConnectionError(f"relay refused registration: {response!r}")
        self._control_writer = writer
        self._control_task = asyncio.create_task(self._control_loop(reader))
        logger.info(f"registered at relay {self.host}:{self.port} as {self.p2p.peer_id}")

    async def _control_loop(self, reader: asyncio.StreamReader) -> None:
        """Wait for INCOMING notifications and accept each relayed dial."""
        try:
            while True:
                frame = await _recv_frame(reader)
                if frame[:1] == b"I" and len(frame) >= 17:
                    token = frame[1:17]
                    asyncio.create_task(self._accept(token))
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            logger.warning(f"relay control line lost: {e!r}")

    async def _accept(self, token: bytes) -> None:
        try:
            reader, writer = await asyncio.open_connection(self.host, self.port)
            await _send_frame(writer, b"A" + token)
            response = await _recv_frame(reader)
            if response != b"O":
                writer.close()
                return
            # from here the socket is a transparent pipe to the dialer: run the
            # normal inbound path (handshake as responder, then mux)
            await self.p2p._on_inbound_connection(reader, writer)
        except Exception as e:
            logger.warning(f"relayed accept failed: {e!r}")

    async def dial(self, target: PeerID) -> PeerID:
        """Connect to a relay-registered peer; returns its authenticated PeerID and
        installs the connection in the P2P node like any direct dial."""
        reader, writer = await asyncio.open_connection(self.host, self.port)
        token = os.urandom(16)
        await _send_frame(writer, b"D" + token + target.to_bytes())
        try:
            response = await _recv_frame(reader)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            # the daemon may close right after its error frame; either way: no route
            writer.close()
            raise ConnectionError(f"relay could not reach {target}") from None
        if response != b"O":
            writer.close()
            raise ConnectionError(f"relay could not reach {target}: {response!r}")
        channel, extras = await handshake(
            reader, writer, self.p2p.identity, is_initiator=True,
            announced_addrs=self.p2p.get_visible_maddrs(),
        )
        from hivemind_tpu.utils.crypto import Ed25519PublicKey
        from hivemind_tpu.p2p.crypto_channel import HandshakeError

        peer_id = PeerID.from_public_key(Ed25519PublicKey.from_bytes(extras["static"]))
        if peer_id != target:
            channel.close()
            raise HandshakeError(f"dialed {target} via relay but found {peer_id}")
        conn = MuxConnection(channel, peer_id, is_initiator=True, on_inbound_stream=self.p2p._route_stream)
        existing = self.p2p._connections.get(peer_id)
        if existing is None or existing.is_closed:
            self.p2p._connections[peer_id] = conn
        self.p2p._all_connections.add(conn)
        conn.start()
        return peer_id

    async def whoami(self) -> Tuple[str, int]:
        """The relay's view of our public endpoint (STUN-style observed address) —
        what a NATed peer advertises for hole punching."""
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            await _send_frame(writer, b"W")
            response = await _recv_frame(reader)
            if not response.startswith(b"O"):
                raise ConnectionError(f"relay whoami failed: {response!r}")
            host, port = response[1:].decode().rsplit(":", 1)
            return host, int(port)
        finally:
            writer.close()

    async def close(self) -> None:
        if self._control_task is not None:
            self._control_task.cancel()
        if self._control_writer is not None:
            with contextlib.suppress(Exception):
                self._control_writer.close()
