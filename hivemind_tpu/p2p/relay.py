"""Client for the native relay daemon (hivemind_tpu/native/relay_daemon.cpp) — the
circuit-relay capability: a firewalled peer registers over an OUTBOUND connection and
becomes dialable as ``/ip4/<relay>/tcp/<port>/p2p-circuit/p2p/<peer>`` (role parity:
reference p2p_daemon.py:114-137 auto-relay). The relay splices raw bytes; the normal
end-to-end Noise handshake runs straight through it, so the relay never sees
plaintext.

Control traffic (REGISTER/PROOF/DIAL/ACCEPT/INCOMING/WHOAMI) additionally runs over
an encrypted channel to the relay itself: an 'H' handshake (X25519 ECDH, relay
Ed25519 identity signature, HKDF-SHA256 keys, ChaCha20-Poly1305 frames) keeps dial
tokens and registration proofs opaque to on-path observers, and pinning the relay's
identity (``relay_pubkey=``) defeats a proxying relay replaying proofs elsewhere."""

from __future__ import annotations

import asyncio
import contextlib
import os
import struct
from typing import Optional, Tuple

try:
    from cryptography.exceptions import InvalidSignature, InvalidTag
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import ed25519 as raw_ed25519
    from cryptography.hazmat.primitives.asymmetric.x25519 import X25519PrivateKey, X25519PublicKey
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
except ImportError:  # no cryptography wheel on this image: system libcrypto shim
    from hivemind_tpu.utils import _libcrypto as _compat
    from hivemind_tpu.utils._libcrypto import (
        ChaCha20Poly1305,
        HKDF,
        InvalidSignature,
        InvalidTag,
        X25519PrivateKey,
        X25519PublicKey,
        hashes,
        serialization,
    )

    raw_ed25519 = _compat.ed25519

from hivemind_tpu.p2p.crypto_channel import handshake
from hivemind_tpu.p2p.mux import MuxConnection
from hivemind_tpu.p2p.peer_id import PeerID
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn

logger = get_logger(__name__)

_HS_PREFIX = b"hivemind-relay-hs:"


async def _send_frame(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(struct.pack(">I", len(payload)) + payload)
    await writer.drain()


async def _recv_frame(reader: asyncio.StreamReader) -> bytes:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    return await reader.readexactly(length)


class RelayChannel:
    """Control-frame transport to the relay: sealed (post-'H' handshake) or
    plaintext (legacy daemon without libcrypto). ``relay_pubkey`` is the verified
    relay identity (raw 32 bytes) when sealed, else None."""

    def __init__(self, reader, writer, send_key=None, recv_key=None, relay_pubkey=None):
        self.reader, self.writer = reader, writer
        self._send_aead = ChaCha20Poly1305(send_key) if send_key is not None else None
        self._recv_aead = ChaCha20Poly1305(recv_key) if recv_key is not None else None
        self._send_ctr = 0
        self._recv_ctr = 0
        self.relay_pubkey = relay_pubkey

    @property
    def encrypted(self) -> bool:
        return self._send_aead is not None

    async def send_frame(self, payload: bytes) -> None:
        if self._send_aead is not None:
            nonce = struct.pack("<4xQ", self._send_ctr)
            self._send_ctr += 1
            payload = self._send_aead.encrypt(nonce, payload, None)
        await _send_frame(self.writer, payload)

    async def recv_frame(self) -> bytes:
        payload = await _recv_frame(self.reader)
        if self._recv_aead is not None:
            nonce = struct.pack("<4xQ", self._recv_ctr)
            self._recv_ctr += 1
            try:
                payload = self._recv_aead.decrypt(nonce, payload, None)
            except InvalidTag:
                # surface as a connection failure so every caller's existing
                # (ConnectionError, ...) handling applies — a tampered or
                # desynced frame means the channel is dead either way
                raise ConnectionError("relay control frame failed AEAD authentication") from None
        return payload

    def close(self) -> None:
        with contextlib.suppress(Exception):
            self.writer.close()


# host:port endpoints that EVER completed an encrypted handshake in this process:
# once a relay has proven it can do crypto, a later handshake "failure" is treated
# as an active downgrade attempt, not a legacy daemon
_ENCRYPTED_ENDPOINTS: set = set()


async def open_relay_channel(
    host: str, port: int, relay_pubkey: Optional[bytes] = None,
    allow_plaintext: bool = False,
) -> RelayChannel:
    """Connect and negotiate the encrypted control channel. ENCRYPTED BY DEFAULT
    (VERDICT r3 #7): a daemon that does not complete the handshake is refused unless
    the caller explicitly opts out with ``allow_plaintext=True`` for a legacy
    daemon — and even then a pinned ``relay_pubkey`` or an endpoint that EVER
    completed an encrypted handshake in this process (TOFU) still refuses, so an
    on-path attacker interfering with the handshake cannot strip encryption from
    an endpoint known to support it."""
    reader, writer = await asyncio.open_connection(host, port)
    ephemeral = X25519PrivateKey.generate()
    eph_pub = ephemeral.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw
    )
    legacy = False
    try:
        await _send_frame(writer, b"H" + eph_pub)
        response = await _recv_frame(reader)
        if response[:1] != b"S" or len(response) != 129:
            legacy = True
    except (asyncio.IncompleteReadError, ConnectionError, OSError):
        legacy = True  # pre-handshake daemon closes on the unknown 'H' frame
    if legacy:
        with contextlib.suppress(Exception):
            writer.close()
        if relay_pubkey is not None:
            raise ConnectionError("relay does not support the encrypted control channel "
                                  "but a pinned identity was required")
        if (host, port) in _ENCRYPTED_ENDPOINTS:
            raise ConnectionError(
                f"relay {host}:{port} previously completed an encrypted handshake but now "
                f"fails it — refusing the plaintext downgrade (possible on-path attacker)"
            )
        if not allow_plaintext:
            raise ConnectionError(
                f"relay {host}:{port} did not complete the encrypted handshake; plaintext "
                f"control is refused by default — pass allow_plaintext=True only for a "
                f"trusted legacy daemon"
            )
        logger.warning(
            f"relay control channel to {host}:{port} is PLAINTEXT (explicitly allowed "
            f"via allow_plaintext=True; the daemon did not complete the encrypted handshake)"
        )
        reader, writer = await asyncio.open_connection(host, port)
        return RelayChannel(reader, writer)

    relay_eph, relay_pub, signature = response[1:33], response[33:65], response[65:129]
    try:
        raw_ed25519.Ed25519PublicKey.from_public_bytes(relay_pub).verify(
            signature, _HS_PREFIX + eph_pub + relay_eph
        )
    except InvalidSignature:
        writer.close()
        raise ConnectionError("relay failed its identity proof") from None
    if relay_pubkey is not None and relay_pub != relay_pubkey:
        writer.close()
        raise ConnectionError(
            f"relay identity mismatch: expected {relay_pubkey.hex()}, got {relay_pub.hex()}"
        )
    shared = ephemeral.exchange(X25519PublicKey.from_public_bytes(relay_eph))
    _ENCRYPTED_ENDPOINTS.add((host, port))
    okm = HKDF(
        algorithm=hashes.SHA256(), length=64, salt=b"hivemind-relay-hs", info=b"control"
    ).derive(shared)
    # client->relay key first, relay->client second (must mirror the daemon)
    return RelayChannel(reader, writer, send_key=okm[:32], recv_key=okm[32:], relay_pubkey=relay_pub)


async def register_control(channel: RelayChannel, peer_id_bytes: bytes, identity) -> bytes:
    """Run the relay REGISTER exchange, answering an Ed25519 challenge if the daemon
    issues one ('C' + 32B nonce → 'P' + raw pubkey + raw signature over
    ``"hivemind-relay-register:" + challenge + peer_id``). Returns the final frame
    ('O' on success). A valid proof also reclaims the peer_id from a stale control
    line — only the key owner can evict a registration."""
    import base64

    await channel.send_frame(b"R" + peer_id_bytes)
    response = await channel.recv_frame()
    if response[:1] == b"C":
        challenge = response[1:]
        message = b"hivemind-relay-register:" + challenge + peer_id_bytes
        signature = base64.b64decode(identity.sign(message))  # sign() returns base64
        pubkey = identity.get_public_key().to_bytes()
        await channel.send_frame(b"P" + pubkey + signature)
        response = await channel.recv_frame()
    return response


class RelayClient:
    """Attach a P2P node to a relay daemon.

    ``await RelayClient.create(p2p, host, port)`` registers the node; incoming
    relayed dials are accepted automatically and served like direct connections.
    ``dial(peer_id)`` connects to a registered peer through the relay."""

    def __init__(self, p2p, host: str, port: int, relay_pubkey: Optional[bytes] = None,
                 allow_plaintext: bool = False):
        self.p2p = p2p
        self.host, self.port = host, port
        if isinstance(relay_pubkey, str):
            relay_pubkey = bytes.fromhex(relay_pubkey)
        self.relay_pubkey = relay_pubkey  # optional pinned relay identity
        self.allow_plaintext = allow_plaintext  # opt-OUT of the encrypted default
        self._control: Optional[RelayChannel] = None
        self._control_task: Optional[asyncio.Task] = None

    @classmethod
    async def create(cls, p2p, host: str, port: int, relay_pubkey: Optional[bytes] = None,
                     allow_plaintext: bool = False) -> "RelayClient":
        self = cls(p2p, host, port, relay_pubkey=relay_pubkey, allow_plaintext=allow_plaintext)
        await self._register()
        return self

    async def _open_channel(self) -> RelayChannel:
        channel = await open_relay_channel(self.host, self.port, self.relay_pubkey,
                                           allow_plaintext=self.allow_plaintext)
        if channel.encrypted and self.relay_pubkey is None:
            # trust-on-first-use: pin the identity we saw so every later control
            # connection in this client talks to the SAME relay
            self.relay_pubkey = channel.relay_pubkey
        return channel

    async def _register(self) -> None:
        channel = await self._open_channel()
        response = await register_control(channel, self.p2p.peer_id.to_bytes(), self.p2p.identity)
        if response != b"O":
            raise ConnectionError(f"relay refused registration: {response!r}")
        self._control = channel
        self._control_task = spawn(self._control_loop(channel), name="relay.control_loop")
        mode = "encrypted" if channel.encrypted else "plaintext"
        logger.info(
            f"registered at relay {self.host}:{self.port} as {self.p2p.peer_id} ({mode} control)"
        )

    async def _control_loop(self, channel: RelayChannel) -> None:
        """Wait for INCOMING notifications and accept each relayed dial."""
        try:
            while True:
                frame = await channel.recv_frame()
                if frame[:1] == b"I" and len(frame) >= 17:
                    token = frame[1:17]
                    spawn(self._accept(token), name="relay.accept")
        except (asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            logger.warning(f"relay control line lost: {e!r}")

    async def _accept(self, token: bytes) -> None:
        try:
            channel = await self._open_channel()
            await channel.send_frame(b"A" + token)
            response = await channel.recv_frame()
            if response != b"O":
                channel.close()
                return
            # from here the socket is a transparent pipe to the dialer: run the
            # normal inbound path (handshake as responder, then mux)
            await self.p2p._on_inbound_connection(channel.reader, channel.writer)
        except Exception as e:
            logger.warning(f"relayed accept failed: {e!r}")

    async def dial(self, target: PeerID) -> PeerID:
        """Connect to a relay-registered peer; returns its authenticated PeerID and
        installs the connection in the P2P node like any direct dial."""
        channel = await self._open_channel()
        token = os.urandom(16)
        await channel.send_frame(b"D" + token + target.to_bytes())
        try:
            response = await channel.recv_frame()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            # the daemon may close right after its error frame; either way: no route
            channel.close()
            raise ConnectionError(f"relay could not reach {target}") from None
        if response != b"O":
            channel.close()
            raise ConnectionError(f"relay could not reach {target}: {response!r}")
        reader, writer = channel.reader, channel.writer  # raw pipe from here on
        noise_channel, extras = await handshake(
            reader, writer, self.p2p.identity, is_initiator=True,
            announced_addrs=self.p2p.get_visible_maddrs(),
        )
        from hivemind_tpu.utils.crypto import Ed25519PublicKey
        from hivemind_tpu.p2p.crypto_channel import HandshakeError

        peer_id = PeerID.from_public_key(Ed25519PublicKey.from_bytes(extras["static"]))
        if peer_id != target:
            noise_channel.close()
            raise HandshakeError(f"dialed {target} via relay but found {peer_id}")
        conn = MuxConnection(
            noise_channel, peer_id, is_initiator=True, on_inbound_stream=self.p2p._route_stream
        )
        # circuits are exempt from connection-manager trimming (the plain dial
        # path cannot re-establish them), but they must still TRIGGER a trim so
        # relay-heavy nodes respect the fd bound via their direct connections
        conn.is_relayed = True
        existing = self.p2p._connections.get(peer_id)
        if existing is None or existing.is_closed:
            self.p2p._connections[peer_id] = conn
        self.p2p._all_connections.add(conn)
        conn.start()
        await self.p2p._trim_connections(protect=conn)
        return peer_id

    async def whoami(self) -> Tuple[str, int]:
        """The relay's view of our public endpoint (STUN-style observed address) —
        what a NATed peer advertises for hole punching."""
        channel = await self._open_channel()
        try:
            await channel.send_frame(b"W")
            response = await channel.recv_frame()
            if not response.startswith(b"O"):
                raise ConnectionError(f"relay whoami failed: {response!r}")
            host, port = response[1:].decode().rsplit(":", 1)
            return host, int(port)
        finally:
            channel.close()

    async def close(self) -> None:
        if self._control_task is not None:
            self._control_task.cancel()
        if self._control is not None:
            self._control.close()
