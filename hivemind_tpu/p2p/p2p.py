"""The P2P node: listeners, dialing, handler registry, unary + streaming RPC.

Capability parity with the reference's P2P facade over the Go daemon
(hivemind/p2p/p2p_daemon.py:42-749) — minus the subprocess: transport runs in-process
on asyncio. One encrypted multiplexed TCP connection per peer pair carries all RPCs
(the reference's unary-vs-stream transport split, p2p_daemon.py:565-616 vs 412-513,
collapses into one stream mechanism; both call styles remain in the API).

NAT traversal / relays are a deployment concern of the native transport daemon
(hivemind_tpu/native, later rounds); the asyncio transport targets direct TCP.
"""

from __future__ import annotations

import asyncio
import os
from dataclasses import dataclass, field
from typing import (
    Any,
    AsyncIterator,
    Awaitable,
    Callable,
    Dict,
    List,
    Optional,
    Sequence,
    Set,
    Type,
    TypeVar,
    Union,
)

import time

from hivemind_tpu.p2p.crypto_channel import HandshakeError, handshake
from hivemind_tpu.p2p.mux import (
    Flags,
    MuxConnection,
    MuxStream,
    RemoteError,
    StreamClosedError,
)
from hivemind_tpu.p2p.peer_id import Multiaddr, PeerID
from hivemind_tpu.resilience import CHAOS as _CHAOS
from hivemind_tpu.resilience import Deadline
from hivemind_tpu.utils.crypto import Ed25519PrivateKey
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.streaming import WireParts

logger = get_logger(__name__)

TRequest = TypeVar("TRequest")
TResponse = TypeVar("TResponse")

# layer-1 telemetry (docs/observability.md): per-handler RPC latency, payload
# bytes and failures on both sides of the wire. label `side`: "server" for
# handlers this peer serves, "client" for calls it makes.
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import (
    finish_span as _finish_span,
    start_span as _start_span,
    trace as _trace,
)

_RPC_LATENCY = _TELEMETRY.histogram(
    "hivemind_p2p_rpc_latency_seconds", "wall time of one RPC", ("handler", "side")
)
_RPC_BYTES = _TELEMETRY.counter(
    "hivemind_p2p_rpc_bytes_total", "serialized RPC payload bytes", ("handler", "direction")
)
_RPC_ERRORS = _TELEMETRY.counter(
    "hivemind_p2p_rpc_errors_total", "RPCs that failed", ("handler", "side")
)

from hivemind_tpu.p2p.mux import MAX_MESSAGE_SIZE as DEFAULT_MAX_MSG_SIZE  # enforced in MuxStream.send


class P2PError(RuntimeError):
    pass


class P2PHandlerError(P2PError):
    """Raised on the client when the remote handler failed (parity: p2p_daemon.py)."""


class PeerNotFoundError(P2PError):
    pass


@dataclass
class P2PContext:
    """Passed to every RPC handler (parity: p2p/p2p_daemon.py P2PContext)."""

    handle_name: str
    local_id: PeerID
    remote_id: PeerID


@dataclass
class _Handler:
    fn: Callable[..., Any]
    request_type: Optional[Type]
    stream_input: bool
    stream_output: bool


def _parse(message_bytes: bytes, message_type: Optional[Type]):
    if message_type is None or message_type is bytes:
        return message_bytes
    message = message_type()
    message.ParseFromString(message_bytes)
    return message


def _serialize(message):
    # memoryview included: raw handlers may echo the zero-copy wire view back
    if isinstance(message, WireParts):
        return message  # scatter-gather: parts ride uncopied into the frame
    if isinstance(message, (bytes, bytearray, memoryview)):
        return bytes(message)
    return message.SerializeToString()


async def _send_payload(stream, payload) -> int:
    """Send one serialized payload (bytes or WireParts) on a stream; returns the
    byte count for the RPC accounting."""
    if isinstance(payload, WireParts):
        await stream.send(b"", *payload.parts)
        return payload.nbytes
    await stream.send(payload)
    return len(payload)


def _chaos_payload(payload):
    """Chaos corruption operates on materialized bytes; WireParts join only on
    this (test-only) path."""
    return payload.join() if isinstance(payload, WireParts) else payload


class P2P:
    """An in-process peer: listens for encrypted connections, dials peers, and routes
    named handlers. Create with ``await P2P.create(...)``."""

    def __init__(self):
        raise RuntimeError("use `await P2P.create(...)`")

    @classmethod
    async def create(
        cls,
        listen_host: str = "127.0.0.1",
        listen_port: int = 0,
        identity: Optional[Ed25519PrivateKey] = None,
        identity_path: Optional[str] = None,
        announce_host: Optional[str] = None,
        announce_port: Optional[int] = None,
        initial_peers: Sequence[Union[str, Multiaddr]] = (),
        dial_timeout: float = 10.0,
        relays: Sequence[str] = (),
        max_connections: int = 0,
        data_proxy_port: Optional[int] = None,
        data_proxy_path: Optional[str] = None,
        inbound_data_proxy: bool = False,
        native_transport: Optional[bool] = None,
    ) -> "P2P":
        """``relays``: relay daemons to register at on startup (reference parity:
        p2p_daemon.py use_relay/use_auto_relay). Each spec is ``host:port`` or
        ``<relay_pubkey_hex>@host:port`` — the pinned form refuses a relay that
        cannot prove the expected Ed25519 identity over the encrypted control
        channel. Registration makes this peer dialable through the relay; failures
        are non-fatal (logged), matching initial_peers semantics.

        ``max_connections``: connection-manager high water (reference analog:
        go-libp2p's ConnManager inside the daemon). 0 disables. Above it, idle
        (stream-less) connections are closed least-recently-used-first down to
        90% of the cap; a trimmed peer is simply re-dialed on next use. This is
        what bounds fd usage for large swarms (hundreds of DHT peers)."""
        self = object.__new__(cls)
        self._identity_lock_fd: Optional[int] = None
        if identity is None:
            if identity_path is not None:
                identity, self._identity_lock_fd = cls._load_or_create_identity(identity_path)
            else:
                identity = Ed25519PrivateKey()
        self.identity = identity
        self.peer_id = PeerID.from_private_key(identity)
        self._handlers: Dict[str, _Handler] = {}
        self._connections: Dict[PeerID, MuxConnection] = {}
        self._all_connections: Set[MuxConnection] = set()  # incl. duplicate-race losers
        self._dial_locks: Dict[PeerID, asyncio.Lock] = {}
        self._peerstore: Dict[PeerID, Set[Multiaddr]] = {}
        self._dial_timeout = dial_timeout
        # native data-plane proxy ('X' mode of the relay daemon): outbound dials
        # route through a LOCAL daemon that terminates the channel AEAD in C++
        # (reference role parity: the whole transport lives in the Go daemon,
        # p2p_daemon.py:84-147). None/0 disables; env vars are the zero-code path.
        # TRUST BOUNDARY: the 'K' upgrade hands session AEAD keys to the daemon.
        # ``data_proxy_path`` (an AF_UNIX socket the daemon creates 0600) confines
        # that hop to this user via filesystem permissions — the reference's unix-
        # domain-socket boundary (p2p_daemon.py daemon listen addr). The TCP
        # loopback ``data_proxy_port`` carries no peer credential: any local
        # process could bind or connect, so it must NOT be used on multi-user
        # hosts (advisor r4). When both are set, the unix socket wins.
        if data_proxy_path is None:
            data_proxy_path = os.environ.get("HIVEMIND_TPU_DATA_PROXY_PATH") or None
        if data_proxy_port is None:
            env_port = os.environ.get("HIVEMIND_TPU_DATA_PROXY_PORT")
            data_proxy_port = int(env_port) if env_port else None
        # zero-config native tier (the reference's default posture: the whole
        # transport terminates in its spawned daemon, p2p_daemon.py:84-147): spawn
        # a PRIVATE daemon on a 0600 unix socket and route both directions
        # through it; a failed spawn degrades to the pure-asyncio transport
        self._native_daemon = None
        if native_transport is None:  # None = env decides; explicit False wins over env
            native_transport = os.environ.get("HIVEMIND_TPU_NATIVE_TRANSPORT", "0") == "1"
        if native_transport and data_proxy_path is None and data_proxy_port is None:
            from hivemind_tpu.p2p.native_transport import spawn_native_transport

            # the spawn may BUILD the daemon (tens of seconds): keep the loop
            # live. If THIS coroutine is cancelled mid-spawn (wait_for timeout),
            # the executor thread still finishes — reap its daemon from a done
            # callback so no orphan child outlives the cancellation.
            spawn_future = asyncio.get_running_loop().run_in_executor(
                None, spawn_native_transport
            )
            try:
                self._native_daemon = await asyncio.shield(spawn_future)
            except asyncio.CancelledError:
                def _reap(fut):
                    if fut.cancelled() or fut.exception() is not None:
                        return
                    daemon = fut.result()
                    if daemon is not None:
                        daemon.shutdown()

                spawn_future.add_done_callback(_reap)
                raise
            if self._native_daemon is not None:
                data_proxy_path = self._native_daemon.unix_path
                inbound_data_proxy = True
        self._data_proxy_path = data_proxy_path or None
        self._data_proxy_port = data_proxy_port or None
        self._proxied_dials = 0  # outbound dials that actually rode the daemon
        # inbound data-plane proxy ('Y'): the DAEMON owns the public listener and
        # forwards wire conns to a loopback server here; inbound AEAD then also
        # terminates in C++ (the reference daemon owns both directions,
        # p2p_daemon.py:84-147). Requires a data proxy endpoint; falls back to
        # direct listening if the daemon refuses.
        if not inbound_data_proxy:
            inbound_data_proxy = os.environ.get("HIVEMIND_TPU_INBOUND_DATA_PROXY", "0") == "1"
        self._inbound_proxy_requested = bool(inbound_data_proxy) and (
            self._data_proxy_port is not None or self._data_proxy_path is not None
        )
        self._inbound_proxy_active = False
        self._inbound_proxy_writer: Optional[asyncio.StreamWriter] = None
        self._announce_port_from_proxy = False
        self._bg_tasks: Set[asyncio.Task] = set()  # strong refs: loop holds tasks weakly
        self._alive_refs = 1  # P2P.replicate parity: shared instance refcount
        self._peer_resolver = None  # optional async fallback route lookup (auto-relay)
        self._max_connections = max_connections
        self._shutting_down = False
        self._relays: list = []  # RelayClients registered via the `relays` kwarg
        self._listen_host = listen_host
        self._announce_host = announce_host or listen_host
        # NATed/port-forwarded deployments: the externally visible port can differ
        # from the bound one (or be closed entirely — AutoNAT then diagnoses it)
        self._announce_port = announce_port

        self._server = None
        self._requested_listen_port = listen_port
        try:
            if self._inbound_proxy_requested:
                # bind LOOPBACK only: the public listener belongs to the daemon
                self._server = await asyncio.start_server(
                    self._on_inbound_connection, "127.0.0.1", 0
                )
                local_port = self._server.sockets[0].getsockname()[1]
                public_port = await self._register_inbound_proxy(listen_port, local_port)
                if public_port is not None:
                    self._inbound_proxy_active = True
                    self._listen_port = local_port
                    if self._announce_port is None:
                        self._announce_port = public_port
                        self._announce_port_from_proxy = True
                    logger.debug(
                        f"P2P {self.peer_id} behind the daemon's inbound proxy: "
                        f"public :{public_port} -> loopback :{local_port}"
                    )
                else:
                    logger.warning(
                        "inbound data-plane proxy registration failed; "
                        "falling back to direct listening"
                    )
                    self._server.close()
                    await self._start_direct_server()
            else:
                await self._start_direct_server()
            logger.debug(f"P2P {self.peer_id} listening on {listen_host}:{self._listen_port}")

            for maddr in initial_peers:
                maddr = Multiaddr.parse(maddr) if isinstance(maddr, str) else maddr
                try:
                    await self.connect(maddr)
                except Exception as e:
                    logger.warning(f"could not reach initial peer {maddr}: {e}")

            for relay_spec in relays:
                from hivemind_tpu.p2p.relay import RelayClient

                pubkey, _, hostport = relay_spec.rpartition("@")
                relay_host, _, relay_port = hostport.rpartition(":")
                try:
                    self._relays.append(  # lint: single-writer — create() runs once
                        await RelayClient.create(
                            self, relay_host, int(relay_port), relay_pubkey=pubkey or None
                        )
                    )
                except Exception as e:
                    logger.warning(f"could not register at relay {relay_spec}: {e}")
        except BaseException:
            # any failure mid-create must not leak the listener, peer connections
            # already established, or the identity flock ("taken") for the process
            if self._server is not None:
                self._server.close()
            for relay in self._relays:
                try:
                    await asyncio.shield(relay.close())
                except BaseException:
                    pass
            for conn in list(self._all_connections):
                try:
                    await asyncio.shield(conn.close())
                except BaseException:
                    pass  # best-effort: cancellation must not strand later closes
            if self._identity_lock_fd is not None:
                os.close(self._identity_lock_fd)
            if self._native_daemon is not None:
                self._native_daemon.shutdown()
            raise
        return self

    # ------------------------------------------------------------------ identity

    @classmethod
    def generate_identity(cls, identity_path: str) -> None:
        """Write a fresh Ed25519 identity file (parity: p2p_daemon.py generate_identity)."""
        key = Ed25519PrivateKey()
        fd = os.open(identity_path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "wb") as f:
            f.write(key.to_bytes())

    class IdentityTakenError(RuntimeError):
        """Another live process already uses this identity file."""

    @staticmethod
    def _load_or_create_identity(identity_path: str):
        """Open-or-create the identity file, flock it for this P2P's lifetime, then
        read (or first-write) the key through the SAME descriptor.

        Capability parity with the reference's ``is_identity_taken`` probe
        (p2p_daemon.py): two peers sharing one identity make the swarm misroute to
        whichever connected last. The reference detects the collision by dialing the
        swarm; single-host collisions (the common operator mistake — two servers
        started with the same --identity_path) are caught earlier and determin-
        istically by an OS file lock, released automatically if the process dies.
        Locking BEFORE writing means two simultaneous first-time creates cannot
        truncate each other's key; a pre-provisioned read-only key file (e.g. a
        mounted secret) is opened read-only — flock works on those descriptors too.

        :returns: (identity, locked fd)"""
        import fcntl

        try:
            fd = os.open(identity_path, os.O_RDWR | os.O_CREAT, 0o600)
        except PermissionError:
            fd = os.open(identity_path, os.O_RDONLY)  # read-only provisioned key
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
        except BlockingIOError:
            os.close(fd)
            raise P2P.IdentityTakenError(
                f"identity file {identity_path!r} is locked by another live process; "
                f"two peers must not share one identity"
            )
        except OSError:
            os.close(fd)  # e.g. ENOLCK on lockless network mounts: NOT a duplicate peer
            raise
        try:
            existing = os.pread(fd, 4096, 0)
            if existing:
                return Ed25519PrivateKey.from_bytes(existing), fd
            identity = Ed25519PrivateKey()
            os.pwrite(fd, identity.to_bytes(), 0)
            return identity, fd
        except BaseException:
            os.close(fd)
            raise

    async def replicate(self) -> "P2P":
        """The reference attaches extra clients to one daemon (p2p_daemon.py:replicate);
        in-process, components simply share this instance."""
        self._alive_refs += 1
        return self

    def get_visible_maddrs(self, latest: bool = False) -> List[Multiaddr]:
        port = self._announce_port if self._announce_port is not None else self._listen_port
        return [Multiaddr(self._announce_host, port, self.peer_id)]

    @property
    def listen_port(self) -> int:
        return self._listen_port

    # ------------------------------------------------------------------ connections

    async def _start_direct_server(self) -> None:
        """Bind the ordinary public listener (initial create, proxy-registration
        failure, and daemon-death fallback all share this)."""
        self._server = await asyncio.start_server(
            self._on_inbound_connection, self._listen_host, self._requested_listen_port
        )
        self._listen_port = self._server.sockets[0].getsockname()[1]

    async def _open_daemon_connection(self):
        """One framed connection to the local proxy daemon (unix socket wins)."""
        if self._data_proxy_path is not None:
            return await asyncio.open_unix_connection(self._data_proxy_path)
        return await asyncio.open_connection("127.0.0.1", self._data_proxy_port)

    async def _register_inbound_proxy(self, public_port: int, local_port: int) -> Optional[int]:
        """Ask the daemon to own our PUBLIC listener ('Y' frame) and forward wire
        conns to ``local_port``; returns the actual public port, or None on
        refusal. The control connection stays open — the daemon ties the
        listener's lifetime to it."""
        import struct

        writer = None
        registered = failed = False
        # ONE dial_timeout budget for the whole registration handshake instead of
        # three stacked hard-coded 5 s waits: a slow host gets the full configured
        # budget, and the worst case can no longer add up to 3x the intended wait
        budget = Deadline(self._dial_timeout)
        try:
            reader, writer = await budget.wait_for(self._open_daemon_connection())
            request = b"Y" + struct.pack(">HH", public_port, local_port)
            writer.write(struct.pack(">I", len(request)) + request)
            await writer.drain()
            header = await budget.wait_for(reader.readexactly(4))
            (length,) = struct.unpack(">I", header)
            response = await budget.wait_for(reader.readexactly(length))
            if len(response) == 3 and response[0:1] == b"O":
                self._inbound_proxy_writer = writer
                registered = True
                # the daemon ties the public listener to this conn: watch it —
                # a daemon crash otherwise leaves us announcing a dead port
                # forever while outbound dials keep working and mask the loss
                watchdog = spawn(self._watch_inbound_proxy(reader), name="p2p.inbound_proxy_watchdog")
                self._bg_tasks.add(watchdog)
                watchdog.add_done_callback(self._bg_tasks.discard)
                return struct.unpack(">H", response[1:3])[0]
            # a well-formed non-'O' reply is an expected REFUSAL, not an error
        except (ConnectionError, OSError, asyncio.TimeoutError, asyncio.IncompleteReadError) as e:
            failed = True
            logger.debug(f"inbound proxy registration failed: {e!r}")
        finally:
            # a registration that did not become the control conn must ALWAYS
            # close its writer — a mid-handshake timeout/refusal otherwise leaks
            # the daemon connection for the process lifetime (ADVICE r5). Only
            # genuine mid-handshake failures count toward the error metric
            # (refusals and cancellations are expected outcomes).
            if writer is not None and not registered:
                if failed:
                    _RPC_ERRORS.inc(handler="_register_inbound_proxy", side="client")
                writer.close()
        return None

    async def _watch_inbound_proxy(self, reader: asyncio.StreamReader) -> None:
        """EOF on the 'Y' control conn means the daemon (and our public listener)
        died: fall back to DIRECT listening and re-announce, loudly."""
        try:
            while await reader.read(4096):
                pass  # the daemon sends nothing after 'O'; drain defensively
        except (ConnectionError, OSError, asyncio.CancelledError):
            pass
        if self._shutting_down or not self._inbound_proxy_active:
            return
        logger.warning(
            "the data-plane proxy daemon died: its public listener is gone; "
            "falling back to a DIRECT listener and re-announcing"
        )
        self._inbound_proxy_active = False
        self._inbound_proxy_writer = None
        if self._announce_port_from_proxy:
            self._announce_port = None
            self._announce_port_from_proxy = False
        old_server = self._server
        try:
            await self._start_direct_server()
        except OSError as e:
            logger.error(f"direct-listener fallback failed: {e!r}; this peer is undialable")
            return
        if old_server is not None:
            old_server.close()  # in-flight loopback conns finish on their transports
        logger.warning(f"now listening directly on {self._listen_host}:{self._listen_port}")

    async def _on_inbound_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        if self._shutting_down:
            writer.close()
            return
        try:
            channel, extras = await handshake(
                reader, writer, self.identity, is_initiator=False,
                announced_addrs=self.get_visible_maddrs(),
                # behind the daemon's listener EVERY inbound conn is a proxy
                # local leg: hand it the session keys and go plaintext here
                proxy_upgrade=self._inbound_proxy_active,
            )
        except (HandshakeError, asyncio.TimeoutError, asyncio.IncompleteReadError, ConnectionError, OSError) as e:
            logger.debug(f"inbound handshake failed: {e!r}")
            writer.close()
            return
        from hivemind_tpu.utils.crypto import Ed25519PublicKey

        peer_id = PeerID.from_public_key(Ed25519PublicKey.from_bytes(extras["static"]))
        if self._shutting_down:
            # a dial (e.g. a hole punch) that completed its handshake mid-shutdown:
            # an untracked live connection here would park Server.wait_closed forever
            channel.close()
            return
        self._register_peer_addrs(peer_id, extras.get("addrs", ()))
        self._prune_dead_connections()
        conn = MuxConnection(channel, peer_id, is_initiator=False, on_inbound_stream=self._route_stream)
        existing = self._connections.get(peer_id)
        if existing is None or existing.is_closed:
            self._connections[peer_id] = conn  # replace stale connections with the live one
        # duplicate-race losers still serve the dialer's streams, and must be tracked
        # so shutdown() can close them
        self._all_connections.add(conn)
        conn.start()
        await self._trim_connections(protect=conn)

    async def _trim_connections(self, protect: Optional[MuxConnection] = None) -> None:
        """Connection manager (see ``create``): close idle LRU connections past the
        high water mark. Never touches connections with live streams, nor relayed
        circuits (their route may not be re-dialable without the relay client
        that created them)."""
        if not self._max_connections:
            return
        self._prune_dead_connections()  # dead entries must not count toward the marks
        if len(self._all_connections) <= self._max_connections:
            return
        low_water = max(int(self._max_connections * 0.9), 1)
        idle = sorted(
            (
                conn
                for conn in self._all_connections
                if conn is not protect
                and not conn.is_closed
                and conn.num_streams == 0
                and not getattr(conn, "is_relayed", False)
            ),
            key=lambda conn: conn.last_used,
        )
        for conn in idle:
            if len(self._all_connections) <= low_water:
                break
            await conn.close()
            self._all_connections.discard(conn)  # lint: single-writer — guarded `is conn` del + idempotent discard
            if self._connections.get(conn.peer_id) is conn:
                del self._connections[conn.peer_id]  # lint: single-writer — guarded `is conn` del + idempotent discard

    def _register_peer_addrs(self, peer_id: PeerID, addrs) -> None:
        store = self._peerstore.setdefault(peer_id, set())
        for addr in addrs:
            try:
                store.add(Multiaddr.parse(addr) if isinstance(addr, str) else addr)
            except ValueError:
                continue

    def add_peer_addr(self, peer_id: PeerID, maddr: Union[str, Multiaddr]) -> None:
        self._register_peer_addrs(peer_id, [maddr])

    async def connect(self, maddr: Union[str, Multiaddr]) -> PeerID:
        """Dial an address; returns the authenticated PeerID behind it."""
        maddr = Multiaddr.parse(maddr) if isinstance(maddr, str) else maddr
        conn = await self._dial(maddr, expected_peer=maddr.peer_id)
        return conn.peer_id

    _DIALABLE_PROTOS = frozenset({"ip4", "ip6", "dns", "dns4", "dns6"})

    async def _dial(
        self, maddr: Multiaddr, expected_peer: Optional[PeerID], replace_existing: bool = False
    ) -> MuxConnection:
        """Dial one address. With ``replace_existing`` a live connection to the same
        peer is superseded for FUTURE streams (hole-punch upgrade: the direct path
        replaces the relayed one; in-flight streams finish on the old connection)."""
        if maddr.host_proto not in self._DIALABLE_PROTOS:
            # peer-announced unix/onion3 addresses parse (codec parity) but the
            # TCP transport cannot reach them — fail INSTANTLY so an attacker
            # announcing them cannot burn a dial timeout per reconnect attempt
            raise ConnectionError(f"no transport for {maddr.host_proto!r} address {maddr}")
        via_proxy = self._data_proxy_port is not None or self._data_proxy_path is not None
        if via_proxy:
            try:
                reader, writer = await asyncio.wait_for(
                    self._open_proxied_connection(maddr.host, maddr.port),
                    timeout=self._dial_timeout,
                )
            except (ConnectionError, OSError, asyncio.TimeoutError) as e:
                # the proxy is an optimization, not a reachability requirement:
                # degrade to a direct dial rather than failing an address a plain
                # socket could reach
                logger.debug(
                    f"data-plane proxy dial to {maddr.host}:{maddr.port} failed "
                    f"({e!r}); falling back to a direct dial"
                )
                via_proxy = False
        if not via_proxy:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(maddr.host, maddr.port), timeout=self._dial_timeout
            )
        try:
            channel, extras = await handshake(
                reader, writer, self.identity, is_initiator=True,
                announced_addrs=self.get_visible_maddrs(),
                proxy_upgrade=via_proxy,
            )
        except BaseException:
            writer.close()
            raise
        from hivemind_tpu.utils.crypto import Ed25519PublicKey

        peer_id = PeerID.from_public_key(Ed25519PublicKey.from_bytes(extras["static"]))
        if expected_peer is not None and peer_id != expected_peer:
            channel.close()
            raise HandshakeError(f"dialed {expected_peer} but found {peer_id}")
        self._register_peer_addrs(peer_id, [maddr.with_peer_id(peer_id)])
        self._register_peer_addrs(peer_id, extras.get("addrs", ()))
        existing = self._connections.get(peer_id)
        if existing is not None and not existing.is_closed:
            if not replace_existing:
                channel.close()
                return existing
            # superseded (e.g. relayed) connection: let in-flight streams finish,
            # then close it — otherwise every punch upgrade leaks a socket on both
            # ends plus a spliced pair on the relay
            self._close_after_grace(existing)
        conn = MuxConnection(channel, peer_id, is_initiator=True, on_inbound_stream=self._route_stream)
        self._connections[peer_id] = conn
        self._all_connections.add(conn)
        conn.start()
        await self._trim_connections(protect=conn)
        return conn

    async def _open_proxied_connection(self, host: str, port: int):
        """Open an outbound connection THROUGH the local native data-plane proxy:
        'X' <port><host> to the daemon, wait for 'O', then the stream behaves like
        a direct socket (the daemon forwards; the AEAD moves into it after the
        handshake's 'K' upgrade — see crypto_channel.handshake proxy_upgrade)."""
        import socket as socket_module
        import struct

        try:
            socket_module.inet_aton(host)
        except OSError:
            # the daemon's 'X' handler takes IPv4 literals only: resolve dns/ip6
            # hosts here (first IPv4 answer) before shipping the target
            infos = await asyncio.get_running_loop().getaddrinfo(
                host, port, family=socket_module.AF_INET, type=socket_module.SOCK_STREAM
            )
            if not infos:
                raise ConnectionError(f"no IPv4 address for {host!r} (data-plane proxy is IPv4-only)")
            host = infos[0][4][0]
        # the 0600 unix socket is the key-handoff trust boundary (see create)
        reader, writer = await self._open_daemon_connection()
        request = b"X" + struct.pack(">H", port) + host.encode()
        writer.write(struct.pack(">I", len(request)) + request)
        await writer.drain()
        header = await reader.readexactly(4)
        (length,) = struct.unpack(">I", header)
        response = await reader.readexactly(length)
        if response != b"O":
            writer.close()
            raise ConnectionError(
                f"data-plane proxy could not reach {host}:{port} (reply {response!r})"
            )
        self._proxied_dials += 1
        return reader, writer

    def _close_after_grace(self, conn: MuxConnection, grace: float = 30.0) -> None:
        """Close a superseded connection once in-flight streams have had time to
        finish. The task is held strongly (the loop keeps only weak task refs)."""

        async def _close():
            await asyncio.sleep(grace)
            await conn.close()

        task = spawn(_close(), name="p2p.close_after_grace")
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)

    def _prune_dead_connections(self) -> None:
        dead = [c for c in self._all_connections if c.is_closed]
        for conn in dead:
            self._all_connections.discard(conn)
            if self._connections.get(conn.peer_id) is conn:
                del self._connections[conn.peer_id]

    async def _get_connection(self, peer_id: PeerID) -> MuxConnection:
        self._prune_dead_connections()
        conn = self._connections.get(peer_id)
        if conn is not None and not conn.is_closed:
            return conn
        lock = self._dial_locks.setdefault(peer_id, asyncio.Lock())
        async with lock:
            conn = self._connections.get(peer_id)
            if conn is not None and not conn.is_closed:
                return conn
            last_error: Optional[Exception] = None
            for maddr in sorted(self._peerstore.get(peer_id, ()), key=str):
                try:
                    return await self._dial(maddr, expected_peer=peer_id)
                except Exception as e:
                    last_error = e
            if self._peer_resolver is not None:
                # no direct route: ask the installed resolver (auto-relay finds the
                # target's published circuits in the DHT and dials through a relay)
                try:
                    conn = await self._peer_resolver(peer_id)
                except Exception as e:
                    conn = None
                    last_error = e
                if conn is not None and not conn.is_closed:
                    return conn
            raise PeerNotFoundError(f"no reachable address for {peer_id}") from last_error

    def set_peer_resolver(self, resolver) -> None:
        """Install an async ``fn(peer_id) -> Optional[MuxConnection]`` used when no
        direct address works (reference analog: the daemon's peer routing + relays,
        p2p_daemon.py:114-137). Pass None to remove."""
        self._peer_resolver = resolver

    # ------------------------------------------------------------------ handlers

    async def add_protobuf_handler(
        self,
        name: str,
        handler: Callable[..., Any],
        request_type: Optional[Type] = None,
        *,
        stream_input: bool = False,
        stream_output: bool = False,
    ) -> None:
        """Register a named handler. Unary: ``async fn(request, context) -> response``.
        Stream input: request is an AsyncIterator. Stream output: fn returns/yields an
        AsyncIterator of responses."""
        if name in self._handlers:
            raise P2PError(f"handler {name!r} is already registered")
        self._handlers[name] = _Handler(handler, request_type, stream_input, stream_output)

    async def remove_protobuf_handler(self, name: str) -> None:
        self._handlers.pop(name, None)

    async def _route_stream(self, stream: MuxStream) -> None:
        handler = self._handlers.get(stream.handler_name)
        if handler is None:
            # fixed label: the name is remote-controlled, and label values live
            # forever — a peer cycling fake names must not grow the registry
            _RPC_ERRORS.inc(handler="<unknown>", side="server")
            await stream.send_error(P2PHandlerError(f"unknown handler {stream.handler_name!r}"))
            await stream.close_send()
            return
        context = P2PContext(stream.handler_name, self.peer_id, stream.peer_id)
        started = time.perf_counter()
        bytes_in = bytes_out = 0
        # the OPEN frame may carry the remote caller's trace context: this
        # handler span then joins the caller's trace as a child, which is what
        # makes a cross-peer timeline reconstructable from per-peer recorders
        handler_trace = _trace(
            f"p2p.handle:{stream.handler_name}",
            remote_context=stream.trace_context,
            peer=str(self.peer_id),
            remote=str(stream.peer_id),
        )
        handler_trace.__enter__()
        try:
            if handler.stream_input:
                async def _counted_stream():
                    nonlocal bytes_in
                    async for message in stream.iter_messages():
                        bytes_in += len(message)
                        yield _parse(message, handler.request_type)

                request: Any = _counted_stream()
            else:
                raw_request = await stream.receive()
                bytes_in += len(raw_request)
                request = _parse(raw_request, handler.request_type)

            if handler.stream_output:
                result = handler.fn(request, context)
                if asyncio.iscoroutine(result):
                    result = await result
                async for response in result:
                    bytes_out += await _send_payload(stream, _serialize(response))
            else:
                response = await handler.fn(request, context)
                bytes_out += await _send_payload(stream, _serialize(response))
            await stream.close_send()
        except StreamClosedError:
            return  # peer reset/vanished mid-call: normal termination for a handler
        except asyncio.CancelledError:
            raise
        except Exception as e:
            _RPC_ERRORS.inc(handler=stream.handler_name, side="server")
            if handler_trace.span is not None:
                handler_trace.span.add_event("error", type=type(e).__name__)
            logger.debug(f"handler {stream.handler_name} failed: {e!r}")
            try:
                await stream.send_error(e)
                await stream.close_send()
            except StreamClosedError:
                pass
        finally:
            handler_trace.__exit__(None, None, None)
            _RPC_LATENCY.observe(time.perf_counter() - started, handler=stream.handler_name, side="server")
            if bytes_in:
                _RPC_BYTES.inc(bytes_in, handler=stream.handler_name, direction="in")
            if bytes_out:
                _RPC_BYTES.inc(bytes_out, handler=stream.handler_name, direction="out")

    # ------------------------------------------------------------------ calls

    async def _open_stream_with_redial(
        self, peer_id: PeerID, name: str, trace_context: Optional[bytes] = None
    ) -> MuxStream:
        """Open a stream, re-dialing once if the cached connection died between
        lookup and use (e.g. the connection manager trimmed it, or the peer
        restarted) — a trimmed idle connection must look like a cache miss, not
        an RPC failure."""
        conn = await self._get_connection(peer_id)
        try:
            return await conn.open_stream(name, trace_context)
        except StreamClosedError:
            conn = await self._get_connection(peer_id)
            return await conn.open_stream(name, trace_context)

    async def call_protobuf_handler(
        self,
        peer_id: PeerID,
        name: str,
        request,
        response_type: Optional[Type] = None,
        *,
        idempotent: bool = False,
    ):
        """Unary call: one request, one response.

        A failure while opening the stream or sending the request provably precedes
        delivery, so it is always retried once on a fresh connection (the LRU trim /
        peer-restart race). A failure while *waiting for the response* does not prove
        the handler never ran — the connection can die after the handler executed but
        before the response arrived — so that retry is gated on ``idempotent``:
        side-effectful calls (rpc_backward, rpc_decode) must fail loudly rather than
        risk double-applying an optimizer step or double-advancing a KV cache.
        """
        payload = _serialize(request)
        started = time.perf_counter()
        # client span: a child of whatever operation issued this RPC; its
        # (trace_id, span_id) ride the OPEN frame so the remote handler span
        # joins the same trace one level down. The with block (not manual
        # enter/exit) so a failed call carries its `error` event.
        with _trace(f"p2p.call:{name}", peer=str(self.peer_id), remote=str(peer_id)) as call_span:
            try:
                if _CHAOS.enabled:  # injection point: drop/delay/corrupt the outbound request
                    payload = await _CHAOS.inject(
                        "p2p.unary.send", payload=_chaos_payload(payload), scope=str(self.peer_id)
                    )
                for attempt in range(2):
                    stream = await self._open_stream_with_redial(
                        peer_id, name, None if call_span is None else call_span.context_bytes()
                    )
                    try:
                        try:
                            payload_len = await _send_payload(stream, payload)
                            await stream.close_send()
                        except StreamClosedError:
                            # the request never left: safe to retry for any RPC
                            if attempt == 0:
                                continue
                            raise P2PHandlerError(f"{name}: connection closed before request was sent") from None
                        try:
                            response = await stream.receive()
                        except RemoteError as e:
                            raise P2PHandlerError(str(e)) from e
                        except StreamClosedError:
                            # nothing was received, but the request WAS sent: the peer may
                            # or may not have processed it. Only retry when the caller
                            # declared the RPC idempotent (reads: rpc_info, DHT ping/find,
                            # or set-semantics writes like rpc_store).
                            if idempotent and attempt == 0 and stream._conn.is_closed:
                                continue
                            raise P2PHandlerError(
                                f"{name}: stream closed before response"
                                + ("" if idempotent else " (not retried: RPC not marked idempotent)")
                            ) from None
                        if _CHAOS.enabled:  # injection point: lose/corrupt the response
                            response = await _CHAOS.inject(
                                "p2p.unary.recv", payload=response, scope=str(self.peer_id)
                            )
                        _RPC_BYTES.inc(payload_len, handler=name, direction="out")
                        _RPC_BYTES.inc(len(response), handler=name, direction="in")
                        return _parse(response, response_type)
                    finally:
                        await stream.reset()
            except asyncio.CancelledError:
                raise
            except BaseException:
                _RPC_ERRORS.inc(handler=name, side="client")
                raise
            finally:
                _RPC_LATENCY.observe(time.perf_counter() - started, handler=name, side="client")

    async def iterate_protobuf_handler(
        self,
        peer_id: PeerID,
        name: str,
        requests,
        response_type: Optional[Type] = None,
    ) -> AsyncIterator:
        """Streaming call: ``requests`` is one message or an async iterator of them;
        yields response messages until the remote closes."""
        # a detached span (start_span, not trace): an async generator's body runs
        # in its consumer's context, so installing a contextvar here would leak
        # the span into the consumer between yields. It still parents to the
        # caller's current span and propagates its context to the remote handler.
        stream_span = _start_span(
            f"p2p.stream:{name}", peer=str(self.peer_id), remote=str(peer_id)
        )
        stream = await self._open_stream_with_redial(
            peer_id, name, None if stream_span is None else stream_span.context_bytes()
        )

        async def _feed():
            nonlocal bytes_out
            try:
                if hasattr(requests, "__aiter__"):
                    async for request in requests:
                        payload = _serialize(request)
                        if _CHAOS.enabled:  # injection point: per streamed request message
                            payload = await _CHAOS.inject(
                                "p2p.stream.send", payload=_chaos_payload(payload), scope=str(self.peer_id)
                            )
                        bytes_out += await _send_payload(stream, payload)
                else:
                    payload = _serialize(requests)
                    if _CHAOS.enabled:
                        payload = await _CHAOS.inject(
                            "p2p.stream.send", payload=_chaos_payload(payload), scope=str(self.peer_id)
                        )
                    bytes_out += await _send_payload(stream, payload)
                await stream.close_send()
            except (StreamClosedError, asyncio.CancelledError):
                pass
            except Exception:
                # the caller's request iterator failed: abort so neither side hangs;
                # the exception is re-raised to the consumer below via feeder.exception()
                await stream.reset()
                raise

        started = time.perf_counter()
        bytes_in = bytes_out = 0
        feeder = asyncio.create_task(_feed())
        try:
            while True:
                try:
                    message = await stream.receive()
                except StreamClosedError:
                    if feeder.done() and not feeder.cancelled() and feeder.exception() is not None:
                        _RPC_ERRORS.inc(handler=name, side="client")
                        raise feeder.exception()
                    return
                except RemoteError as e:
                    _RPC_ERRORS.inc(handler=name, side="client")
                    raise P2PHandlerError(str(e)) from e
                if _CHAOS.enabled:  # injection point: per streamed response message
                    message = await _CHAOS.inject(
                        "p2p.stream.recv", payload=message, scope=str(self.peer_id)
                    )
                bytes_in += len(message)
                yield _parse(message, response_type)
        finally:
            feeder.cancel()
            _finish_span(stream_span)
            _RPC_LATENCY.observe(time.perf_counter() - started, handler=name, side="client")
            if bytes_in:
                _RPC_BYTES.inc(bytes_in, handler=name, direction="in")
            if bytes_out:
                _RPC_BYTES.inc(bytes_out, handler=name, direction="out")
            await stream.reset()

    # ------------------------------------------------------------------ lifecycle

    async def list_peers(self) -> List[PeerID]:
        return [pid for pid, conn in self._connections.items() if not conn.is_closed]

    async def disconnect(self, peer_id: PeerID) -> None:
        conn = self._connections.pop(peer_id, None)
        if conn is not None:
            await conn.close()

    async def shutdown(self) -> None:
        self._alive_refs -= 1
        if self._alive_refs > 0:
            return
        self._shutting_down = True
        self._server.close()
        if self._inbound_proxy_writer is not None:
            # closing the control conn tears down the daemon's public listener
            self._inbound_proxy_writer.close()
            self._inbound_proxy_writer = None
        if self._native_daemon is not None:
            self._native_daemon.shutdown()
            self._native_daemon = None
        for relay in self._relays:
            await relay.close()
        self._relays.clear()
        for task in list(self._bg_tasks):
            task.cancel()
        # loop until drained: a connection may land (accepted before server.close,
        # e.g. a peer's hole-punch dial) while earlier closes are awaited
        while self._all_connections:
            for conn in list(self._all_connections):
                await conn.close()
                self._all_connections.discard(conn)  # lint: single-writer — shutdown runs once
        self._connections.clear()
        try:
            # py3.12 wait_closed waits for every server-spawned transport; a peer
            # whose handshake is still mid-flight holds one open, so bound the wait
            await asyncio.wait_for(self._server.wait_closed(), timeout=3.0)
        except Exception:
            pass
        if self._identity_lock_fd is not None:
            os.close(self._identity_lock_fd)  # releases the identity flock
            self._identity_lock_fd = None

    def __repr__(self):
        return f"P2P({self.peer_id}, port={self._listen_port}, handlers={len(self._handlers)})"
