"""Message-oriented stream multiplexing over one secure channel per peer pair.

The reference gets multiplexing from go-libp2p (yamux/mplex inside the daemon) plus a
persistent control connection for unary calls (p2p_daemon_bindings/control.py:172-311).
Here both collapse into one mechanism: lightweight in-process streams over a single
encrypted TCP connection. Frames are whole messages (an RPC message = one frame), which
removes the reference's 8-byte-header + marker reframing layer entirely.

Mux frame layout (inside the AEAD envelope): [u64 stream_id][u8 flags][payload].
Flags: OPEN (payload = handler name utf-8, optionally followed by NUL + a 16-byte
trace context — handler names never contain NUL), DATA (payload = message), CLOSE
(graceful end-of-stream from that side), RESET (abort), ERROR (payload = msgpack
error info). The trace context (telemetry/tracing.py pack_context) is how a
server-side handler span becomes a child of the remote caller's span; absent
when the caller has no active span, ignored when malformed.
Flow control: per-stream inboxes are unbounded (the read loop never head-of-line-blocks
one stream on another), with a per-connection buffered-bytes cap as the memory backstop
— a peer that overruns it loses the connection, not the process. TCP backpressure plus
eager reads in the RPC layer keep buffers small in practice.
"""

from __future__ import annotations

import asyncio
import struct
import time
from enum import IntFlag
from typing import AsyncIterator, Awaitable, Callable, Dict, Optional, Union

# what receive() yields: bytes for locally-generated items, a zero-copy memoryview
# of the decrypted wire frame for DATA payloads
Message = Union[bytes, memoryview]

from hivemind_tpu.p2p.crypto_channel import SecureChannel
from hivemind_tpu.telemetry.tracing import unpack_context
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)

_HEADER = struct.Struct(">QB")

# one RPC message per frame; larger payloads must be chunked by the caller
# (parity: reference DEFAULT_MAX_MSG_SIZE, p2p_daemon_bindings/control.py:36-39)
MAX_MESSAGE_SIZE = 4 * 1024 * 1024


class Flags(IntFlag):
    OPEN = 1
    DATA = 2
    CLOSE = 4
    RESET = 8
    ERROR = 16


class StreamClosedError(ConnectionError):
    """The stream (or its connection) closed before the operation completed."""


class RemoteError(RuntimeError):
    """The remote handler raised an exception; carries its type name and message."""

    def __init__(self, type_name: str, message: str):
        super().__init__(f"{type_name}: {message}")
        self.type_name = type_name
        self.remote_message = message


_EOF = object()


class MuxStream:
    """One bidirectional message stream. ``send``/``receive`` whole byte messages.

    Inboxes are unbounded so the connection read loop never head-of-line-blocks on a
    slow consumer; memory is bounded per connection (``MuxConnection.max_buffered_bytes``)
    — exceeding it kills the whole connection rather than stalling unrelated streams.
    """

    def __init__(self, conn: "MuxConnection", stream_id: int, handler_name: str):
        self._conn = conn
        self.stream_id = stream_id
        self.handler_name = handler_name
        self.trace_context = None  # (trace_id, span_id) from the remote OPEN, if any
        self._inbox: asyncio.Queue = asyncio.Queue()
        self._recv_closed = False
        self._send_closed = False
        self._reset = False
        self._inbox_bytes = 0  # bytes currently debited against the connection cap

    @property
    def peer_id(self):
        return self._conn.peer_id

    async def send(self, message: bytes, *extra: bytes) -> None:
        """Send one message; ``extra`` buffers travel scatter-gather with it as a
        single frame (a spliced protobuf's tensor buffers ride uncopied into the
        AEAD — the serving-path analog of the averaging framing)."""
        if self._send_closed or self._reset:
            raise StreamClosedError(f"stream {self.stream_id} is closed for sending")
        total = len(message) + sum(len(part) for part in extra)
        if total > MAX_MESSAGE_SIZE:
            raise ValueError(
                f"message of {total} bytes exceeds MAX_MESSAGE_SIZE={MAX_MESSAGE_SIZE}; "
                f"split large tensors with utils.streaming.split_for_streaming"
            )
        await self._conn.send_frame(self.stream_id, Flags.DATA, message, *extra)

    async def send_error(self, exc: BaseException) -> None:
        if self._send_closed or self._reset:
            return
        payload = MSGPackSerializer.dumps({"type": type(exc).__name__, "message": str(exc)})
        await self._conn.send_frame(self.stream_id, Flags.ERROR, payload)

    async def close_send(self) -> None:
        """Half-close: no more messages from this side."""
        if not self._send_closed and not self._reset:
            self._send_closed = True
            try:
                await self._conn.send_frame(self.stream_id, Flags.CLOSE, b"")
            except (ConnectionError, StreamClosedError):
                pass

    async def reset(self) -> None:
        if not self._reset:
            self._reset = True
            self._send_closed = True
            try:
                await self._conn.send_frame(self.stream_id, Flags.RESET, b"")
            except (ConnectionError, StreamClosedError):
                pass
            self._push_eof()
            self._conn._forget_stream(self.stream_id)

    async def receive(self) -> Message:
        """Next message (bytes-like: may be a zero-copy memoryview of the wire
        frame); raises StreamClosedError at end-of-stream, RemoteError if the peer's
        handler failed."""
        if self._recv_closed:
            raise StreamClosedError(f"stream {self.stream_id}: receive side closed")
        item = await self._inbox.get()
        if isinstance(item, (bytes, bytearray, memoryview)) and self._inbox_bytes > 0:
            self._inbox_bytes -= len(item)
            self._conn._credit_bytes(len(item))
        if item is _EOF:
            self._recv_closed = True
            raise StreamClosedError(f"stream {self.stream_id} ended")
        if isinstance(item, RemoteError):
            self._recv_closed = True
            raise item
        return item

    async def __aiter__(self) -> AsyncIterator[Message]:
        while True:
            try:
                yield await self.receive()
            except StreamClosedError:
                return

    def iter_messages(self) -> AsyncIterator[Message]:
        return self.__aiter__()

    def _push(self, item) -> None:
        if isinstance(item, (bytes, bytearray, memoryview)):
            self._inbox_bytes += len(item)
        self._inbox.put_nowait(item)  # unbounded: never blocks the read loop

    def _push_eof(self) -> None:
        self._inbox.put_nowait(_EOF)

    def _return_credit(self) -> None:
        """Credit back all undrained inbox bytes (stream reset/forgotten)."""
        if self._inbox_bytes > 0:
            self._conn._credit_bytes(self._inbox_bytes)
            self._inbox_bytes = 0


class MuxConnection:
    """All streams between this node and one peer, over one SecureChannel."""

    def __init__(
        self,
        channel: SecureChannel,
        peer_id,
        is_initiator: bool,
        on_inbound_stream: Callable[[MuxStream], Awaitable[None]],
        max_buffered_bytes: int = 256 * 1024 * 1024,
    ):
        self._channel = channel
        self.peer_id = peer_id
        self._next_stream_id = 1 if is_initiator else 2
        self._streams: Dict[int, MuxStream] = {}
        self._on_inbound_stream = on_inbound_stream
        self._closed = False
        self._read_task: Optional[asyncio.Task] = None
        self._handler_tasks: set = set()
        # stream_id -> running inbound handler task: a peer's RESET cancels the
        # handler MID-COMPUTE (ISSUE 13 hedged requests: the losing server must
        # stop working on an answer nobody will read, not just fail its send)
        self._stream_handler_tasks: Dict[int, asyncio.Task] = {}
        self._buffered_bytes = 0
        self._max_buffered_bytes = max_buffered_bytes
        self.last_used = time.monotonic()  # LRU key for the connection manager

    def _credit_bytes(self, nbytes: int) -> None:
        self._buffered_bytes -= nbytes

    def start(self) -> None:
        self._read_task = spawn(self._read_loop(), name="mux.read_loop")

    @property
    def is_closed(self) -> bool:
        return self._closed

    async def open_stream(
        self, handler_name: str, trace_context: Optional[bytes] = None
    ) -> MuxStream:
        if self._closed:
            raise StreamClosedError(f"connection to {self.peer_id} is closed")
        stream_id = self._next_stream_id
        self._next_stream_id += 2
        stream = MuxStream(self, stream_id, handler_name)
        self._streams[stream_id] = stream
        if trace_context is not None:
            await self.send_frame(
                stream_id, Flags.OPEN, handler_name.encode("utf-8"), b"\x00", trace_context
            )
        else:
            await self.send_frame(stream_id, Flags.OPEN, handler_name.encode("utf-8"))
        return stream

    @property
    def num_streams(self) -> int:
        return len(self._streams)

    async def send_frame(self, stream_id: int, flags: Flags, *payload: bytes) -> None:
        """Send one frame; the payload may arrive as several buffers which travel
        scatter-gather all the way into the AEAD (no header+payload concat here)."""
        if self._closed:
            raise StreamClosedError(f"connection to {self.peer_id} is closed")
        self.last_used = time.monotonic()
        try:
            await self._channel.send(_HEADER.pack(stream_id, int(flags)), *payload)
        except (ConnectionError, OSError, asyncio.IncompleteReadError) as e:
            await self._shutdown(e)
            raise StreamClosedError(f"connection to {self.peer_id} lost: {e}") from e

    async def _read_loop(self) -> None:
        error: Optional[BaseException] = None
        try:
            while True:
                frame = await self._channel.recv()
                stream_id, flags = _HEADER.unpack_from(frame)
                # zero-copy: DATA payloads ride to their consumer as a view of the
                # decrypted frame instead of re-materializing frame[9:] per message
                payload = memoryview(frame)[_HEADER.size :]
                await self._dispatch(stream_id, Flags(flags), payload)
        except (ConnectionError, OSError, asyncio.IncompleteReadError, EOFError) as e:
            error = e
        except asyncio.CancelledError:
            raise
        except Exception as e:
            logger.warning(f"connection to {self.peer_id}: read loop failed with {e!r}")
            error = e
        finally:
            await self._shutdown(error)

    async def _dispatch(self, stream_id: int, flags: Flags, payload) -> None:
        # ``payload`` is a memoryview into the decrypted frame; the rare control
        # frames (OPEN/ERROR) materialize it, DATA frames pass the view through
        self.last_used = time.monotonic()
        if flags & Flags.OPEN:
            # a remote OPEN must use the REMOTE side's id parity and a fresh id: a
            # misbehaving peer reusing a local-parity or existing id would silently
            # replace a live stream in _streams, misrouting its responses and
            # orphaning its credit accounting
            if stream_id % 2 == self._next_stream_id % 2 or stream_id in self._streams:
                logger.warning(
                    f"connection to {self.peer_id}: rejecting OPEN with "
                    f"{'local-parity' if stream_id % 2 == self._next_stream_id % 2 else 'duplicate'} "
                    f"stream id {stream_id}"
                )
                await self.send_frame(stream_id, Flags.RESET, b"")
                return
            name_bytes, _nul, trace_raw = bytes(payload).partition(b"\x00")
            handler_name = name_bytes.decode("utf-8", errors="replace")
            stream = MuxStream(self, stream_id, handler_name)
            if trace_raw:
                stream.trace_context = unpack_context(trace_raw)
            self._streams[stream_id] = stream
            task = spawn(self._on_inbound_stream(stream), name="mux.inbound_stream")
            self._handler_tasks.add(task)
            self._stream_handler_tasks[stream_id] = task

            def _forget_handler(finished, *, stream_id=stream_id):
                self._handler_tasks.discard(finished)
                if self._stream_handler_tasks.get(stream_id) is finished:
                    self._stream_handler_tasks.pop(stream_id, None)

            task.add_done_callback(_forget_handler)
            return
        stream = self._streams.get(stream_id)
        if stream is None:
            return  # already reset/forgotten
        if flags & Flags.DATA:
            self._buffered_bytes += len(payload)
            if self._buffered_bytes > self._max_buffered_bytes:
                logger.warning(
                    f"connection to {self.peer_id}: buffered {self._buffered_bytes} bytes "
                    f"exceeds cap; closing connection"
                )
                raise ConnectionError("per-connection buffer cap exceeded")
            stream._push(payload)
        if flags & Flags.ERROR:
            try:
                info = MSGPackSerializer.loads(bytes(payload))
                stream._push(RemoteError(info.get("type", "RemoteError"), info.get("message", "")))
            except Exception:
                stream._push(RemoteError("RemoteError", "malformed error payload"))
        if flags & (Flags.CLOSE | Flags.RESET):
            stream._push_eof()
            if flags & Flags.RESET:
                # peer aborted: local side must stop sending immediately
                stream._reset = True
                stream._send_closed = True
                self._forget_stream(stream_id)
                # ...and stop COMPUTING: a still-running inbound handler for
                # this stream is work nobody will read (a hedge's losing
                # request, an abandoned call). A handler that already finished
                # is no longer in the map — its completed response stands.
                handler_task = self._stream_handler_tasks.pop(stream_id, None)
                if handler_task is not None and not handler_task.done():
                    handler_task.cancel()

    def _forget_stream(self, stream_id: int) -> None:
        stream = self._streams.pop(stream_id, None)
        if stream is not None:
            stream._return_credit()

    async def _shutdown(self, error: Optional[BaseException]) -> None:
        if self._closed:
            return
        self._closed = True
        for stream in list(self._streams.values()):
            stream._push_eof()  # guaranteed: queue is unbounded
            stream._return_credit()
        self._streams.clear()
        self._channel.close()

    async def close(self) -> None:
        if self._read_task is not None:
            self._read_task.cancel()
            try:
                await self._read_task
            except (asyncio.CancelledError, Exception):
                pass
        await self._shutdown(None)
        for task in list(self._handler_tasks):
            task.cancel()
        await self._channel.wait_closed()
