"""Auto-relay via the DHT (capability parity: reference use_auto_relay + AutoNAT,
hivemind/p2p/p2p_daemon.py:114-137): a peer behind NAT finds public relays in the
swarm WITHOUT any operator-curated relay list.

Protocol:

- **Advertising** (`advertise_relay`): whoever operates a relay daemon
  (`hivemind_tpu/native/relay_daemon.cpp`) publishes it under the well-known DHT key
  ``hivemind:relays`` — subkey ``host:port``, value the relay's Ed25519 identity hex
  (printed by the daemon at startup). Records expire, so dead relays age out.
- **Self-diagnosis** (`AutoRelay.create`): the peer asks a connected peer to dial
  back its announced addresses (``nat.check``, the AutoNAT dial-back from
  ``p2p/nat.py``). If none are reachable, it is NATed.
- **Registration**: a NATed peer fetches the relay list, shuffles it, and registers
  (`RelayClient`) at up to ``max_relays`` of them — pinning each relay's advertised
  identity, so a swarm member cannot advertise a MITM relay for an endpoint it does
  not control. It then publishes its reachable circuits under
  ``hivemind:relayed:<peer_id>`` so dialers can find them.
- **Resolution**: every `AutoRelay` installs a *peer resolver* on its `P2P` node:
  when a direct dial finds no route, the resolver looks up the target's published
  circuits and dials through one of its relays. Combined with `NATTraversal`'s
  DCUtR-style hole punch (registered here too), the relayed connection is upgraded
  to a direct one when the NAT allows.
- **Maintenance**: a background task re-publishes records at half their TTL and
  re-registers when a relay's control line drops, replacing dead relays with fresh
  picks from the DHT.
"""

from __future__ import annotations

import asyncio
import random
from typing import Dict, List, Optional, Tuple

from hivemind_tpu.p2p.nat import NATTraversal
from hivemind_tpu.p2p.peer_id import PeerID
from hivemind_tpu.p2p.relay import RelayClient
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn

logger = get_logger(__name__)

RELAY_DHT_KEY = "hivemind:relays"
RELAYED_PEER_PREFIX = "hivemind:relayed:"
DEFAULT_TTL = 600.0


def advertise_relay(
    dht, host: str, port: int, pubkey_hex: str = "", ttl: float = DEFAULT_TTL
) -> bool:
    """Publish a relay daemon endpoint to the swarm (run by the relay's operator,
    typically next to the daemon process). Returns True when the record stored."""
    from hivemind_tpu.utils.timed_storage import get_dht_time

    return bool(
        dht.store(
            RELAY_DHT_KEY,
            subkey=f"{host}:{port}",
            value=pubkey_hex,
            expiration_time=get_dht_time() + ttl,
        )
    )


def _parse_relay_records(record) -> List[Tuple[str, int, str]]:
    """[(host, port, pubkey_hex)] from a ``hivemind:relays`` DHT record."""
    if record is None or not isinstance(record.value, dict):
        return []
    relays = []
    for endpoint, item in record.value.items():
        try:
            if isinstance(endpoint, bytes):
                endpoint = endpoint.decode()
            host, _, port = str(endpoint).rpartition(":")
            relays.append((host, int(port), str(item.value or "")))
        except (ValueError, AttributeError):
            continue
    return relays


class AutoRelay:
    """See module docstring.

    :param p2p: this node's transport
    :param dht: this node's DHT (relay discovery + circuit publication)
    :param max_relays: how many relays a NATed peer registers at
    :param probe_via: peer to run the AutoNAT dial-back through; default = any
        connected peer. With no peers and no ``force_relay``, the node assumes it
        is reachable (nothing to diagnose with — matching AutoNAT's "unknown").
    :param force_relay: skip the probe and register regardless (reference
        force_reachability private)
    :param ttl: lifetime of published DHT records; refreshed at half-life
    """

    def __init__(self, p2p, dht, *, max_relays: int = 2, ttl: float = DEFAULT_TTL,
                 allow_plaintext: bool = False):
        self.p2p = p2p
        self.dht = dht
        self.max_relays = max_relays
        self.ttl = ttl
        # opt-OUT of the encrypted-control default: only set True to accept relays
        # advertised without an identity (legacy no-libcrypto daemons)
        self.allow_plaintext = allow_plaintext
        self.nat = NATTraversal(p2p)
        self.relay_clients: Dict[Tuple[str, int], RelayClient] = {}
        self._maintenance_task: Optional[asyncio.Task] = None
        self._bg_tasks: set = set()  # strong refs: the loop holds tasks weakly
        self._natted = False
        self._probe_via: Optional[PeerID] = None
        self._closed = False

    @classmethod
    async def create(
        cls,
        p2p,
        dht,
        *,
        max_relays: int = 2,
        probe_via: Optional[PeerID] = None,
        force_relay: bool = False,
        ttl: float = DEFAULT_TTL,
        allow_plaintext: bool = False,
    ) -> "AutoRelay":
        self = cls(p2p, dht, max_relays=max_relays, ttl=ttl, allow_plaintext=allow_plaintext)
        self._probe_via = probe_via
        await self.nat.register_handlers()  # serve nat.check/nat.punch for others
        p2p.set_peer_resolver(self._resolve_and_dial)
        self._natted = force_relay or not await self._probe_reachable(probe_via)
        if self._natted:
            await self._ensure_registrations()
            if not self.relay_clients:
                logger.warning("NATed but no advertised relay accepted registration")
        self._maintenance_task = spawn(self._maintenance_loop(), name="autorelay.maintenance_loop")
        return self

    # ------------------------------------------------------------------ diagnosis

    async def _probe_reachable(self, probe_via: Optional[PeerID]) -> bool:
        """AutoNAT dial-back; True = at least one announced address is reachable.
        With nobody to probe through, returns True (unknown ≠ private)."""
        if probe_via is None:
            peers = await self.p2p.list_peers()
            if not peers:
                return True
            probe_via = random.choice(peers)
        try:
            reachable = await self.nat.check_reachability(probe_via)
            return bool(reachable)
        except Exception as e:
            logger.debug(f"reachability probe via {probe_via} failed: {e!r}")
            return True

    # ------------------------------------------------------------------ registration

    async def _ensure_registrations(self) -> None:
        """Register at up to ``max_relays`` advertised relays and publish circuits."""
        candidates = await asyncio.wrap_future(
            self.dht.get(RELAY_DHT_KEY, latest=True, return_future=True)
        )
        relays = _parse_relay_records(candidates)
        random.shuffle(relays)
        for host, port, pubkey_hex in relays:
            if len(self.relay_clients) >= self.max_relays:
                break
            if (host, port) in self.relay_clients:
                continue
            try:
                client = await RelayClient.create(
                    self.p2p,
                    host,
                    port,
                    relay_pubkey=pubkey_hex or None,
                    # encrypted by default; a relay advertised WITH an identity can
                    # never be downgraded (the pin refuses), and one advertised
                    # without is only accepted under the explicit opt-out
                    allow_plaintext=self.allow_plaintext and not pubkey_hex,
                )
                self.relay_clients[(host, port)] = client  # lint: single-writer — maintenance loop only
            except Exception as e:
                logger.debug(f"auto-relay registration at {host}:{port} failed: {e!r}")
        if self.relay_clients:
            await self._publish_circuits()

    async def _publish_circuits(self) -> None:
        from hivemind_tpu.utils.timed_storage import get_dht_time

        circuits = [
            {"endpoint": f"{host}:{port}", "pubkey": client.relay_pubkey.hex() if client.relay_pubkey else ""}
            for (host, port), client in self.relay_clients.items()
        ]
        stored = await asyncio.wrap_future(
            self.dht.store(
                RELAYED_PEER_PREFIX + self.p2p.peer_id.to_base58(),
                value=circuits,
                expiration_time=get_dht_time() + self.ttl,
                return_future=True,
            )
        )
        if stored:
            logger.info(
                f"published {len(circuits)} relay circuit(s) for {self.p2p.peer_id}"
            )

    # ------------------------------------------------------------------ resolution

    async def _resolve_and_dial(self, peer_id: PeerID):
        """Peer resolver installed on the P2P node: find the target's published
        circuits and dial through one of its relays. Returns a live MuxConnection
        or None (the caller then raises its usual PeerNotFoundError)."""
        record = await asyncio.wrap_future(
            self.dht.get(RELAYED_PEER_PREFIX + peer_id.to_base58(), latest=True, return_future=True)
        )
        if record is None or not isinstance(record.value, list):
            return None
        circuits = list(record.value)
        random.shuffle(circuits)
        for circuit in circuits:
            try:
                host, _, port = str(circuit.get("endpoint", "")).rpartition(":")
                pubkey = circuit.get("pubkey") or None
                client = RelayClient(
                    self.p2p, host, int(port), relay_pubkey=pubkey,
                    allow_plaintext=self.allow_plaintext and not pubkey,
                )
                await client.dial(peer_id)
                conn = self.p2p._connections.get(peer_id)
                if conn is not None and not conn.is_closed:
                    # opportunistic DCUtR upgrade: swap endpoints through the fresh
                    # relayed path and race direct dials; failure keeps the circuit
                    task = spawn(self._try_upgrade(peer_id), name="autorelay.try_upgrade")
                    self._bg_tasks.add(task)  # lint: single-writer — add/discard are idempotent
                    task.add_done_callback(self._bg_tasks.discard)
                    return conn
            except Exception as e:
                logger.debug(f"relayed dial to {peer_id} via {circuit} failed: {e!r}")
        return None

    async def _try_upgrade(self, peer_id: PeerID) -> None:
        try:
            await self.nat.hole_punch(peer_id)
        except Exception as e:
            logger.debug(f"hole punch with {peer_id} failed: {e!r}")

    # ------------------------------------------------------------------ maintenance

    async def _maintenance_once(self) -> None:
        """One maintenance pass: RE-probe NAT status while not relayed (a peer that
        diagnosed itself before it had anyone to probe through — unknown → assumed
        reachable — must register once evidence of being NATed appears), drop
        registrations whose control line died, and re-register/re-publish."""
        if not self._natted:
            self._natted = not await self._probe_reachable(self._probe_via)
        if self._natted:
            dead = [
                key
                for key, client in self.relay_clients.items()
                if client._control_task is None or client._control_task.done()
            ]
            for key in dead:
                client = self.relay_clients.pop(key)  # lint: single-writer — maintenance loop only
                await client.close()
            await self._ensure_registrations()

    async def _maintenance_loop(self) -> None:
        interval = max(self.ttl / 2.0, 5.0)
        while not self._closed:
            await asyncio.sleep(interval)
            try:
                await self._maintenance_once()
            except Exception as e:
                logger.warning(f"auto-relay maintenance failed: {e!r}")

    async def close(self) -> None:
        self._closed = True
        if self._maintenance_task is not None:
            self._maintenance_task.cancel()
        for task in list(self._bg_tasks):
            task.cancel()
        for client in self.relay_clients.values():
            await client.close()
        self.relay_clients.clear()
        # bound methods are created per access, so identity comparison would always
        # be False here; == compares (func, instance) and matches the installed one
        if getattr(self.p2p, "_peer_resolver", None) == self._resolve_and_dial:
            self.p2p.set_peer_resolver(None)
