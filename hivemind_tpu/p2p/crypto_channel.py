"""Authenticated encrypted channel over a TCP connection.

The reference delegates transport security to the Go daemon (TLS1.3 / noise inside
go-libp2p, hivemind/p2p/p2p_daemon.py:99). Here the equivalent is a Noise-style
XX-pattern handshake implemented with the ``cryptography`` primitives:

1. both sides exchange a plaintext hello: {ed25519 static pub, x25519 ephemeral pub,
   sig = Ed25519_sign(transcript_prefix || x25519_pub)}, proving static-key possession.
2. shared secret = X25519(own ephemeral, peer ephemeral); two ChaCha20-Poly1305 keys
   are derived with HKDF-SHA256 (one per direction), giving forward secrecy.
3. every subsequent frame is AEAD-sealed with a per-direction 64-bit counter nonce and
   the 4-byte length header as associated data.

Frame wire format: [u32 big-endian ciphertext length][ciphertext].
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional, Tuple

from cryptography.exceptions import InvalidTag
from cryptography.hazmat.primitives import hashes, serialization
from cryptography.hazmat.primitives.asymmetric.x25519 import X25519PrivateKey, X25519PublicKey
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
from cryptography.hazmat.primitives.kdf.hkdf import HKDF

from hivemind_tpu.utils.crypto import Ed25519PrivateKey, Ed25519PublicKey
from hivemind_tpu.utils.serializer import MSGPackSerializer

MAX_FRAME_SIZE = 16 * 1024 * 1024  # hard cap on one encrypted frame
_HANDSHAKE_PREFIX = b"hivemind-tpu-noise-v1:"


class HandshakeError(RuntimeError):
    pass


class SecureChannel:
    """Length-prefixed AEAD frames over an asyncio stream pair. Use ``handshake`` to
    construct. ``send``/``recv`` exchange whole messages (frames)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_key: bytes,
        recv_key: bytes,
        peer_public_key: Ed25519PublicKey,
    ):
        self._reader = reader
        self._writer = writer
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_counter = 0
        self._recv_counter = 0
        self.peer_public_key = peer_public_key
        self._send_lock = asyncio.Lock()

    async def send(self, payload: bytes) -> None:
        # size check BEFORE the counter moves: raising after an increment would
        # desynchronize AEAD nonces and poison the whole connection
        if len(payload) + 16 > MAX_FRAME_SIZE:  # +16: poly1305 tag
            raise ValueError(f"frame too large: {len(payload)} > {MAX_FRAME_SIZE - 16}")
        async with self._send_lock:
            nonce = struct.pack("<4xQ", self._send_counter)
            self._send_counter += 1
            ciphertext = self._send_aead.encrypt(nonce, payload, None)
            header = struct.pack(">I", len(ciphertext))
            self._writer.write(header + ciphertext)
            await self._writer.drain()

    async def recv(self) -> bytes:
        header = await self._reader.readexactly(4)
        (length,) = struct.unpack(">I", header)
        if length > MAX_FRAME_SIZE:
            raise HandshakeError(f"oversized frame: {length}")
        ciphertext = await self._reader.readexactly(length)
        nonce = struct.pack("<4xQ", self._recv_counter)
        self._recv_counter += 1
        try:
            return self._recv_aead.decrypt(nonce, ciphertext, None)
        except InvalidTag:
            raise HandshakeError("AEAD authentication failed (corrupted or replayed frame)")

    def close(self) -> None:
        try:
            self._writer.close()
        except Exception:
            pass

    async def wait_closed(self) -> None:
        try:
            await self._writer.wait_closed()
        except Exception:
            pass


async def _send_plain(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(struct.pack(">I", len(payload)) + payload)
    await writer.drain()


async def _recv_plain(reader: asyncio.StreamReader, max_size: int = 4096) -> bytes:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > max_size:
        raise HandshakeError(f"oversized handshake frame: {length}")
    return await reader.readexactly(length)


async def handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    identity: Ed25519PrivateKey,
    is_initiator: bool,
    announced_addrs: Optional[list] = None,
    timeout: float = 15.0,
) -> Tuple[SecureChannel, dict]:
    """Perform the mutual-authentication handshake. Returns (channel, peer_hello_extras)
    where extras carries the peer's announced listen addresses."""

    async def _run() -> Tuple[SecureChannel, dict]:
        ephemeral = X25519PrivateKey.generate()
        eph_pub = ephemeral.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        # the signature covers the ENTIRE hello payload (not just the ephemeral), so a
        # MITM cannot rewrite the announced addresses without failing verification
        static_pub = identity.get_public_key().to_bytes()
        addrs = [str(a) for a in (announced_addrs or [])]
        signed_payload = MSGPackSerializer.dumps([static_pub, eph_pub, addrs, 1])
        hello = {
            "payload": signed_payload,
            "sig": identity.sign(_HANDSHAKE_PREFIX + signed_payload),
        }
        await _send_plain(writer, MSGPackSerializer.dumps(hello))
        peer_hello_outer = MSGPackSerializer.loads(await _recv_plain(reader))

        peer_payload = peer_hello_outer["payload"]
        peer_static_bytes, peer_eph_bytes, peer_addrs, peer_version = MSGPackSerializer.loads(peer_payload)
        peer_static = Ed25519PublicKey.from_bytes(peer_static_bytes)
        if not peer_static.verify(_HANDSHAKE_PREFIX + peer_payload, peer_hello_outer["sig"]):
            raise HandshakeError("peer failed static key proof")
        peer_hello = {"static": peer_static_bytes, "ephemeral": peer_eph_bytes, "addrs": peer_addrs}

        peer_eph = X25519PublicKey.from_public_bytes(peer_hello["ephemeral"])
        shared = ephemeral.exchange(peer_eph)
        okm = HKDF(
            algorithm=hashes.SHA256(), length=64, salt=b"hivemind-tpu-hs", info=b"channel-keys"
        ).derive(shared)
        initiator_key, responder_key = okm[:32], okm[32:]
        send_key, recv_key = (
            (initiator_key, responder_key) if is_initiator else (responder_key, initiator_key)
        )
        channel = SecureChannel(reader, writer, send_key, recv_key, peer_static)
        # key confirmation: proves the peer holds the ephemeral private key, which a
        # replayed hello cannot (helloes alone are replayable — sig covers only the
        # static prefix + own ephemeral). Both sides send first, then verify.
        await channel.send(b"confirm")
        if await channel.recv() != b"confirm":
            raise HandshakeError("peer failed key confirmation")
        return channel, {"addrs": peer_hello.get("addrs", []), "static": peer_hello["static"]}

    return await asyncio.wait_for(_run(), timeout=timeout)
