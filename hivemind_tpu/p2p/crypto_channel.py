"""Authenticated encrypted channel over a TCP connection.

The reference delegates transport security to the Go daemon (TLS1.3 / noise inside
go-libp2p, hivemind/p2p/p2p_daemon.py:99). Here the equivalent is a Noise-style
XX-pattern handshake implemented with the ``cryptography`` primitives:

1. both sides exchange a plaintext hello: {ed25519 static pub, x25519 ephemeral pub,
   sig = Ed25519_sign(transcript_prefix || x25519_pub)}, proving static-key possession.
2. shared secret = X25519(own ephemeral, peer ephemeral); two ChaCha20-Poly1305 keys
   are derived with HKDF-SHA256 (one per direction), giving forward secrecy.
3. every subsequent frame is AEAD-sealed with a per-direction 64-bit counter nonce and
   the 4-byte length header as associated data.

Frame wire format: [u32 big-endian ciphertext length][ciphertext].

Data-plane parallelism: the reference's Go daemon spreads AEAD + IO over goroutines
(p2p_daemon.py:84-147 delegates the whole data path); a single asyncio thread doing
AEAD in-line caps the cross-pod tier at one core. Both directions are therefore
PIPELINED: ``send`` assigns the nonce and enqueues the seal, a writer task emits
ciphertexts strictly in nonce order; the reader task prefetches and unseals ahead of
``recv``. Frames above ``_OFFLOAD_THRESHOLD`` are sealed/opened in a shared thread
pool — ChaCha20-Poly1305 releases the GIL in OpenSSL, so on a multi-core host k
connections (or k queued frames of one connection) use k cores. On a single-core
host the pool is disabled (``HIVEMIND_AEAD_THREADS=0`` forces this; any other value
overrides the default ``min(4, cpu_count)``) and the pipeline still batches socket
writes. In-flight frames are bounded both ways (send semaphore / bounded prefetch
queue), so memory stays capped and TCP backpressure propagates to callers.
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import struct
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

try:
    from cryptography.exceptions import InvalidTag
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric.x25519 import X25519PrivateKey, X25519PublicKey
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305
    from cryptography.hazmat.primitives.kdf.hkdf import HKDF
except ImportError:  # no cryptography wheel on this image: system libcrypto shim
    from hivemind_tpu.utils._libcrypto import (
        ChaCha20Poly1305,
        HKDF,
        InvalidTag,
        X25519PrivateKey,
        X25519PublicKey,
        hashes,
        serialization,
    )

from hivemind_tpu.utils.crypto import Ed25519PrivateKey, Ed25519PublicKey
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.asyncio_utils import spawn

MAX_FRAME_SIZE = 16 * 1024 * 1024  # hard cap on one encrypted frame
_HANDSHAKE_PREFIX = b"hivemind-tpu-noise-v1:"

# frames at least this large have their AEAD offloaded to the worker pool; smaller
# ones are sealed inline (executor hop costs more than the cipher call)
_OFFLOAD_THRESHOLD = 128 * 1024
_MAX_INFLIGHT_SEND = 16  # per channel; bounds sender memory at 16 frames
_RECV_PREFETCH = 8  # frames unsealed ahead of recv(); bounds receiver memory

_aead_executor: Optional[ThreadPoolExecutor] = None


def _aead_workers() -> int:
    configured = os.environ.get("HIVEMIND_AEAD_THREADS")
    if configured is not None:
        return max(0, int(configured))
    count = os.cpu_count() or 1
    return min(4, count) if count > 1 else 0


def _get_aead_executor() -> Optional[ThreadPoolExecutor]:
    global _aead_executor
    workers = _aead_workers()
    if workers <= 0:
        return None
    if _aead_executor is None or _aead_executor._max_workers != workers:
        if _aead_executor is not None:
            _aead_executor.shutdown(wait=False)
        # hmtpu- prefix: the test thread sanitizer exempts the shared
        # process-lifetime executors by this naming convention
        _aead_executor = ThreadPoolExecutor(max_workers=workers, thread_name_prefix="hmtpu-aead")
    return _aead_executor


class HandshakeError(RuntimeError):
    pass


class SecureChannel:
    """Length-prefixed AEAD frames over an asyncio stream pair. Use ``handshake`` to
    construct. ``send``/``recv`` exchange whole messages (frames)."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        send_key: bytes,
        recv_key: bytes,
        peer_public_key: Ed25519PublicKey,
    ):
        self._reader = reader
        self._writer = writer
        self._send_aead = ChaCha20Poly1305(send_key)
        self._recv_aead = ChaCha20Poly1305(recv_key)
        self._send_counter = 0
        self._recv_counter = 0
        self.peer_public_key = peer_public_key
        # ordered pipelines (see module docstring); tasks start lazily so a channel
        # that fails mid-handshake never spawns them without a closer
        self._send_queue: asyncio.Queue = asyncio.Queue()
        self._send_sem = asyncio.Semaphore(_MAX_INFLIGHT_SEND)
        self._send_error: Optional[BaseException] = None
        self._writer_task: Optional[asyncio.Task] = None
        self._recv_queue: asyncio.Queue = asyncio.Queue(maxsize=_RECV_PREFETCH)
        self._recv_error: Optional[BaseException] = None
        self._recv_stopped = False  # auth failure: no frame past it may be delivered
        self._reader_task: Optional[asyncio.Task] = None
        self._closed = False

    # ------------------------------------------------------------------ send side

    async def send(self, payload: bytes, *extra_buffers: bytes) -> None:
        """Seal and send one frame. The plaintext is the concatenation of all given
        buffers — scatter-gather: callers framing a header in front of a large
        payload pass both instead of concatenating (the AEAD walks the pieces;
        only the ciphertext output is a fresh buffer)."""
        if self._send_error is not None:
            raise self._send_failed()
        total_len = len(payload) + sum(len(buffer) for buffer in extra_buffers)
        # size check BEFORE the counter moves: raising after an increment would
        # desynchronize AEAD nonces and poison the whole connection
        if total_len + 16 > MAX_FRAME_SIZE:  # +16: poly1305 tag
            raise ValueError(f"frame too large: {total_len} > {MAX_FRAME_SIZE - 16}")
        await self._send_sem.acquire()
        if self._send_error is not None:
            self._send_sem.release()
            raise self._send_failed()
        # no await between the counter assignment and the enqueue: nonce order and
        # wire order are decided atomically on the event loop
        nonce = struct.pack("<4xQ", self._send_counter)
        self._send_counter += 1
        executor = _get_aead_executor()
        if executor is not None and total_len >= _OFFLOAD_THRESHOLD:
            sealed = asyncio.get_running_loop().run_in_executor(
                executor, self._seal, nonce, payload, extra_buffers
            )
        else:
            sealed = self._seal(nonce, payload, extra_buffers)
        if self._writer_task is None:
            self._writer_task = spawn(self._writer_loop(), name="crypto_channel.writer_loop")
        self._send_queue.put_nowait(sealed)

    def _seal(self, nonce: bytes, payload: bytes, extra_buffers: Tuple[bytes, ...]) -> bytes:
        if not extra_buffers:
            return self._send_aead.encrypt(nonce, payload, None)
        encrypt_parts = getattr(self._send_aead, "encrypt_parts", None)
        if encrypt_parts is not None:
            return encrypt_parts(nonce, (payload, *extra_buffers), None)
        # cipher without multi-buffer support: one join is still cheaper than
        # making every caller concatenate ahead of the size check
        return self._send_aead.encrypt(nonce, b"".join((payload, *extra_buffers)), None)

    def _send_failed(self) -> ConnectionError:
        error = self._send_error
        if isinstance(error, (ConnectionError, OSError)):
            return error  # type: ignore[return-value]
        return ConnectionError(f"secure channel send failed: {error!r}")

    def _fail_send(self, error: BaseException) -> None:
        if self._send_error is None:
            self._send_error = error
        # wake every sender parked on the in-flight semaphore
        for _ in range(_MAX_INFLIGHT_SEND):
            self._send_sem.release()

    async def _writer_loop(self) -> None:
        try:
            while True:
                sealed = await self._send_queue.get()
                if sealed is None:
                    return
                ciphertext = (await sealed) if asyncio.isfuture(sealed) else sealed
                header = struct.pack(">I", len(ciphertext))
                if len(ciphertext) >= _OFFLOAD_THRESHOLD:
                    # two writes skip the megabyte-scale header+body concat copy
                    self._writer.write(header)
                    self._writer.write(ciphertext)
                else:
                    self._writer.write(header + ciphertext)
                self._send_sem.release()
                # drain() is a no-op below the transport high-water mark; above it,
                # this is where TCP backpressure propagates: writer blocks → queue
                # fills → the in-flight semaphore parks the senders
                await self._writer.drain()
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            self._fail_send(e)

    # ------------------------------------------------------------------ recv side

    async def recv(self) -> bytes:
        if self._reader_task is None:
            self._reader_task = spawn(self._reader_loop(), name="crypto_channel.reader_loop")
        while True:
            if self._recv_stopped or (self._recv_error is not None and self._recv_queue.empty()):
                raise self._recv_error
            opened = await self._recv_queue.get()
            if opened is None:  # reader loop ended; the stored error says why
                # one sentinel must serve EVERY concurrent recv(): re-enqueue it so
                # a second parked waiter wakes and raises too instead of hanging
                with contextlib.suppress(asyncio.QueueFull):
                    self._recv_queue.put_nowait(None)  # lint: single-writer — sentinel re-enqueue is idempotent
                if self._recv_error is not None:
                    raise self._recv_error
                continue
            try:
                return (await opened) if asyncio.isfuture(opened) else opened
            except HandshakeError:
                # the prefetch queue is FIFO, so frames behind the tampered one sit
                # behind this failure: stop delivery for good (a clean reader death
                # still drains prefetched VALID frames — only auth failure stops)
                self._recv_stopped = True
                raise
            except InvalidTag:
                # defensive: _open_offloaded normally converts + poisons already
                error = HandshakeError("AEAD authentication failed (corrupted or replayed frame)")
                self._recv_stopped = True
                self._poison(error)
                raise error

    def _poison(self, error: BaseException) -> None:
        """Fatal receive-side failure: kill BOTH directions and stop the reader.
        Authentication failure must be fatal regardless of frame size — nonces are
        counters, so if the channel survived one InvalidTag, later frames would
        still authenticate and an on-path attacker could selectively delete a
        frame by corrupting it."""
        if self._recv_error is None:
            self._recv_error = error
        self._fail_send(error)
        if self._reader_task is not None and not self._reader_task.done():
            self._reader_task.cancel()
        if self._writer_task is not None:
            self._send_queue.put_nowait(None)
        with contextlib.suppress(asyncio.QueueFull):
            self._recv_queue.put_nowait(None)

    async def _open_offloaded(self, future: "asyncio.Future[bytes]") -> bytes:
        try:
            return await future
        except InvalidTag:
            error = HandshakeError("AEAD authentication failed (corrupted or replayed frame)")
            self._poison(error)
            raise error from None

    async def _reader_loop(self) -> None:
        error: BaseException
        try:
            while True:
                header = await self._reader.readexactly(4)
                (length,) = struct.unpack(">I", header)
                if length > MAX_FRAME_SIZE:
                    raise HandshakeError(f"oversized frame: {length}")
                ciphertext = await self._reader.readexactly(length)
                nonce = struct.pack("<4xQ", self._recv_counter)
                self._recv_counter += 1  # lint: single-writer — sole reader loop owns the nonce
                executor = _get_aead_executor()
                if executor is not None and length >= _OFFLOAD_THRESHOLD:
                    # wrap the executor future so an InvalidTag poisons the channel
                    # the moment the decrypt finishes — even if recv() never awaits
                    # this particular frame
                    opened = asyncio.ensure_future(
                        self._open_offloaded(
                            asyncio.get_running_loop().run_in_executor(
                                executor, self._recv_aead.decrypt, nonce, ciphertext, None
                            )
                        )
                    )
                    # mark a never-awaited failure as retrieved (recv may have
                    # already raised on an earlier frame and stopped consuming)
                    opened.add_done_callback(lambda t: t.cancelled() or t.exception())
                else:
                    try:
                        opened = self._recv_aead.decrypt(nonce, ciphertext, None)
                    except InvalidTag:
                        raise HandshakeError(
                            "AEAD authentication failed (corrupted or replayed frame)"
                        )
                await self._recv_queue.put(opened)  # bounded: backpressures the socket
        except asyncio.CancelledError:
            raise
        except BaseException as e:
            error = e
        if self._recv_error is None:  # don't overwrite an earlier poison error
            self._recv_error = error
        # a dead connection must also stop the writer (it may be parked on its queue)
        self._fail_send(error)
        if self._writer_task is not None:
            self._send_queue.put_nowait(None)
        await self._recv_queue.put(None)  # wake a parked recv()

    # ------------------------------------------------------------------ lifecycle

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._fail_send(ConnectionError("secure channel closed"))
        if self._recv_error is None:
            self._recv_error = ConnectionError("secure channel closed")
        for task in (self._writer_task, self._reader_task):
            if task is not None:
                task.cancel()
        with contextlib.suppress(Exception):
            self._recv_queue.put_nowait(None)  # wake a parked recv()
        try:
            self._writer.close()
        except Exception:
            pass

    async def wait_closed(self) -> None:
        try:
            await self._writer.wait_closed()
        except Exception:
            pass


async def _send_plain(writer: asyncio.StreamWriter, payload: bytes) -> None:
    writer.write(struct.pack(">I", len(payload)) + payload)
    await writer.drain()


async def _recv_plain(reader: asyncio.StreamReader, max_size: int = 4096) -> bytes:
    header = await reader.readexactly(4)
    (length,) = struct.unpack(">I", header)
    if length > max_size:
        raise HandshakeError(f"oversized handshake frame: {length}")
    return await reader.readexactly(length)


class _NullAEAD:
    """Cipher stand-in for daemon-proxied channels: the LOCAL hop to the native
    data-plane proxy carries plaintext frames (loopback trust boundary — exactly
    the reference's unix-socket hop to its Go daemon, p2p_daemon.py:84-147); the
    daemon performs the real ChaCha20-Poly1305 with the keys handed over in the
    'K' upgrade frame. Wire format and security toward the REMOTE peer are
    unchanged."""

    @staticmethod
    def encrypt(nonce: bytes, data: bytes, aad) -> bytes:
        return data

    @staticmethod
    def encrypt_parts(nonce: bytes, parts, aad) -> bytes:
        return b"".join(parts)

    @staticmethod
    def decrypt(nonce: bytes, data: bytes, aad) -> bytes:
        return data


async def handshake(
    reader: asyncio.StreamReader,
    writer: asyncio.StreamWriter,
    identity: Ed25519PrivateKey,
    is_initiator: bool,
    announced_addrs: Optional[list] = None,
    timeout: float = 15.0,
    proxy_upgrade: bool = False,
) -> Tuple[SecureChannel, dict]:
    """Perform the mutual-authentication handshake. Returns (channel, peer_hello_extras)
    where extras carries the peer's announced listen addresses.

    ``proxy_upgrade``: the stream runs through the native daemon's data-plane
    proxy ('X' mode): after deriving keys, hand them to the daemon in a 'K' frame
    and switch this end to plaintext framing — the daemon seals/opens every
    subsequent frame (including the key-confirmation exchange) in C++."""

    async def _run() -> Tuple[SecureChannel, dict]:
        ephemeral = X25519PrivateKey.generate()
        eph_pub = ephemeral.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw
        )
        # the signature covers the ENTIRE hello payload (not just the ephemeral), so a
        # MITM cannot rewrite the announced addresses without failing verification
        static_pub = identity.get_public_key().to_bytes()
        addrs = [str(a) for a in (announced_addrs or [])]
        signed_payload = MSGPackSerializer.dumps([static_pub, eph_pub, addrs, 1])
        hello = {
            "payload": signed_payload,
            "sig": identity.sign(_HANDSHAKE_PREFIX + signed_payload),
        }
        await _send_plain(writer, MSGPackSerializer.dumps(hello))
        peer_hello_outer = MSGPackSerializer.loads(await _recv_plain(reader))

        peer_payload = peer_hello_outer["payload"]
        peer_static_bytes, peer_eph_bytes, peer_addrs, peer_version = MSGPackSerializer.loads(peer_payload)
        peer_static = Ed25519PublicKey.from_bytes(peer_static_bytes)
        if not peer_static.verify(_HANDSHAKE_PREFIX + peer_payload, peer_hello_outer["sig"]):
            raise HandshakeError("peer failed static key proof")
        peer_hello = {"static": peer_static_bytes, "ephemeral": peer_eph_bytes, "addrs": peer_addrs}

        peer_eph = X25519PublicKey.from_public_bytes(peer_hello["ephemeral"])
        shared = ephemeral.exchange(peer_eph)
        okm = HKDF(
            algorithm=hashes.SHA256(), length=64, salt=b"hivemind-tpu-hs", info=b"channel-keys"
        ).derive(shared)
        initiator_key, responder_key = okm[:32], okm[32:]
        send_key, recv_key = (
            (initiator_key, responder_key) if is_initiator else (responder_key, initiator_key)
        )
        channel = SecureChannel(reader, writer, send_key, recv_key, peer_static)
        if proxy_upgrade:
            # hand the channel keys (and current counters — the confirm below is
            # the first sealed frame each way) to the local daemon, then go
            # plaintext on this hop: the daemon does the AEAD from here on
            upgrade = (
                b"K" + send_key + recv_key
                + struct.pack("<Q", channel._send_counter)
                + struct.pack("<Q", channel._recv_counter)
            )
            await _send_plain(writer, upgrade)
            channel._send_aead = _NullAEAD()  # type: ignore[assignment]
            channel._recv_aead = _NullAEAD()  # type: ignore[assignment]
        # key confirmation: proves the peer holds the ephemeral private key, which a
        # replayed hello cannot (helloes alone are replayable — sig covers only the
        # static prefix + own ephemeral). Both sides send first, then verify.
        try:
            await channel.send(b"confirm")
            if await channel.recv() != b"confirm":
                raise HandshakeError("peer failed key confirmation")
        except BaseException:
            channel.close()  # reap the pipeline tasks the confirm exchange started
            raise
        return channel, {"addrs": peer_hello.get("addrs", []), "static": peer_hello["static"]}

    try:
        return await asyncio.wait_for(_run(), timeout=timeout)
    except (ValueError, KeyError, TypeError, IndexError, struct.error) as e:
        # a malformed/hostile hello (bad msgpack, wrong shapes, junk key bytes)
        # must read as a handshake failure the acceptor already handles — not
        # crash the per-connection task with an unretrieved msgpack error
        raise HandshakeError(f"malformed handshake from peer: {e!r}") from e
