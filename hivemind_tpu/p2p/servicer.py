"""Reflection-based RPC services (capability parity: reference hivemind/p2p/servicer.py:19-158).

Subclass ``ServicerBase`` and define ``async def rpc_*`` methods with protobuf type
annotations; ``add_p2p_handlers`` registers them all, and ``get_stub`` builds a caller
object with matching methods. Streaming is inferred from AsyncIterator annotations on
the request parameter / return type.
"""

from __future__ import annotations

import asyncio
import typing
from dataclasses import dataclass
from typing import Any, AsyncIterator, Optional, Type

from hivemind_tpu.p2p.p2p import P2P, P2PContext
from hivemind_tpu.p2p.peer_id import PeerID


@dataclass
class _RPCSpec:
    method_name: str
    request_type: Type
    response_type: Type
    stream_input: bool
    stream_output: bool
    idempotent: bool = False


import collections.abc


def _unwrap_iterator(annotation) -> tuple[Any, bool]:
    """(inner_type, True) for AsyncIterator/Iterable/Generator annotations, else
    (annotation, False). typing.get_origin resolves typing aliases to collections.abc."""
    origin = typing.get_origin(annotation)
    if origin in (
        collections.abc.AsyncIterator,
        collections.abc.AsyncIterable,
        collections.abc.AsyncGenerator,
    ):
        return typing.get_args(annotation)[0], True
    return annotation, False


class StubBase:
    """Base for generated stubs: holds the p2p node, target peer, and namespace."""

    def __init__(self, p2p: P2P, peer_id: PeerID, namespace: Optional[str]):
        self._p2p = p2p
        self._peer_id = peer_id
        self._namespace = namespace


class ServicerBase:
    """A collection of rpc_* methods exposed over P2P under
    ``{namespace::}ClassName.method`` handles (reference servicer.py:146-151)."""

    _rpc_specs: Optional[list] = None
    _stub_class: Optional[Type[StubBase]] = None

    @classmethod
    def _collect_rpc_specs(cls) -> list:
        if cls.__dict__.get("_rpc_specs") is not None:
            return cls.__dict__["_rpc_specs"]
        specs = []
        for name in sorted(dir(cls)):
            if not name.startswith("rpc_"):
                continue
            method = getattr(cls, name)
            hints = typing.get_type_hints(method)
            params = [p for p in hints if p not in ("return",)]
            # expected signature: (self), request, context
            request_param = None
            for param in params:
                if hints[param] is P2PContext:
                    continue
                request_param = param
            assert request_param is not None, f"{cls.__name__}.{name} must annotate its request parameter"
            request_type, stream_input = _unwrap_iterator(hints[request_param])
            response_type, stream_output = _unwrap_iterator(hints.get("return"))
            assert response_type is not None, f"{cls.__name__}.{name} must annotate its return type"
            # subclasses whitelist safe-to-retry RPCs (reads or set-semantics writes)
            # via ``_idempotent_rpcs``; everything else fails loudly on an ambiguous
            # connection loss instead of risking a double-applied side effect
            idempotent = name in getattr(cls, "_idempotent_rpcs", frozenset())
            specs.append(_RPCSpec(name, request_type, response_type, stream_input, stream_output, idempotent))
        cls._rpc_specs = specs
        return specs

    @classmethod
    def _handle_name(cls, method_name: str, namespace: Optional[str]) -> str:
        # subclasses may pin a shared wire name (e.g. every averager subclass speaks
        # as "DecentralizedAverager") so heterogeneous peers interoperate
        class_name = getattr(cls, "_class_handle_name", cls.__name__)
        if namespace is not None:
            return f"{namespace}::{class_name}.{method_name}"
        return f"{class_name}.{method_name}"

    async def add_p2p_handlers(
        self, p2p: P2P, wrapper: Optional[object] = None, *, namespace: Optional[str] = None
    ) -> None:
        """Register all rpc_* methods on the given p2p node. ``wrapper`` substitutes the
        bound target (used by auth wrappers, reference utils/auth.py AuthRPCWrapper)."""
        target = wrapper if wrapper is not None else self
        for spec in type(self)._collect_rpc_specs():
            await p2p.add_protobuf_handler(
                self._handle_name(spec.method_name, namespace),
                getattr(target, spec.method_name),
                spec.request_type,
                stream_input=spec.stream_input,
                stream_output=spec.stream_output,
            )

    async def remove_p2p_handlers(self, p2p: P2P, *, namespace: Optional[str] = None) -> None:
        for spec in type(self)._collect_rpc_specs():
            await p2p.remove_protobuf_handler(self._handle_name(spec.method_name, namespace))

    @classmethod
    def get_stub(cls, p2p: P2P, peer_id: PeerID, *, namespace: Optional[str] = None) -> StubBase:
        """A caller object with one async method per rpc_*; unary methods accept
        ``timeout=`` (reference servicer.py:92-105)."""
        if cls.__dict__.get("_stub_class") is None:
            methods = {}
            for spec in cls._collect_rpc_specs():
                methods[spec.method_name] = cls._make_caller(spec)
            cls._stub_class = type(f"{cls.__name__}Stub", (StubBase,), methods)
        return cls.__dict__["_stub_class"](p2p, peer_id, namespace)

    @classmethod
    def _make_caller(cls, spec: _RPCSpec):
        handle = spec.method_name

        if spec.stream_output:

            def stream_caller(self: StubBase, requests, timeout: Optional[float] = None):
                name = cls._handle_name(handle, self._namespace)
                iterator = self._p2p.iterate_protobuf_handler(
                    self._peer_id, name, requests, spec.response_type
                )
                if timeout is not None:
                    from hivemind_tpu.utils.asyncio_utils import aiter_with_timeout

                    return aiter_with_timeout(iterator, timeout)
                return iterator

            stream_caller.__name__ = handle
            return stream_caller

        async def unary_caller(self: StubBase, request, timeout: Optional[float] = None):
            name = cls._handle_name(handle, self._namespace)
            if spec.stream_input:
                # client-streaming with single response: iterate and keep the last
                result = None
                iterator = self._p2p.iterate_protobuf_handler(
                    self._peer_id, name, request, spec.response_type
                )
                if timeout is not None:
                    from hivemind_tpu.utils.asyncio_utils import aiter_with_timeout

                    iterator = aiter_with_timeout(iterator, timeout)
                async for item in iterator:
                    result = item
                return result
            return await asyncio.wait_for(
                self._p2p.call_protobuf_handler(
                    self._peer_id, name, request, spec.response_type, idempotent=spec.idempotent
                ),
                timeout=timeout,
            )

        unary_caller.__name__ = handle
        return unary_caller
