"""Peer identity and addressing.

Capability parity with the reference's libp2p identity surface
(hivemind/p2p/p2p_daemon_bindings/datastructures.py:134): a PeerID is a multihash of
the node's public key, rendered in base58. This build derives it from an Ed25519 public
key: ``base58(0x12 0x20 || sha256(pubkey))`` (the same shape as a libp2p CIDv0).
Addresses are a minimal multiaddr dialect: ``/ip4/<host>/tcp/<port>[/p2p/<peer_id>]``.
"""

from __future__ import annotations

import hashlib
from typing import Optional, Tuple

from hivemind_tpu.utils.crypto import Ed25519PrivateKey, Ed25519PublicKey

_B58_ALPHABET = "123456789ABCDEFGHJKLMNPQRSTUVWXYZabcdefghijkmnopqrstuvwxyz"
_B58_INDEX = {c: i for i, c in enumerate(_B58_ALPHABET)}


def base58_encode(data: bytes) -> str:
    num = int.from_bytes(data, "big")
    out = []
    while num:
        num, rem = divmod(num, 58)
        out.append(_B58_ALPHABET[rem])
    pad = 0
    for byte in data:
        if byte == 0:
            pad += 1
        else:
            break
    return "1" * pad + "".join(reversed(out))


def base58_decode(text: str) -> bytes:
    num = 0
    for char in text:
        try:
            num = num * 58 + _B58_INDEX[char]
        except KeyError:
            raise ValueError(f"invalid base58 character {char!r}") from None
    raw = num.to_bytes((num.bit_length() + 7) // 8, "big")
    pad = 0
    for char in text:
        if char == "1":
            pad += 1
        else:
            break
    return b"\x00" * pad + raw


_MULTIHASH_SHA256 = b"\x12\x20"  # sha2-256, 32 bytes


class PeerID:
    """An opaque, hashable, orderable node identity."""

    __slots__ = ("_bytes", "_b58")

    def __init__(self, peer_id_bytes: bytes):
        self._bytes = bytes(peer_id_bytes)
        self._b58 = base58_encode(self._bytes)

    @classmethod
    def from_public_key(cls, public_key: Ed25519PublicKey) -> "PeerID":
        digest = hashlib.sha256(public_key.to_bytes()).digest()
        return cls(_MULTIHASH_SHA256 + digest)

    @classmethod
    def from_private_key(cls, private_key: Ed25519PrivateKey) -> "PeerID":
        return cls.from_public_key(private_key.get_public_key())

    @classmethod
    def from_base58(cls, b58: str) -> "PeerID":
        return cls(base58_decode(b58))

    def to_bytes(self) -> bytes:
        return self._bytes

    def to_base58(self) -> str:
        return self._b58

    def __repr__(self) -> str:
        return f"<PeerID {self._b58[:12]}…>" if len(self._b58) > 12 else f"<PeerID {self._b58}>"

    def __str__(self) -> str:
        return self._b58

    def __eq__(self, other) -> bool:
        return isinstance(other, PeerID) and self._bytes == other._bytes

    def __lt__(self, other: "PeerID") -> bool:
        return self._bytes < other._bytes

    def __hash__(self) -> int:
        return hash(self._bytes)

    def xor_distance(self, other: "PeerID") -> int:
        return int.from_bytes(hashlib.sha256(self._bytes).digest(), "big") ^ int.from_bytes(
            hashlib.sha256(other._bytes).digest(), "big"
        )


class Multiaddr:
    """Minimal multiaddr: /<host_proto>/<host>/tcp/<port>[/p2p/<peer_id>] with
    host_proto one of ip4/ip6/dns/dns4/dns6 — plus the reference's vendored
    codec extras (hivemind/utils/multiaddr/): ``/unix/<path>`` (the path
    consumes the remainder, go-multiaddr semantics) and
    ``/onion3/<56-char-base32>:<port>``. Codec parity only: the TCP transport
    dials ip/dns addresses; unix/onion3 addresses round-trip through configs
    and DHT records."""

    __slots__ = ("host", "port", "peer_id", "host_proto")

    def __init__(self, host: str, port: int, peer_id: Optional[PeerID] = None, host_proto: str = "ip4"):
        self.host = host
        self.port = int(port)
        self.peer_id = peer_id
        self.host_proto = host_proto

    @classmethod
    def parse(cls, text: str) -> "Multiaddr":
        parts = [p for p in str(text).split("/") if p]
        host = port = None
        peer_id = None
        host_proto = "ip4"
        i = 0
        while i < len(parts):
            proto = parts[i]
            if i + 1 >= len(parts):
                raise ValueError(f"multiaddr {text!r}: protocol {proto!r} is missing its value")
            value = parts[i + 1]
            try:
                if proto in ("ip4", "ip6", "dns4", "dns6", "dns"):
                    host, host_proto = value, proto
                elif proto == "tcp":
                    port = int(value)
                elif proto == "p2p":
                    peer_id = PeerID.from_base58(value)
                elif proto == "unix":
                    # the path consumes the remainder (go-multiaddr semantics) —
                    # except a trailing /p2p/<id>, which stays the peer identity
                    # so with_peer_id round-trips through str/parse
                    rest = parts[i + 1:]
                    if len(rest) >= 2 and rest[-2] == "p2p":
                        # only a REAL sha2-256 multihash identity strips the
                        # tail: base58 alone is not enough (a path like
                        # /var/run/p2p/sock has an all-base58 last segment and
                        # must stay a path)
                        try:
                            candidate = PeerID.from_base58(rest[-1])
                            raw = candidate.to_bytes()
                            if len(raw) == 34 and raw[0] == 0x12 and raw[1] == 0x20:
                                peer_id = candidate
                                rest = rest[:-2]
                        except Exception:
                            pass
                    host, host_proto = "/" + "/".join(rest), "unix"
                    return cls(host, 0, peer_id, host_proto)
                elif proto == "onion3":
                    addr, sep, onion_port = value.partition(":")
                    if not sep or len(addr) != 56:
                        raise ValueError(
                            f"onion3 address must be <56-char-base32>:<port>, got {value!r}"
                        )
                    host, host_proto, port = addr, "onion3", int(onion_port)
                else:
                    raise ValueError(f"unsupported multiaddr protocol {proto!r} in {text!r}")
            except ValueError:
                raise
            except Exception as e:
                raise ValueError(f"malformed multiaddr {text!r}: {e}") from e
            i += 2
        if host is None or port is None:
            raise ValueError(f"multiaddr {text!r} must contain a host and tcp port")
        return cls(host, port, peer_id, host_proto)

    def with_peer_id(self, peer_id: PeerID) -> "Multiaddr":
        return Multiaddr(self.host, self.port, peer_id, self.host_proto)

    @property
    def endpoint(self) -> Tuple[str, int]:
        return (self.host, self.port)

    def __str__(self) -> str:
        if self.host_proto == "unix":
            base = f"/unix{self.host}"
        elif self.host_proto == "onion3":
            base = f"/onion3/{self.host}:{self.port}"
        else:
            base = f"/{self.host_proto}/{self.host}/tcp/{self.port}"
        if self.peer_id is not None:
            base += f"/p2p/{self.peer_id.to_base58()}"
        return base

    def __repr__(self) -> str:
        return f"Multiaddr({self})"

    def __eq__(self, other) -> bool:
        # host_proto matters: /onion3/<x>:9443 and /dns/<x>/tcp/9443 share host
        # and port but are DIFFERENT addresses (peerstores are Set[Multiaddr])
        return (
            isinstance(other, Multiaddr)
            and self.host == other.host
            and self.port == other.port
            and self.peer_id == other.peer_id
            and self.host_proto == other.host_proto
        )

    def __hash__(self) -> int:
        return hash((self.host, self.port, self.peer_id, self.host_proto))
