from hivemind_tpu.p2p.crypto_channel import HandshakeError
from hivemind_tpu.p2p.mux import RemoteError, StreamClosedError
from hivemind_tpu.p2p.p2p import (
    DEFAULT_MAX_MSG_SIZE,
    P2P,
    P2PContext,
    P2PError,
    P2PHandlerError,
    PeerNotFoundError,
)
from hivemind_tpu.p2p.autorelay import AutoRelay, advertise_relay
from hivemind_tpu.p2p.nat import NATTraversal
from hivemind_tpu.p2p.peer_id import Multiaddr, PeerID
from hivemind_tpu.p2p.servicer import ServicerBase, StubBase
