// hivemind_tpu relay daemon — the native transport component.
//
// Role parity: the circuit-relay v2 capability of the reference's go-libp2p daemon
// (hivemind/p2p/p2p_daemon.py:114-137 enables relay + auto-relay): peers behind NAT
// register here over an OUTBOUND connection and become dialable through the relay.
// Security model: the relay splices raw bytes; peers run their end-to-end Noise
// handshake THROUGH it, so the relay only ever sees AEAD ciphertext.
//
// Control protocol (length-prefixed frames: u32 big-endian length + payload):
//   REGISTER  'R' <peer_id bytes>        -> 'O'   (this conn becomes the control line)
//   DIAL      'D' <16B token> <target_id>-> 'O' then splice  (sent on a FRESH conn)
//   ACCEPT    'A' <16B token>            -> 'O' then splice  (fresh conn from target)
//   INCOMING  'I' <16B token>            relay -> target's control line
//   WHOAMI    'W'                        -> 'O' <ip:port>  (the conn's observed
//             public endpoint — the STUN-style observation NATed peers need for
//             hole punching; role parity with libp2p identify/observed-addr)
// After 'O' on a DIAL/ACCEPT pair the two sockets are spliced byte-for-byte.
//
// Build: g++ -O2 -std=c++17 -o relay_daemon relay_daemon.cpp   (see Makefile)

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <map>
#include <string>
#include <vector>

static constexpr size_t MAX_FRAME = 1 << 20;       // control frames only
static constexpr size_t SPLICE_BUF = 1 << 16;      // per-direction pipe buffer
static constexpr int PENDING_DIAL_TTL_MS = 30000;  // unmatched dials expire
// Backpressure water marks. epoll here is level-triggered, so merely breaking out
// of the read loop is NOT backpressure (the next epoll_wait re-fires EPOLLIN and
// reads another 64 KiB): above HIGH_WATER the reading fd DROPS EPOLLIN interest and
// is re-armed from on_writable once the partner drains below LOW_WATER — bounding
// each direction at HIGH_WATER + one read (~576 KiB).
static constexpr size_t HIGH_WATER = 8 * SPLICE_BUF;
static constexpr size_t LOW_WATER = 2 * SPLICE_BUF;
static constexpr int FLUSH_TTL_MS = 60000;  // closing_after_flush conns expire

static double now_ms() {
  using namespace std::chrono;
  return duration_cast<duration<double, std::milli>>(steady_clock::now().time_since_epoch()).count();
}

enum class ConnState { ReadingFrame, Control, SplicedWaiting, Spliced, Closed };

struct Conn {
  int fd = -1;
  ConnState state = ConnState::ReadingFrame;
  std::string inbuf;        // frame assembly
  std::string outbuf;       // pending writes
  std::string peer_id;      // set for control lines
  std::string token;        // set for pending dial/accept conns
  int peer_fd = -1;         // spliced counterpart
  double created_ms = 0;
  bool want_write = false;
  bool read_paused = false;  // EPOLLIN interest dropped (partner over HIGH_WATER)
  bool closing_after_flush = false;  // partner gone: close once outbuf drains
};

static int g_epoll = -1;
static std::map<int, Conn*> g_conns;
static std::map<std::string, int> g_control;        // peer_id -> control fd
static std::map<std::string, int> g_pending_dials;  // token -> dialer fd

static void set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

static void update_events(Conn* c) {
  epoll_event ev{};
  ev.events = (c->read_paused ? 0 : EPOLLIN) | (c->want_write ? EPOLLOUT : 0);
  ev.data.fd = c->fd;
  epoll_ctl(g_epoll, EPOLL_CTL_MOD, c->fd, &ev);
}

static void close_conn(int fd);

static void queue_write(Conn* c, const char* data, size_t len) {
  c->outbuf.append(data, len);
  if (!c->want_write) {
    c->want_write = true;
    update_events(c);
  }
}

static void queue_frame(Conn* c, const std::string& payload) {
  uint32_t n = htonl((uint32_t)payload.size());
  std::string frame((char*)&n, 4);
  frame += payload;
  queue_write(c, frame.data(), frame.size());
}

static void close_conn(int fd) {
  auto it = g_conns.find(fd);
  if (it == g_conns.end()) return;
  Conn* c = it->second;
  if (!c->peer_id.empty()) {
    auto reg = g_control.find(c->peer_id);
    if (reg != g_control.end() && reg->second == fd) g_control.erase(reg);
  }
  if (!c->token.empty()) {
    auto pend = g_pending_dials.find(c->token);
    if (pend != g_pending_dials.end() && pend->second == fd) g_pending_dials.erase(pend);
  }
  int partner = c->peer_fd;
  epoll_ctl(g_epoll, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  g_conns.erase(it);
  delete c;
  if (partner >= 0) {
    auto pit = g_conns.find(partner);
    if (pit != g_conns.end()) {
      Conn* p = pit->second;
      p->peer_fd = -1;
      if (p->outbuf.empty()) {
        close_conn(partner);  // pipe is bidirectional: one side gone, tear down both
      } else {
        // in-flight bytes the peer already sent must not be discarded: stop
        // reading, flush the tail, then close from on_writable (the periodic
        // sweep reaps flushers whose receiver never drains)
        p->closing_after_flush = true;
        p->read_paused = true;
        p->created_ms = now_ms();
        update_events(p);
      }
    }
  }
}

static void enable_keepalive(int fd) {
  int ka = 1, idle = 30, intvl = 10, cnt = 3;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &ka, sizeof(ka));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
}

static void splice_pair(Conn* a, Conn* b) {
  a->peer_fd = b->fd;
  b->peer_fd = a->fd;
  a->state = b->state = ConnState::Spliced;
  enable_keepalive(a->fd);
  enable_keepalive(b->fd);
  const char ok[] = {0, 0, 0, 1, 'O'};
  queue_write(a, ok, 5);
  queue_write(b, ok, 5);
  // any bytes that raced ahead of the match are forwarded
  if (!a->inbuf.empty()) { queue_write(b, a->inbuf.data(), a->inbuf.size()); a->inbuf.clear(); }
  if (!b->inbuf.empty()) { queue_write(a, b->inbuf.data(), b->inbuf.size()); b->inbuf.clear(); }
}

static void handle_control_frame(Conn* c, const std::string& payload) {
  if (payload.empty()) { close_conn(c->fd); return; }
  char kind = payload[0];
  if (kind == 'R') {
    std::string peer_id = payload.substr(1);
    if (peer_id.empty()) { close_conn(c->fd); return; }
    // First registration wins: a later REGISTER for the same peer_id is REFUSED
    // while the original control line is alive, so an attacker cannot evict a
    // registered peer and capture its INCOMING notifications. (Proof-of-identity
    // via Ed25519 challenge would be stronger, but this image has no crypto
    // library for the daemon; dead lines are reaped by TCP keepalive + EPOLLHUP,
    // after which the legitimate peer can re-register.)
    auto old = g_control.find(peer_id);
    if (old != g_control.end() && old->second != c->fd) {
      queue_frame(c, "E");
      return;
    }
    c->peer_id = peer_id;
    g_control[c->peer_id] = c->fd;
    c->state = ConnState::Control;
    enable_keepalive(c->fd);
    queue_frame(c, "O");
  } else if (kind == 'D' && payload.size() > 17) {
    std::string token = payload.substr(1, 16);
    std::string target = payload.substr(17);
    auto reg = g_control.find(target);
    if (reg == g_control.end()) { queue_frame(c, "E"); close_conn(c->fd); return; }
    c->token = token;
    c->state = ConnState::SplicedWaiting;
    g_pending_dials[token] = c->fd;
    c->created_ms = now_ms();
    queue_frame(g_conns[reg->second], std::string("I") + token);
  } else if (kind == 'W') {
    sockaddr_in observed{};
    socklen_t olen = sizeof(observed);
    if (getpeername(c->fd, (sockaddr*)&observed, &olen) == 0) {
      char ip[INET_ADDRSTRLEN];
      inet_ntop(AF_INET, &observed.sin_addr, ip, sizeof(ip));
      char reply[64];
      int n = snprintf(reply, sizeof(reply), "O%s:%d", ip, ntohs(observed.sin_port));
      queue_frame(c, std::string(reply, n));
    } else {
      queue_frame(c, "E");
    }
  } else if (kind == 'A' && payload.size() >= 17) {
    std::string token = payload.substr(1, 16);
    auto pend = g_pending_dials.find(token);
    if (pend == g_pending_dials.end()) { queue_frame(c, "E"); close_conn(c->fd); return; }
    Conn* dialer = g_conns[pend->second];
    g_pending_dials.erase(pend);
    dialer->token.clear();
    splice_pair(dialer, c);
  } else {
    close_conn(c->fd);
  }
}

static void on_readable(Conn* c) {
  char buf[SPLICE_BUF];
  while (true) {
    ssize_t n = read(c->fd, buf, sizeof(buf));
    if (n == 0) { close_conn(c->fd); return; }
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      close_conn(c->fd); return;
    }
    if (c->state == ConnState::Spliced) {
      auto pit = g_conns.find(c->peer_fd);
      if (pit == g_conns.end()) { close_conn(c->fd); return; }
      queue_write(pit->second, buf, n);
      if (pit->second->outbuf.size() > HIGH_WATER) {
        // real backpressure: drop EPOLLIN interest until the partner drains
        c->read_paused = true;
        update_events(c);
        break;
      }
    } else {
      c->inbuf.append(buf, n);
      while (c->state != ConnState::Spliced && c->inbuf.size() >= 4) {
        uint32_t len = ntohl(*(uint32_t*)c->inbuf.data());
        if (len > MAX_FRAME) { close_conn(c->fd); return; }
        if (c->inbuf.size() < 4 + len) break;
        std::string payload = c->inbuf.substr(4, len);
        c->inbuf.erase(0, 4 + len);
        handle_control_frame(c, payload);
        if (g_conns.find(c->fd) == g_conns.end()) return;  // frame handler closed us
      }
    }
  }
}

static void maybe_resume_partner(Conn* c) {
  // our queue drained below LOW_WATER: re-arm the peer that was paused on us
  if (c->outbuf.size() >= LOW_WATER || c->peer_fd < 0) return;
  auto pit = g_conns.find(c->peer_fd);
  if (pit != g_conns.end() && pit->second->read_paused) {
    pit->second->read_paused = false;
    update_events(pit->second);
  }
}

static void on_writable(Conn* c) {
  while (!c->outbuf.empty()) {
    ssize_t n = write(c->fd, c->outbuf.data(), c->outbuf.size());
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) { maybe_resume_partner(c); return; }
      close_conn(c->fd); return;
    }
    c->outbuf.erase(0, n);
  }
  if (c->closing_after_flush) { close_conn(c->fd); return; }
  c->want_write = false;
  update_events(c);
  maybe_resume_partner(c);
}

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 34000;
  signal(SIGPIPE, SIG_IGN);

  int listener = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)port);
  if (bind(listener, (sockaddr*)&addr, sizeof(addr)) < 0) { perror("bind"); return 1; }
  if (listen(listener, 128) < 0) { perror("listen"); return 1; }
  set_nonblock(listener);

  socklen_t alen = sizeof(addr);
  getsockname(listener, (sockaddr*)&addr, &alen);
  printf("relay listening on port %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  g_epoll = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = listener;
  epoll_ctl(g_epoll, EPOLL_CTL_ADD, listener, &ev);

  std::vector<epoll_event> events(256);
  double last_sweep = now_ms();
  while (true) {
    int n = epoll_wait(g_epoll, events.data(), (int)events.size(), 1000);
    for (int i = 0; i < n; i++) {
      int fd = events[i].data.fd;
      if (fd == listener) {
        while (true) {
          int client = accept(listener, nullptr, nullptr);
          if (client < 0) break;
          set_nonblock(client);
          setsockopt(client, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn* c = new Conn();
          c->fd = client;
          c->created_ms = now_ms();
          g_conns[client] = c;
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = client;
          epoll_ctl(g_epoll, EPOLL_CTL_ADD, client, &cev);
        }
        continue;
      }
      auto it = g_conns.find(fd);
      if (it == g_conns.end()) continue;
      if (events[i].events & (EPOLLERR | EPOLLHUP)) { close_conn(fd); continue; }
      if (events[i].events & EPOLLIN) on_readable(it->second);
      if (g_conns.find(fd) == g_conns.end()) continue;
      if (events[i].events & EPOLLOUT) on_writable(it->second);
    }
    if (now_ms() - last_sweep > 5000) {  // expire unmatched dials + stuck flushers
      last_sweep = now_ms();
      std::vector<int> expired;
      for (auto& [token, fd] : g_pending_dials) {
        auto it = g_conns.find(fd);
        if (it == g_conns.end() || now_ms() - it->second->created_ms > PENDING_DIAL_TTL_MS)
          expired.push_back(fd);
      }
      for (auto& [fd, conn] : g_conns) {
        if (conn->closing_after_flush && now_ms() - conn->created_ms > FLUSH_TTL_MS)
          expired.push_back(fd);
      }
      for (int fd : expired) close_conn(fd);
    }
  }
}
