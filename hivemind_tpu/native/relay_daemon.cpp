// hivemind_tpu relay daemon — the native transport component.
//
// Role parity: the circuit-relay v2 capability of the reference's go-libp2p daemon
// (hivemind/p2p/p2p_daemon.py:114-137 enables relay + auto-relay): peers behind NAT
// register here over an OUTBOUND connection and become dialable through the relay.
// Security model: the relay splices raw bytes; peers run their end-to-end Noise
// handshake THROUGH it, so the relay only ever sees AEAD ciphertext.
//
// Control protocol (length-prefixed frames: u32 big-endian length + payload):
//   REGISTER  'R' <peer_id bytes>        -> 'C' <32B challenge>  (when libcrypto is
//             available; peer must prove it owns the Ed25519 key its peer_id hashes)
//             -> 'O' directly in legacy mode (no libcrypto on the host)
//   PROOF     'P' <32B ed25519 pubkey> <64B signature>  -> 'O'  (signature over
//             "hivemind-relay-register:" + challenge + peer_id; sha256(pubkey) must
//             equal the multihash digest in peer_id). A VALID proof also evicts any
//             stale control line for the same peer_id — only the key owner can, so
//             a NAT-rebound peer reclaims its identity immediately instead of
//             waiting for TCP keepalive to reap the dead line.
//   HANDSHAKE 'H' <32B client X25519 eph>  -> 'S' <32B relay eph> <32B relay
//             Ed25519 pub> <64B sig over "hivemind-relay-hs:" + client_eph +
//             relay_eph>. Derives per-direction ChaCha20-Poly1305 keys
//             (HKDF-SHA256 of the ECDH secret, salt "hivemind-relay-hs", info
//             "control"; nonce = 4 zero bytes + LE64 counter); every later control
//             frame on the conn is sealed, so INCOMING tokens and registration
//             proofs are opaque to on-path observers. Clients that PIN the relay
//             identity also defeat a malicious relay proxying the registration
//             challenge to a second relay (the proxy cannot read or re-wrap the
//             sealed proof); unpinned trust-on-first-use still leaves that window
//             on the very first connect, like SSH.
//   DIAL      'D' <16B token> <target_id>-> 'O' then splice  (sent on a FRESH conn)
//   ACCEPT    'A' <16B token>            -> 'O' then splice  (fresh conn from target)
//   INCOMING  'I' <16B token>            relay -> target's control line
//   WHOAMI    'W'                        -> 'O' <ip:port>  (the conn's observed
//             public endpoint — the STUN-style observation NATed peers need for
//             hole punching; role parity with libp2p identify/observed-addr)
//   PROXY     'X' <u16 BE port> <ip>     -> 'O' once the outbound connect lands.
//             Local DATA-PLANE proxy: the daemon terminates the peer's channel
//             AEAD so Python ships plaintext frames over loopback and the native
//             side does ChaCha20-Poly1305 + wire IO (the reference keeps its whole
//             transport in the Go daemon the same way, p2p_daemon.py:84-147).
//             After 'O': local frame #1 (hello) crosses raw, frame #2 must be
//             'K' <send_key 32><recv_key 32><LE64 send_ctr><LE64 recv_ctr>
//             (consumed), frames #3+ are sealed toward the wire; wire frame #1
//             (peer hello) crosses raw, #2+ are opened with recv_key. Ciphertext
//             arriving before 'K' is held, so the upgrade cannot race.
//   LISTEN    'Y' <u16 BE public_port> <u16 BE local_port> -> 'O' <u16 actual>.
//             INBOUND data-plane proxy: the daemon binds the PUBLIC listener
//             and forwards each accepted wire conn to the local server at
//             127.0.0.1:local_port as a ProxyRemote/ProxyLocal pair — the same
//             frame machine as 'X', fed by the responder-side handshake (hello
//             #1, 'K' #2, sealed #3+), so a busy server's cipher work for BOTH
//             directions leaves the Python event loop. The listener lives
//             exactly as long as the control conn that registered it.
// After 'O' on a DIAL/ACCEPT pair the two sockets are spliced byte-for-byte.
//
// Usage: relay_daemon [port] [identity_file] [unix_socket_path]
//
// With a unix_socket_path, the daemon ALSO listens on a 0600 AF_UNIX socket —
// the trust boundary for the local data-plane proxy hop: the 'K' upgrade ships
// session AEAD keys, and a TCP loopback port offers no peer credential, so
// multi-user hosts must hand keys over the unix socket (filesystem-permission
// enforced), never the port.
//   identity_file (optional): raw 32-byte Ed25519 private key, loaded if present,
//   created (0600) otherwise — keeps the relay identity stable across restarts so
//   client pins keep working.
// Build: g++ -O2 -std=c++17 -o relay_daemon relay_daemon.cpp -ldl  (see Makefile)

#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/random.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/un.h>
#include <unistd.h>

#include <chrono>
#include <map>
#include <string>
#include <vector>

static constexpr size_t MAX_FRAME = 1 << 20;       // control frames only
static constexpr size_t SPLICE_BUF = 1 << 16;      // per-direction pipe buffer
static constexpr int PENDING_DIAL_TTL_MS = 30000;  // unmatched dials expire
// Backpressure water marks. epoll here is level-triggered, so merely breaking out
// of the read loop is NOT backpressure (the next epoll_wait re-fires EPOLLIN and
// reads another 64 KiB): above HIGH_WATER the reading fd DROPS EPOLLIN interest and
// is re-armed from on_writable once the partner drains below LOW_WATER — bounding
// each direction at HIGH_WATER + one read (~576 KiB).
static constexpr size_t HIGH_WATER = 8 * SPLICE_BUF;
static constexpr size_t LOW_WATER = 2 * SPLICE_BUF;
static constexpr int FLUSH_TTL_MS = 60000;  // closing_after_flush conns expire

static double now_ms() {
  using namespace std::chrono;
  return duration_cast<duration<double, std::milli>>(steady_clock::now().time_since_epoch()).count();
}

// ---- Ed25519 registration proof via the system libcrypto ----------------------
// This image ships libcrypto.so.3 but no OpenSSL headers, so the few stable-ABI
// entry points needed for one-shot Ed25519 verification are declared here and
// resolved with dlopen at startup. If libcrypto is absent the daemon degrades to
// the legacy unauthenticated first-registration-wins behavior (and says so).
namespace relay_crypto {
typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_md_ctx_st EVP_MD_CTX;
static constexpr int EVP_PKEY_ED25519 = 1087;  // NID_ED25519, stable since 1.1.1

static EVP_PKEY* (*new_raw_public_key)(int, void*, const unsigned char*, size_t) = nullptr;
static void (*pkey_free)(EVP_PKEY*) = nullptr;
static EVP_MD_CTX* (*md_ctx_new)() = nullptr;
static void (*md_ctx_free)(EVP_MD_CTX*) = nullptr;
static int (*digest_verify_init)(EVP_MD_CTX*, void**, const void*, void*, EVP_PKEY*) = nullptr;
static int (*digest_verify)(EVP_MD_CTX*, const unsigned char*, size_t, const unsigned char*, size_t) = nullptr;
static unsigned char* (*sha256_fn)(const unsigned char*, size_t, unsigned char*) = nullptr;

// additional entry points for the encrypted control channel (X25519 ECDH +
// Ed25519 relay identity + HKDF-SHA256 + ChaCha20-Poly1305 AEAD)
typedef struct evp_pkey_ctx_st EVP_PKEY_CTX;
typedef struct evp_cipher_ctx_st EVP_CIPHER_CTX;
typedef struct evp_cipher_st EVP_CIPHER;
typedef struct evp_md_st EVP_MD;
static constexpr int EVP_PKEY_X25519 = 1034;  // NID_X25519
static constexpr int CTRL_AEAD_GET_TAG = 0x10, CTRL_AEAD_SET_TAG = 0x11;

static EVP_PKEY* (*new_raw_private_key)(int, void*, const unsigned char*, size_t) = nullptr;
static int (*get_raw_private_key)(const EVP_PKEY*, unsigned char*, size_t*) = nullptr;
static EVP_PKEY_CTX* (*pkey_ctx_new_id)(int, void*) = nullptr;
static void (*pkey_ctx_free)(EVP_PKEY_CTX*) = nullptr;
static int (*keygen_init)(EVP_PKEY_CTX*) = nullptr;
static int (*keygen)(EVP_PKEY_CTX*, EVP_PKEY**) = nullptr;
static int (*get_raw_public_key)(const EVP_PKEY*, unsigned char*, size_t*) = nullptr;
static int (*digest_sign_init)(EVP_MD_CTX*, EVP_PKEY_CTX**, const EVP_MD*, void*, EVP_PKEY*) = nullptr;
static int (*digest_sign)(EVP_MD_CTX*, unsigned char*, size_t*, const unsigned char*, size_t) = nullptr;
static int (*derive_init)(EVP_PKEY_CTX*) = nullptr;
static int (*derive_set_peer)(EVP_PKEY_CTX*, EVP_PKEY*) = nullptr;
static int (*derive)(EVP_PKEY_CTX*, unsigned char*, size_t*) = nullptr;
static EVP_PKEY_CTX* (*pkey_ctx_new)(EVP_PKEY*, void*) = nullptr;
static unsigned char* (*hmac_fn)(const EVP_MD*, const void*, int, const unsigned char*, size_t,
                                 unsigned char*, unsigned int*) = nullptr;
static const EVP_MD* (*sha256_md)() = nullptr;
static EVP_CIPHER_CTX* (*cipher_ctx_new)() = nullptr;
static void (*cipher_ctx_free)(EVP_CIPHER_CTX*) = nullptr;
static const EVP_CIPHER* (*chacha20_poly1305)() = nullptr;
static int (*encrypt_init)(EVP_CIPHER_CTX*, const EVP_CIPHER*, void*, const unsigned char*, const unsigned char*) = nullptr;
static int (*encrypt_update)(EVP_CIPHER_CTX*, unsigned char*, int*, const unsigned char*, int) = nullptr;
static int (*encrypt_final)(EVP_CIPHER_CTX*, unsigned char*, int*) = nullptr;
static int (*decrypt_init)(EVP_CIPHER_CTX*, const EVP_CIPHER*, void*, const unsigned char*, const unsigned char*) = nullptr;
static int (*decrypt_update)(EVP_CIPHER_CTX*, unsigned char*, int*, const unsigned char*, int) = nullptr;
static int (*decrypt_final)(EVP_CIPHER_CTX*, unsigned char*, int*) = nullptr;
static int (*cipher_ctx_ctrl)(EVP_CIPHER_CTX*, int, int, void*) = nullptr;

static bool channel_available = false;  // handshake ops resolved

static bool load() {
  void* lib = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_LOCAL);
  if (!lib) lib = dlopen("libcrypto.so.1.1", RTLD_NOW | RTLD_LOCAL);
  if (!lib) lib = dlopen("libcrypto.so", RTLD_NOW | RTLD_LOCAL);
  if (!lib) return false;
  new_raw_public_key = (decltype(new_raw_public_key))dlsym(lib, "EVP_PKEY_new_raw_public_key");
  pkey_free = (decltype(pkey_free))dlsym(lib, "EVP_PKEY_free");
  md_ctx_new = (decltype(md_ctx_new))dlsym(lib, "EVP_MD_CTX_new");
  md_ctx_free = (decltype(md_ctx_free))dlsym(lib, "EVP_MD_CTX_free");
  digest_verify_init = (decltype(digest_verify_init))dlsym(lib, "EVP_DigestVerifyInit");
  digest_verify = (decltype(digest_verify))dlsym(lib, "EVP_DigestVerify");
  sha256_fn = (decltype(sha256_fn))dlsym(lib, "SHA256");

  new_raw_private_key = (decltype(new_raw_private_key))dlsym(lib, "EVP_PKEY_new_raw_private_key");
  get_raw_private_key = (decltype(get_raw_private_key))dlsym(lib, "EVP_PKEY_get_raw_private_key");
  pkey_ctx_new_id = (decltype(pkey_ctx_new_id))dlsym(lib, "EVP_PKEY_CTX_new_id");
  pkey_ctx_free = (decltype(pkey_ctx_free))dlsym(lib, "EVP_PKEY_CTX_free");
  keygen_init = (decltype(keygen_init))dlsym(lib, "EVP_PKEY_keygen_init");
  keygen = (decltype(keygen))dlsym(lib, "EVP_PKEY_keygen");
  get_raw_public_key = (decltype(get_raw_public_key))dlsym(lib, "EVP_PKEY_get_raw_public_key");
  digest_sign_init = (decltype(digest_sign_init))dlsym(lib, "EVP_DigestSignInit");
  digest_sign = (decltype(digest_sign))dlsym(lib, "EVP_DigestSign");
  derive_init = (decltype(derive_init))dlsym(lib, "EVP_PKEY_derive_init");
  derive_set_peer = (decltype(derive_set_peer))dlsym(lib, "EVP_PKEY_derive_set_peer");
  derive = (decltype(derive))dlsym(lib, "EVP_PKEY_derive");
  pkey_ctx_new = (decltype(pkey_ctx_new))dlsym(lib, "EVP_PKEY_CTX_new");
  hmac_fn = (decltype(hmac_fn))dlsym(lib, "HMAC");
  sha256_md = (decltype(sha256_md))dlsym(lib, "EVP_sha256");
  cipher_ctx_new = (decltype(cipher_ctx_new))dlsym(lib, "EVP_CIPHER_CTX_new");
  cipher_ctx_free = (decltype(cipher_ctx_free))dlsym(lib, "EVP_CIPHER_CTX_free");
  chacha20_poly1305 = (decltype(chacha20_poly1305))dlsym(lib, "EVP_chacha20_poly1305");
  encrypt_init = (decltype(encrypt_init))dlsym(lib, "EVP_EncryptInit_ex");
  encrypt_update = (decltype(encrypt_update))dlsym(lib, "EVP_EncryptUpdate");
  encrypt_final = (decltype(encrypt_final))dlsym(lib, "EVP_EncryptFinal_ex");
  decrypt_init = (decltype(decrypt_init))dlsym(lib, "EVP_DecryptInit_ex");
  decrypt_update = (decltype(decrypt_update))dlsym(lib, "EVP_DecryptUpdate");
  decrypt_final = (decltype(decrypt_final))dlsym(lib, "EVP_DecryptFinal_ex");
  cipher_ctx_ctrl = (decltype(cipher_ctx_ctrl))dlsym(lib, "EVP_CIPHER_CTX_ctrl");

  channel_available = new_raw_private_key && get_raw_private_key &&
                      pkey_ctx_new_id && pkey_ctx_free && keygen_init && keygen &&
                      get_raw_public_key && digest_sign_init && digest_sign && derive_init &&
                      derive_set_peer && derive && pkey_ctx_new && hmac_fn && sha256_md &&
                      cipher_ctx_new && cipher_ctx_free && chacha20_poly1305 && encrypt_init &&
                      encrypt_update && encrypt_final && decrypt_init && decrypt_update &&
                      decrypt_final && cipher_ctx_ctrl;
  return new_raw_public_key && pkey_free && md_ctx_new && md_ctx_free &&
         digest_verify_init && digest_verify && sha256_fn;
}

static bool available = false;

static bool sha256(const std::string& data, unsigned char out[32]) {
  if (!available) return false;
  return sha256_fn((const unsigned char*)data.data(), data.size(), out) != nullptr;
}

static bool ed25519_verify(const std::string& pubkey_raw, const std::string& message,
                           const std::string& signature) {
  if (!available || pubkey_raw.size() != 32 || signature.size() != 64) return false;
  EVP_PKEY* key = new_raw_public_key(EVP_PKEY_ED25519, nullptr,
                                     (const unsigned char*)pubkey_raw.data(), pubkey_raw.size());
  if (!key) return false;
  EVP_MD_CTX* ctx = md_ctx_new();
  bool ok = false;
  if (ctx && digest_verify_init(ctx, nullptr, nullptr, nullptr, key) == 1) {
    ok = digest_verify(ctx, (const unsigned char*)signature.data(), signature.size(),
                       (const unsigned char*)message.data(), message.size()) == 1;
  }
  if (ctx) md_ctx_free(ctx);
  pkey_free(key);
  return ok;
}
static EVP_PKEY* generate_key(int type) {
  EVP_PKEY_CTX* ctx = pkey_ctx_new_id(type, nullptr);
  if (!ctx) return nullptr;
  EVP_PKEY* key = nullptr;
  if (keygen_init(ctx) != 1 || keygen(ctx, &key) != 1) key = nullptr;
  pkey_ctx_free(ctx);
  return key;
}

static bool raw_public(EVP_PKEY* key, unsigned char out[32]) {
  size_t len = 32;
  return get_raw_public_key(key, out, &len) == 1 && len == 32;
}

static bool ed25519_sign(EVP_PKEY* key, const std::string& message, unsigned char sig[64]) {
  EVP_MD_CTX* ctx = md_ctx_new();
  if (!ctx) return false;
  size_t siglen = 64;
  bool ok = digest_sign_init(ctx, nullptr, nullptr, nullptr, key) == 1 &&
            digest_sign(ctx, sig, &siglen, (const unsigned char*)message.data(),
                        message.size()) == 1 &&
            siglen == 64;
  md_ctx_free(ctx);
  return ok;
}

static bool x25519_shared(EVP_PKEY* own, const unsigned char peer_pub[32],
                          unsigned char out[32]) {
  EVP_PKEY* peer = new_raw_public_key(EVP_PKEY_X25519, nullptr, peer_pub, 32);
  if (!peer) return false;
  EVP_PKEY_CTX* ctx = pkey_ctx_new(own, nullptr);
  size_t len = 32;
  bool ok = ctx && derive_init(ctx) == 1 && derive_set_peer(ctx, peer) == 1 &&
            derive(ctx, out, &len) == 1 && len == 32;
  if (ctx) pkey_ctx_free(ctx);
  pkey_free(peer);
  return ok;
}

// HKDF-SHA256 (RFC 5869), 64-byte output — matches the Python client's HKDF call
static bool hkdf64(const unsigned char ikm[32], const std::string& salt,
                   const std::string& info, unsigned char out[64]) {
  unsigned char prk[32];
  unsigned int prk_len = 32;
  if (!hmac_fn(sha256_md(), salt.data(), (int)salt.size(), ikm, 32, prk, &prk_len)) return false;
  std::string t1_input = info + '\x01';
  unsigned int block_len = 32;
  if (!hmac_fn(sha256_md(), prk, 32, (const unsigned char*)t1_input.data(), t1_input.size(),
               out, &block_len))
    return false;
  std::string t2_input((char*)out, 32);
  t2_input += info;
  t2_input += '\x02';
  if (!hmac_fn(sha256_md(), prk, 32, (const unsigned char*)t2_input.data(), t2_input.size(),
               out + 32, &block_len))
    return false;
  return true;
}

// ChaCha20-Poly1305 seal/open; nonce = 4 zero bytes + LE64 counter, tag appended
static bool aead_seal(const unsigned char key[32], uint64_t counter,
                      const std::string& plaintext, std::string& out) {
  unsigned char nonce[12] = {0};
  memcpy(nonce + 4, &counter, 8);  // little-endian on all supported targets
  EVP_CIPHER_CTX* ctx = cipher_ctx_new();
  if (!ctx) return false;
  out.resize(plaintext.size() + 16);
  int len = 0, total = 0;
  bool ok = encrypt_init(ctx, chacha20_poly1305(), nullptr, key, nonce) == 1;
  if (ok && !plaintext.empty()) {
    ok = encrypt_update(ctx, (unsigned char*)&out[0], &len,
                        (const unsigned char*)plaintext.data(), (int)plaintext.size()) == 1;
    total = len;
  }
  ok = ok && encrypt_final(ctx, (unsigned char*)&out[0] + total, &len) == 1;
  total += len;
  ok = ok && cipher_ctx_ctrl(ctx, CTRL_AEAD_GET_TAG, 16, &out[total]) == 1;
  cipher_ctx_free(ctx);
  if (!ok) return false;
  out.resize(total + 16);
  return true;
}

static bool aead_open(const unsigned char key[32], uint64_t counter,
                      const std::string& ciphertext, std::string& out) {
  if (ciphertext.size() < 16) return false;
  unsigned char nonce[12] = {0};
  memcpy(nonce + 4, &counter, 8);
  EVP_CIPHER_CTX* ctx = cipher_ctx_new();
  if (!ctx) return false;
  size_t body = ciphertext.size() - 16;
  out.resize(body);
  int len = 0, total = 0;
  bool ok = decrypt_init(ctx, chacha20_poly1305(), nullptr, key, nonce) == 1;
  ok = ok && cipher_ctx_ctrl(ctx, CTRL_AEAD_SET_TAG, 16, (void*)(ciphertext.data() + body)) == 1;
  if (ok && body) {
    ok = decrypt_update(ctx, (unsigned char*)&out[0], &len, (const unsigned char*)ciphertext.data(),
                        (int)body) == 1;
    total = len;
  }
  unsigned char scratch[16];  // AEAD final emits no bytes; it only checks the tag
  len = 0;
  ok = ok && decrypt_final(ctx, scratch, &len) == 1;
  cipher_ctx_free(ctx);
  if (!ok) return false;
  out.resize(total + len);
  return true;
}
}  // namespace relay_crypto

static bool fill_random(unsigned char* buf, size_t len) {
  // getrandom(2): no fd, so an attacker holding connections open (EMFILE) cannot
  // starve challenge generation the way an open("/dev/urandom") path could
  size_t have = 0;
  while (have < len) {
    ssize_t n = getrandom(buf + have, len - have, 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    have += (size_t)n;
  }
  return true;
}

enum class ConnState {
  ReadingFrame, Control, SplicedWaiting, Spliced, Closed,
  // local data-plane proxy ('X'): the daemon terminates the peer's AEAD, so the
  // Python event loop ships PLAINTEXT frames over loopback and the native side
  // does the ChaCha20-Poly1305 seal/open + wire IO (reference role parity: the
  // entire transport lives in the Go daemon, hivemind/p2p/p2p_daemon.py:84-147)
  ProxyLocalWait,   // local conn: 'X' accepted, outbound connect in flight
  ProxyConnecting,  // outbound conn: awaiting connect() completion
  ProxyLocal,       // local side of an established proxy pair (plaintext frames)
  ProxyRemote,      // remote side (wire AEAD frames; holds the pair's keys)
  // inbound listen-proxy ('Y'): the daemon owns the PUBLIC listener and pairs
  // each accepted wire conn with a fresh loopback conn to the Python server —
  // the same ProxyLocal/ProxyRemote frame machine then runs with the roles
  // produced by the responder-side handshake (hello #1, 'K' #2, sealed #3+)
  InboundRemoteWait,       // accepted wire conn: local leg still connecting
  InboundLocalConnecting,  // daemon->server loopback conn: awaiting connect()
};

static constexpr size_t MAX_PROXY_FRAME = (16u << 20) + 16;  // crypto_channel MAX_FRAME_SIZE + tag

struct Conn {
  int fd = -1;
  ConnState state = ConnState::ReadingFrame;
  std::string inbuf;        // frame assembly
  std::string outbuf;       // pending writes
  std::string peer_id;      // set for control lines
  std::string pending_peer_id;  // REGISTER received, awaiting Ed25519 proof
  std::string challenge;        // 32B nonce the proof must sign
  std::string token;        // set for pending dial/accept conns
  // encrypted control channel ('H' handshake): per-direction ChaCha20-Poly1305
  bool enc = false;
  unsigned char send_key[32] = {0}, recv_key[32] = {0};
  uint64_t send_ctr = 0, recv_ctr = 0;
  // proxy pair ('X'): key material lives on the ProxyRemote conn (send = seal
  // local->wire, recv = open wire->local); distinct from `enc` so queue_frame's
  // control sealing can never alias the data-plane keys
  uint64_t proxy_frames = 0;  // parsed frames in this direction (1 = raw hello)
  bool proxy_keys = false;
  int peer_fd = -1;         // spliced counterpart
  double created_ms = 0;
  bool want_write = false;
  bool read_paused = false;  // EPOLLIN interest dropped (partner over HIGH_WATER)
  bool closing_after_flush = false;  // partner gone: close once outbuf drains
  std::vector<int> owned_listeners;  // 'Y' listeners tied to this control conn
};

static int g_epoll = -1;
static relay_crypto::EVP_PKEY* g_relay_identity = nullptr;  // Ed25519, fresh per run
static unsigned char g_relay_pub[32] = {0};
static std::map<int, Conn*> g_conns;
static std::map<std::string, int> g_control;        // peer_id -> control fd
static std::map<std::string, int> g_pending_dials;  // token -> dialer fd
static std::map<int, uint16_t> g_inbound_listeners;  // 'Y' listener fd -> local port

static void set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

static bool is_local_client(int fd) {
  // the proxy control surface ('X'/'Y' and the 'K' key handoff) is local-only:
  // AF_UNIX peers are local by construction; AF_INET peers must be loopback.
  // (sockaddr_storage, NOT sockaddr_in: reading sin_addr from an AF_UNIX peer
  // yields path bytes and mis-classifies every unix client.)
  sockaddr_storage src{};
  socklen_t slen = sizeof(src);
  if (getpeername(fd, (sockaddr*)&src, &slen) != 0) return false;
  if (src.ss_family == AF_UNIX) return true;
  if (src.ss_family == AF_INET)
    return (ntohl(((sockaddr_in*)&src)->sin_addr.s_addr) >> 24) == 127;
  return false;
}

static void update_events(Conn* c) {
  epoll_event ev{};
  ev.events = (c->read_paused ? 0 : EPOLLIN) | (c->want_write ? EPOLLOUT : 0);
  ev.data.fd = c->fd;
  epoll_ctl(g_epoll, EPOLL_CTL_MOD, c->fd, &ev);
}

static void close_conn(int fd);

static void queue_write(Conn* c, const char* data, size_t len) {
  c->outbuf.append(data, len);
  if (!c->want_write) {
    c->want_write = true;
    update_events(c);
  }
}

static void queue_frame(Conn* c, const std::string& payload) {
  std::string body = payload;
  if (c->enc) {
    std::string sealed;
    if (!relay_crypto::aead_seal(c->send_key, c->send_ctr++, payload, sealed)) {
      close_conn(c->fd);
      return;
    }
    body.swap(sealed);
  }
  uint32_t n = htonl((uint32_t)body.size());
  std::string frame((char*)&n, 4);
  frame += body;
  queue_write(c, frame.data(), frame.size());
}

static void close_conn(int fd) {
  auto it = g_conns.find(fd);
  if (it == g_conns.end()) return;
  Conn* c = it->second;
  for (int lfd : c->owned_listeners) {
    // 'Y' listener lifetime is its owner control conn's: a dead server must not
    // leave the daemon accepting wire conns nobody will answer
    g_inbound_listeners.erase(lfd);
    epoll_ctl(g_epoll, EPOLL_CTL_DEL, lfd, nullptr);
    close(lfd);
  }
  if (!c->peer_id.empty()) {
    auto reg = g_control.find(c->peer_id);
    if (reg != g_control.end() && reg->second == fd) g_control.erase(reg);
  }
  if (!c->token.empty()) {
    auto pend = g_pending_dials.find(c->token);
    if (pend != g_pending_dials.end() && pend->second == fd) g_pending_dials.erase(pend);
  }
  int partner = c->peer_fd;
  epoll_ctl(g_epoll, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  g_conns.erase(it);
  delete c;
  if (partner >= 0) {
    auto pit = g_conns.find(partner);
    if (pit != g_conns.end()) {
      Conn* p = pit->second;
      p->peer_fd = -1;
      if (p->outbuf.empty()) {
        close_conn(partner);  // pipe is bidirectional: one side gone, tear down both
      } else {
        // in-flight bytes the peer already sent must not be discarded: stop
        // reading, flush the tail, then close from on_writable (the periodic
        // sweep reaps flushers whose receiver never drains)
        p->closing_after_flush = true;
        p->read_paused = true;
        p->created_ms = now_ms();
        update_events(p);
      }
    }
  }
}

static void enable_keepalive(int fd) {
  int ka = 1, idle = 30, intvl = 10, cnt = 3;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &ka, sizeof(ka));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
}

static void splice_pair(Conn* a, Conn* b) {
  a->peer_fd = b->fd;
  b->peer_fd = a->fd;
  a->state = b->state = ConnState::Spliced;
  enable_keepalive(a->fd);
  enable_keepalive(b->fd);
  // 'O' goes out under whatever framing each side negotiated; after it, both
  // sockets are a raw byte pipe (the peers' own end-to-end Noise takes over).
  // queue_frame can close a conn on an AEAD-seal failure — re-check liveness
  // before touching either side again (close_conn also tears down the partner).
  int a_fd = a->fd, b_fd = b->fd;
  queue_frame(a, "O");
  if (g_conns.find(a_fd) == g_conns.end()) return;
  queue_frame(b, "O");
  if (g_conns.find(b_fd) == g_conns.end()) return;
  a->enc = b->enc = false;
  // any bytes that raced ahead of the match are forwarded
  if (!a->inbuf.empty()) { queue_write(b, a->inbuf.data(), a->inbuf.size()); a->inbuf.clear(); }
  if (!b->inbuf.empty()) { queue_write(a, b->inbuf.data(), b->inbuf.size()); b->inbuf.clear(); }
}

static void refuse_and_close(Conn* c) {
  // 'E' then close — but through the flush path, or the refusal frame would be
  // discarded with the fd (close_conn does not drain outbuf)
  queue_frame(c, "E");
  c->closing_after_flush = true;
  c->read_paused = true;
  c->created_ms = now_ms();
  update_events(c);
}

static void forward_frame(Conn* partner, const std::string& payload) {
  uint32_t be = htonl((uint32_t)payload.size());
  std::string frame((char*)&be, 4);
  frame += payload;
  queue_write(partner, frame.data(), frame.size());
}

static bool proxy_process(Conn* c) {
  // Parse frames buffered on one side of a proxy pair; returns false when `c`
  // was closed. Frame protocol per direction (fixed by the Python handshake):
  //   local  #1 = plaintext hello (forward raw)   #2 = 'K' key install (consume)
  //          #3+ = plaintext payloads (seal toward the wire)
  //   remote #1 = plaintext hello (forward raw)   #2+ = AEAD ciphertext (open);
  //          held unparsed until the keys arrive — race-free by construction
  auto pit = g_conns.find(c->peer_fd);
  if (pit == g_conns.end()) { close_conn(c->fd); return false; }
  Conn* partner = pit->second;
  Conn* remote = (c->state == ConnState::ProxyRemote) ? c : partner;
  while (c->inbuf.size() >= 4) {
    uint32_t len = ntohl(*(uint32_t*)c->inbuf.data());
    if (len > MAX_PROXY_FRAME) { close_conn(c->fd); return false; }
    if (c->inbuf.size() < 4 + (size_t)len) break;
    if (c->state == ConnState::ProxyRemote && c->proxy_frames >= 1 && !c->proxy_keys)
      break;  // ciphertext before the local 'K': hold (bounded by the flood cap)
    std::string payload = c->inbuf.substr(4, len);
    c->inbuf.erase(0, 4 + len);
    c->proxy_frames++;
    if (c->proxy_frames == 1) {  // the handshake hello crosses unmodified
      forward_frame(partner, payload);
      continue;
    }
    if (c->state == ConnState::ProxyLocal) {
      if (c->proxy_frames == 2) {  // 'K' + send_key + recv_key + LE64 ctr x2
        if (payload.size() != 1 + 32 + 32 + 8 + 8 || payload[0] != 'K' ||
            !relay_crypto::channel_available) {
          close_conn(c->fd);
          return false;
        }
        memcpy(remote->send_key, payload.data() + 1, 32);
        memcpy(remote->recv_key, payload.data() + 33, 32);
        memcpy(&remote->send_ctr, payload.data() + 65, 8);
        memcpy(&remote->recv_ctr, payload.data() + 73, 8);
        remote->proxy_keys = true;
        // wire frames that arrived before the keys can drain now; a dead remote
        // makes the pair useless, so tear down both sides
        if (remote != c && !remote->inbuf.empty()) {
          int self_fd = c->fd;
          if (!proxy_process(remote)) {
            if (g_conns.find(self_fd) != g_conns.end()) close_conn(self_fd);
            return false;
          }
        }
        continue;
      }
      std::string sealed;
      if (!remote->proxy_keys ||
          !relay_crypto::aead_seal(remote->send_key, remote->send_ctr++, payload, sealed)) {
        close_conn(c->fd);
        return false;
      }
      forward_frame(partner, sealed);
    } else {  // ProxyRemote: open wire ciphertext, forward plaintext to local
      std::string opened;
      if (!relay_crypto::aead_open(c->recv_key, c->recv_ctr++, payload, opened)) {
        close_conn(c->fd);  // tampered/desynced frame is fatal to the pair
        return false;
      }
      forward_frame(partner, opened);
    }
  }
  return true;
}

static void handle_control_frame(Conn* c, const std::string& payload) {
  if (payload.empty()) { close_conn(c->fd); return; }
  char kind = payload[0];
  if (kind == 'H') {
    // Channel handshake: 'H' + client X25519 ephemeral(32) ->
    // 'S' + relay ephemeral(32) + relay Ed25519 pub(32) + sig(64) over
    // "hivemind-relay-hs:" + client_eph + relay_eph. All later frames on this
    // conn are ChaCha20-Poly1305 sealed (keys = HKDF-SHA256 of the ECDH secret),
    // so INCOMING tokens and registration proofs are opaque to on-path observers,
    // and pinning the relay pub on the client defeats a proxying relay.
    if (c->enc || !relay_crypto::channel_available || g_relay_identity == nullptr ||
        payload.size() != 1 + 32) {
      refuse_and_close(c);
      return;
    }
    relay_crypto::EVP_PKEY* eph = relay_crypto::generate_key(relay_crypto::EVP_PKEY_X25519);
    unsigned char eph_pub[32], shared[32], okm[64], sig[64];
    bool ok = eph != nullptr && relay_crypto::raw_public(eph, eph_pub) &&
              relay_crypto::x25519_shared(eph, (const unsigned char*)payload.data() + 1, shared);
    if (ok) {
      std::string transcript = "hivemind-relay-hs:" + payload.substr(1, 32) +
                               std::string((char*)eph_pub, 32);
      ok = relay_crypto::hkdf64(shared, "hivemind-relay-hs", "control", okm) &&
           relay_crypto::ed25519_sign(g_relay_identity, transcript, sig);
    }
    if (eph) relay_crypto::pkey_free(eph);
    if (!ok) { refuse_and_close(c); return; }
    std::string reply = "S" + std::string((char*)eph_pub, 32) +
                        std::string((char*)g_relay_pub, 32) + std::string((char*)sig, 64);
    queue_frame(c, reply);  // plaintext: the client derives keys from this reply
    memcpy(c->recv_key, okm, 32);       // client -> relay
    memcpy(c->send_key, okm + 32, 32);  // relay -> client
    c->send_ctr = c->recv_ctr = 0;
    c->enc = true;
  } else if (kind == 'R') {
    std::string peer_id = payload.substr(1);
    if (peer_id.empty()) { close_conn(c->fd); return; }
    if (relay_crypto::available) {
      // Challenge-response registration: a peer_id is sha2-256 multihash
      // (0x12 0x20 + 32B digest of the Ed25519 pubkey), so ownership is provable.
      if (peer_id.size() != 34 || peer_id[0] != 0x12 || (unsigned char)peer_id[1] != 0x20) {
        refuse_and_close(c);
        return;
      }
      unsigned char nonce[32];
      if (!fill_random(nonce, sizeof(nonce))) { refuse_and_close(c); return; }
      c->pending_peer_id = peer_id;
      c->challenge.assign((char*)nonce, sizeof(nonce));
      queue_frame(c, std::string("C") + c->challenge);
      return;
    }
    // Legacy (no libcrypto): first registration wins — a later REGISTER for the
    // same peer_id is REFUSED while the original control line is alive, so an
    // attacker cannot evict a registered peer and capture its INCOMING
    // notifications; dead lines are reaped by TCP keepalive + EPOLLHUP.
    auto old = g_control.find(peer_id);
    if (old != g_control.end() && old->second != c->fd) {
      queue_frame(c, "E");
      return;
    }
    // re-registering a different id on the same line must not leave a dangling
    // g_control entry pointing at this fd (a later DIAL would deref a stale conn)
    if (!c->peer_id.empty() && c->peer_id != peer_id) g_control.erase(c->peer_id);
    c->peer_id = peer_id;
    g_control[c->peer_id] = c->fd;
    c->state = ConnState::Control;
    enable_keepalive(c->fd);
    queue_frame(c, "O");
  } else if (kind == 'P') {
    // PROOF: 'P' + 32B raw Ed25519 pubkey + 64B signature over
    // "hivemind-relay-register:" + challenge + peer_id
    if (c->pending_peer_id.empty() || payload.size() != 1 + 32 + 64) {
      refuse_and_close(c);
      return;
    }
    std::string pubkey = payload.substr(1, 32);
    std::string signature = payload.substr(33, 64);
    unsigned char digest[32];
    bool id_matches = relay_crypto::sha256(pubkey, digest) &&
                      memcmp(digest, c->pending_peer_id.data() + 2, 32) == 0;
    std::string message = "hivemind-relay-register:" + c->challenge + c->pending_peer_id;
    if (!id_matches || !relay_crypto::ed25519_verify(pubkey, message, signature)) {
      refuse_and_close(c);
      return;
    }
    // proven owner: evict any stale control line for this id (only the key holder
    // reaches this point, so this is reclamation, not hijack)
    auto old = g_control.find(c->pending_peer_id);
    if (old != g_control.end() && old->second != c->fd) close_conn(old->second);
    if (!c->peer_id.empty() && c->peer_id != c->pending_peer_id) g_control.erase(c->peer_id);
    c->peer_id = c->pending_peer_id;
    c->pending_peer_id.clear();
    c->challenge.clear();
    g_control[c->peer_id] = c->fd;
    c->state = ConnState::Control;
    enable_keepalive(c->fd);
    queue_frame(c, "O");
  } else if (kind == 'D' && payload.size() > 17) {
    std::string token = payload.substr(1, 16);
    std::string target = payload.substr(17);
    auto reg = g_control.find(target);
    auto target_conn = reg == g_control.end() ? g_conns.end() : g_conns.find(reg->second);
    if (target_conn == g_conns.end()) { refuse_and_close(c); return; }
    c->token = token;
    c->state = ConnState::SplicedWaiting;
    g_pending_dials[token] = c->fd;
    c->created_ms = now_ms();
    queue_frame(target_conn->second, std::string("I") + token);
  } else if (kind == 'X' && payload.size() >= 4) {
    // PROXY-CONNECT: 'X' + u16 BE port + ip — open an outbound data-plane
    // connection; reply 'O' once connected, then frame-forward with AEAD
    // termination (see proxy_process). Requires libcrypto (the whole point is
    // native seal/open). STRICTLY LOOPBACK-ONLY: this is a local data-plane
    // offload for co-resident peers — honoring it from a remote client would
    // turn every public relay into an open TCP proxy / SSRF vector.
    if (!is_local_client(c->fd) || c->peer_fd >= 0 || c->enc ||
        !relay_crypto::channel_available) {
      refuse_and_close(c);
      return;
    }
    uint16_t port = ((uint8_t)payload[1] << 8) | (uint8_t)payload[2];
    std::string host = payload.substr(3);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(port);
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1) { refuse_and_close(c); return; }
    int rfd = socket(AF_INET, SOCK_STREAM, 0);
    if (rfd < 0) { refuse_and_close(c); return; }
    set_nonblock(rfd);
    int rc = connect(rfd, (sockaddr*)&addr, sizeof(addr));
    if (rc < 0 && errno != EINPROGRESS) { close(rfd); refuse_and_close(c); return; }
    Conn* r = new Conn();
    r->fd = rfd;
    r->state = ConnState::ProxyConnecting;
    r->created_ms = now_ms();
    r->peer_fd = c->fd;
    r->want_write = true;
    g_conns[rfd] = r;
    epoll_event rev{};
    rev.events = EPOLLOUT;
    rev.data.fd = rfd;
    epoll_ctl(g_epoll, EPOLL_CTL_ADD, rfd, &rev);
    c->peer_fd = rfd;
    c->state = ConnState::ProxyLocalWait;
    c->created_ms = now_ms();
  } else if (kind == 'Y' && payload.size() == 5) {
    // inbound listen-proxy registration: 'Y' <u16 BE public_port> <u16 BE
    // local_port> from a LOCAL server process. The daemon binds public_port
    // (0 = ephemeral), replies 'O' <u16 BE actual_port>, and forwards every
    // accepted wire conn to 127.0.0.1:local_port as a ProxyRemote/ProxyLocal
    // pair — the server's AEAD then terminates HERE for both directions. The
    // listener dies with this control conn.
    if (!is_local_client(c->fd) || !relay_crypto::channel_available) {
      refuse_and_close(c);
      return;
    }
    uint16_t public_port = ((uint8_t)payload[1] << 8) | (uint8_t)payload[2];
    uint16_t local_port = ((uint8_t)payload[3] << 8) | (uint8_t)payload[4];
    int lfd = socket(AF_INET, SOCK_STREAM, 0);
    if (lfd < 0) { refuse_and_close(c); return; }
    int one = 1;
    setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in baddr{};
    baddr.sin_family = AF_INET;
    baddr.sin_addr.s_addr = INADDR_ANY;
    baddr.sin_port = htons(public_port);
    if (bind(lfd, (sockaddr*)&baddr, sizeof(baddr)) < 0 || listen(lfd, 128) < 0) {
      close(lfd);
      refuse_and_close(c);
      return;
    }
    set_nonblock(lfd);
    socklen_t blen = sizeof(baddr);
    getsockname(lfd, (sockaddr*)&baddr, &blen);
    g_inbound_listeners[lfd] = local_port;
    c->owned_listeners.push_back(lfd);
    epoll_event lev{};
    lev.events = EPOLLIN;
    lev.data.fd = lfd;
    epoll_ctl(g_epoll, EPOLL_CTL_ADD, lfd, &lev);
    uint16_t actual = ntohs(baddr.sin_port);
    char reply[3] = {'O', (char)(actual >> 8), (char)(actual & 0xff)};
    queue_frame(c, std::string(reply, 3));
  } else if (kind == 'W') {
    sockaddr_in observed{};
    socklen_t olen = sizeof(observed);
    if (getpeername(c->fd, (sockaddr*)&observed, &olen) == 0) {
      char ip[INET_ADDRSTRLEN];
      inet_ntop(AF_INET, &observed.sin_addr, ip, sizeof(ip));
      char reply[64];
      int n = snprintf(reply, sizeof(reply), "O%s:%d", ip, ntohs(observed.sin_port));
      queue_frame(c, std::string(reply, n));
    } else {
      queue_frame(c, "E");
    }
  } else if (kind == 'A' && payload.size() >= 17) {
    std::string token = payload.substr(1, 16);
    auto pend = g_pending_dials.find(token);
    auto dialer_it = pend == g_pending_dials.end() ? g_conns.end() : g_conns.find(pend->second);
    if (dialer_it == g_conns.end()) { refuse_and_close(c); return; }
    Conn* dialer = dialer_it->second;
    g_pending_dials.erase(pend);
    dialer->token.clear();
    splice_pair(dialer, c);
  } else {
    close_conn(c->fd);
  }
}

static void on_readable(Conn* c) {
  char buf[SPLICE_BUF];
  while (true) {
    ssize_t n = read(c->fd, buf, sizeof(buf));
    if (n == 0) { close_conn(c->fd); return; }
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      close_conn(c->fd); return;
    }
    if (c->state == ConnState::Spliced) {
      auto pit = g_conns.find(c->peer_fd);
      if (pit == g_conns.end()) { close_conn(c->fd); return; }
      queue_write(pit->second, buf, n);
      if (pit->second->outbuf.size() > HIGH_WATER) {
        // real backpressure: drop EPOLLIN interest until the partner drains
        c->read_paused = true;
        update_events(c);
        break;
      }
    } else if (c->state == ConnState::ProxyLocal || c->state == ConnState::ProxyRemote) {
      c->inbuf.append(buf, n);
      // pre-key flood bound: a remote shipping ciphertext before the local 'K'
      // may buffer at most one max frame + slack
      if (c->inbuf.size() > MAX_PROXY_FRAME + (1u << 20)) { close_conn(c->fd); return; }
      if (!proxy_process(c)) return;
      auto pit = g_conns.find(c->peer_fd);
      if (pit != g_conns.end() && pit->second->outbuf.size() > HIGH_WATER) {
        c->read_paused = true;
        update_events(c);
        break;
      }
    } else if (c->state == ConnState::ProxyLocalWait ||
               c->state == ConnState::InboundRemoteWait) {
      // partner leg still connecting: buffer ('X' local: at most an eager
      // hello; 'Y' wire: the initiator hello plus possibly its sealed confirm)
      c->inbuf.append(buf, n);
      if (c->inbuf.size() > MAX_PROXY_FRAME + (1u << 20)) { close_conn(c->fd); return; }
    } else {
      c->inbuf.append(buf, n);
      while (c->state != ConnState::Spliced && c->inbuf.size() >= 4) {
        uint32_t len = ntohl(*(uint32_t*)c->inbuf.data());
        if (len > MAX_FRAME) { close_conn(c->fd); return; }
        if (c->inbuf.size() < 4 + len) break;
        std::string payload = c->inbuf.substr(4, len);
        c->inbuf.erase(0, 4 + len);
        if (c->enc) {
          std::string opened;
          if (!relay_crypto::aead_open(c->recv_key, c->recv_ctr++, payload, opened)) {
            close_conn(c->fd);  // tampered/replayed frame: drop the connection
            return;
          }
          payload.swap(opened);
        }
        handle_control_frame(c, payload);
        if (g_conns.find(c->fd) == g_conns.end()) return;  // frame handler closed us
        if (c->closing_after_flush) return;  // refused: flush 'E', ignore further input
      }
    }
  }
}

static void maybe_resume_partner(Conn* c) {
  // our queue drained below LOW_WATER: re-arm the peer that was paused on us
  if (c->outbuf.size() >= LOW_WATER || c->peer_fd < 0) return;
  auto pit = g_conns.find(c->peer_fd);
  if (pit != g_conns.end() && pit->second->read_paused) {
    pit->second->read_paused = false;
    update_events(pit->second);
  }
}

static void on_writable(Conn* c) {
  if (c->state == ConnState::InboundLocalConnecting) {
    // daemon->server loopback leg of a 'Y' pair landed: the accepted wire conn
    // becomes ProxyRemote (its buffered initiator hello/ciphertext drains
    // through the shared frame machine) and this conn carries plaintext
    int err = 0;
    socklen_t elen = sizeof(err);
    getsockopt(c->fd, SOL_SOCKET, SO_ERROR, &err, &elen);
    auto pit = g_conns.find(c->peer_fd);
    if (err != 0 || pit == g_conns.end()) { close_conn(c->fd); return; }
    c->state = ConnState::ProxyLocal;
    c->want_write = false;
    int one = 1;
    setsockopt(c->fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    enable_keepalive(c->fd);
    update_events(c);
    Conn* wire = pit->second;
    wire->state = ConnState::ProxyRemote;
    enable_keepalive(wire->fd);
    if (!wire->inbuf.empty()) proxy_process(wire);
    return;
  }
  if (c->state == ConnState::ProxyConnecting) {
    int err = 0;
    socklen_t elen = sizeof(err);
    getsockopt(c->fd, SOL_SOCKET, SO_ERROR, &err, &elen);
    auto pit = g_conns.find(c->peer_fd);
    if (err != 0 || pit == g_conns.end()) { close_conn(c->fd); return; }
    c->state = ConnState::ProxyRemote;
    c->want_write = false;
    int one = 1;
    setsockopt(c->fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    enable_keepalive(c->fd);
    update_events(c);
    Conn* local = pit->second;
    local->state = ConnState::ProxyLocal;
    enable_keepalive(local->fd);
    queue_frame(local, "O");
    if (!local->inbuf.empty()) proxy_process(local);  // an eager hello was buffered
    return;
  }
  while (!c->outbuf.empty()) {
    ssize_t n = write(c->fd, c->outbuf.data(), c->outbuf.size());
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) { maybe_resume_partner(c); return; }
      close_conn(c->fd); return;
    }
    c->outbuf.erase(0, n);
  }
  if (c->closing_after_flush) { close_conn(c->fd); return; }
  c->want_write = false;
  update_events(c);
  maybe_resume_partner(c);
}

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 34000;
  signal(SIGPIPE, SIG_IGN);
  relay_crypto::available = relay_crypto::load();
  if (!relay_crypto::available)
    fprintf(stderr, "relay: libcrypto unavailable, registrations are UNAUTHENTICATED\n");
  if (relay_crypto::channel_available) {
    const char* identity_path = argc > 2 && argv[2][0] != '\0' ? argv[2] : nullptr;
    if (identity_path != nullptr) {
      // persistent identity so client pins survive daemon restarts
      FILE* f = fopen(identity_path, "rb");
      if (f != nullptr) {
        unsigned char raw[32];
        if (fread(raw, 1, 32, f) == 32)
          g_relay_identity = relay_crypto::new_raw_private_key(
              relay_crypto::EVP_PKEY_ED25519, nullptr, raw, 32);
        fclose(f);
      }
    }
    if (g_relay_identity == nullptr) {
      g_relay_identity = relay_crypto::generate_key(relay_crypto::EVP_PKEY_ED25519);
      if (g_relay_identity != nullptr && identity_path != nullptr) {
        unsigned char raw[32];
        size_t raw_len = 32;
        int fd = open(identity_path, O_WRONLY | O_CREAT | O_TRUNC, 0600);
        if (fd >= 0 &&
            relay_crypto::get_raw_private_key(g_relay_identity, raw, &raw_len) == 1 &&
            raw_len == 32) {
          if (write(fd, raw, 32) != 32)
            fprintf(stderr, "relay: could not persist identity to %s\n", identity_path);
        }
        if (fd >= 0) close(fd);
      }
    }
    if (g_relay_identity != nullptr && !relay_crypto::raw_public(g_relay_identity, g_relay_pub)) {
      g_relay_identity = nullptr;
      fprintf(stderr, "relay: identity keygen failed, encrypted control disabled\n");
    }
  }

  int listener = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  // argv[4]: optional TCP bind address. A PRIVATE daemon (spawned for one
  // process with a unix control socket) binds 127.0.0.1 so it exposes no
  // remote relay surface; public relay deployments keep the INADDR_ANY default.
  const char* bind_host = argc > 4 && argv[4][0] != '\0' ? argv[4] : nullptr;
  if (bind_host != nullptr && inet_pton(AF_INET, bind_host, &addr.sin_addr) != 1) {
    fprintf(stderr, "relay: invalid bind address %s\n", bind_host);
    return 1;
  }
  addr.sin_port = htons((uint16_t)port);
  if (bind(listener, (sockaddr*)&addr, sizeof(addr)) < 0) { perror("bind"); return 1; }
  if (listen(listener, 128) < 0) { perror("listen"); return 1; }
  set_nonblock(listener);

  // optional same-user-only AF_UNIX listener (see usage comment): the socket
  // file is created 0600, so the kernel enforces that only this user's
  // processes can reach the 'K' key-handoff path
  int unix_listener = -1;
  const char* unix_path = argc > 3 && argv[3][0] != '\0' ? argv[3] : nullptr;
  if (unix_path != nullptr) {
    unix_listener = socket(AF_UNIX, SOCK_STREAM, 0);
    sockaddr_un uaddr{};
    uaddr.sun_family = AF_UNIX;
    if (strlen(unix_path) >= sizeof(uaddr.sun_path)) {
      fprintf(stderr, "relay: unix socket path too long: %s\n", unix_path);
      return 1;
    }
    strncpy(uaddr.sun_path, unix_path, sizeof(uaddr.sun_path) - 1);
    unlink(unix_path);
    mode_t old_umask = umask(0177);
    int rc = bind(unix_listener, (sockaddr*)&uaddr, sizeof(uaddr));
    umask(old_umask);
    if (rc < 0) { perror("unix bind"); return 1; }
    if (listen(unix_listener, 128) < 0) { perror("unix listen"); return 1; }
    set_nonblock(unix_listener);
  }

  socklen_t alen = sizeof(addr);
  getsockname(listener, (sockaddr*)&addr, &alen);
  printf("relay listening on port %d\n", ntohs(addr.sin_port));
  if (g_relay_identity != nullptr) {
    char hex[65];
    for (int i = 0; i < 32; i++) snprintf(hex + 2 * i, 3, "%02x", g_relay_pub[i]);
    printf("relay identity %s\n", hex);
  } else {
    // exactly two startup lines in EVERY build, emitted in one flush: launchers
    // can block-read both instead of racing a buffered stream with select()
    printf("relay encryption unavailable\n");
  }
  fflush(stdout);

  g_epoll = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = listener;
  epoll_ctl(g_epoll, EPOLL_CTL_ADD, listener, &ev);
  if (unix_listener >= 0) {
    epoll_event uev{};
    uev.events = EPOLLIN;
    uev.data.fd = unix_listener;
    epoll_ctl(g_epoll, EPOLL_CTL_ADD, unix_listener, &uev);
  }

  std::vector<epoll_event> events(256);
  double last_sweep = now_ms();
  while (true) {
    int n = epoll_wait(g_epoll, events.data(), (int)events.size(), 1000);
    for (int i = 0; i < n; i++) {
      int fd = events[i].data.fd;
      if (fd == listener || (unix_listener >= 0 && fd == unix_listener)) {
        while (true) {
          int client = accept(fd, nullptr, nullptr);
          if (client < 0) break;
          set_nonblock(client);
          // harmless no-op (ENOTSUP) on AF_UNIX clients
          setsockopt(client, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn* c = new Conn();
          c->fd = client;
          c->created_ms = now_ms();
          g_conns[client] = c;
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = client;
          epoll_ctl(g_epoll, EPOLL_CTL_ADD, client, &cev);
        }
        continue;
      }
      auto inbound_it = g_inbound_listeners.find(fd);
      if (inbound_it != g_inbound_listeners.end()) {
        // 'Y' public listener: pair every accepted wire conn with a fresh
        // loopback connect to the registered server port
        uint16_t local_port = inbound_it->second;
        while (true) {
          int wire = accept(fd, nullptr, nullptr);
          if (wire < 0) break;
          set_nonblock(wire);
          setsockopt(wire, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          sockaddr_in laddr{};
          laddr.sin_family = AF_INET;
          laddr.sin_port = htons(local_port);
          inet_pton(AF_INET, "127.0.0.1", &laddr.sin_addr);
          int local = socket(AF_INET, SOCK_STREAM, 0);
          bool ok = local >= 0;
          if (ok) {
            set_nonblock(local);
            int rc = connect(local, (sockaddr*)&laddr, sizeof(laddr));
            ok = rc == 0 || errno == EINPROGRESS;
          }
          if (!ok) {
            if (local >= 0) close(local);
            close(wire);
            continue;
          }
          Conn* r = new Conn();
          r->fd = wire;
          r->state = ConnState::InboundRemoteWait;
          r->created_ms = now_ms();
          r->peer_fd = local;
          g_conns[wire] = r;
          epoll_event wev{};
          wev.events = EPOLLIN;
          wev.data.fd = wire;
          epoll_ctl(g_epoll, EPOLL_CTL_ADD, wire, &wev);
          Conn* l = new Conn();
          l->fd = local;
          l->state = ConnState::InboundLocalConnecting;
          l->created_ms = now_ms();
          l->peer_fd = wire;
          l->want_write = true;
          g_conns[local] = l;
          epoll_event lev2{};
          lev2.events = EPOLLOUT;
          lev2.data.fd = local;
          epoll_ctl(g_epoll, EPOLL_CTL_ADD, local, &lev2);
        }
        continue;
      }
      auto it = g_conns.find(fd);
      if (it == g_conns.end()) continue;
      if (events[i].events & (EPOLLERR | EPOLLHUP)) { close_conn(fd); continue; }
      if (events[i].events & EPOLLIN) on_readable(it->second);
      if (g_conns.find(fd) == g_conns.end()) continue;
      if (events[i].events & EPOLLOUT) on_writable(it->second);
    }
    if (now_ms() - last_sweep > 5000) {  // expire unmatched dials + stuck flushers
      last_sweep = now_ms();
      std::vector<int> expired;
      for (auto& [token, fd] : g_pending_dials) {
        auto it = g_conns.find(fd);
        if (it == g_conns.end() || now_ms() - it->second->created_ms > PENDING_DIAL_TTL_MS)
          expired.push_back(fd);
      }
      for (auto& [fd, conn] : g_conns) {
        if (conn->closing_after_flush && now_ms() - conn->created_ms > FLUSH_TTL_MS)
          expired.push_back(fd);
        if ((conn->state == ConnState::ProxyConnecting || conn->state == ConnState::ProxyLocalWait
             || conn->state == ConnState::InboundRemoteWait
             || conn->state == ConnState::InboundLocalConnecting)
            && now_ms() - conn->created_ms > PENDING_DIAL_TTL_MS)
          expired.push_back(fd);
      }
      for (int fd : expired) close_conn(fd);
    }
  }
}
