// hivemind_tpu relay daemon — the native transport component.
//
// Role parity: the circuit-relay v2 capability of the reference's go-libp2p daemon
// (hivemind/p2p/p2p_daemon.py:114-137 enables relay + auto-relay): peers behind NAT
// register here over an OUTBOUND connection and become dialable through the relay.
// Security model: the relay splices raw bytes; peers run their end-to-end Noise
// handshake THROUGH it, so the relay only ever sees AEAD ciphertext.
//
// Control protocol (length-prefixed frames: u32 big-endian length + payload):
//   REGISTER  'R' <peer_id bytes>        -> 'C' <32B challenge>  (when libcrypto is
//             available; peer must prove it owns the Ed25519 key its peer_id hashes)
//             -> 'O' directly in legacy mode (no libcrypto on the host)
//   PROOF     'P' <32B ed25519 pubkey> <64B signature>  -> 'O'  (signature over
//             "hivemind-relay-register:" + challenge + peer_id; sha256(pubkey) must
//             equal the multihash digest in peer_id). A VALID proof also evicts any
//             stale control line for the same peer_id — only the key owner can, so
//             a NAT-rebound peer reclaims its identity immediately instead of
//             waiting for TCP keepalive to reap the dead line.
//             Known limitation: the proof does not authenticate the RELAY, so a
//             malicious relay the victim actively registers through can proxy the
//             live challenge from another relay and capture the victim's
//             registration THERE (availability only: dialers still authenticate the
//             target end-to-end via Noise, so a captured INCOMING cannot be
//             answered convincingly — the dial just fails). Closing it requires a
//             relay keypair + encrypted control line (Noise to a pinned relay id);
//             message-binding schemes don't survive a transparent-proxy relay.
//   DIAL      'D' <16B token> <target_id>-> 'O' then splice  (sent on a FRESH conn)
//   ACCEPT    'A' <16B token>            -> 'O' then splice  (fresh conn from target)
//   INCOMING  'I' <16B token>            relay -> target's control line
//   WHOAMI    'W'                        -> 'O' <ip:port>  (the conn's observed
//             public endpoint — the STUN-style observation NATed peers need for
//             hole punching; role parity with libp2p identify/observed-addr)
// After 'O' on a DIAL/ACCEPT pair the two sockets are spliced byte-for-byte.
//
// Build: g++ -O2 -std=c++17 -o relay_daemon relay_daemon.cpp   (see Makefile)

#include <arpa/inet.h>
#include <dlfcn.h>
#include <errno.h>
#include <fcntl.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <signal.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/random.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>
#include <map>
#include <string>
#include <vector>

static constexpr size_t MAX_FRAME = 1 << 20;       // control frames only
static constexpr size_t SPLICE_BUF = 1 << 16;      // per-direction pipe buffer
static constexpr int PENDING_DIAL_TTL_MS = 30000;  // unmatched dials expire
// Backpressure water marks. epoll here is level-triggered, so merely breaking out
// of the read loop is NOT backpressure (the next epoll_wait re-fires EPOLLIN and
// reads another 64 KiB): above HIGH_WATER the reading fd DROPS EPOLLIN interest and
// is re-armed from on_writable once the partner drains below LOW_WATER — bounding
// each direction at HIGH_WATER + one read (~576 KiB).
static constexpr size_t HIGH_WATER = 8 * SPLICE_BUF;
static constexpr size_t LOW_WATER = 2 * SPLICE_BUF;
static constexpr int FLUSH_TTL_MS = 60000;  // closing_after_flush conns expire

static double now_ms() {
  using namespace std::chrono;
  return duration_cast<duration<double, std::milli>>(steady_clock::now().time_since_epoch()).count();
}

// ---- Ed25519 registration proof via the system libcrypto ----------------------
// This image ships libcrypto.so.3 but no OpenSSL headers, so the few stable-ABI
// entry points needed for one-shot Ed25519 verification are declared here and
// resolved with dlopen at startup. If libcrypto is absent the daemon degrades to
// the legacy unauthenticated first-registration-wins behavior (and says so).
namespace relay_crypto {
typedef struct evp_pkey_st EVP_PKEY;
typedef struct evp_md_ctx_st EVP_MD_CTX;
static constexpr int EVP_PKEY_ED25519 = 1087;  // NID_ED25519, stable since 1.1.1

static EVP_PKEY* (*new_raw_public_key)(int, void*, const unsigned char*, size_t) = nullptr;
static void (*pkey_free)(EVP_PKEY*) = nullptr;
static EVP_MD_CTX* (*md_ctx_new)() = nullptr;
static void (*md_ctx_free)(EVP_MD_CTX*) = nullptr;
static int (*digest_verify_init)(EVP_MD_CTX*, void**, const void*, void*, EVP_PKEY*) = nullptr;
static int (*digest_verify)(EVP_MD_CTX*, const unsigned char*, size_t, const unsigned char*, size_t) = nullptr;
static unsigned char* (*sha256_fn)(const unsigned char*, size_t, unsigned char*) = nullptr;

static bool load() {
  void* lib = dlopen("libcrypto.so.3", RTLD_NOW | RTLD_LOCAL);
  if (!lib) lib = dlopen("libcrypto.so", RTLD_NOW | RTLD_LOCAL);
  if (!lib) return false;
  new_raw_public_key = (decltype(new_raw_public_key))dlsym(lib, "EVP_PKEY_new_raw_public_key");
  pkey_free = (decltype(pkey_free))dlsym(lib, "EVP_PKEY_free");
  md_ctx_new = (decltype(md_ctx_new))dlsym(lib, "EVP_MD_CTX_new");
  md_ctx_free = (decltype(md_ctx_free))dlsym(lib, "EVP_MD_CTX_free");
  digest_verify_init = (decltype(digest_verify_init))dlsym(lib, "EVP_DigestVerifyInit");
  digest_verify = (decltype(digest_verify))dlsym(lib, "EVP_DigestVerify");
  sha256_fn = (decltype(sha256_fn))dlsym(lib, "SHA256");
  return new_raw_public_key && pkey_free && md_ctx_new && md_ctx_free &&
         digest_verify_init && digest_verify && sha256_fn;
}

static bool available = false;

static bool sha256(const std::string& data, unsigned char out[32]) {
  if (!available) return false;
  return sha256_fn((const unsigned char*)data.data(), data.size(), out) != nullptr;
}

static bool ed25519_verify(const std::string& pubkey_raw, const std::string& message,
                           const std::string& signature) {
  if (!available || pubkey_raw.size() != 32 || signature.size() != 64) return false;
  EVP_PKEY* key = new_raw_public_key(EVP_PKEY_ED25519, nullptr,
                                     (const unsigned char*)pubkey_raw.data(), pubkey_raw.size());
  if (!key) return false;
  EVP_MD_CTX* ctx = md_ctx_new();
  bool ok = false;
  if (ctx && digest_verify_init(ctx, nullptr, nullptr, nullptr, key) == 1) {
    ok = digest_verify(ctx, (const unsigned char*)signature.data(), signature.size(),
                       (const unsigned char*)message.data(), message.size()) == 1;
  }
  if (ctx) md_ctx_free(ctx);
  pkey_free(key);
  return ok;
}
}  // namespace relay_crypto

static bool fill_random(unsigned char* buf, size_t len) {
  // getrandom(2): no fd, so an attacker holding connections open (EMFILE) cannot
  // starve challenge generation the way an open("/dev/urandom") path could
  size_t have = 0;
  while (have < len) {
    ssize_t n = getrandom(buf + have, len - have, 0);
    if (n < 0) {
      if (errno == EINTR) continue;
      return false;
    }
    have += (size_t)n;
  }
  return true;
}

enum class ConnState { ReadingFrame, Control, SplicedWaiting, Spliced, Closed };

struct Conn {
  int fd = -1;
  ConnState state = ConnState::ReadingFrame;
  std::string inbuf;        // frame assembly
  std::string outbuf;       // pending writes
  std::string peer_id;      // set for control lines
  std::string pending_peer_id;  // REGISTER received, awaiting Ed25519 proof
  std::string challenge;        // 32B nonce the proof must sign
  std::string token;        // set for pending dial/accept conns
  int peer_fd = -1;         // spliced counterpart
  double created_ms = 0;
  bool want_write = false;
  bool read_paused = false;  // EPOLLIN interest dropped (partner over HIGH_WATER)
  bool closing_after_flush = false;  // partner gone: close once outbuf drains
};

static int g_epoll = -1;
static std::map<int, Conn*> g_conns;
static std::map<std::string, int> g_control;        // peer_id -> control fd
static std::map<std::string, int> g_pending_dials;  // token -> dialer fd

static void set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

static void update_events(Conn* c) {
  epoll_event ev{};
  ev.events = (c->read_paused ? 0 : EPOLLIN) | (c->want_write ? EPOLLOUT : 0);
  ev.data.fd = c->fd;
  epoll_ctl(g_epoll, EPOLL_CTL_MOD, c->fd, &ev);
}

static void close_conn(int fd);

static void queue_write(Conn* c, const char* data, size_t len) {
  c->outbuf.append(data, len);
  if (!c->want_write) {
    c->want_write = true;
    update_events(c);
  }
}

static void queue_frame(Conn* c, const std::string& payload) {
  uint32_t n = htonl((uint32_t)payload.size());
  std::string frame((char*)&n, 4);
  frame += payload;
  queue_write(c, frame.data(), frame.size());
}

static void close_conn(int fd) {
  auto it = g_conns.find(fd);
  if (it == g_conns.end()) return;
  Conn* c = it->second;
  if (!c->peer_id.empty()) {
    auto reg = g_control.find(c->peer_id);
    if (reg != g_control.end() && reg->second == fd) g_control.erase(reg);
  }
  if (!c->token.empty()) {
    auto pend = g_pending_dials.find(c->token);
    if (pend != g_pending_dials.end() && pend->second == fd) g_pending_dials.erase(pend);
  }
  int partner = c->peer_fd;
  epoll_ctl(g_epoll, EPOLL_CTL_DEL, fd, nullptr);
  close(fd);
  g_conns.erase(it);
  delete c;
  if (partner >= 0) {
    auto pit = g_conns.find(partner);
    if (pit != g_conns.end()) {
      Conn* p = pit->second;
      p->peer_fd = -1;
      if (p->outbuf.empty()) {
        close_conn(partner);  // pipe is bidirectional: one side gone, tear down both
      } else {
        // in-flight bytes the peer already sent must not be discarded: stop
        // reading, flush the tail, then close from on_writable (the periodic
        // sweep reaps flushers whose receiver never drains)
        p->closing_after_flush = true;
        p->read_paused = true;
        p->created_ms = now_ms();
        update_events(p);
      }
    }
  }
}

static void enable_keepalive(int fd) {
  int ka = 1, idle = 30, intvl = 10, cnt = 3;
  setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &ka, sizeof(ka));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle, sizeof(idle));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl, sizeof(intvl));
  setsockopt(fd, IPPROTO_TCP, TCP_KEEPCNT, &cnt, sizeof(cnt));
}

static void splice_pair(Conn* a, Conn* b) {
  a->peer_fd = b->fd;
  b->peer_fd = a->fd;
  a->state = b->state = ConnState::Spliced;
  enable_keepalive(a->fd);
  enable_keepalive(b->fd);
  const char ok[] = {0, 0, 0, 1, 'O'};
  queue_write(a, ok, 5);
  queue_write(b, ok, 5);
  // any bytes that raced ahead of the match are forwarded
  if (!a->inbuf.empty()) { queue_write(b, a->inbuf.data(), a->inbuf.size()); a->inbuf.clear(); }
  if (!b->inbuf.empty()) { queue_write(a, b->inbuf.data(), b->inbuf.size()); b->inbuf.clear(); }
}

static void refuse_and_close(Conn* c) {
  // 'E' then close — but through the flush path, or the refusal frame would be
  // discarded with the fd (close_conn does not drain outbuf)
  queue_frame(c, "E");
  c->closing_after_flush = true;
  c->read_paused = true;
  c->created_ms = now_ms();
  update_events(c);
}

static void handle_control_frame(Conn* c, const std::string& payload) {
  if (payload.empty()) { close_conn(c->fd); return; }
  char kind = payload[0];
  if (kind == 'R') {
    std::string peer_id = payload.substr(1);
    if (peer_id.empty()) { close_conn(c->fd); return; }
    if (relay_crypto::available) {
      // Challenge-response registration: a peer_id is sha2-256 multihash
      // (0x12 0x20 + 32B digest of the Ed25519 pubkey), so ownership is provable.
      if (peer_id.size() != 34 || peer_id[0] != 0x12 || (unsigned char)peer_id[1] != 0x20) {
        refuse_and_close(c);
        return;
      }
      unsigned char nonce[32];
      if (!fill_random(nonce, sizeof(nonce))) { refuse_and_close(c); return; }
      c->pending_peer_id = peer_id;
      c->challenge.assign((char*)nonce, sizeof(nonce));
      queue_frame(c, std::string("C") + c->challenge);
      return;
    }
    // Legacy (no libcrypto): first registration wins — a later REGISTER for the
    // same peer_id is REFUSED while the original control line is alive, so an
    // attacker cannot evict a registered peer and capture its INCOMING
    // notifications; dead lines are reaped by TCP keepalive + EPOLLHUP.
    auto old = g_control.find(peer_id);
    if (old != g_control.end() && old->second != c->fd) {
      queue_frame(c, "E");
      return;
    }
    // re-registering a different id on the same line must not leave a dangling
    // g_control entry pointing at this fd (a later DIAL would deref a stale conn)
    if (!c->peer_id.empty() && c->peer_id != peer_id) g_control.erase(c->peer_id);
    c->peer_id = peer_id;
    g_control[c->peer_id] = c->fd;
    c->state = ConnState::Control;
    enable_keepalive(c->fd);
    queue_frame(c, "O");
  } else if (kind == 'P') {
    // PROOF: 'P' + 32B raw Ed25519 pubkey + 64B signature over
    // "hivemind-relay-register:" + challenge + peer_id
    if (c->pending_peer_id.empty() || payload.size() != 1 + 32 + 64) {
      refuse_and_close(c);
      return;
    }
    std::string pubkey = payload.substr(1, 32);
    std::string signature = payload.substr(33, 64);
    unsigned char digest[32];
    bool id_matches = relay_crypto::sha256(pubkey, digest) &&
                      memcmp(digest, c->pending_peer_id.data() + 2, 32) == 0;
    std::string message = "hivemind-relay-register:" + c->challenge + c->pending_peer_id;
    if (!id_matches || !relay_crypto::ed25519_verify(pubkey, message, signature)) {
      refuse_and_close(c);
      return;
    }
    // proven owner: evict any stale control line for this id (only the key holder
    // reaches this point, so this is reclamation, not hijack)
    auto old = g_control.find(c->pending_peer_id);
    if (old != g_control.end() && old->second != c->fd) close_conn(old->second);
    if (!c->peer_id.empty() && c->peer_id != c->pending_peer_id) g_control.erase(c->peer_id);
    c->peer_id = c->pending_peer_id;
    c->pending_peer_id.clear();
    c->challenge.clear();
    g_control[c->peer_id] = c->fd;
    c->state = ConnState::Control;
    enable_keepalive(c->fd);
    queue_frame(c, "O");
  } else if (kind == 'D' && payload.size() > 17) {
    std::string token = payload.substr(1, 16);
    std::string target = payload.substr(17);
    auto reg = g_control.find(target);
    auto target_conn = reg == g_control.end() ? g_conns.end() : g_conns.find(reg->second);
    if (target_conn == g_conns.end()) { refuse_and_close(c); return; }
    c->token = token;
    c->state = ConnState::SplicedWaiting;
    g_pending_dials[token] = c->fd;
    c->created_ms = now_ms();
    queue_frame(target_conn->second, std::string("I") + token);
  } else if (kind == 'W') {
    sockaddr_in observed{};
    socklen_t olen = sizeof(observed);
    if (getpeername(c->fd, (sockaddr*)&observed, &olen) == 0) {
      char ip[INET_ADDRSTRLEN];
      inet_ntop(AF_INET, &observed.sin_addr, ip, sizeof(ip));
      char reply[64];
      int n = snprintf(reply, sizeof(reply), "O%s:%d", ip, ntohs(observed.sin_port));
      queue_frame(c, std::string(reply, n));
    } else {
      queue_frame(c, "E");
    }
  } else if (kind == 'A' && payload.size() >= 17) {
    std::string token = payload.substr(1, 16);
    auto pend = g_pending_dials.find(token);
    auto dialer_it = pend == g_pending_dials.end() ? g_conns.end() : g_conns.find(pend->second);
    if (dialer_it == g_conns.end()) { refuse_and_close(c); return; }
    Conn* dialer = dialer_it->second;
    g_pending_dials.erase(pend);
    dialer->token.clear();
    splice_pair(dialer, c);
  } else {
    close_conn(c->fd);
  }
}

static void on_readable(Conn* c) {
  char buf[SPLICE_BUF];
  while (true) {
    ssize_t n = read(c->fd, buf, sizeof(buf));
    if (n == 0) { close_conn(c->fd); return; }
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) break;
      close_conn(c->fd); return;
    }
    if (c->state == ConnState::Spliced) {
      auto pit = g_conns.find(c->peer_fd);
      if (pit == g_conns.end()) { close_conn(c->fd); return; }
      queue_write(pit->second, buf, n);
      if (pit->second->outbuf.size() > HIGH_WATER) {
        // real backpressure: drop EPOLLIN interest until the partner drains
        c->read_paused = true;
        update_events(c);
        break;
      }
    } else {
      c->inbuf.append(buf, n);
      while (c->state != ConnState::Spliced && c->inbuf.size() >= 4) {
        uint32_t len = ntohl(*(uint32_t*)c->inbuf.data());
        if (len > MAX_FRAME) { close_conn(c->fd); return; }
        if (c->inbuf.size() < 4 + len) break;
        std::string payload = c->inbuf.substr(4, len);
        c->inbuf.erase(0, 4 + len);
        handle_control_frame(c, payload);
        if (g_conns.find(c->fd) == g_conns.end()) return;  // frame handler closed us
        if (c->closing_after_flush) return;  // refused: flush 'E', ignore further input
      }
    }
  }
}

static void maybe_resume_partner(Conn* c) {
  // our queue drained below LOW_WATER: re-arm the peer that was paused on us
  if (c->outbuf.size() >= LOW_WATER || c->peer_fd < 0) return;
  auto pit = g_conns.find(c->peer_fd);
  if (pit != g_conns.end() && pit->second->read_paused) {
    pit->second->read_paused = false;
    update_events(pit->second);
  }
}

static void on_writable(Conn* c) {
  while (!c->outbuf.empty()) {
    ssize_t n = write(c->fd, c->outbuf.data(), c->outbuf.size());
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) { maybe_resume_partner(c); return; }
      close_conn(c->fd); return;
    }
    c->outbuf.erase(0, n);
  }
  if (c->closing_after_flush) { close_conn(c->fd); return; }
  c->want_write = false;
  update_events(c);
  maybe_resume_partner(c);
}

int main(int argc, char** argv) {
  int port = argc > 1 ? atoi(argv[1]) : 34000;
  signal(SIGPIPE, SIG_IGN);
  relay_crypto::available = relay_crypto::load();
  if (!relay_crypto::available)
    fprintf(stderr, "relay: libcrypto unavailable, registrations are UNAUTHENTICATED\n");

  int listener = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(listener, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)port);
  if (bind(listener, (sockaddr*)&addr, sizeof(addr)) < 0) { perror("bind"); return 1; }
  if (listen(listener, 128) < 0) { perror("listen"); return 1; }
  set_nonblock(listener);

  socklen_t alen = sizeof(addr);
  getsockname(listener, (sockaddr*)&addr, &alen);
  printf("relay listening on port %d\n", ntohs(addr.sin_port));
  fflush(stdout);

  g_epoll = epoll_create1(0);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = listener;
  epoll_ctl(g_epoll, EPOLL_CTL_ADD, listener, &ev);

  std::vector<epoll_event> events(256);
  double last_sweep = now_ms();
  while (true) {
    int n = epoll_wait(g_epoll, events.data(), (int)events.size(), 1000);
    for (int i = 0; i < n; i++) {
      int fd = events[i].data.fd;
      if (fd == listener) {
        while (true) {
          int client = accept(listener, nullptr, nullptr);
          if (client < 0) break;
          set_nonblock(client);
          setsockopt(client, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn* c = new Conn();
          c->fd = client;
          c->created_ms = now_ms();
          g_conns[client] = c;
          epoll_event cev{};
          cev.events = EPOLLIN;
          cev.data.fd = client;
          epoll_ctl(g_epoll, EPOLL_CTL_ADD, client, &cev);
        }
        continue;
      }
      auto it = g_conns.find(fd);
      if (it == g_conns.end()) continue;
      if (events[i].events & (EPOLLERR | EPOLLHUP)) { close_conn(fd); continue; }
      if (events[i].events & EPOLLIN) on_readable(it->second);
      if (g_conns.find(fd) == g_conns.end()) continue;
      if (events[i].events & EPOLLOUT) on_writable(it->second);
    }
    if (now_ms() - last_sweep > 5000) {  // expire unmatched dials + stuck flushers
      last_sweep = now_ms();
      std::vector<int> expired;
      for (auto& [token, fd] : g_pending_dials) {
        auto it = g_conns.find(fd);
        if (it == g_conns.end() || now_ms() - it->second->created_ms > PENDING_DIAL_TTL_MS)
          expired.push_back(fd);
      }
      for (auto& [fd, conn] : g_conns) {
        if (conn->closing_after_flush && now_ms() - conn->created_ms > FLUSH_TTL_MS)
          expired.push_back(fd);
      }
      for (int fd : expired) close_conn(fd);
    }
  }
}
