"""Ring attention: sequence/context parallelism over a mesh axis.

No reference equivalent (SURVEY §5: long-context is absent upstream; this is the
TPU-native capability layer). Keys/values rotate around the ``sp`` mesh axis via
``jax.lax.ppermute`` while each device keeps its local queries; softmax is merged
online (log-sum-exp carry), so memory stays O(seq_local²) and the full sequence never
materializes on one chip. Designed for use inside shard_map over a Mesh axis."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str) -> jax.Array:
    """Bidirectional (encoder) ring attention. All inputs are the LOCAL sequence
    shard: [batch, seq_local, heads, head_dim]. Must run inside shard_map with
    ``axis_name`` mapped over the sequence-parallel mesh axis."""
    axis_size = lax.psum(1, axis_name)
    batch, seq_local, heads, dim = q.shape
    # derive initial carries from q so they inherit its varying manual axes
    # (jax >= 0.9 shard_map rejects unvarying zeros as scan carries)
    zeros_bht = jnp.transpose(q[..., 0], (0, 2, 1)) * 0  # [B, H, T_local]
    row_max = zeros_bht - jnp.inf
    row_sum = zeros_bht
    acc = q * 0
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, _):
        k_cur, v_cur, row_max, row_sum, acc = carry
        scale = dim ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        block_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[..., None])
        acc_new = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", probs, v_cur
        )
        row_sum_new = row_sum * correction + jnp.sum(probs, axis=-1)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, new_max, row_sum_new, acc_new), None

    (k_final, v_final, row_max, row_sum, acc), _ = lax.scan(
        body, (k, v, row_max, row_sum, acc), None, length=axis_size
    )
    return acc / row_sum.transpose(0, 2, 1)[..., None]


def plain_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
) -> jax.Array:
    """Single-device attention core with the same [B, T, H, D] convention.

    :param mask: optional [B, T] key-validity mask
    :param causal: lower-triangular masking (decoder blocks); position t attends
        only to positions <= t, so right-padding never leaks into real positions
    """
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    neg = jnp.finfo(scores.dtype).min
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :], scores, neg)
    if causal:
        # offset so queries align to the END of the key sequence: incremental
        # decode (q_len=1 vs cached k_len) sees all past keys, not just key 0
        q_len, k_len = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((q_len, k_len), bool), k=k_len - q_len)
        scores = jnp.where(tri[None, None], scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
