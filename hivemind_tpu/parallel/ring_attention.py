"""Ring attention: sequence/context parallelism over a mesh axis.

No reference equivalent (SURVEY §5: long-context is absent upstream; this is the
TPU-native capability layer). Keys/values rotate around the ``sp`` mesh axis via
``jax.lax.ppermute`` while each device keeps its local queries; softmax is merged
online (log-sum-exp carry), so memory stays O(seq_local²) and the full sequence never
materializes on one chip. Designed for use inside shard_map over a Mesh axis."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30  # matches ops/pallas_attention: finite, so lse merges stay NaN-free


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str, causal: bool = False
) -> jax.Array:
    """Ring attention over the sequence-parallel mesh axis. All inputs are the
    LOCAL sequence shard: [batch, seq_local, heads, head_dim]. Must run inside
    shard_map with ``axis_name`` mapped over that axis.

    ``causal=True`` (decoder models): shards are contiguous sequence chunks in
    rank order, so the KV block received at ring step s originates from rank
    j = (i - s) mod P and contributes fully when j < i (every key precedes every
    local query), causally when j == i (the local diagonal block), and not at
    all when j > i (the whole block is in the future)."""
    axis_size = lax.psum(1, axis_name)
    my_rank = lax.axis_index(axis_name)
    batch, seq_local, heads, dim = q.shape
    # derive initial carries from q so they inherit its varying manual axes
    # (jax >= 0.9 shard_map rejects unvarying zeros as scan carries)
    zeros_bht = jnp.transpose(q[..., 0], (0, 2, 1)) * 0  # [B, H, T_local]
    row_max = zeros_bht - jnp.inf
    row_sum = zeros_bht
    acc = q * 0
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    tri = jnp.tril(jnp.ones((seq_local, seq_local), bool))  # loop-invariant

    def body(carry, step):
        k_cur, v_cur, row_max, row_sum, acc = carry
        scale = dim ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        if causal:
            source = (my_rank - step) % axis_size
            block_mask = (source < my_rank) | ((source == my_rank) & tri)
            scores = jnp.where(block_mask[None, None], scores, _NEG_INF)
        block_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[..., None])
        if causal:
            # a fully-masked block (future shard) leaves scores == new_max == NEG_INF
            # and exp(0) would contribute weight 1 — masked entries must stay 0
            probs = jnp.where(scores <= _NEG_INF / 2, 0.0, probs)
        acc_new = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", probs, v_cur
        )
        row_sum_new = row_sum * correction + jnp.sum(probs, axis=-1)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, new_max, row_sum_new, acc_new), None

    (k_final, v_final, row_max, row_sum, acc), _ = lax.scan(
        body, (k, v, row_max, row_sum, acc), jnp.arange(axis_size)
    )
    return acc / row_sum.transpose(0, 2, 1)[..., None]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str,
    interpret: bool = False, causal: bool = False,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the per-step core.

    Same contract as :func:`ring_attention` (incl. ``causal``), but each ring
    step runs the fused flash kernel (scores never leave VMEM) and the per-shard
    outputs are merged through their log-sum-exp statistics — peak memory drops
    from O(seq_local²) score blocks to O(seq_local·head_dim) accumulators, which
    is what makes long local shards viable. In causal mode the local (diagonal)
    block runs the kernel's causal path and future shards are excluded by
    forcing their lse to −∞ before the merge. Backward recomputes through the
    einsum ring (`jax.vjp(ring_attention)`), the same remat trade
    `flash_attention` makes on one chip."""
    return _ring_flash_forward(q, k, v, axis_name, interpret, causal)


def _ring_flash_forward(q, k, v, axis_name: str, interpret: bool, causal: bool):
    from hivemind_tpu.ops.pallas_attention import flash_attention_lse

    axis_size = lax.psum(1, axis_name)
    my_rank = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    # step 0 is always the LOCAL block (causal within it when causal=True — the
    # kernel's static causal flag cannot vary per scan step, so it runs outside)
    out_acc, lse_acc = flash_attention_lse(q, k, v, causal=causal, interpret=interpret)
    # accumulate in float32 regardless of the input dtype: the kernel's lse output
    # is float32, and lax.scan requires carry dtypes to be identical across steps
    # (bf16 inits would be promoted by the merge and fail tracing)
    out_acc = out_acc.astype(jnp.float32)
    k = lax.ppermute(k, axis_name, perm)
    v = lax.ppermute(v, axis_name, perm)

    def body(carry, step):
        k_cur, v_cur, out_acc, lse_acc = carry
        out_i, lse_i = flash_attention_lse(q, k_cur, v_cur, interpret=interpret)
        out_i = out_i.astype(jnp.float32)
        if causal:
            # source rank of this block; future shards contribute nothing
            source = (my_rank - step) % axis_size
            lse_i = jnp.where(source > my_rank, _NEG_INF, lse_i)
        new_lse = jnp.logaddexp(lse_acc, lse_i)
        w_old = jnp.exp(lse_acc - new_lse)
        w_new = jnp.exp(lse_i - new_lse)
        out_acc = (
            out_acc * jnp.transpose(w_old, (0, 2, 1))[..., None]
            + out_i * jnp.transpose(w_new, (0, 2, 1))[..., None]
        )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, out_acc, new_lse), None

    if axis_size > 1:
        (_, _, out_acc, _), _ = lax.scan(
            body, (k, v, out_acc, lse_acc), jnp.arange(1, axis_size)
        )
    return out_acc.astype(q.dtype)


def _ring_flash_fwd(q, k, v, axis_name, interpret, causal):
    return _ring_flash_forward(q, k, v, axis_name, interpret, causal), (q, k, v)


def _ring_flash_bwd(axis_name, interpret, causal, residuals, grad_out):
    q, k, v = residuals
    _, vjp = jax.vjp(partial(ring_attention, axis_name=axis_name, causal=causal), q, k, v)
    return vjp(grad_out.astype(q.dtype))


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def mesh_attention_core(mesh, q, k, v, mask=None, causal: bool = False):
    """The shared attention dispatch for mesh-aware models: sequence-parallel
    meshes (sp > 1) run (flash-)ring attention under shard_map — the fused-kernel
    ring when the TPU flash opt-in is active — and everything else runs
    single-device `plain_attention`. ``mask`` (key-validity) is only supported on
    the single-device path: ring shards carry full sequences."""
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        from jax.sharding import PartitionSpec as P

        from hivemind_tpu.parallel._compat import NO_CHECK, shard_map

        from hivemind_tpu.ops.pallas_attention import _flash_enabled, _flash_forced

        assert mask is None, "ring attention shards carry full sequences (no padding mask)"
        spec = P("dp", "sp", "tp" if mesh.shape.get("tp", 1) > 1 else None, None)
        extra = {}
        if _flash_enabled() and (jax.default_backend() == "tpu" or _flash_forced()):
            # flash core per ring step: scores stay in VMEM, shard outputs merge
            # via log-sum-exp. check_vma off: the varying-axes checker cannot see
            # through pallas_call outputs.
            def inner(q, k, v):
                return ring_flash_attention(q, k, v, "sp", False, causal)

            extra.update(NO_CHECK)
        else:
            inner = partial(ring_attention, axis_name="sp", causal=causal)
        core = shard_map(inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, **extra)
        return core(q, k, v)
    # single-device: attention_auto picks the fused flash kernel on TPU (full,
    # unmasked sequences) and the einsum core elsewhere — the flagship train step
    # (mask=None via loss_masked_only) gets the kernel by default this way
    from hivemind_tpu.ops.pallas_attention import attention_auto

    return attention_auto(q, k, v, mask=mask, causal=causal)


def plain_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
) -> jax.Array:
    """Single-device attention core with the same [B, T, H, D] convention.

    :param mask: optional [B, T] key-validity mask
    :param causal: lower-triangular masking (decoder blocks); position t attends
        only to positions <= t, so right-padding never leaks into real positions
    """
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    neg = jnp.finfo(scores.dtype).min
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :], scores, neg)
    if causal:
        # offset so queries align to the END of the key sequence: incremental
        # decode (q_len=1 vs cached k_len) sees all past keys, not just key 0
        q_len, k_len = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((q_len, k_len), bool), k=k_len - q_len)
        scores = jnp.where(tri[None, None], scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
