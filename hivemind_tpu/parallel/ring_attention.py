"""Ring attention: sequence/context parallelism over a mesh axis.

No reference equivalent (SURVEY §5: long-context is absent upstream; this is the
TPU-native capability layer). Keys/values rotate around the ``sp`` mesh axis via
``jax.lax.ppermute`` while each device keeps its local queries; softmax is merged
online (log-sum-exp carry), so memory stays O(seq_local²) and the full sequence never
materializes on one chip. Designed for use inside shard_map over a Mesh axis."""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30  # matches ops/pallas_attention: finite, so lse merges stay NaN-free


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str) -> jax.Array:
    """Bidirectional (encoder) ring attention. All inputs are the LOCAL sequence
    shard: [batch, seq_local, heads, head_dim]. Must run inside shard_map with
    ``axis_name`` mapped over the sequence-parallel mesh axis."""
    axis_size = lax.psum(1, axis_name)
    batch, seq_local, heads, dim = q.shape
    # derive initial carries from q so they inherit its varying manual axes
    # (jax >= 0.9 shard_map rejects unvarying zeros as scan carries)
    zeros_bht = jnp.transpose(q[..., 0], (0, 2, 1)) * 0  # [B, H, T_local]
    row_max = zeros_bht - jnp.inf
    row_sum = zeros_bht
    acc = q * 0
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(carry, _):
        k_cur, v_cur, row_max, row_sum, acc = carry
        scale = dim ** -0.5
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k_cur) * scale
        block_max = jnp.max(scores, axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        correction = jnp.exp(row_max - new_max)
        probs = jnp.exp(scores - new_max[..., None])
        acc_new = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", probs, v_cur
        )
        row_sum_new = row_sum * correction + jnp.sum(probs, axis=-1)
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, new_max, row_sum_new, acc_new), None

    (k_final, v_final, row_max, row_sum, acc), _ = lax.scan(
        body, (k, v, row_max, row_sum, acc), None, length=axis_size
    )
    return acc / row_sum.transpose(0, 2, 1)[..., None]


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_flash_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, axis_name: str, interpret: bool = False
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the per-step core.

    Same contract as :func:`ring_attention`, but each ring step runs the fused
    flash kernel (scores never leave VMEM) and the per-shard outputs are merged
    through their log-sum-exp statistics — peak memory drops from
    O(seq_local²) score blocks to O(seq_local·head_dim) accumulators, which is
    what makes long local shards viable. Backward recomputes through the einsum
    ring (`jax.vjp(ring_attention)`), the same remat trade `flash_attention`
    makes on one chip."""
    return _ring_flash_forward(q, k, v, axis_name, interpret)


def _ring_flash_forward(q, k, v, axis_name: str, interpret: bool):
    from hivemind_tpu.ops.pallas_attention import flash_attention_lse

    axis_size = lax.psum(1, axis_name)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    # accumulate in float32 regardless of the input dtype: the kernel's lse output
    # is float32, and lax.scan requires carry dtypes to be identical across steps
    # (bf16 inits would be promoted by the merge and fail tracing)
    out_acc = (q * 0).astype(jnp.float32)
    # [B, H, T_local] lse carry, derived from q to inherit its varying manual axes
    lse_acc = (jnp.transpose(q[..., 0], (0, 2, 1)) * 0).astype(jnp.float32) + _NEG_INF

    def body(carry, _):
        k_cur, v_cur, out_acc, lse_acc = carry
        out_i, lse_i = flash_attention_lse(q, k_cur, v_cur, interpret=interpret)
        out_i = out_i.astype(jnp.float32)
        new_lse = jnp.logaddexp(lse_acc, lse_i)
        w_old = jnp.exp(lse_acc - new_lse)
        w_new = jnp.exp(lse_i - new_lse)
        out_acc = (
            out_acc * jnp.transpose(w_old, (0, 2, 1))[..., None]
            + out_i * jnp.transpose(w_new, (0, 2, 1))[..., None]
        )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, out_acc, new_lse), None

    (_, _, out_acc, _), _ = lax.scan(body, (k, v, out_acc, lse_acc), None, length=axis_size)
    return out_acc.astype(q.dtype)


def _ring_flash_fwd(q, k, v, axis_name, interpret):
    return _ring_flash_forward(q, k, v, axis_name, interpret), (q, k, v)


def _ring_flash_bwd(axis_name, interpret, residuals, grad_out):
    q, k, v = residuals
    _, vjp = jax.vjp(partial(ring_attention, axis_name=axis_name), q, k, v)
    return vjp(grad_out.astype(q.dtype))


ring_flash_attention.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def plain_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mask: Optional[jax.Array] = None,
    causal: bool = False,
) -> jax.Array:
    """Single-device attention core with the same [B, T, H, D] convention.

    :param mask: optional [B, T] key-validity mask
    :param causal: lower-triangular masking (decoder blocks); position t attends
        only to positions <= t, so right-padding never leaks into real positions
    """
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    neg = jnp.finfo(scores.dtype).min
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :], scores, neg)
    if causal:
        # offset so queries align to the END of the key sequence: incremental
        # decode (q_len=1 vs cached k_len) sees all past keys, not just key 0
        q_len, k_len = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((q_len, k_len), bool), k=k_len - q_len)
        scores = jnp.where(tri[None, None], scores, neg)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
