"""ICI tier of the two-tier communication backend (SURVEY §5): one device mesh is ONE
logical swarm peer.

The reference's hot loop reduces tensor parts with in-place host arithmetic on a single
machine (reference hivemind/averaging/partition.py:242-260, ``add_``/``div_``). On TPU
the intra-peer half of that reduction belongs ON the mesh: per-replica values are
reduced with ``jax.lax.pmean`` (an ICI psum) under ``shard_map``, shards are assembled
with XLA all-gathers by resharding to a replicated layout, and the host only ever
stages the single already-reduced copy at the network boundary. The swarm (internet)
tier then averages those host copies across peers; the result is scattered back onto
the mesh as one ``device_put`` per leaf.

Two entry points:

- :class:`MeshTensorBridge` — the device↔host boundary: ``mesh_mean`` (on-device psum
  reduction over one mesh axis), ``gather_to_host`` (ICI all-gather → one fp32 host
  copy per leaf), ``scatter_from_host`` (host → original shardings).
- :class:`hivemind_tpu.averaging.ici.MeshAverager` — a DecentralizedAverager whose
  local tensors live sharded on a mesh and cross the host boundary only per round.
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # top-level since jax 0.8; experimental path for older versions
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]


def _leaf_spec(leaf) -> P:
    sharding = getattr(leaf, "sharding", None)
    if isinstance(sharding, NamedSharding):
        return sharding.spec
    return P()


class MeshTensorBridge:
    """Device↔host staging for one mesh-resident logical peer. jit-compiled transfer
    functions are cached per (treedef, shapes/dtypes/specs) signature so steady-state
    rounds pay zero retracing."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._fn_cache: dict = {}

    # ---------------------------------------------------------------- on-device reduce

    def mesh_mean(self, stacked_tree: Any, axis: str = "dp") -> Any:
        """Reduce per-replica values across one mesh axis WITHOUT leaving the device.

        Each leaf must have leading dimension ``mesh.shape[axis]`` sharded over
        ``axis`` (the jax representation of "every replica holds its own copy").
        Returns the tree with the leading axis reduced away — the mean runs as a
        ``psum`` over ICI under ``shard_map``, the TPU-native equivalent of the
        reference's host-side accumulate/divide loop (partition.py:242-260)."""
        leaves, treedef = jax.tree_util.tree_flatten(stacked_tree)
        axis_size = self.mesh.shape[axis]
        in_specs, out_specs = [], []
        for leaf in leaves:
            if leaf.ndim < 1 or leaf.shape[0] != axis_size:
                raise ValueError(
                    f"mesh_mean leaf {leaf.shape} lacks leading {axis}-dim of {axis_size}"
                )
            spec = _leaf_spec(leaf)
            rest = tuple(spec)[1:] if len(spec) else ()
            in_specs.append(P(axis, *rest))
            out_specs.append(P(*rest))
        in_specs = jax.tree_util.tree_unflatten(treedef, in_specs)
        out_specs = jax.tree_util.tree_unflatten(treedef, out_specs)

        key = ("mean", axis, treedef, tuple((l.shape, str(l.dtype), str(_leaf_spec(l))) for l in leaves))
        fn = self._fn_cache.get(key)
        if fn is None:

            def _reduce(tree):
                return jax.tree_util.tree_map(
                    lambda x: jax.lax.pmean(jnp.squeeze(x, axis=0), axis), tree
                )

            fn = jax.jit(
                shard_map(_reduce, mesh=self.mesh, in_specs=(in_specs,), out_specs=out_specs)
            )
            self._fn_cache[key] = fn
        return fn(stacked_tree)

    # ---------------------------------------------------------------- host boundary

    def gather_to_host(self, tree: Any) -> List[np.ndarray]:
        """Assemble full fp32 copies of every leaf on the host: XLA inserts the
        all-gathers over ICI when resharding to a replicated layout; exactly one host
        transfer happens per leaf, of the final reduced bytes."""
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        key = ("gather", treedef, tuple((l.shape, str(l.dtype), str(_leaf_spec(l))) for l in leaves))
        fn = self._fn_cache.get(key)
        if fn is None:
            replicated = NamedSharding(self.mesh, P())
            fn = jax.jit(
                lambda ls: [x.astype(jnp.float32) for x in ls],
                out_shardings=[replicated] * len(leaves),
            )
            self._fn_cache[key] = fn
        return [np.asarray(x) for x in fn(leaves)]

    def scatter_from_host(self, like_tree: Any, host_tensors: Sequence[np.ndarray]) -> Any:
        """Push host values back onto the mesh with ``like_tree``'s shardings and
        dtypes (one device_put per leaf; each device receives only its shard)."""
        leaves, treedef = jax.tree_util.tree_flatten(like_tree)
        assert len(leaves) == len(host_tensors), (len(leaves), len(host_tensors))
        new_leaves = []
        for leaf, host in zip(leaves, host_tensors):
            value = np.asarray(host, dtype=leaf.dtype).reshape(leaf.shape)
            sharding = getattr(leaf, "sharding", None)
            if isinstance(sharding, NamedSharding):
                new_leaves.append(jax.device_put(value, sharding))
            else:
                new_leaves.append(jnp.asarray(value))
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def broadcast_scatter_from_host(
        self, like_stacked_tree: Any, host_tensors: Sequence[np.ndarray], axis: str = "dp"
    ) -> Any:
        """Scatter reduced host values back to a per-replica stacked tree: every
        replica along ``axis`` adopts the (swarm-averaged) value."""
        leaves, treedef = jax.tree_util.tree_flatten(like_stacked_tree)
        axis_size = self.mesh.shape[axis]
        stacked = [
            np.broadcast_to(
                np.asarray(h, dtype=l.dtype).reshape(l.shape[1:]), (axis_size,) + tuple(l.shape[1:])
            )
            for l, h in zip(leaves, host_tensors)
        ]
        return self.scatter_from_host(like_stacked_tree, stacked)
