"""ICI tier of the two-tier communication backend (SURVEY §5): one device mesh is ONE
logical swarm peer.

The reference's hot loop reduces tensor parts with in-place host arithmetic on a single
machine (reference hivemind/averaging/partition.py:242-260, ``add_``/``div_``). On TPU
the intra-peer half of that reduction belongs ON the mesh: per-replica values are
reduced with ``jax.lax.pmean`` (an ICI psum) under ``shard_map``, then every leaf is
assembled on the host SHARD BY SHARD — each distinct region is pulled from exactly one
device with async DMAs and written straight into a preallocated mirror, so neither the
device (no replicated resharding) nor the host (no transient second copy) ever holds
more than one model copy plus one in-flight shard. The swarm (internet) tier then
averages those host mirrors across peers; the result is scattered back onto the mesh
one leaf at a time (each device receives only its shard).

Two entry points:

- :class:`MeshTensorBridge` — the device↔host boundary: ``mesh_mean`` (on-device psum
  reduction over one mesh axis), ``stage_into_mirrors``/``gather_to_host`` (shard-wise
  device→host assembly), ``scatter_leaf``/``scatter_from_host`` (host → original
  shardings).
- :class:`hivemind_tpu.averaging.ici.MeshAverager` — a DecentralizedAverager whose
  local tensors live sharded on a mesh and cross the host boundary only per round.
"""

from __future__ import annotations

from functools import partial
from typing import Any, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hivemind_tpu.parallel._compat import shard_map


def _leaf_spec(leaf) -> P:
    sharding = getattr(leaf, "sharding", None)
    if isinstance(sharding, NamedSharding):
        return sharding.spec
    return P()


class MeshTensorBridge:
    """Device↔host staging for one mesh-resident logical peer. jit-compiled transfer
    functions are cached per (treedef, shapes/dtypes/specs) signature so steady-state
    rounds pay zero retracing."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._fn_cache: dict = {}

    # ---------------------------------------------------------------- on-device reduce

    def mesh_mean(self, stacked_tree: Any, axis: str = "dp") -> Any:
        """Reduce per-replica values across one mesh axis WITHOUT leaving the device.

        Each leaf must have leading dimension ``mesh.shape[axis]`` sharded over
        ``axis`` (the jax representation of "every replica holds its own copy").
        Returns the tree with the leading axis reduced away — the mean runs as a
        ``psum`` over ICI under ``shard_map``, the TPU-native equivalent of the
        reference's host-side accumulate/divide loop (partition.py:242-260)."""
        leaves, treedef = jax.tree_util.tree_flatten(stacked_tree)
        axis_size = self.mesh.shape[axis]
        in_specs, out_specs = [], []
        for leaf in leaves:
            if leaf.ndim < 1 or leaf.shape[0] != axis_size:
                raise ValueError(
                    f"mesh_mean leaf {leaf.shape} lacks leading {axis}-dim of {axis_size}"
                )
            spec = _leaf_spec(leaf)
            rest = tuple(spec)[1:] if len(spec) else ()
            in_specs.append(P(axis, *rest))
            out_specs.append(P(*rest))
        in_specs = jax.tree_util.tree_unflatten(treedef, in_specs)
        out_specs = jax.tree_util.tree_unflatten(treedef, out_specs)

        key = ("mean", axis, treedef, tuple((l.shape, str(l.dtype), str(_leaf_spec(l))) for l in leaves))
        fn = self._fn_cache.get(key)
        if fn is None:

            def _reduce(tree):
                return jax.tree_util.tree_map(
                    lambda x: jax.lax.pmean(jnp.squeeze(x, axis=0), axis), tree
                )

            fn = jax.jit(
                shard_map(_reduce, mesh=self.mesh, in_specs=(in_specs,), out_specs=out_specs)
            )
            self._fn_cache[key] = fn
        return fn(stacked_tree)

    def _mesh_mean_leaf(self, leaf, axis: str):
        """Per-leaf variant of ``mesh_mean``: reduce ONE leaf's leading per-replica
        dimension on device. Used by the streaming staging path so the whole
        reduced tree is never materialized at once (peak transient = one leaf)."""
        axis_size = self.mesh.shape[axis]
        if leaf.ndim < 1 or leaf.shape[0] != axis_size:
            raise ValueError(f"leaf {leaf.shape} lacks leading {axis}-dim of {axis_size}")
        spec = _leaf_spec(leaf)
        rest = tuple(spec)[1:] if len(spec) else ()
        key = ("mean_leaf", axis, leaf.shape, str(leaf.dtype), str(spec))
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = self._fn_cache[key] = jax.jit(
                shard_map(
                    lambda x: jax.lax.pmean(jnp.squeeze(x, axis=0), axis),
                    mesh=self.mesh,
                    in_specs=(P(axis, *rest),),
                    out_specs=P(*rest),
                )
            )
        return fn(leaf)

    def stage_reduced_into_mirrors(
        self, tree: Any, mirrors: Sequence[np.ndarray], reduce_axis: Optional[str] = None
    ) -> None:
        """STREAMING stage: optionally reduce each leaf over ``reduce_axis`` and
        assemble it into its host mirror ONE LEAF AT A TIME, freeing the reduced
        transient before the next leaf. Peak memory beyond the persistent model +
        mirrors is a single reduced leaf — this is what keeps a steady-state
        averaging round's RSS growth bounded by the mirrors, not another model copy
        (VERDICT r3 #4; device↔host analog of the reference's 512 KiB part
        streaming, hivemind/averaging/partition.py:104-112).

        Collective on a multi-process mesh (the per-leaf reduce and the replication
        fallback are jax collectives): every process must call it in the same order."""
        leaves, _ = jax.tree_util.tree_flatten(tree)
        assert len(leaves) == len(mirrors), (len(leaves), len(mirrors))
        for leaf, mirror in zip(leaves, mirrors):
            reduced = self._mesh_mean_leaf(leaf, reduce_axis) if reduce_axis is not None else leaf
            self.stage_into_mirrors([reduced], [mirror])
            if reduced is not leaf:
                reduced.delete()  # free the on-device transient before the next leaf

    # ---------------------------------------------------------------- host boundary

    @staticmethod
    def _unique_shards(leaf) -> list:
        """The addressable shards covering the array once: replicated dims make
        several devices hold identical shards — pull each distinct region from one
        device only, so host traffic equals the array size, not the device count."""
        seen, unique = set(), []
        for shard in leaf.addressable_shards:
            key = tuple((s.start, s.stop, s.step) for s in shard.index)
            if key not in seen:
                seen.add(key)
                unique.append(shard)
        return unique

    def stage_into_mirrors(self, tree: Any, mirrors: Sequence[np.ndarray]) -> None:
        """Assemble every leaf DIRECTLY into its preallocated host mirror, one
        shard at a time: no on-device resharding (a replicated gather would cost a
        full model replica of HBM **per device**) and no second host copy (peak
        host memory = the mirrors + one in-flight shard). Leaf ``i+1``'s
        device→host DMAs are started asynchronously while leaf ``i`` assembles, so
        the transfer pipeline stays full. This is the device↔host analog of the
        reference's 512 KiB part streaming (hivemind/averaging/partition.py:104-112);
        here the natural chunk is the device shard."""
        leaves, _ = jax.tree_util.tree_flatten(tree)
        assert len(leaves) == len(mirrors), (len(leaves), len(mirrors))
        if not all(getattr(leaf, "is_fully_addressable", True) for leaf in leaves):
            # multi-process mesh: some shards live on other hosts' devices, so a
            # shard pull cannot cover the mirror. Replicate ONE LEAF AT A TIME on
            # device (transient HBM = one leaf per device, never a model copy) and
            # read the now-local copy. See averaging/ici.py multi-host notes.
            self._stage_with_per_leaf_replication(leaves, mirrors)
            return
        shard_lists = [self._unique_shards(leaf) for leaf in leaves]
        for shard in shard_lists[0] if shard_lists else []:
            shard.data.copy_to_host_async()
        for index, (leaf, mirror) in enumerate(zip(leaves, mirrors)):
            if index + 1 < len(leaves):
                for shard in shard_lists[index + 1]:
                    shard.data.copy_to_host_async()
            out = mirror.reshape(leaf.shape)  # view (mirrors are C-contiguous)
            if not shard_lists[index]:  # zero-size leaf
                continue
            for shard in shard_lists[index]:
                out[shard.index] = np.asarray(shard.data).astype(out.dtype, copy=False)

    def _stage_with_per_leaf_replication(self, leaves: Sequence[Any], mirrors: Sequence[np.ndarray]) -> None:
        """Multi-host staging path: a collective (all processes must call this in
        the same order) per-leaf replicate-and-read. Bounded: unlike the old
        whole-tree replicated gather, at most one leaf is replicated at a time."""
        replicated = NamedSharding(self.mesh, P())
        key = ("replicate_one",)
        fn = self._fn_cache.get(key)
        if fn is None:
            fn = self._fn_cache[key] = jax.jit(
                lambda x: x.astype(jnp.float32), out_shardings=replicated
            )
        for leaf, mirror in zip(leaves, mirrors):
            full = fn(leaf)
            shard = next(iter(full.addressable_shards))  # replicated: any local device
            mirror.reshape(leaf.shape)[...] = np.asarray(shard.data)
            full.delete()  # free the replicated copy before the next leaf

    def allocate_mirrors(self, tree: Any) -> List[np.ndarray]:
        """Fresh fp32 host mirrors shaped like the tree's leaves."""
        leaves, _ = jax.tree_util.tree_flatten(tree)
        return [np.empty(leaf.shape, np.float32) for leaf in leaves]

    def allocate_reduced_mirrors(self, tree: Any, reduce_axis: Optional[str] = None) -> List[np.ndarray]:
        """Mirrors shaped like the tree's leaves AFTER the per-replica reduction
        (leading axis dropped), computed without materializing the reduced tree."""
        leaves, _ = jax.tree_util.tree_flatten(tree)
        return [
            np.empty(leaf.shape[1:] if reduce_axis is not None else leaf.shape, np.float32)
            for leaf in leaves
        ]

    def gather_reduced_to_host(self, tree: Any, reduce_axis: Optional[str] = None) -> List[np.ndarray]:
        """Streaming equivalent of ``gather_to_host(mesh_mean(tree))``: the reduced
        tree is never materialized whole (one leaf in flight)."""
        mirrors = self.allocate_reduced_mirrors(tree, reduce_axis)
        self.stage_reduced_into_mirrors(tree, mirrors, reduce_axis=reduce_axis)
        return mirrors

    def gather_to_host(self, tree: Any) -> List[np.ndarray]:
        """Full fp32 host copies of every leaf, assembled shard-by-shard (see
        ``stage_into_mirrors`` — no on-device replication happens)."""
        mirrors = self.allocate_mirrors(tree)
        self.stage_into_mirrors(tree, mirrors)
        return mirrors

    def scatter_leaf(self, like_leaf, host_value: np.ndarray, stack_axis_size: Optional[int] = None):
        """Push ONE host value back to the mesh with ``like_leaf``'s sharding and
        dtype. With ``stack_axis_size``, ``host_value`` is the reduced (unstacked)
        value and every replica row adopts it via a broadcast VIEW — the stacked
        array is never materialized on host."""
        value = np.asarray(host_value, dtype=like_leaf.dtype)
        if stack_axis_size is not None:
            value = np.broadcast_to(
                value.reshape(like_leaf.shape[1:]), tuple(like_leaf.shape)
            )
        else:
            value = value.reshape(like_leaf.shape)
        sharding = getattr(like_leaf, "sharding", None)
        if not isinstance(sharding, NamedSharding):
            return jnp.asarray(value)
        if getattr(like_leaf, "is_fully_addressable", True):
            return jax.device_put(value, sharding)
        # multi-process mesh: device_put cannot target other hosts' devices. Every
        # process holds the SAME host value (guaranteed by the slice protocol's
        # broadcast); each one uploads its local shards and the global array is
        # assembled from them (the documented multi-host construction path).
        index_map = sharding.addressable_devices_indices_map(tuple(value.shape))
        locals_ = [
            jax.device_put(np.ascontiguousarray(value[index]), device)
            for device, index in index_map.items()
        ]
        return jax.make_array_from_single_device_arrays(tuple(value.shape), sharding, locals_)

    def scatter_from_host(self, like_tree: Any, host_tensors: Sequence[np.ndarray]) -> Any:
        """Push host values back onto the mesh with ``like_tree``'s shardings and
        dtypes (one device_put per leaf; each device receives only its shard)."""
        leaves, treedef = jax.tree_util.tree_flatten(like_tree)
        assert len(leaves) == len(host_tensors), (len(leaves), len(host_tensors))
        new_leaves = [
            self.scatter_leaf(leaf, host) for leaf, host in zip(leaves, host_tensors)
        ]
        return jax.tree_util.tree_unflatten(treedef, new_leaves)

    def broadcast_scatter_from_host(
        self, like_stacked_tree: Any, host_tensors: Sequence[np.ndarray], axis: str = "dp"
    ) -> Any:
        """Scatter reduced host values back to a per-replica stacked tree: every
        replica along ``axis`` adopts the (swarm-averaged) value."""
        leaves, treedef = jax.tree_util.tree_flatten(like_stacked_tree)
        axis_size = self.mesh.shape[axis]
        stacked = [
            np.broadcast_to(
                np.asarray(h, dtype=l.dtype).reshape(l.shape[1:]), (axis_size,) + tuple(l.shape[1:])
            )
            for l, h in zip(leaves, host_tensors)
        ]
        return self.scatter_from_host(like_stacked_tree, stacked)
