"""jax version-compatibility shims shared by the parallel layer (and its tests).

- ``shard_map``: top-level since jax 0.8, ``jax.experimental.shard_map`` before.
- ``NO_CHECK``: the kwargs disabling the replication/varying-axes checker, whose
  flag was renamed ``check_rep`` -> ``check_vma`` across versions. Both shims
  live here so the next jax rename is a one-file fix.
"""

import inspect

try:  # top-level since jax 0.8; experimental path for older versions
    from jax import shard_map
except ImportError:  # pragma: no cover - depends on the installed jax
    from jax.experimental.shard_map import shard_map  # type: ignore[no-redef]

_NO_CHECK_FLAG = (
    "check_vma" if "check_vma" in inspect.signature(shard_map).parameters else "check_rep"
)
# pass **NO_CHECK to shard_map when the checker cannot see through the body
# (e.g. pallas_call outputs)
NO_CHECK = {_NO_CHECK_FLAG: False}

__all__ = ["shard_map", "NO_CHECK"]
