from hivemind_tpu.parallel.ici import MeshTensorBridge
from hivemind_tpu.parallel.mesh import (
    batch_sharding,
    make_mesh,
    param_spec,
    params_shardings,
    replicated,
)
from hivemind_tpu.parallel.ring_attention import plain_attention, ring_attention
