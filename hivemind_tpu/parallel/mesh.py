"""Device-mesh construction and sharding rules — the intra-slice parallelism layer
beneath the swarm (SURVEY §2.9: TP/SP/DP come from pjit/shard_map over the ICI mesh;
one slice acts as one logical swarm peer).

Axes: ``dp`` (data), ``tp`` (tensor/model), ``sp`` (sequence/context). Collectives ride
ICI when the mesh maps onto a physical slice; the swarm layer handles cross-pod."""

from __future__ import annotations

import re
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    dp: int = 1, tp: int = 1, sp: int = 1, devices: Optional[Sequence] = None
) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    needed = dp * tp * sp
    assert len(devices) >= needed, f"need {needed} devices, have {len(devices)}"
    array = np.array(devices[:needed]).reshape(dp, tp, sp)
    return Mesh(array, axis_names=("dp", "tp", "sp"))


# sharding rules for transformer parameters, matched against '/'-joined param paths.
# TP shards attention heads and the ffn intermediate dimension; everything else is
# replicated (embeddings stay replicated: ALBERT's factorized embedding is small).
_PARAM_RULES = [
    (r".*(query|key|value)/kernel$", P(None, "tp")),
    (r".*(query|key|value)/bias$", P("tp")),
    (r".*attention_out/kernel$", P("tp", None)),
    (r".*attention_out/bias$", P()),
    (r".*ffn_up/kernel$", P(None, "tp")),
    (r".*ffn_up/bias$", P("tp")),
    (r".*ffn_down/kernel$", P("tp", None)),
    (r".*ffn_down/bias$", P()),
]


def param_spec(path: str, value) -> P:
    for pattern, spec in _PARAM_RULES:
        if re.fullmatch(pattern, path):
            return spec
    return P()  # replicated


def params_shardings(params, mesh: Mesh):
    """NamedShardings for a flax param pytree, by path-matching the rules above."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]

    def path_str(key_path) -> str:
        parts = []
        for entry in key_path:
            name = getattr(entry, "key", None)
            parts.append(str(name) if name is not None else str(entry))
        return "/".join(parts)

    specs = {path_str(kp): param_spec(path_str(kp), v) for kp, v in flat}

    def to_sharding(key_path, value):
        return NamedSharding(mesh, specs[path_str(key_path)])

    return jax.tree_util.tree_map_with_path(to_sharding, params)


def batch_sharding(mesh: Mesh, seq_sharded: bool = True) -> NamedSharding:
    """Input batch [batch, seq]: batch over dp, sequence over sp (context parallel)."""
    return NamedSharding(mesh, P("dp", "sp" if seq_sharded else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
