#!/bin/sh
# Regenerate *_pb2.py from the .proto schemas. Run from the repo root.
# Generated files are checked in so the package needs no build step.
set -e
cd "$(dirname "$0")/../.."
protoc --python_out=. hivemind_tpu/proto/*.proto
echo "regenerated: $(ls hivemind_tpu/proto/*_pb2.py)"
