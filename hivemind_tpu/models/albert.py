"""ALBERT-style masked-LM — the flagship collaborative-pretraining model
(capability parity: the reference's examples/albert recipe targets HF ALBERT on
torch; this is an own flax implementation, TPU-first: bf16 compute, layer-shared
encoder on the MXU, pluggable attention core that switches to ring attention when the
mesh has a sequence-parallel axis).

ALBERT signature features: factorized embeddings (vocab → embedding_size →
hidden_size) and cross-layer parameter sharing (one transformer block applied
num_layers times)."""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp



@dataclasses.dataclass(frozen=True)
class AlbertConfig:
    vocab_size: int = 30000
    embedding_size: int = 128
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 512
    dtype: Any = jnp.bfloat16
    remat: bool = False  # checkpoint each shared-layer application (see setup)
    # sequence parallelism: when mesh is set and its 'sp' axis > 1, attention runs as
    # ring attention sharded over the sequence (mask support: full sequences only)
    mesh: Optional[Any] = None

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_heads == 0
        return self.hidden_size // self.num_heads

    @classmethod
    def base(cls, **overrides) -> "AlbertConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides) -> "AlbertConfig":
        defaults = dict(
            vocab_size=1024, embedding_size=32, hidden_size=64, num_layers=2,
            num_heads=4, intermediate_size=128, max_position=128,
        )
        defaults.update(overrides)
        return cls(**defaults)


def _attention_core(config: AlbertConfig, q, k, v, mask):
    from hivemind_tpu.parallel.ring_attention import mesh_attention_core

    return mesh_attention_core(config.mesh, q, k, v, mask=mask)


class AlbertLayer(nn.Module):
    """One shared transformer block (post-layernorm, gelu FFN)."""

    config: AlbertConfig

    @nn.compact
    def __call__(self, hidden: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
        cfg = self.config
        batch, seq, _ = hidden.shape
        dense = partial(nn.Dense, dtype=cfg.dtype, param_dtype=jnp.float32)
        q = dense(cfg.hidden_size, name="query")(hidden).reshape(batch, seq, cfg.num_heads, cfg.head_dim)
        k = dense(cfg.hidden_size, name="key")(hidden).reshape(batch, seq, cfg.num_heads, cfg.head_dim)
        v = dense(cfg.hidden_size, name="value")(hidden).reshape(batch, seq, cfg.num_heads, cfg.head_dim)
        context = _attention_core(cfg, q, k, v, mask)
        attn_out = dense(cfg.hidden_size, name="attention_out")(context.reshape(batch, seq, -1))
        hidden = nn.LayerNorm(dtype=cfg.dtype, name="attention_norm")(hidden + attn_out)
        up = dense(cfg.intermediate_size, name="ffn_up")(hidden)
        down = dense(cfg.hidden_size, name="ffn_down")(jax.nn.gelu(up))
        return nn.LayerNorm(dtype=cfg.dtype, name="ffn_norm")(hidden + down)


class AlbertForMaskedLM(nn.Module):
    config: AlbertConfig

    def setup(self):
        cfg = self.config
        self.word_embeddings = nn.Embed(
            cfg.vocab_size, cfg.embedding_size, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="word_embeddings",
        )
        self.position_embeddings = self.param(
            "position_embeddings",
            nn.initializers.normal(0.02),
            (cfg.max_position, cfg.embedding_size),
            jnp.float32,
        )
        self.embedding_norm = nn.LayerNorm(dtype=cfg.dtype, name="embedding_norm")
        self.embedding_projection = nn.Dense(
            cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32, name="embedding_projection"
        )
        # remat: recompute each shared-layer application's activations in the backward
        # pass instead of keeping them in HBM for the whole step — buys batch size when
        # the step is memory-bound (the classic single-chip MFU lever). The module name
        # is pinned so the parameter tree is identical either way.
        layer_cls = nn.remat(AlbertLayer) if cfg.remat else AlbertLayer
        self.shared_layer = layer_cls(cfg, name="shared_layer")
        self.mlm_transform = nn.Dense(
            cfg.embedding_size, dtype=cfg.dtype, param_dtype=jnp.float32, name="mlm_transform"
        )
        self.mlm_norm = nn.LayerNorm(dtype=cfg.dtype, name="mlm_norm")
        self.mlm_bias = self.param("mlm_bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32)

    def encode(self, input_ids: jax.Array, attention_mask: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.config
        seq = input_ids.shape[1]
        x = self.word_embeddings(input_ids) + self.position_embeddings[None, :seq].astype(cfg.dtype)
        x = self.embedding_projection(self.embedding_norm(x))
        for _ in range(cfg.num_layers):  # cross-layer parameter sharing
            x = self.shared_layer(x, attention_mask)
        return x

    def _mlm_logits(self, hidden: jax.Array) -> jax.Array:
        transformed = self.mlm_norm(jax.nn.gelu(self.mlm_transform(hidden)))
        logits = self.word_embeddings.attend(transformed)  # tied decoder
        return logits.astype(jnp.float32) + self.mlm_bias

    def __call__(self, input_ids: jax.Array, attention_mask: Optional[jax.Array] = None) -> jax.Array:
        """Returns MLM logits [batch, seq, vocab] (float32 for a stable softmax)."""
        return self._mlm_logits(self.encode(input_ids, attention_mask))

    def loss_masked_only(
        self, input_ids: jax.Array, labels: jax.Array, mlm_mask: jax.Array, budget: int
    ) -> jax.Array:
        """MLM loss computed ONLY at masked positions (up to ``budget`` per row).

        The full-logits path materializes fp32 [batch, seq, vocab] — ~2 GB at
        batch 32 × seq 512 × vocab 30k — yet only ~15% of positions carry loss.
        Gathering those positions first shrinks the decoder matmul and the softmax
        by seq/budget (≈4× at budget=seq/4) in both passes: the single biggest
        single-chip throughput lever for this model. ``budget`` must be static
        (XLA shapes); rows with more masked positions than the budget contribute
        their first ``budget`` ones (at 15% masking, budget seq/4 is ≈ +6σ above
        the binomial mean, so truncation is virtually never hit)."""
        hidden = self.encode(input_ids)
        order = jnp.argsort(~mlm_mask, axis=1, stable=True)[:, :budget]  # masked first
        selected_mask = jnp.take_along_axis(mlm_mask, order, axis=1)
        selected_hidden = jnp.take_along_axis(hidden, order[..., None], axis=1)
        selected_labels = jnp.take_along_axis(labels, order, axis=1)
        logits = self._mlm_logits(selected_hidden)  # [batch, budget, vocab]
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        label_ll = jnp.take_along_axis(log_probs, selected_labels[..., None], axis=-1)[..., 0]
        mask = selected_mask.astype(jnp.float32)
        return -(label_ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def mlm_loss(logits: jax.Array, labels: jax.Array, mlm_mask: jax.Array) -> jax.Array:
    """Masked cross-entropy: mlm_mask selects the positions that were masked out."""
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    label_ll = jnp.take_along_axis(log_probs, labels[..., None], axis=-1)[..., 0]
    mask = mlm_mask.astype(jnp.float32)
    return -(label_ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def make_mlm_loss_fn(model: "AlbertForMaskedLM", masked_loss_fraction: Optional[float] = None):
    """``loss(params, batch) -> scalar`` for dict(input_ids, labels, mlm_mask).

    :param masked_loss_fraction: compute the MLM head only on this fraction of
        positions per row (the masked ones — see ``loss_masked_only``). Opt-in:
        rows with more masked positions than ``fraction * seq`` contribute only
        the first that many, so callers must size it above their masking rate
        (0.25 gives ≈+6σ headroom over 15% masking at seq 512). None = exact
        full-logits objective."""

    def loss_fn(params, batch):
        if masked_loss_fraction is not None:
            budget = max(1, int(batch["input_ids"].shape[1] * masked_loss_fraction))
            return model.apply(
                {"params": params}, batch["input_ids"], batch["labels"], batch["mlm_mask"],
                budget, method=AlbertForMaskedLM.loss_masked_only,
            )
        logits = model.apply({"params": params}, batch["input_ids"])
        return mlm_loss(logits, batch["labels"], batch["mlm_mask"])

    return loss_fn


def make_train_step(config: AlbertConfig, optimizer, masked_loss_fraction: Optional[float] = None):
    """A jittable (params, opt_state, batch) -> (loss, params, opt_state) step.
    ``batch``: dict(input_ids, labels, mlm_mask). See ``make_mlm_loss_fn`` for
    ``masked_loss_fraction`` (None keeps the exact full-logits objective)."""
    import optax

    model = AlbertForMaskedLM(config)
    loss_fn = make_mlm_loss_fn(model, masked_loss_fraction)

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return loss, params, opt_state

    return model, train_step


def make_synthetic_mlm_batch(rng: jax.Array, config: AlbertConfig, batch_size: int, seq_len: int):
    """Deterministic synthetic MLM data for benchmarks/tests (15% masking)."""
    ids_key, mask_key = jax.random.split(rng)
    labels = jax.random.randint(ids_key, (batch_size, seq_len), 0, config.vocab_size)
    mlm_mask = jax.random.bernoulli(mask_key, 0.15, (batch_size, seq_len))
    mask_token = jnp.asarray(config.vocab_size - 1)
    input_ids = jnp.where(mlm_mask, mask_token, labels)
    return {"input_ids": input_ids, "labels": labels, "mlm_mask": mlm_mask}
