"""Decoder-only causal LM — the GPT-family training counterpart to the ALBERT MLM
flagship (the reference's example recipe covers only ALBERT; causal pretraining is
the other model family users expect from a collaborative-training framework, and the
serving side already ships causal/llama blocks — moe/server/layers/common.py).

TPU-first: bf16 compute with fp32 params, pre-norm blocks whose parameter names
match ``parallel/mesh.py``'s TP sharding rules, and a pluggable attention core —
plain causal attention on one chip, CAUSAL ring attention over the ``sp`` mesh axis
for long contexts (shards are contiguous sequence chunks in rank order; see
``parallel/ring_attention.ring_attention(causal=True)``)."""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp



@dataclasses.dataclass(frozen=True)
class CausalLMConfig:
    vocab_size: int = 32000
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position: int = 1024
    dtype: Any = jnp.bfloat16
    remat: bool = False  # checkpoint each layer (see AlbertConfig.remat)
    mesh: Optional[Any] = None  # sp>1 switches to causal ring attention

    @property
    def head_dim(self) -> int:
        assert self.hidden_size % self.num_heads == 0
        return self.hidden_size // self.num_heads

    @classmethod
    def base(cls, **overrides) -> "CausalLMConfig":
        return cls(**overrides)

    @classmethod
    def tiny(cls, **overrides) -> "CausalLMConfig":
        defaults = dict(
            vocab_size=1024, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position=128,
        )
        defaults.update(overrides)
        return cls(**defaults)


def _causal_attention_core(config: CausalLMConfig, q, k, v):
    from hivemind_tpu.parallel.ring_attention import mesh_attention_core

    return mesh_attention_core(config.mesh, q, k, v, causal=True)


class DecoderLayer(nn.Module):
    """One pre-norm decoder block: causal attention + gelu FFN. Parameter names
    (query/key/value/attention_out/ffn_up/ffn_down) match the mesh TP rules."""

    config: CausalLMConfig

    @nn.compact
    def __call__(self, hidden: jax.Array) -> jax.Array:
        cfg = self.config
        batch, seq, _ = hidden.shape
        dense = partial(nn.Dense, dtype=cfg.dtype, param_dtype=jnp.float32)
        normed = nn.LayerNorm(dtype=cfg.dtype, name="attention_norm")(hidden)
        q = dense(cfg.hidden_size, name="query")(normed).reshape(batch, seq, cfg.num_heads, cfg.head_dim)
        k = dense(cfg.hidden_size, name="key")(normed).reshape(batch, seq, cfg.num_heads, cfg.head_dim)
        v = dense(cfg.hidden_size, name="value")(normed).reshape(batch, seq, cfg.num_heads, cfg.head_dim)
        context = _causal_attention_core(cfg, q, k, v)
        hidden = hidden + dense(cfg.hidden_size, name="attention_out")(context.reshape(batch, seq, -1))
        normed = nn.LayerNorm(dtype=cfg.dtype, name="ffn_norm")(hidden)
        up = dense(cfg.intermediate_size, name="ffn_up")(normed)
        return hidden + dense(cfg.hidden_size, name="ffn_down")(jax.nn.gelu(up))


class CausalLM(nn.Module):
    config: CausalLMConfig

    def setup(self):
        cfg = self.config
        self.word_embeddings = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=cfg.dtype, param_dtype=jnp.float32,
            name="word_embeddings",
        )
        self.position_embeddings = self.param(
            "position_embeddings", nn.initializers.normal(0.02),
            (cfg.max_position, cfg.hidden_size), jnp.float32,
        )
        layer_cls = nn.remat(DecoderLayer) if cfg.remat else DecoderLayer
        self.layers = [layer_cls(cfg, name=f"layer_{i}") for i in range(cfg.num_layers)]
        self.final_norm = nn.LayerNorm(dtype=cfg.dtype, name="final_norm")

    def __call__(self, input_ids: jax.Array) -> jax.Array:
        """Returns next-token logits [batch, seq, vocab] (fp32 for a stable softmax;
        decoder = transposed embedding — weight tying)."""
        cfg = self.config
        seq = input_ids.shape[1]
        x = self.word_embeddings(input_ids) + self.position_embeddings[None, :seq].astype(cfg.dtype)
        for layer in self.layers:
            x = layer(x)
        x = self.final_norm(x)
        return self.word_embeddings.attend(x).astype(jnp.float32)


def causal_lm_loss(logits: jax.Array, input_ids: jax.Array) -> jax.Array:
    """Next-token cross-entropy: position t predicts token t+1 (the last position
    has no target and is dropped)."""
    log_probs = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    targets = input_ids[:, 1:]
    token_ll = jnp.take_along_axis(log_probs, targets[..., None], axis=-1)[..., 0]
    return -jnp.mean(token_ll)


def make_train_step(config: CausalLMConfig, optimizer):
    """A jittable (params, opt_state, batch) -> (loss, params, opt_state) step;
    ``batch``: dict(input_ids)."""
    import optax

    model = CausalLM(config)

    def loss_fn(params, batch):
        return causal_lm_loss(model.apply({"params": params}, batch["input_ids"]), batch["input_ids"])

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return loss, params, opt_state

    return model, train_step


def make_synthetic_lm_batch(rng: jax.Array, config: CausalLMConfig, batch_size: int, seq_len: int):
    """Deterministic synthetic token stream for benchmarks/tests."""
    input_ids = jax.random.randint(rng, (batch_size, seq_len), 0, config.vocab_size)
    return {"input_ids": input_ids}
