from hivemind_tpu.models.albert import (
    AlbertConfig,
    AlbertForMaskedLM,
    AlbertLayer,
    make_synthetic_mlm_batch,
    make_mlm_loss_fn,
    make_train_step,
    mlm_loss,
)
from hivemind_tpu.models.causal_lm import (
    CausalLM,
    CausalLMConfig,
    causal_lm_loss,
    make_synthetic_lm_batch,
)
from hivemind_tpu.models.causal_lm import make_train_step as make_causal_train_step
