from hivemind_tpu.models.albert import (
    AlbertConfig,
    AlbertForMaskedLM,
    AlbertLayer,
    make_synthetic_mlm_batch,
    make_mlm_loss_fn,
    make_train_step,
    mlm_loss,
)
