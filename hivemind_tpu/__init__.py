"""hivemind_tpu: a TPU-native framework for decentralized deep learning.

Capabilities mirror learning-at-home/hivemind (see SURVEY.md): a Kademlia-style DHT
for masterless peer discovery, fault-tolerant butterfly all-reduce with gradient
compression, a collaborative optimizer equivalent to large-batch synchronous training
over an elastic swarm, and a decentralized Mixture-of-Experts serving stack — designed
TPU-first on jax/XLA/pjit: device math is jax, a TPU slice acts as one logical swarm
peer (intra-slice reductions ride the ICI mesh via jax collectives), and networking is
a single-process asyncio runtime instead of the reference's fork-per-service topology
(reference: hivemind/__init__.py:1-14).
"""

from hivemind_tpu.utils.loop import EventLoopShutdownError, LoopRunner, get_loop_runner
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import (
    DHTExpiration,
    TimedStorage,
    ValueWithExpiration,
    get_dht_time,
)

__version__ = "0.1.0"


def __getattr__(name):  # lazy top-level API so `import hivemind_tpu` stays light
    import importlib

    top_level = {
        "DHT": "hivemind_tpu.dht",
        "DHTNode": "hivemind_tpu.dht.node",
        "P2P": "hivemind_tpu.p2p",
        "PeerID": "hivemind_tpu.p2p",
        "DecentralizedAverager": "hivemind_tpu.averaging",
        "MeshAverager": "hivemind_tpu.averaging",
        "NATTraversal": "hivemind_tpu.p2p",
        "Optimizer": "hivemind_tpu.optim",
        "GradientAverager": "hivemind_tpu.optim",
        "TrainingStateAverager": "hivemind_tpu.optim",
        "PowerSGDGradientAverager": "hivemind_tpu.optim",
        "GradScaler": "hivemind_tpu.optim",
        "TrainingAverager": "hivemind_tpu.optim",
        "ProgressTracker": "hivemind_tpu.optim",
        "Server": "hivemind_tpu.moe",
        "ModuleBackend": "hivemind_tpu.moe",
        "RemoteExpert": "hivemind_tpu.moe",
        "RemoteMixtureOfExperts": "hivemind_tpu.moe",
        "RemoteSequential": "hivemind_tpu.moe",
        "RemoteSwitchMixtureOfExperts": "hivemind_tpu.moe",
        "register_expert_class": "hivemind_tpu.moe",
        "RetryPolicy": "hivemind_tpu.resilience",
        "Deadline": "hivemind_tpu.resilience",
        "BreakerBoard": "hivemind_tpu.resilience",
        "CHAOS": "hivemind_tpu.resilience",
        "SimNetwork": "hivemind_tpu.sim",
        "SimPeer": "hivemind_tpu.sim",
        "LinkMatrix": "hivemind_tpu.sim",
        "run_scenario": "hivemind_tpu.sim",
    }
    if name in top_level:
        module = importlib.import_module(top_level[name])
        return getattr(module, name)
    raise AttributeError(f"module 'hivemind_tpu' has no attribute {name!r}")
