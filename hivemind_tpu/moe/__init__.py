from hivemind_tpu.moe.client import (
    MoEBeamSearcher,
    RemoteExpert,
    RemoteExpertWorker,
    RemoteMixtureOfExperts,
    RemoteSequential,
    RemoteSwitchMixtureOfExperts,
)
from hivemind_tpu.moe.expert_uid import ExpertInfo, ExpertUID, is_valid_prefix, is_valid_uid, split_uid
from hivemind_tpu.moe.server import (
    ConnectionHandler,
    MeshModuleBackend,
    ModuleBackend,
    Runtime,
    Server,
    background_server,
    declare_experts,
    get_experts,
    register_expert_class,
)
