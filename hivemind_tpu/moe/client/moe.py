"""RemoteMixtureOfExperts: route each input to its top-k experts across the swarm and
mix their outputs (capability parity: reference hivemind/moe/client/moe.py:25-442).

Host-orchestrated gating, device-vectorized mixing: expert fan-out happens through
ONE batched RemoteCallMany primitive (concurrent RPCs, alive-mask fault tolerance —
reference _RemoteCallMany) and the mixture itself is a single masked-softmax einsum
over [batch, k] slots, not per-sample Python loops. ``k_min``/``backward_k_min``
bound how many experts must answer per sample; ``timeout_after_k_min`` caps how long
stragglers are awaited once enough answered."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.client.beam_search import MoEBeamSearcher
from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS, RemoteCallMany
from hivemind_tpu.moe.client.expert import RemoteExpert
from hivemind_tpu.moe.expert_uid import ExpertInfo
from hivemind_tpu.p2p import P2P
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class RemoteMixtureOfExperts:
    """:param grid_size: experts live on a grid of this shape under uid_prefix
    :param k_best: experts per sample
    :param k_min: minimum experts that must respond (reference k_min semantics)
    :param backward_k_min: minimum experts whose backward must succeed per sample
    :param timeout_after_k_min: extra seconds granted to stragglers once every
        sample has k_min responses (reference moe.py:41-44)"""

    def __init__(
        self,
        *,
        dht: DHT,
        in_features: int,
        grid_size: Sequence[int],
        uid_prefix: str,
        k_best: int = 4,
        k_min: int = 1,
        backward_k_min: int = 1,
        forward_timeout: Optional[float] = None,
        backward_timeout: Optional[float] = None,
        timeout_after_k_min: Optional[float] = None,
        beam_size: Optional[int] = None,
        seed: int = 0,
    ):
        self.dht = dht
        from hivemind_tpu.utils.loop import get_loop_runner

        self.p2p: P2P = get_loop_runner().run_coroutine(dht.replicate_p2p())
        self.grid_size = tuple(grid_size)
        self.k_best, self.k_min, self.backward_k_min = k_best, k_min, backward_k_min
        self.forward_timeout, self.backward_timeout = forward_timeout, backward_timeout
        self.timeout_after_k_min = timeout_after_k_min
        self.beam_size = beam_size if beam_size is not None else k_best * 2
        self.beam_searcher = MoEBeamSearcher(dht, uid_prefix, grid_size)
        rng = np.random.RandomState(seed)
        # the trainable gating projection (reference: nn.Linear at moe.py:74)
        self.proj = jnp.asarray(rng.randn(in_features, sum(grid_size)) * 0.01, jnp.float32)
        self._experts: Dict[str, RemoteExpert] = {}

    def _get_expert(self, info: ExpertInfo) -> RemoteExpert:
        expert = self._experts.get(info.uid)
        if expert is None:
            expert = self._experts[info.uid] = RemoteExpert(info, self.p2p)
        elif expert.expert_info != info:
            expert.update_info(info)  # replica set / primary may have moved
        return expert

    def expert_scorecards(self) -> Dict[str, dict]:
        """This client's serving scorecards (ISSUE 9) for the experts this
        mixture has called: success rate, latency quantiles, timeouts, sheds —
        the caller-side view that rides the DHT telemetry snapshot."""
        from hivemind_tpu.telemetry.serving import SCORECARDS

        cards = SCORECARDS.export()
        return {uid: cards[uid] for uid in self._experts if uid in cards}

    def _split_scores(self, flat_scores: jax.Array) -> List[jax.Array]:
        out, offset = [], 0
        for size in self.grid_size:
            out.append(flat_scores[:, offset : offset + size])
            offset += size
        return out

    def _uid_coords(self, uid: str) -> List[int]:
        """Grid coordinates = the part of the uid after the grid prefix (the prefix
        itself may contain numeric components, e.g. per-layer grids 'ffn.3.')."""
        prefix = self.beam_searcher.uid_prefix
        assert uid.startswith(prefix), (uid, prefix)
        return [int(c) for c in uid[len(prefix):].split(".")]

    def _expert_logit(self, grid_scores: List[jax.Array], sample: int, uid: str) -> jax.Array:
        coords = self._uid_coords(uid)
        return sum(grid_scores[d][sample, c] for d, c in enumerate(coords))

    def __call__(self, x: jax.Array, proj: Optional[jax.Array] = None) -> jax.Array:
        """x: [batch, in_features]. Returns the expert mixture [batch, out_features].
        Eager-mode API (expert selection is data-dependent host orchestration)."""
        proj = proj if proj is not None else self.proj
        grid_scores = self._split_scores(x @ proj)
        chosen = self.beam_searcher.batch_find_best_experts(
            [np.asarray(jax.lax.stop_gradient(s)) for s in grid_scores], self.beam_size
        )
        return self._mix(x, grid_scores, chosen)

    def _mix(self, x: jax.Array, grid_scores: List[jax.Array], chosen: List[List[ExpertInfo]]) -> jax.Array:
        batch_size = x.shape[0]
        # breaker-aware routing (resilience/breaker.py): experts whose circuit is
        # hard-open are demoted below every live candidate, so a dead expert does
        # not burn one of a sample's k_best slots while healthy ones rank lower.
        # `in EXPERT_BREAKERS` is a pure read; half-open probes happen in _fan_out.
        sample_experts = []
        for sample in range(batch_size):
            candidates = chosen[sample]
            live = [info for info in candidates if info.uid not in EXPERT_BREAKERS]
            banned = [info for info in candidates if info.uid in EXPERT_BREAKERS]
            sample_experts.append((live + banned)[: self.k_best])
        if not any(sample_experts):
            raise RuntimeError("beam search found no experts; is any server declared on this grid?")
        k = max(len(infos) for infos in sample_experts)

        # one batched, concurrent, fault-tolerant fan-out for the whole batch
        rows = [
            [self._get_expert(info) for info in infos] + [None] * (k - len(infos))
            for infos in sample_experts
        ]
        call_many = RemoteCallMany(
            rows,
            k_min=self.k_min,
            backward_k_min=self.backward_k_min,
            forward_timeout=self.forward_timeout,
            backward_timeout=self.backward_timeout,
            timeout_after_k_min=self.timeout_after_k_min,
        )
        outputs, alive = call_many(x)  # [batch, k, d_out], [batch, k]

        # vectorized gating: logit[b, slot] = sum_d grid_scores[d][b, coord_d]
        ndim = len(self.grid_size)
        coords = np.zeros((batch_size, k, ndim), np.int32)
        valid = np.zeros((batch_size, k), bool)
        for sample, infos in enumerate(sample_experts):
            for slot, info in enumerate(infos):
                coords[sample, slot] = self._uid_coords(info.uid)
                valid[sample, slot] = True
        rows_index = jnp.arange(batch_size)[:, None]
        logits = sum(
            grid_scores[dim][rows_index, jnp.asarray(coords[:, :, dim])] for dim in range(ndim)
        )
        mask = jnp.asarray(valid) & alive
        logits = jnp.where(mask, logits, -1e9)  # finite: -inf NaNs the softmax grad
        weights = jax.nn.softmax(logits, axis=-1)
        weights = jnp.where(mask, weights, 0.0)  # dead slots contribute exactly zero
        return jnp.einsum("bk,bkd->bd", weights, outputs)


class RemoteSwitchMixtureOfExperts(RemoteMixtureOfExperts):
    """Switch-Transformer routing: top-1 expert, multiplicative jitter on inputs to
    the gate, grid dropout for load spreading, and a utilization EMA for
    load-balancing diagnostics (capability parity: reference
    hivemind/moe/client/switch_moe.py:17-225).

    :param grid_dropout: keep-probability per grid COORDINATE per call; dropped
        coordinates get -inf gating score so no sample routes to them this batch,
        forcing exploration across the grid (reference switch_moe.py:46,84-98).
        1.0 disables dropout."""

    def __init__(
        self,
        *,
        jitter_eps: float = 1e-2,
        utilization_alpha: float = 0.01,
        grid_dropout: float = 1.0,
        **kwargs,
    ):
        kwargs.setdefault("k_best", 1)
        # reference switch defaults (switch_moe.py:49-51): a token whose expert
        # fails contributes ZEROS instead of failing the whole batch
        kwargs.setdefault("k_min", 0)
        kwargs.setdefault("backward_k_min", 0)
        super().__init__(**kwargs)
        self.jitter_eps = jitter_eps
        self.utilization_alpha = utilization_alpha
        self.grid_dropout = grid_dropout
        self.grid_utilization = [np.full(size, 1.0 / size, np.float64) for size in self.grid_size]
        self._jitter_rng = np.random.RandomState(self.beam_size)

    def __call__(self, x: jax.Array, proj: Optional[jax.Array] = None) -> jax.Array:
        # jitter perturbs the GATING scores only; experts see the original input and
        # only ONE beam search runs (reference switch_moe.py:78-79,126)
        noise = self._jitter_rng.uniform(
            1 - self.jitter_eps, 1 + self.jitter_eps, size=(x.shape[0], 1)
        ).astype(np.float32)
        proj = proj if proj is not None else self.proj
        grid_scores = self._split_scores((x * jnp.asarray(noise)) @ proj)
        if self.grid_dropout < 1.0:
            keep_masks = [
                self._jitter_rng.rand(size) < self.grid_dropout for size in self.grid_size
            ]
            for dim, mask in enumerate(keep_masks):
                if not mask.any():
                    # never drop a whole dimension (that would un-restrict routing
                    # to arbitrary tie-breaks among -1e9 scores): keep the
                    # coordinate the gate likes best on this batch
                    best = int(np.argmax(np.asarray(jnp.mean(grid_scores[dim], axis=0))))
                    mask[best] = True
            grid_scores = [
                jnp.where(jnp.asarray(mask)[None, :], score, -1e9)
                for score, mask in zip(grid_scores, keep_masks)
            ]
        chosen = self.beam_searcher.batch_find_best_experts(
            [np.asarray(jax.lax.stop_gradient(s)) for s in grid_scores], self.beam_size
        )
        self._update_utilization(chosen)
        return self._mix(x, grid_scores, chosen)

    def _update_utilization(self, chosen: List[List[ExpertInfo]]) -> None:
        alpha = self.utilization_alpha
        for sample_infos in chosen:
            for info in sample_infos[:1]:  # top-1 routing
                for dim, coord in enumerate(self._uid_coords(info.uid)):
                    self.grid_utilization[dim] *= 1 - alpha
                    self.grid_utilization[dim][coord] += alpha
