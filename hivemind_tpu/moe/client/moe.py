"""RemoteMixtureOfExperts: route each input to its top-k experts across the swarm and
mix their outputs (capability parity: reference hivemind/moe/client/moe.py:25-442).

Host-orchestrated: gating + mixing are differentiable jax ops; expert calls go through
RemoteExpert's custom_vjp (RPC on both passes). Fault tolerance mirrors the
reference's _RemoteCallMany: experts that fail are masked out of the softmax, and the
forward proceeds if at least ``k_min`` experts responded per sample."""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.client.beam_search import MoEBeamSearcher
from hivemind_tpu.moe.client.expert import RemoteExpert
from hivemind_tpu.moe.expert_uid import ExpertInfo
from hivemind_tpu.p2p import P2P
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class RemoteMixtureOfExperts:
    """:param grid_size: experts live on a grid of this shape under uid_prefix
    :param k_best: experts per sample
    :param k_min: minimum experts that must respond (reference k_min semantics)"""

    def __init__(
        self,
        *,
        dht: DHT,
        in_features: int,
        grid_size: Sequence[int],
        uid_prefix: str,
        k_best: int = 4,
        k_min: int = 1,
        beam_size: Optional[int] = None,
        seed: int = 0,
    ):
        self.dht = dht
        from hivemind_tpu.utils.loop import get_loop_runner

        self.p2p: P2P = get_loop_runner().run_coroutine(dht.replicate_p2p())
        self.grid_size = tuple(grid_size)
        self.k_best, self.k_min = k_best, k_min
        self.beam_size = beam_size if beam_size is not None else k_best * 2
        self.beam_searcher = MoEBeamSearcher(dht, uid_prefix, grid_size)
        rng = np.random.RandomState(seed)
        # the trainable gating projection (reference: nn.Linear at moe.py:74)
        self.proj = jnp.asarray(rng.randn(in_features, sum(grid_size)) * 0.01, jnp.float32)
        self._experts: Dict[str, RemoteExpert] = {}

    def _get_expert(self, info: ExpertInfo) -> RemoteExpert:
        if info.uid not in self._experts:
            self._experts[info.uid] = RemoteExpert(info, self.p2p)
        return self._experts[info.uid]

    def _split_scores(self, flat_scores: jax.Array) -> List[jax.Array]:
        out, offset = [], 0
        for size in self.grid_size:
            out.append(flat_scores[:, offset : offset + size])
            offset += size
        return out

    def _uid_coords(self, uid: str) -> List[int]:
        """Grid coordinates = the part of the uid after the grid prefix (the prefix
        itself may contain numeric components, e.g. per-layer grids 'ffn.3.')."""
        prefix = self.beam_searcher.uid_prefix
        assert uid.startswith(prefix), (uid, prefix)
        return [int(c) for c in uid[len(prefix):].split(".")]

    def _expert_logit(self, grid_scores: List[jax.Array], sample: int, uid: str) -> jax.Array:
        coords = self._uid_coords(uid)
        return sum(grid_scores[d][sample, c] for d, c in enumerate(coords))

    def __call__(self, x: jax.Array, proj: Optional[jax.Array] = None) -> jax.Array:
        """x: [batch, in_features]. Returns the expert mixture [batch, out_features].
        Eager-mode API (expert selection is data-dependent host orchestration)."""
        proj = proj if proj is not None else self.proj
        grid_scores = self._split_scores(x @ proj)
        chosen = self.beam_searcher.batch_find_best_experts(
            [np.asarray(jax.lax.stop_gradient(s)) for s in grid_scores], self.beam_size
        )
        return self._mix(x, grid_scores, chosen)

    def _mix(self, x: jax.Array, grid_scores: List[jax.Array], chosen: List[List[ExpertInfo]]) -> jax.Array:
        batch_size = x.shape[0]
        # group samples by expert so each expert gets ONE batched call
        expert_to_samples: Dict[str, List[int]] = {}
        sample_experts: List[List[ExpertInfo]] = []
        for sample in range(batch_size):
            infos = chosen[sample][: self.k_best]
            sample_experts.append(infos)
            for info in infos:
                expert_to_samples.setdefault(info.uid, []).append(sample)
        if not expert_to_samples:
            raise RuntimeError("beam search found no experts; is any server declared on this grid?")

        uid_to_info = {}
        for sample_infos in sample_experts:
            for info in sample_infos:
                uid_to_info[info.uid] = info

        # fault-tolerant scatter: ALL experts are called concurrently (the reference's
        # _RemoteCallMany, moe.py:114-139); a slow expert costs max(), not sum(), and
        # failed experts are masked out of the softmax
        expert_outputs: Dict[str, jax.Array] = {}
        expert_sample_pos: Dict[str, Dict[int, int]] = {}

        def _call_one(uid: str, samples: List[int]):
            expert = self._get_expert(uid_to_info[uid])
            sub = x[jnp.asarray(samples)]
            return jax.block_until_ready(expert(sub))

        with ThreadPoolExecutor(max_workers=max(len(expert_to_samples), 1)) as pool:
            futures = {
                uid: pool.submit(_call_one, uid, samples)
                for uid, samples in expert_to_samples.items()
            }
            for uid, future in futures.items():
                try:
                    expert_outputs[uid] = future.result()
                    expert_sample_pos[uid] = {s: i for i, s in enumerate(expert_to_samples[uid])}
                except Exception as e:
                    logger.warning(f"expert {uid} failed: {e!r}; masking it out")

        if not expert_outputs:
            raise RuntimeError("all chosen experts failed")

        outputs = []
        for sample in range(batch_size):
            live: List[Tuple[jax.Array, jax.Array]] = []  # (logit, output)
            for info in sample_experts[sample]:
                if info.uid in expert_outputs:
                    position = expert_sample_pos[info.uid][sample]
                    live.append(
                        (self._expert_logit(grid_scores, sample, info.uid), expert_outputs[info.uid][position])
                    )
            if len(live) < self.k_min:
                raise RuntimeError(f"sample {sample}: only {len(live)} experts responded (k_min={self.k_min})")
            logits = jnp.stack([logit for logit, _ in live])
            weights = jax.nn.softmax(logits)
            stacked = jnp.stack([out for _, out in live])
            outputs.append(jnp.einsum("e,ed->d", weights, stacked))
        return jnp.stack(outputs)


class RemoteSwitchMixtureOfExperts(RemoteMixtureOfExperts):
    """Switch-Transformer routing: top-1 expert, multiplicative jitter on inputs to
    the gate, and a utilization EMA for load-balancing diagnostics (capability
    parity: reference hivemind/moe/client/switch_moe.py:17-225)."""

    def __init__(self, *, jitter_eps: float = 1e-2, utilization_alpha: float = 0.01, **kwargs):
        kwargs.setdefault("k_best", 1)
        kwargs.setdefault("k_min", 1)
        super().__init__(**kwargs)
        self.jitter_eps = jitter_eps
        self.utilization_alpha = utilization_alpha
        self.grid_utilization = [np.full(size, 1.0 / size, np.float64) for size in self.grid_size]
        self._jitter_rng = np.random.RandomState(self.beam_size)

    def __call__(self, x: jax.Array, proj: Optional[jax.Array] = None) -> jax.Array:
        # jitter perturbs the GATING scores only; experts see the original input and
        # only ONE beam search runs (reference switch_moe.py:78-79,126)
        noise = self._jitter_rng.uniform(
            1 - self.jitter_eps, 1 + self.jitter_eps, size=(x.shape[0], 1)
        ).astype(np.float32)
        proj = proj if proj is not None else self.proj
        grid_scores = self._split_scores((x * jnp.asarray(noise)) @ proj)
        chosen = self.beam_searcher.batch_find_best_experts(
            [np.asarray(jax.lax.stop_gradient(s)) for s in grid_scores], self.beam_size
        )
        self._update_utilization(chosen)
        return self._mix(x, grid_scores, chosen)

    def _update_utilization(self, chosen: List[List[ExpertInfo]]) -> None:
        alpha = self.utilization_alpha
        for sample_infos in chosen:
            for info in sample_infos[:1]:  # top-1 routing
                for dim, coord in enumerate(self._uid_coords(info.uid)):
                    self.grid_utilization[dim] *= 1 - alpha
                    self.grid_utilization[dim][coord] += alpha
