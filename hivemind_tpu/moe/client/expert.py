"""RemoteExpert: call an expert on another peer as if it were a local jax function
(capability parity: reference hivemind/moe/client/expert.py:32-233).

Autograd transparency: the reference wraps RPC in a torch.autograd.Function; here the
equivalent is jax.custom_vjp around jax.pure_callback — forward RPC on the primal
pass, backward RPC on the cotangent pass, usable under jax.grad (and jit: the callback
escapes the trace). Large payloads switch from unary to streaming at the same 2 MiB
threshold (reference expert.py:149-191)."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.compression import (
    CompressionBase,
    deserialize_tensor,
    expert_request_parts,
    resolve_activation_codec,
    serialize_tensor,
    split_tensor_for_streaming,
)
from hivemind_tpu.moe.expert_uid import IDEMPOTENT_CONNECTION_RPCS, ExpertInfo
from hivemind_tpu.p2p import P2P, PeerID
from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.telemetry.serving import (
    SCORECARDS,
    WIRE_BYTES_RECEIVED,
    WIRE_BYTES_SENT,
    is_overload_error,
)
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.loop import LoopRunner, get_loop_runner
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)

MAX_UNARY_PAYLOAD_SIZE = 2 * 1024 * 1024  # parity: p2p_daemon_bindings/control.py:36-39
_OFF_LOOP_CODEC_BYTES = 256 * 1024  # payloads past this compress/decompress in the executor

# serving wire accounting, this process as the CALLER (docs/observability.md)
_CLIENT_BYTES_SENT = WIRE_BYTES_SENT.labels("client")
_CLIENT_BYTES_RECEIVED = WIRE_BYTES_RECEIVED.labels("client")


class RemoteExpertWorker:
    """Compatibility shim over the shared loop runner (the reference runs a dedicated
    uvloop thread, moe/client/remote_expert_worker.py:10-37)."""

    @staticmethod
    def run_coroutine(coro, return_future: bool = False):
        runner = get_loop_runner()
        return runner.run_coroutine(coro, return_future=return_future)


class RemoteExpert:
    """A callable handle to a remote expert; differentiable via custom_vjp."""

    def __init__(self, expert_info: ExpertInfo, p2p: P2P,
                 request_compression: Optional[str] = None):
        self.expert_info = expert_info
        self.p2p = p2p
        self.span: Optional[List[str]] = None  # see _span_metadata
        # wire-dtype override for requests; None = negotiate the server's
        # advertised codec (DHT declaration, else rpc_info; "none" fallback
        # keeps pre-negotiation servers bit-identical)
        self.request_compression = request_compression
        self._info: Optional[Dict[str, Any]] = None
        self._info_lock = threading.Lock()

    @property
    def uid(self) -> str:
        return self.expert_info.uid

    @property
    def peer_id(self) -> PeerID:
        return self.expert_info.peer_id

    @property
    def info(self) -> Dict[str, Any]:
        """Forward/output schemas fetched lazily via rpc_info (reference expert.py)."""
        with self._info_lock:
            if self._info is not None:
                return self._info
        info = RemoteExpertWorker.run_coroutine(self._fetch_info())
        return info

    async def _fetch_info(self) -> Dict[str, Any]:
        """Async twin of :attr:`info` (usable ON the RPC loop — the sync property
        would deadlock there)."""
        with self._info_lock:
            if self._info is not None:
                return self._info
        response = await self.p2p.call_protobuf_handler(
            self.peer_id,
            "ConnectionHandler.rpc_info",
            runtime_pb2.ExpertUID(uid=self.uid),
            runtime_pb2.ExpertInfoResponse,
            idempotent=True,
        )
        info = MSGPackSerializer.loads(response.serialized_info)
        with self._info_lock:
            if self._info is None:
                self._info = info
            return self._info

    async def _wire_codec(self) -> CompressionBase:
        """The negotiated request wire dtype (ISSUE 10): an explicit
        ``request_compression`` override wins; otherwise the server's advertised
        codec — from its DHT declaration when present (zero extra round-trips),
        else from ``rpc_info`` (fetched once, cached with the schemas). Servers
        that advertise nothing get bit-identical NONE."""
        if self.request_compression is not None:
            return resolve_activation_codec(self.request_compression)
        name: Optional[str] = None
        with self._info_lock:
            if self._info is not None:
                name = self._info.get("activation_compression") or "none"
        if name is None:
            name = self.expert_info.compression
        if name is None:
            info = await self._fetch_info()
            name = info.get("activation_compression") or "none"
        try:
            return resolve_activation_codec(name)
        except ValueError:
            # a newer server advertising a codec this build lacks: stay correct
            logger.warning(f"expert {self.uid}: unknown advertised compression {name!r}; using none")
            return resolve_activation_codec("none")

    # ------------------------------------------------------------------ raw RPC

    async def _call(
        self, method: str, tensors: Sequence[np.ndarray], metadata: bytes = b""
    ) -> List[np.ndarray]:
        """One expert RPC, scorecarded (ISSUE 9): every outcome — success,
        failure, timeout/cancellation, server shed — lands on this expert's
        per-client scorecard, and a shed additionally feeds the expert's
        circuit breaker (the server said "overloaded", which is exactly the
        evidence the breaker exists to accumulate)."""
        started = time.perf_counter()
        try:
            result = await self._call_inner(method, tensors, metadata)
        except BaseException as e:
            SCORECARDS.record(
                self.uid, time.perf_counter() - started, ok=False, kind=method, error=e
            )
            if isinstance(e, Exception) and is_overload_error(e):
                # feed the shed into the expert's breaker HERE (the one choke
                # point every caller shares); call_many skips its own
                # register_failure for overloads so a shed counts exactly once
                from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS

                EXPERT_BREAKERS.register_failure(self.uid)
            raise
        SCORECARDS.record(self.uid, time.perf_counter() - started, ok=True, kind=method)
        return result

    async def _call_inner(
        self, method: str, tensors: Sequence[np.ndarray], metadata: bytes = b""
    ) -> List[np.ndarray]:
        codec = await self._wire_codec()

        def _serialize_all() -> List[runtime_pb2.Tensor]:
            # astype(copy=False): an fp32 input serializes as a VIEW (the old
            # np.asarray(t, np.float32) spelling forced the same cast but reads
            # as a copy; the explicit copy= keeps the hot-path lint honest); the
            # codec owns any further conversion and must NOT write into
            # caller-owned memory (no allow_inplace here)
            return [
                serialize_tensor(np.asarray(t).astype(np.float32, copy=False), codec)
                for t in tensors
            ]

        # big payloads compress off the shared client loop (the same loop runs
        # the DHT and every concurrent expert fan-out); small ones inline — the
        # executor hop would dominate a 4 KB decode step
        if sum(getattr(t, "nbytes", 0) for t in tensors) >= _OFF_LOOP_CODEC_BYTES:
            from hivemind_tpu.utils.asyncio_utils import run_in_executor

            serialized = await run_in_executor(_serialize_all)
        else:
            serialized = _serialize_all()
        # unary/stream decision on the fp32-EQUIVALENT size, not the compressed
        # bytes: a NONE server answers an fp16 request with a response ~2x the
        # request, and a unary response must stay under the mux frame cap
        payload = sum(int(np.asarray(t).size) * 4 for t in tensors)
        if payload <= MAX_UNARY_PAYLOAD_SIZE:
            # spliced scatter-gather request: tensor buffers ride to the AEAD
            # uncopied instead of being re-materialized by SerializeToString
            request = expert_request_parts(self.uid, serialized, metadata)
            response = await self.p2p.call_protobuf_handler(
                self.peer_id,
                f"ConnectionHandler.rpc_{method}",
                request,
                runtime_pb2.ExpertResponse,
                idempotent=(f"rpc_{method}" in IDEMPOTENT_CONNECTION_RPCS),
            )
            # counted AFTER the round-trip: a shed/dead-peer attempt must not
            # drift client-sent above server-received (retries count once, like
            # the server's parsed-request accounting)
            _CLIENT_BYTES_SENT.inc(request.nbytes)
            received = response.ByteSize()
            _CLIENT_BYTES_RECEIVED.inc(received)
            if received >= _OFF_LOOP_CODEC_BYTES:
                from hivemind_tpu.utils.asyncio_utils import run_in_executor

                return await run_in_executor(
                    lambda: [deserialize_tensor(t) for t in response.tensors]
                )
            return [deserialize_tensor(t) for t in response.tensors]
        # streaming path for big payloads (metadata rides the first message)

        async def requests():
            first = True
            for tensor in serialized:
                for chunk in split_tensor_for_streaming(tensor, 2**20):
                    message = runtime_pb2.ExpertRequest(
                        uid=self.uid if first else "", tensors=[chunk],
                        metadata=metadata if first else b"",
                    )
                    _CLIENT_BYTES_SENT.inc(message.ByteSize())
                    yield message
                    first = False

        from hivemind_tpu.compression import deserialize_tensor_stream

        stream = self.p2p.iterate_protobuf_handler(
            self.peer_id, f"ConnectionHandler.rpc_{method}_stream", requests(), runtime_pb2.ExpertResponse
        )

        async def parts():
            async for response in stream:
                _CLIENT_BYTES_RECEIVED.inc(response.ByteSize())
                yield list(response.tensors)

        # off_loop: this is by definition the multi-MB path, and the client
        # loop is shared with the DHT and every concurrent expert fan-out
        return await deserialize_tensor_stream(parts(), off_loop=True)

    def forward_np(self, *xs: np.ndarray) -> List[np.ndarray]:
        return RemoteExpertWorker.run_coroutine(
            self._call("forward", list(xs), self._span_metadata())
        )

    def decode_np(
        self, x: np.ndarray, session_id: str, reset: bool = False, span: Optional[list] = None
    ) -> np.ndarray:
        """One KV-cache decode-session step on the serving peer (rpc_decode):
        the prefill call (``reset=True``) seeds the session with the prompt chunk,
        later calls advance one token each — O(context) per token instead of the
        right-padded O(context²) recompute. Sessions are sticky to the peer; a
        continuation on an evicted session raises (restart with ``reset=True``).
        Prefill chunks over the unary cap use the streaming decode RPC.

        :param span: uids of CONSECUTIVE pipeline blocks co-located on this peer
            (first must be this expert's uid): the server chains their session
            steps in one RPC, so a pipeline's per-token round-trips drop from
            #blocks to #servers (Petals serves block spans the same way)."""
        meta = {"session_id": session_id, "reset": reset}
        if span is not None:
            assert span[0] == self.uid, (span, self.uid)
            meta["uids"] = list(span)
        metadata = MSGPackSerializer.dumps(meta)
        [output] = RemoteExpertWorker.run_coroutine(self._call("decode", [x], metadata))
        return output

    def backward_np(self, *tensors: np.ndarray) -> List[np.ndarray]:
        """``tensors`` = forward inputs followed by one grad per output."""
        return RemoteExpertWorker.run_coroutine(
            self._call("backward", list(tensors), self._span_metadata())
        )

    def _span_metadata(self) -> bytes:
        """Span execution (``self.span``: uids of consecutive co-located blocks,
        first = this uid): forward/backward requests carry the chain so the server
        runs every block of the span in one RPC."""
        if not self.span:
            return b""
        assert self.span[0] == self.uid, (self.span, self.uid)
        return MSGPackSerializer.dumps({"uids": list(self.span)})

    # ------------------------------------------------------------------ jax surface

    def __call__(self, *xs: jax.Array):
        """Differentiable remote call; supports multi-input/multi-output expert
        schemas (reference module_backend.py:68-74). Returns one array for
        single-output experts, a tuple otherwise. Output shapes derive from the
        expert's declared schemas with this call's batch size."""
        out_schemas = self.info["outputs_schema"]
        batch = xs[0].shape[0]
        # the server's schema reflects ITS sample batch: when the rank matches this
        # call's input, the expert preserves leading dims (batch, seq, ...) and only
        # the feature dim follows the schema — a sample-length seq baked into
        # out_structs would shape-mismatch any other sequence length. Rank-changing
        # experts (e.g. pooling) keep the schema's trailing dims as declared.
        out_structs = tuple(
            jax.ShapeDtypeStruct(
                (*xs[0].shape[:-1], schema.shape[-1])
                if len(schema.shape) == xs[0].ndim
                else (batch, *schema.shape[1:]),
                jnp.float32,
            )
            for schema in out_schemas
        )
        single_output = len(out_structs) == 1
        expert = self

        @jax.custom_vjp
        def remote_call(*xs):
            outs = jax.pure_callback(
                lambda *aa: tuple(
                    np.asarray(o, np.float32)
                    for o in expert.forward_np(*(np.asarray(a) for a in aa))
                ),
                out_structs,
                *xs,
            )
            return outs[0] if single_output else tuple(outs)

        def fwd(*xs):
            return remote_call(*xs), xs

        def bwd(residual_xs, g):
            grads_out = (g,) if single_output else tuple(g)
            grad_structs = tuple(jax.ShapeDtypeStruct(x.shape, jnp.float32) for x in residual_xs)
            grads_in = jax.pure_callback(
                lambda *aa: tuple(
                    np.asarray(gg, np.float32)
                    for gg in expert.backward_np(*(np.asarray(a) for a in aa))
                ),
                grad_structs,
                *residual_xs,
                *grads_out,
            )
            return tuple(
                g_in.astype(x.dtype, copy=False) for g_in, x in zip(grads_in, residual_xs)
            )

        remote_call.defvjp(fwd, bwd)
        return remote_call(*xs)

    def __repr__(self):
        return f"RemoteExpert({self.uid} @ {self.peer_id})"
