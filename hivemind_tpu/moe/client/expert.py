"""RemoteExpert: call an expert on another peer as if it were a local jax function
(capability parity: reference hivemind/moe/client/expert.py:32-233).

Autograd transparency: the reference wraps RPC in a torch.autograd.Function; here the
equivalent is jax.custom_vjp around jax.pure_callback — forward RPC on the primal
pass, backward RPC on the cotangent pass, usable under jax.grad (and jit: the callback
escapes the trace). Large payloads switch from unary to streaming at the same 2 MiB
threshold (reference expert.py:149-191).

Replica routing (ISSUE 13): an expert's DHT record is a *replica set* — every
call picks a replica by scorecard latency (seeded-random while cold, so fresh
clients don't thundering-herd the first declared server), fails over onto the
next replica when the chosen one sheds (typed ``ServerOverloadedError`` —
provably never executed) or proves unreachable, and **hedges the tail**: once
an idempotent request's in-flight latency crosses the replica's scorecard p95,
a second replica races it and the loser is cancelled (the RESET frame cancels
the losing server's handler mid-compute — p2p/mux.py). Hedge bookkeeping is
exact: the cancelled loser never feeds a scorecard or a breaker — only
completed outcomes are evidence. Per-replica circuit breakers
(``uid@peer`` keys on the shared EXPERT_BREAKERS board) gate routing; the
uid-level breaker keeps its PR 8 semantics (it trips only when the whole call
— i.e. every usable replica — fails)."""

from __future__ import annotations

import asyncio
import os
import random
import threading
import time
import zlib
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.compression import (
    CompressionBase,
    deserialize_tensor,
    expert_request_parts,
    resolve_activation_codec,
    serialize_tensor,
    split_tensor_for_streaming,
)
from hivemind_tpu.moe.expert_uid import IDEMPOTENT_CONNECTION_RPCS, ExpertInfo, ReplicaInfo
from hivemind_tpu.p2p import P2P, PeerID
from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.telemetry.serving import (
    HEDGES,
    REPLICA_FAILOVERS,
    SCORECARDS,
    WIRE_BYTES_RECEIVED,
    WIRE_BYTES_SENT,
    is_overload_error,
)
from hivemind_tpu.utils.asyncio_utils import aiter_with_timeout
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.loop import LoopRunner, get_loop_runner
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)

MAX_UNARY_PAYLOAD_SIZE = 2 * 1024 * 1024  # parity: p2p_daemon_bindings/control.py:36-39
_OFF_LOOP_CODEC_BYTES = 256 * 1024  # payloads past this compress/decompress in the executor
# hard ceiling on a single expert RPC (unary round-trip / per streamed message)
# and on the info fetch: a server that stalls mid-call must surface as a replica
# failure the hedging/breaker layer can act on, not wedge the caller forever
EXPERT_RPC_TIMEOUT = float(os.getenv("HIVEMIND_TPU_EXPERT_RPC_TIMEOUT", "120"))
_INFO_RPC_TIMEOUT = 10.0

# serving wire accounting, this process as the CALLER (docs/observability.md)
_CLIENT_BYTES_SENT = WIRE_BYTES_SENT.labels("client")
_CLIENT_BYTES_RECEIVED = WIRE_BYTES_RECEIVED.labels("client")

# hedging (ISSUE 13): only side-effect-free RPCs may be raced — a hedged
# rpc_backward could double-step an optimizer, a hedged rpc_decode would
# double-advance a KV session. rpc_forward is inference-only (expert_uid.py).
HEDGEABLE_METHODS = frozenset({"forward"})
# the hedge threshold is the replica's scorecard p95, floored here so a
# microsecond-fast expert cannot turn every call into a double-send storm
HEDGE_MIN_DELAY_S = 0.02

# transport-shaped failure text from across the RPC boundary (P2PHandlerError
# wraps the remote/type text): evidence the REPLICA is gone or no longer hosts
# the expert, which is exactly when another replica should be dialed. Keep the
# snippets NARROW — matching generic text ("KeyError", "connection to") turns
# arbitrary server-side bugs into failover storms that mask the real defect.
# Local transport losses raise ConnectionError subclasses (StreamClosedError
# included) and are covered by the isinstance check below.
_REPLICA_GONE_SNIPPETS = (
    "stream closed before response",       # P2PHandlerError: transport died mid-call
    "connection closed before request",    # P2PHandlerError: transport died pre-send
    "no reachable address",  # PeerNotFoundError: dangling declaration of a dead peer
    "unknown expert",        # remote handler's KeyError: this server stopped hosting it
)


def replica_breaker_key(uid: str, peer_id: PeerID) -> str:
    """Per-replica breaker key on the shared EXPERT_BREAKERS board: one dead
    replica trips ITS key while the uid's other replicas keep serving."""
    return f"{uid}@{peer_id.to_base58()}"


def is_replica_gone_error(error: BaseException) -> bool:
    """Transport loss / expert-not-here answers — safe failover evidence for
    idempotent RPCs (a response may have been computed, never observed)."""
    if isinstance(error, (ConnectionError, OSError, EOFError)):
        return True
    text = str(error)
    return any(snippet in text for snippet in _REPLICA_GONE_SNIPPETS)


def classify_replicas(uid: str, replicas: Sequence[ReplicaInfo], breakers):
    """The ONE replica-health policy — RemoteExpert routing and
    RemoteSequential block selection both rank through here. Returns
    ``(measured, cold, failing, banned)``: measured as
    ``(failure_bucket, mean_latency, replica)`` sorted healthiest-first, cold
    (no attempts yet — callers spread over these seeded-randomly), failing
    (attempts happened and NONE succeeded: known bad until the breaker opens,
    a last resort before banned), and breaker-banned."""
    measured, cold, failing, banned = [], [], [], []
    for replica in replicas:
        if breakers.is_banned(replica_breaker_key(uid, replica.peer_id)):
            banned.append(replica)
            continue
        mean, failure_rate = SCORECARDS.replica_health(uid, replica.peer_id.to_base58())
        if mean == float("inf"):
            # durations record successes only, so inf mean + nonzero failure
            # rate = every attempt failed — that is not "cold"
            (failing if failure_rate > 0 else cold).append(replica)
        else:
            measured.append((round(failure_rate, 1), mean, replica))
    measured.sort(key=lambda entry: (entry[0], entry[1]))
    return measured, cold, failing, banned


class RemoteExpertWorker:
    """Compatibility shim over the shared loop runner (the reference runs a dedicated
    uvloop thread, moe/client/remote_expert_worker.py:10-37)."""

    @staticmethod
    def run_coroutine(coro, return_future: bool = False):
        runner = get_loop_runner()
        return runner.run_coroutine(coro, return_future=return_future)


class RemoteExpert:
    """A callable handle to a remote expert; differentiable via custom_vjp."""

    def __init__(self, expert_info: ExpertInfo, p2p: P2P,
                 request_compression: Optional[str] = None,
                 seed: Optional[int] = None, hedging: bool = True):
        self.expert_info = expert_info
        self.p2p = p2p
        self.span: Optional[List[str]] = None  # see _span_metadata
        # wire-dtype override for requests; None = negotiate the server's
        # advertised codec (DHT declaration, else rpc_info; "none" fallback
        # keeps pre-negotiation servers bit-identical)
        self.request_compression = request_compression
        self.hedging = hedging
        # seeded replica choice (ISSUE 13): deterministic per (client, uid) so a
        # cold swarm of clients spreads across replicas instead of all dialing
        # the first declared record value, yet any one client is reproducible
        if seed is None:
            seed = zlib.crc32(f"{expert_info.uid}|{p2p.peer_id}".encode())
        self._rng = random.Random(seed)
        # decode sessions are sticky to the replica that holds their KV cache
        self._session_replicas: "OrderedDict[str, ReplicaInfo]" = OrderedDict()
        self._max_pinned_sessions = 256
        self._info: Optional[Dict[str, Any]] = None
        self._info_lock = threading.Lock()

    @property
    def uid(self) -> str:
        return self.expert_info.uid

    @property
    def peer_id(self) -> PeerID:
        return self.expert_info.peer_id

    @property
    def replicas(self) -> Tuple[ReplicaInfo, ...]:
        return self.expert_info.replica_set

    def update_info(self, info: ExpertInfo, *, keep_primary: bool = True) -> None:
        """Adopt a fresh resolution (replica set may have changed). With
        ``keep_primary`` (the default, what re-resolution wants) the
        currently-selected primary is KEPT when it is still in the refreshed
        set — resolution's deterministic first-replica choice must not undo an
        answered-replica re-pin (and ping-pong would clear the rpc_info cache
        on every flip). ``keep_primary=False`` forces ``info.peer_id`` as the
        new primary (the answered-replica re-pin itself). The cached schemas
        are invalidated only when the primary actually moves."""
        previous = self.expert_info
        current = next(
            (r for r in info.replica_set if r.peer_id == previous.peer_id), None
        ) if keep_primary else None
        if current is not None:
            info = ExpertInfo(info.uid, current.peer_id, current.compression, info.replicas)
        self.expert_info = info
        if previous.peer_id != info.peer_id:
            with self._info_lock:
                self._info = None

    @property
    def info(self) -> Dict[str, Any]:
        """Forward/output schemas fetched lazily via rpc_info (reference expert.py)."""
        with self._info_lock:
            if self._info is not None:
                return self._info
        info = RemoteExpertWorker.run_coroutine(self._fetch_info())
        return info

    async def _fetch_info(self) -> Dict[str, Any]:
        """Async twin of :attr:`info` (usable ON the RPC loop — the sync property
        would deadlock there). Tries every replica in routing order — a dead
        primary must not make the expert's schemas unfetchable."""
        with self._info_lock:
            if self._info is not None:
                return self._info
        last_error: Optional[BaseException] = None
        for replica in (self._replica_order() or list(self.replicas)):
            try:
                response = await asyncio.wait_for(
                    self.p2p.call_protobuf_handler(
                        replica.peer_id,
                        "ConnectionHandler.rpc_info",
                        runtime_pb2.ExpertUID(uid=self.uid),
                        runtime_pb2.ExpertInfoResponse,
                        idempotent=True,
                    ),
                    timeout=_INFO_RPC_TIMEOUT,
                )
                break
            except Exception as e:
                last_error = e
        else:
            raise last_error if last_error is not None else RuntimeError(
                f"expert {self.uid}: no replica to fetch info from"
            )
        info = MSGPackSerializer.loads(response.serialized_info)
        with self._info_lock:
            if self._info is None:
                self._info = info
            return self._info

    async def _wire_codec(self, replica: Optional[ReplicaInfo] = None) -> CompressionBase:
        """The negotiated request wire dtype (ISSUE 10): an explicit
        ``request_compression`` override wins; otherwise the TARGET replica's
        advertised codec — from its DHT declaration when present (zero extra
        round-trips), else from ``rpc_info`` (fetched once, cached with the
        schemas). Servers that advertise nothing get bit-identical NONE."""
        if self.request_compression is not None:
            return resolve_activation_codec(self.request_compression)
        name: Optional[str] = None
        if replica is not None:
            name = replica.compression
        if name is None:
            with self._info_lock:
                if self._info is not None:
                    name = self._info.get("activation_compression") or "none"
        if name is None:
            name = self.expert_info.compression
        if name is None:
            info = await self._fetch_info()
            name = info.get("activation_compression") or "none"
        try:
            return resolve_activation_codec(name)
        except ValueError:
            # a newer server advertising a codec this build lacks: stay correct
            logger.warning(f"expert {self.uid}: unknown advertised compression {name!r}; using none")
            return resolve_activation_codec("none")

    # ------------------------------------------------------------------ routing

    @staticmethod
    def _breakers():
        from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS

        return EXPERT_BREAKERS

    def _primary_replica(self) -> ReplicaInfo:
        for replica in self.replicas:
            if replica.peer_id == self.expert_info.peer_id:
                return replica
        return ReplicaInfo(self.expert_info.peer_id, self.expert_info.compression)

    def _replica_order(self) -> List[ReplicaInfo]:
        """Routing order: breaker-admitted replicas first, measured ones sorted
        by scorecard health (failure-rate bucket, then mean latency), cold ones
        (no scorecard data yet) after them in seeded-random order — a cold
        client spreads across the replica set instead of thundering-herding the
        first declared value — then replicas whose EVERY attempt failed (known
        bad beats unknown only as a last resort before the breaker catches up),
        and hard-open replicas last (failover of last resort)."""
        replicas = list(self.replicas)
        if len(replicas) <= 1:
            return replicas
        measured, cold, failing, banned = classify_replicas(
            self.uid, replicas, self._breakers()
        )
        self._rng.shuffle(cold)
        return [replica for _rate, _mean, replica in measured] + cold + failing + banned

    def _route_candidates(
        self, method: str, session: Optional[str], session_reset: bool
    ) -> List[ReplicaInfo]:
        if self.span:
            # span execution is co-location-pinned: the group was computed for
            # THIS primary; other replicas may not host the whole span chain
            # (RemoteSequential owns route-level failover)
            return [self._primary_replica()]
        if method == "decode" and session is not None and not session_reset:
            # continuations are sticky: only the pinned replica holds the cache
            pinned = self._session_replicas.get(session)
            return [pinned if pinned is not None else self._primary_replica()]
        order = self._replica_order()
        return order if order else [self._primary_replica()]

    def _pin_session(self, session: str, replica: ReplicaInfo) -> None:
        sessions = self._session_replicas
        sessions[session] = replica
        sessions.move_to_end(session)
        while len(sessions) > self._max_pinned_sessions:
            sessions.popitem(last=False)

    def _hedge_threshold(self, replica: ReplicaInfo) -> Optional[float]:
        """Seconds of in-flight latency after which a second replica is raced:
        the replica's scorecard p95 (uid-level fallback), floored — None while
        cold (no evidence of what 'slow' means yet → no hedge)."""
        p95 = SCORECARDS.replica_latency(self.uid, replica.peer_id.to_base58())
        if p95 is None:
            return None
        return max(p95, HEDGE_MIN_DELAY_S)

    def _failover_allowed(self, method: str, session_reset: bool, error: BaseException) -> bool:
        """May this failed attempt move to the next replica? A typed shed
        provably never executed (any method). Otherwise only side-effect-free
        attempts fail over, and only on replica-gone evidence: rpc_forward, and
        a decode PREFILL (re-running reset on a fresh replica just seeds its
        session; continuations are sticky and never fail over here)."""
        if isinstance(error, Exception) and is_overload_error(error):
            return True
        if method in HEDGEABLE_METHODS or (method == "decode" and session_reset):
            return isinstance(error, Exception) and is_replica_gone_error(error)
        return False

    def _note_replica_outcome(
        self, replica: ReplicaInfo, started: float, error: Optional[BaseException] = None
    ) -> None:
        """Per-replica bookkeeping for COMPLETED attempts only — a hedge's
        cancelled loser reaches neither this scorecard nor this breaker."""
        key = replica_breaker_key(self.uid, replica.peer_id)
        peer = replica.peer_id.to_base58()
        elapsed = time.perf_counter() - started
        if error is None:
            SCORECARDS.record_replica(self.uid, peer, elapsed, ok=True)
            self._breakers().register_success(key)
        else:
            shed = isinstance(error, Exception) and is_overload_error(error)
            SCORECARDS.record_replica(self.uid, peer, elapsed, ok=False, shed=shed)
            self._breakers().register_failure(key)

    # ------------------------------------------------------------------ raw RPC

    async def _call(
        self, method: str, tensors: Sequence[np.ndarray], metadata: bytes = b"",
        *, session: Optional[str] = None, session_reset: bool = False,
    ) -> List[np.ndarray]:
        """One expert RPC, scorecarded (ISSUE 9): every outcome — success,
        failure, timeout/cancellation, server shed — lands on this expert's
        per-client scorecard, and a shed additionally feeds the expert's
        circuit breaker (the server said "overloaded", which is exactly the
        evidence the breaker exists to accumulate). Routing across the replica
        set — balancing, failover, hedging — happens INSIDE this choke point
        (ISSUE 13), so the uid-level card/breaker keep their meaning: one
        logical call, one outcome, and a failure means every usable replica
        failed."""
        started = time.perf_counter()
        try:
            result = await self._call_routed(method, tensors, metadata, session, session_reset)
        except BaseException as e:
            SCORECARDS.record(
                self.uid, time.perf_counter() - started, ok=False, kind=method, error=e
            )
            if isinstance(e, Exception) and is_overload_error(e):
                # feed the shed into the expert's breaker HERE (the one choke
                # point every caller shares); call_many skips its own
                # register_failure for overloads so a shed counts exactly once
                self._breakers().register_failure(self.uid)
            raise
        SCORECARDS.record(self.uid, time.perf_counter() - started, ok=True, kind=method)
        return result

    async def _call_routed(
        self, method: str, tensors: Sequence[np.ndarray], metadata: bytes,
        session: Optional[str], session_reset: bool,
    ) -> List[np.ndarray]:
        """The replica scheduler: launch on the preferred replica; once the
        in-flight latency crosses that replica's scorecard p95 race a hedge on
        the next one (idempotent methods only) and cancel the loser; after a
        typed shed / replica-gone failure, fail over down the order."""
        candidates = self._route_candidates(method, session, session_reset)
        breakers = self._breakers()
        queue: List[ReplicaInfo] = list(candidates)
        in_flight: Dict[asyncio.Task, Tuple[ReplicaInfo, float]] = {}

        def launch() -> Optional[ReplicaInfo]:
            while queue:
                replica = queue.pop(0)
                if len(candidates) > 1 and not breakers.allow(
                    replica_breaker_key(self.uid, replica.peer_id)
                ):
                    continue  # hard-open replica: skipping is not fresh evidence
                task = asyncio.ensure_future(
                    self._call_replica(method, replica, tensors, metadata)
                )
                in_flight[task] = (replica, time.perf_counter())
                return replica
            return None

        primary = launch()
        if primary is None:
            # every replica hard-open: degrade to single-replica behavior — dial
            # the preferred candidate anyway (the uid-level breaker in call_many
            # owns the "skip this expert entirely" decision)
            primary = candidates[0]
            task = asyncio.ensure_future(
                self._call_replica(method, primary, tensors, metadata)
            )
            in_flight[task] = (primary, time.perf_counter())
        hedged = False
        last_error: Optional[BaseException] = None
        try:
            while in_flight:
                timeout = None
                if (
                    self.hedging
                    and not hedged
                    and queue
                    and method in HEDGEABLE_METHODS
                    and len(in_flight) == 1
                ):
                    (replica, attempt_started), = in_flight.values()
                    threshold = self._hedge_threshold(replica)
                    if threshold is not None:
                        timeout = max(
                            threshold - (time.perf_counter() - attempt_started), 0.0
                        )
                done, _pending = await asyncio.wait(
                    set(in_flight), timeout=timeout, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    # the hedge timer fired: race a second replica — the slow
                    # attempt is NOT failed; first answer wins, loser cancelled.
                    # Only a hedge that actually LAUNCHED counts as hedged
                    # (every queued replica may be breaker-banned), else the
                    # win would be recorded as a race that never happened.
                    if launch() is not None:
                        hedged = True
                        HEDGES.labels("fired").inc()
                    continue
                for task in done:
                    replica, attempt_started = in_flight.pop(task)
                    try:
                        result = task.result()
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        last_error = e
                        self._note_replica_outcome(replica, attempt_started, error=e)
                        continue
                    self._note_replica_outcome(replica, attempt_started)
                    if hedged:
                        HEDGES.labels(
                            "primary_won" if replica is primary else "hedge_won"
                        ).inc()
                    for loser, loser_started in in_flight.values():
                        # censored observation, NOT an outcome: the loser took
                        # at least this long (keeps a hanging replica from
                        # winning the next pick on stale fast quantiles)
                        SCORECARDS.note_hedge_loss(
                            self.uid, loser.peer_id.to_base58(),
                            time.perf_counter() - loser_started,
                        )
                    if session is not None:
                        self._pin_session(session, replica)
                    if replica.peer_id != self.expert_info.peer_id:
                        # the replica that ANSWERED is the selected primary now:
                        # route metadata, span pinning and the sticky-session
                        # fallback follow the server that is actually serving,
                        # not a dead peer's dangling declaration
                        self.update_info(ExpertInfo(
                            self.uid, replica.peer_id, replica.compression,
                            self.expert_info.replicas,
                        ), keep_primary=False)
                    return result
                if not in_flight:
                    assert last_error is not None
                    if queue and self._failover_allowed(method, session_reset, last_error):
                        REPLICA_FAILOVERS.labels(method).inc()
                        logger.warning(
                            f"expert {self.uid}: replica failed ({last_error!r}); "
                            f"failing over to the next replica"
                        )
                        if launch() is not None:
                            continue
                    raise last_error
            raise last_error if last_error is not None else RuntimeError(
                f"expert {self.uid}: no replica attempt was launched"
            )
        finally:
            for task in in_flight:
                # hedge losers / outer cancellation: cancelling propagates a
                # RESET through the mux so the losing server stops computing;
                # deliberately NO scorecard/breaker bookkeeping here
                task.cancel()

    async def _call_replica(
        self, method: str, replica: ReplicaInfo,
        tensors: Sequence[np.ndarray], metadata: bytes = b"",
    ) -> List[np.ndarray]:
        codec = await self._wire_codec(replica)
        target_peer = replica.peer_id

        def _serialize_all() -> List[runtime_pb2.Tensor]:
            # astype(copy=False): an fp32 input serializes as a VIEW (the old
            # np.asarray(t, np.float32) spelling forced the same cast but reads
            # as a copy; the explicit copy= keeps the hot-path lint honest); the
            # codec owns any further conversion and must NOT write into
            # caller-owned memory (no allow_inplace here)
            return [
                serialize_tensor(np.asarray(t).astype(np.float32, copy=False), codec)
                for t in tensors
            ]

        # big payloads compress off the shared client loop (the same loop runs
        # the DHT and every concurrent expert fan-out); small ones inline — the
        # executor hop would dominate a 4 KB decode step
        if sum(getattr(t, "nbytes", 0) for t in tensors) >= _OFF_LOOP_CODEC_BYTES:
            from hivemind_tpu.utils.asyncio_utils import run_in_executor

            serialized = await run_in_executor(_serialize_all)
        else:
            serialized = _serialize_all()
        # unary/stream decision on the fp32-EQUIVALENT size, not the compressed
        # bytes: a NONE server answers an fp16 request with a response ~2x the
        # request, and a unary response must stay under the mux frame cap
        payload = sum(int(np.asarray(t).size) * 4 for t in tensors)
        if payload <= MAX_UNARY_PAYLOAD_SIZE:
            # spliced scatter-gather request: tensor buffers ride to the AEAD
            # uncopied instead of being re-materialized by SerializeToString
            request = expert_request_parts(self.uid, serialized, metadata)
            response = await asyncio.wait_for(
                self.p2p.call_protobuf_handler(
                    target_peer,
                    f"ConnectionHandler.rpc_{method}",
                    request,
                    runtime_pb2.ExpertResponse,
                    idempotent=(f"rpc_{method}" in IDEMPOTENT_CONNECTION_RPCS),
                ),
                timeout=EXPERT_RPC_TIMEOUT,
            )
            # counted AFTER the round-trip: a shed/dead-peer attempt must not
            # drift client-sent above server-received (retries count once, like
            # the server's parsed-request accounting)
            _CLIENT_BYTES_SENT.inc(request.nbytes)
            received = response.ByteSize()
            _CLIENT_BYTES_RECEIVED.inc(received)
            if received >= _OFF_LOOP_CODEC_BYTES:
                from hivemind_tpu.utils.asyncio_utils import run_in_executor

                return await run_in_executor(
                    lambda: [deserialize_tensor(t) for t in response.tensors]
                )
            return [deserialize_tensor(t) for t in response.tensors]
        # streaming path for big payloads (metadata rides the first message)

        async def requests():
            first = True
            for tensor in serialized:
                for chunk in split_tensor_for_streaming(tensor, 2**20):
                    message = runtime_pb2.ExpertRequest(
                        uid=self.uid if first else "", tensors=[chunk],
                        metadata=metadata if first else b"",
                    )
                    _CLIENT_BYTES_SENT.inc(message.ByteSize())
                    yield message
                    first = False

        from hivemind_tpu.compression import deserialize_tensor_stream

        stream = self.p2p.iterate_protobuf_handler(
            target_peer, f"ConnectionHandler.rpc_{method}_stream", requests(), runtime_pb2.ExpertResponse
        )

        async def parts():
            # per-message deadline: total transfer time is unbounded, but any
            # single inter-message stall past the RPC timeout fails the replica
            async for response in aiter_with_timeout(stream, EXPERT_RPC_TIMEOUT):
                _CLIENT_BYTES_RECEIVED.inc(response.ByteSize())
                yield list(response.tensors)

        # off_loop: this is by definition the multi-MB path, and the client
        # loop is shared with the DHT and every concurrent expert fan-out
        return await deserialize_tensor_stream(parts(), off_loop=True)

    def forward_np(self, *xs: np.ndarray) -> List[np.ndarray]:
        return RemoteExpertWorker.run_coroutine(
            self._call("forward", list(xs), self._span_metadata())
        )

    def decode_np(
        self, x: np.ndarray, session_id: str, reset: bool = False, span: Optional[list] = None
    ) -> np.ndarray:
        """One KV-cache decode-session step on the serving peer (rpc_decode):
        the prefill call (``reset=True``) seeds the session with the prompt chunk,
        later calls advance one token each — O(context) per token instead of the
        right-padded O(context²) recompute. Sessions are sticky to the peer; a
        continuation on an evicted session raises (restart with ``reset=True``).
        Prefill chunks over the unary cap use the streaming decode RPC.

        :param span: uids of CONSECUTIVE pipeline blocks co-located on this peer
            (first must be this expert's uid): the server chains their session
            steps in one RPC, so a pipeline's per-token round-trips drop from
            #blocks to #servers (Petals serves block spans the same way)."""
        meta = {"session_id": session_id, "reset": reset}
        if span is not None:
            assert span[0] == self.uid, (span, self.uid)
            meta["uids"] = list(span)
        metadata = MSGPackSerializer.dumps(meta)
        [output] = RemoteExpertWorker.run_coroutine(
            self._call("decode", [x], metadata, session=session_id, session_reset=reset)
        )
        return output

    def backward_np(self, *tensors: np.ndarray) -> List[np.ndarray]:
        """``tensors`` = forward inputs followed by one grad per output."""
        return RemoteExpertWorker.run_coroutine(
            self._call("backward", list(tensors), self._span_metadata())
        )

    def _span_metadata(self) -> bytes:
        """Span execution (``self.span``: uids of consecutive co-located blocks,
        first = this uid): forward/backward requests carry the chain so the server
        runs every block of the span in one RPC."""
        if not self.span:
            return b""
        assert self.span[0] == self.uid, (self.span, self.uid)
        return MSGPackSerializer.dumps({"uids": list(self.span)})

    # ------------------------------------------------------------------ jax surface

    def __call__(self, *xs: jax.Array):
        """Differentiable remote call; supports multi-input/multi-output expert
        schemas (reference module_backend.py:68-74). Returns one array for
        single-output experts, a tuple otherwise. Output shapes derive from the
        expert's declared schemas with this call's batch size."""
        out_schemas = self.info["outputs_schema"]
        batch = xs[0].shape[0]
        # the server's schema reflects ITS sample batch: when the rank matches this
        # call's input, the expert preserves leading dims (batch, seq, ...) and only
        # the feature dim follows the schema — a sample-length seq baked into
        # out_structs would shape-mismatch any other sequence length. Rank-changing
        # experts (e.g. pooling) keep the schema's trailing dims as declared.
        out_structs = tuple(
            jax.ShapeDtypeStruct(
                (*xs[0].shape[:-1], schema.shape[-1])
                if len(schema.shape) == xs[0].ndim
                else (batch, *schema.shape[1:]),
                jnp.float32,
            )
            for schema in out_schemas
        )
        single_output = len(out_structs) == 1
        expert = self

        @jax.custom_vjp
        def remote_call(*xs):
            outs = jax.pure_callback(
                lambda *aa: tuple(
                    np.asarray(o, np.float32)
                    for o in expert.forward_np(*(np.asarray(a) for a in aa))
                ),
                out_structs,
                *xs,
            )
            return outs[0] if single_output else tuple(outs)

        def fwd(*xs):
            return remote_call(*xs), xs

        def bwd(residual_xs, g):
            grads_out = (g,) if single_output else tuple(g)
            grad_structs = tuple(jax.ShapeDtypeStruct(x.shape, jnp.float32) for x in residual_xs)
            grads_in = jax.pure_callback(
                lambda *aa: tuple(
                    np.asarray(gg, np.float32)
                    for gg in expert.backward_np(*(np.asarray(a) for a in aa))
                ),
                grad_structs,
                *residual_xs,
                *grads_out,
            )
            return tuple(
                g_in.astype(x.dtype, copy=False) for g_in, x in zip(grads_in, residual_xs)
            )

        remote_call.defvjp(fwd, bwd)
        return remote_call(*xs)

    def __repr__(self):
        return f"RemoteExpert({self.uid} @ {self.peer_id})"
