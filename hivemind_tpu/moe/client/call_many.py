"""Batched fault-tolerant scatter/gather to many experts (capability parity:
reference hivemind/moe/client/moe.py:192-442, the ``_RemoteCallMany`` autograd
Function).

One ``jax.custom_vjp`` primitive covers the whole expert fan-out: the primal pass
issues every expert's forward RPC CONCURRENTLY on the shared asyncio loop (a slow
expert costs max(), not sum()) and returns stacked per-slot outputs plus an alive
mask; the cotangent pass issues backward RPCs for the experts that answered.
Per-sample guarantees mirror the reference:

- ``k_min`` / ``backward_k_min``: each sample needs at least this many live expert
  responses on forward/backward, else the call raises;
- ``timeout_after_k_min``: once every sample has k_min responses, stragglers get at
  most this many extra seconds before being abandoned (their slots stay masked);
- ``forward_timeout`` / ``backward_timeout``: hard deadlines for each pass.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.moe.client.expert import RemoteExpert
from hivemind_tpu.resilience import CHAOS as _CHAOS
from hivemind_tpu.resilience import BreakerBoard, BreakerOpenError
from hivemind_tpu.telemetry.serving import is_overload_error as _is_overload_error
from hivemind_tpu.telemetry.tracing import trace as _tracing_span
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.loop import get_loop_runner
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)

# cross-call expert health (ISSUE 3): before this board, a dead expert cost every
# batch a full forward_timeout re-probe. Two consecutive failures trip the
# expert's breaker open for 30 s (doubling per re-trip); while open the expert is
# skipped instantly, and the half-open probe re-admits it after recovery.
EXPERT_BREAKERS = BreakerBoard(
    "moe_expert",
    failure_threshold=2,
    recovery_time=30.0,
    backoff_rate=2.0,
    max_recovery_time=600.0,
)


class RemoteCallMany:
    """Callable: ``outputs [batch, k, d_out], alive [batch, k] = rcm(x)``.

    :param experts_per_sample: for each sample, up to k experts (shorter rows are
        padded internally; padded slots always report dead)
    """

    def __init__(
        self,
        experts_per_sample: Sequence[Sequence[RemoteExpert]],
        *,
        k_min: int = 1,
        backward_k_min: int = 1,
        forward_timeout: Optional[float] = None,
        backward_timeout: Optional[float] = None,
        timeout_after_k_min: Optional[float] = None,
    ):
        self.experts_per_sample = [list(row) for row in experts_per_sample]
        self.batch_size = len(self.experts_per_sample)
        self.k_max = max((len(row) for row in self.experts_per_sample), default=0)
        assert self.k_max > 0, "every sample needs at least one expert"
        self.k_min, self.backward_k_min = k_min, backward_k_min
        self.forward_timeout, self.backward_timeout = forward_timeout, backward_timeout
        self.timeout_after_k_min = timeout_after_k_min

        # expert uid -> (expert, [(sample, slot), ...]): ONE batched RPC per expert
        self.jobs: Dict[str, Tuple[RemoteExpert, List[Tuple[int, int]]]] = {}
        for sample, row in enumerate(self.experts_per_sample):
            for slot, expert in enumerate(row):
                if expert is None:
                    continue
                self.jobs.setdefault(expert.uid, (expert, []))[1].append((sample, slot))

    # ------------------------------------------------------------------ fan-out core

    async def _fan_out(
        self,
        make_call,
        need_per_sample: int,
        timeout: Optional[float],
        job_uids: Sequence[str],
        chaos_point: str = "moe.forward",
    ) -> Dict[str, List[np.ndarray]]:
        """Run one RPC per expert concurrently; return {uid: tensors} for the ones
        that answered in time. Applies the k_min / timeout_after_k_min policy.
        Per-expert circuit breakers skip known-dead experts instantly and track
        each outcome (resilience/breaker.py)."""

        async def _guarded_call(uid: str):
            # one span per expert RPC ("moe.forward"/"moe.backward" — the chaos
            # point names double as span names so an injected fault is
            # attributable to the exact expert call it hit)
            expert = self.jobs[uid][0]
            with _tracing_span(
                chaos_point,
                expert=uid,
                peer=str(expert.p2p.peer_id),
                remote=str(expert.peer_id),
            ):
                if not EXPERT_BREAKERS.allow(uid):
                    raise BreakerOpenError(f"expert {uid} breaker is open; skipping")
                if _CHAOS.enabled:  # injection point: per expert forward/backward RPC
                    await _CHAOS.inject(chaos_point, scope=uid)
                result = await make_call(expert, uid)
                EXPERT_BREAKERS.register_success(uid)
                return result

        loop_tasks = {
            asyncio.ensure_future(_guarded_call(uid)): uid for uid in job_uids
        }
        results: Dict[str, List[np.ndarray]] = {}
        alive_count = [0] * self.batch_size
        # the straggler deadline opens once every row has at least ONE response
        # (even under k_min=0, where missing rows merely output zeros instead of
        # raising — the window must not open on the first completion and abandon
        # everyone else). A row can only deliver as many responses as it has real
        # experts, and an empty row is trivially satisfied.
        needed = [
            min(max(need_per_sample, 1), sum(e is not None for e in row))
            for row in self.experts_per_sample
        ]
        hard_deadline = get_dht_time() + timeout if timeout is not None else None
        soft_deadline = None  # set once every sample is satisfied

        pending = set(loop_tasks)
        try:
            while pending:
                now = get_dht_time()
                wait_for = None
                if hard_deadline is not None:
                    wait_for = max(hard_deadline - now, 0.0)
                if soft_deadline is not None:
                    soft_left = max(soft_deadline - now, 0.0)
                    wait_for = soft_left if wait_for is None else min(wait_for, soft_left)
                if wait_for is not None and wait_for <= 0:
                    break
                done, pending = await asyncio.wait(
                    pending, timeout=wait_for, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    break  # deadline
                for task in done:
                    uid = loop_tasks[task]
                    try:
                        results[uid] = task.result()
                        for sample, _slot in self.jobs[uid][1]:
                            alive_count[sample] += 1
                    except BreakerOpenError as e:
                        # not fresh evidence — the breaker already holds the failure
                        logger.debug(str(e))
                    except Exception as e:
                        # a server shed (ServerOverloadedError over the wire) was
                        # already fed to the breaker by RemoteExpert._call — do
                        # not double-count one shed as two failures
                        if not _is_overload_error(e):
                            EXPERT_BREAKERS.register_failure(uid)
                        logger.warning(f"expert {uid} failed: {e!r}; masking it out")
                if (
                    soft_deadline is None
                    and self.timeout_after_k_min is not None
                    and all(count >= need for count, need in zip(alive_count, needed))
                ):
                    soft_deadline = get_dht_time() + self.timeout_after_k_min
        finally:
            for task in pending:
                task.cancel()
                # a deadline-abandoned expert is the breaker's primary target
                # (the hang that used to cost every batch a full timeout): being
                # cancelled means it never reached the success/failure paths, so
                # record the failure here or the breaker can never trip on hangs
                EXPERT_BREAKERS.register_failure(loop_tasks[task])
        return results

    # ------------------------------------------------------------------ forward

    def _forward_np(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        d_out = self._output_dim()
        x = np.asarray(x, np.float32)

        async def call_forward(expert: RemoteExpert, uid: str):
            samples = [s for s, _ in self.jobs[uid][1]]
            return await expert._call("forward", [x[samples]])

        results = get_loop_runner().run_coroutine(
            self._fan_out(
                call_forward, self.k_min, self.forward_timeout, list(self.jobs),
                chaos_point="moe.forward",
            )
        )

        outputs = np.zeros((self.batch_size, self.k_max, d_out), np.float32)
        alive = np.zeros((self.batch_size, self.k_max), bool)
        for uid, tensors in results.items():
            out = np.asarray(tensors[0], np.float32)
            for position, (sample, slot) in enumerate(self.jobs[uid][1]):
                outputs[sample, slot] = out[position]
                alive[sample, slot] = True
        real_slots = [sum(e is not None for e in row) for row in self.experts_per_sample]
        short = np.flatnonzero(alive.sum(1) < np.minimum(self.k_min, real_slots))
        if short.size:
            raise RuntimeError(
                f"samples {short.tolist()} got fewer than k_min={self.k_min} expert responses"
            )
        return outputs, alive

    def _output_dim(self) -> int:
        last_error: Optional[Exception] = None
        for expert, _ in self.jobs.values():
            try:
                schema = expert.info["outputs_schema"][0]
                return int(np.prod(schema.shape[1:]))
            except Exception as e:  # expert unreachable: its schema can't be fetched
                last_error = e
        raise RuntimeError(f"could not fetch any expert's output schema: {last_error!r}")

    # ------------------------------------------------------------------ backward

    def _backward_np(self, x: np.ndarray, grad_outputs: np.ndarray, alive: np.ndarray) -> np.ndarray:
        x = np.asarray(x, np.float32)
        grad_outputs = np.asarray(grad_outputs, np.float32)
        alive = np.asarray(alive, bool)
        # only experts that answered the forward participate in the backward
        live_uids = [
            uid
            for uid, (_e, positions) in self.jobs.items()
            if any(alive[sample, slot] for sample, slot in positions)
        ]

        async def call_backward(expert: RemoteExpert, uid: str):
            positions = self.jobs[uid][1]
            samples = [s for s, _ in positions]
            grads = np.stack([grad_outputs[s, slot] for s, slot in positions])
            return await expert._call("backward", [x[samples], grads])

        results = get_loop_runner().run_coroutine(
            self._fan_out(
                call_backward, self.backward_k_min, self.backward_timeout, live_uids,
                chaos_point="moe.backward",
            )
        )

        grad_x = np.zeros_like(x)
        grads_per_sample = [0] * self.batch_size
        for uid, tensors in results.items():
            grad = np.asarray(tensors[0], np.float32)
            for position, (sample, _slot) in enumerate(self.jobs[uid][1]):
                grad_x[sample] += grad[position]
                grads_per_sample[sample] += 1
        short = [
            s
            for s, row in enumerate(self.experts_per_sample)
            if grads_per_sample[s] < min(self.backward_k_min, sum(e is not None for e in row))
        ]
        if short:
            raise RuntimeError(
                f"samples {short} got fewer than backward_k_min={self.backward_k_min} gradients"
            )
        return grad_x

    # ------------------------------------------------------------------ jax surface

    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        d_out = self._output_dim()
        batch, k = self.batch_size, self.k_max
        outer = self

        @jax.custom_vjp
        def call_many(x):
            out, mask = jax.pure_callback(
                outer._forward_np,
                (
                    jax.ShapeDtypeStruct((batch, k, d_out), jnp.float32),
                    jax.ShapeDtypeStruct((batch, k), jnp.bool_),
                ),
                x,
            )
            return out, mask

        def fwd(x):
            out, mask = call_many(x)
            return (out, mask), (x, mask)

        def bwd(residuals, cotangents):
            x, mask = residuals
            g_out, _g_mask = cotangents
            grad_x = jax.pure_callback(
                outer._backward_np,
                jax.ShapeDtypeStruct(x.shape, jnp.float32),
                x,
                g_out,
                mask,
            )
            return (grad_x.astype(x.dtype),)

        call_many.defvjp(fwd, bwd)
        return call_many(x)
