"""MoEBeamSearcher: find the k best experts on the UID grid via left-to-right beam
search over DHT prefix dictionaries (capability parity: reference
hivemind/moe/client/beam_search.py:27-401). Runs inside the DHT's event loop via
dht.run_coroutine (reference beam_search.py:106-117), with negative caching of dead
prefixes (reference 60-74,152-160)."""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.expert_uid import UID_DELIMITER, ExpertInfo, is_valid_prefix
from hivemind_tpu.p2p import PeerID
from hivemind_tpu.telemetry.tracing import trace as _tracing_span
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import TimedStorage, get_dht_time

logger = get_logger(__name__)


class MoEBeamSearcher:
    """:param uid_prefix: grid name, e.g. 'ffn.' (trailing delimiter required)
    :param grid_size: number of indices per grid dimension"""

    def __init__(
        self,
        dht: DHT,
        uid_prefix: str,
        grid_size: Sequence[int],
        *,
        num_workers: Optional[int] = None,
        negative_cache_time: float = 30.0,
    ):
        if not uid_prefix.endswith(UID_DELIMITER):
            uid_prefix += UID_DELIMITER
        assert is_valid_prefix(uid_prefix), f"invalid prefix {uid_prefix!r}"
        self.dht = dht
        self.uid_prefix = uid_prefix
        self.grid_size = tuple(grid_size)
        self.negative_cache_time = negative_cache_time
        self._negative_cache: TimedStorage[str, bool] = TimedStorage()

    def find_best_experts(self, grid_scores: Sequence[np.ndarray], beam_size: int) -> List[ExpertInfo]:
        """``grid_scores[d][i]`` scores coordinate i of dimension d for ONE sample;
        returns up to beam_size experts sorted by total score (descending)."""
        batched = self.batch_find_best_experts([np.asarray(s)[None] for s in grid_scores], beam_size)
        return batched[0]

    def batch_find_best_experts(
        self, batch_grid_scores: Sequence[np.ndarray], beam_size: int
    ) -> List[List[ExpertInfo]]:
        """``batch_grid_scores[d][b, i]``: per-sample scores. One DHT pass serves the
        whole batch (prefix fetches are shared across samples)."""
        scores = [np.asarray(dim_scores, np.float32) for dim_scores in batch_grid_scores]
        assert len(scores) == len(self.grid_size)

        async def _search(dht_obj, node) -> List[List[ExpertInfo]]:
            return await self._find_best_experts_async(node, scores, beam_size)

        return self.dht.run_coroutine(_search)

    async def _find_best_experts_async(self, node, scores, beam_size: int) -> List[List[ExpertInfo]]:
        with _tracing_span(
            "moe.beam_search",
            peer=str(node.protocol.p2p.peer_id),
            prefix=self.uid_prefix,
            beam_size=beam_size,
        ):
            return await self._beam_search_traced(node, scores, beam_size)

    async def _beam_search_traced(self, node, scores, beam_size: int) -> List[List[ExpertInfo]]:
        batch_size = scores[0].shape[0]
        # per-sample beams: list of (neg_total_score, prefix_without_trailing_delim)
        beams: List[List[Tuple[float, str]]] = [
            [(0.0, self.uid_prefix.rstrip(UID_DELIMITER))] for _ in range(batch_size)
        ]
        for dim, dim_scores in enumerate(scores):
            # gather every active prefix across the batch (deduplicated)
            active: Dict[str, None] = {}
            for beam in beams:
                for _neg_score, prefix in beam:
                    if prefix not in self._negative_cache:
                        active[prefix] = None
            prefix_coords = await self._fetch_prefix_dicts(node, list(active.keys()))
            new_beams: List[List[Tuple[float, str]]] = []
            for sample in range(batch_size):
                candidates: List[Tuple[float, str]] = []
                for neg_score, prefix in beams[sample]:
                    coords = prefix_coords.get(prefix, {})
                    if not coords:
                        continue
                    for coord in coords:
                        if not (0 <= coord < self.grid_size[dim]):
                            continue
                        score = -neg_score + float(dim_scores[sample, coord])
                        candidates.append((-score, f"{prefix}{UID_DELIMITER}{coord}"))
                new_beams.append(heapq.nsmallest(beam_size, candidates))
            beams = new_beams

        # resolve leaves to peers
        leaf_uids: Dict[str, None] = {}
        for beam in beams:
            for _neg, uid in beam:
                leaf_uids[uid] = None
        uid_to_info = await self._resolve_leaves(node, list(leaf_uids.keys()))
        results: List[List[ExpertInfo]] = []
        for beam in beams:
            sample_result = []
            for neg_score, uid in sorted(beam):
                resolved = uid_to_info.get(uid)
                if resolved is not None:
                    sample_result.append(resolved)
            results.append(sample_result)
        return results

    async def _fetch_prefix_dicts(self, node, prefixes: List[str]) -> Dict[str, Dict[int, None]]:
        if not prefixes:
            return {}
        found = await node.get_many(prefixes)
        out: Dict[str, Dict[int, None]] = {}
        for prefix in prefixes:
            entry = found.get(prefix)
            coords: Dict[int, None] = {}
            if entry is not None and isinstance(entry.value, dict):
                for subkey in entry.value:
                    if isinstance(subkey, int):
                        coords[subkey] = None
            if coords:
                out[prefix] = coords
            else:
                # dead prefix: don't ask again for a while (reference negative caching)
                self._negative_cache.store(prefix, True, get_dht_time() + self.negative_cache_time)
        return out

    async def _resolve_leaves(self, node, uids: List[str]):
        """uid -> resolved :class:`ExpertInfo` carrying the FULL replica set
        (ISSUE 13); the record may be a bare peer id, ``peer|compression``, or
        a subkey dictionary of replica records (dht_handler)."""
        from hivemind_tpu.moe.server.dht_handler import expert_info_from_entry

        if not uids:
            return {}
        # deliberately FIRST-FRESH (not the merging REPLICA_SET_SUFFICIENCY
        # traversal get_experts uses): beam-search leaf resolution runs on the
        # per-forward hot path of RemoteMixtureOfExperts, and an unreachable
        # sufficiency would force full network traversals per leaf per batch.
        # The cost of a partial subkey dict here is a temporarily thinner
        # replica set for this call — balancing is less informed, while
        # failover/breakers/alive-mask still handle a stale dead entry exactly
        # as they did for single-value records.
        found = await node.get_many(uids)
        out = {}
        for uid in uids:
            entry = found.get(uid)
            info = expert_info_from_entry(uid, entry.value) if entry is not None else None
            if info is not None:
                out[uid] = info
        return out

    def get_initial_beam(self, dim_scores: np.ndarray, beam_size: int):
        """Compatibility helper: top-scoring first-dimension prefixes."""
        order = np.argsort(-np.asarray(dim_scores))[:beam_size]
        return [(float(dim_scores[i]), f"{self.uid_prefix}{i}{UID_DELIMITER}") for i in order]
