"""RemoteSequential: run a model as a CHAIN of remote transformer blocks served by
swarm peers — pipelined model parallelism over the DHT (the Petals-style capability
layered on the DMoE stack; the reference README positions Petals as the downstream
project built exactly this way on hivemind, README.md:35-40, and SURVEY §7.10 lists
it as the capability layer above the expert server).

Blocks are ordinary experts named ``{prefix}{index}`` ("gpt_block.0", "gpt_block.1",
…): any :class:`hivemind_tpu.moe.Server` can host any subset of blocks and declares
them in the DHT. The client resolves each index lazily, chains the blocks'
``RemoteExpert`` calls — each differentiable via custom_vjp — so ``jax.grad`` flows
through the WHOLE pipeline, and every backward RPC also trains the server-side block
(ModuleBackend on_backward semantics). A failed block call triggers re-resolution
(a replacement server re-declaring the same uid takes over transparently)."""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Dict, Optional

import jax

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.client.expert import RemoteExpert
from hivemind_tpu.moe.expert_uid import ExpertInfo
from hivemind_tpu.moe.server.dht_handler import get_experts
from hivemind_tpu.p2p import PeerID
from hivemind_tpu.resilience import RetryPolicy
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.loop import get_loop_runner

logger = get_logger(__name__)


class _ResilientBlock(RemoteExpert):
    """A RemoteExpert whose RPCs retry with DHT re-resolution INSIDE forward_np /
    backward_np — i.e. inside the pure_callback — so failover covers the backward
    pass of jax.grad and jitted execution, not just the eager forward dispatch."""

    def __init__(self, sequential: "RemoteSequential", index: int, info: ExpertInfo):
        super().__init__(info, sequential.p2p,
                         request_compression=sequential.request_compression)
        self._sequential = sequential
        self._index = index

    def _with_retries(self, operation):
        def on_retry(retry_index: int, error: BaseException) -> None:
            logger.warning(
                f"block {self.uid} via {self.peer_id} failed (attempt {retry_index + 1}): {error!r}"
            )
            fresh = self._sequential._resolve_info(self._index, force=True)
            self.expert_info = fresh
            with self._info_lock:
                self._info = None  # schema may differ on the new server

        try:
            return self._sequential.retry_policy.execute_sync(operation, on_retry=on_retry)
        except Exception as last_error:
            raise RuntimeError(f"block {self.uid} failed after retries") from last_error

    def forward_np(self, *xs):
        return self._with_retries(lambda: RemoteExpert.forward_np(self, *xs))

    def backward_np(self, *tensors):
        return self._with_retries(lambda: RemoteExpert.backward_np(self, *tensors))

    @property
    def info(self):
        # the schema fetch at dispatch time must fail over too
        return self._with_retries(lambda: RemoteExpert.info.fget(self))


class RemoteSequential:
    """See module docstring.

    :param prefix: block uid prefix incl. trailing delimiter, e.g. ``"gpt_block."``
    :param num_blocks: pipeline depth; block i is expert ``{prefix}{i}``
    :param update_period: re-resolve a cached block after this many seconds
    :param max_retries: per block call: failures before giving up (each retry
        re-resolves the uid from the DHT first)
    """

    def __init__(
        self,
        dht: DHT,
        prefix: str,
        num_blocks: int,
        *,
        update_period: float = 30.0,
        max_retries: int = 2,
        max_failover_history: int = 4096,
        request_compression: Optional[str] = None,
    ):
        self.dht, self.prefix, self.num_blocks = dht, prefix, num_blocks
        self.update_period, self.max_retries = update_period, max_retries
        # wire-dtype override for every block request; None = negotiate each
        # server's advertised codec (ISSUE 10 — see docs/benchmarks.md)
        self.request_compression = request_compression
        # decode failover retains each session's input history for re-prefill; the
        # cap bounds client memory (past it, failover degrades to the pre-r4
        # raise-and-reset behavior for that session). 0 disables retention.
        self.max_failover_history = max_failover_history
        self.p2p = get_loop_runner().run_coroutine(dht.replicate_p2p())
        self._blocks: Dict[int, _ResilientBlock] = {}
        self._infos: Dict[int, ExpertInfo] = {}
        self._resolved_at: Dict[int, float] = {}
        self._span_support: Dict[object, bool] = {}  # peer_id -> server groups spans
        # session_id -> {"route": pinned block handles, "chunks": list of input
        # chunks retained for failover re-prefill (None = over the retention cap),
        # "positions": retained position count}
        self._decode_routes: Dict[str, dict] = {}
        self.max_decode_routes = 256  # oldest pinned routes drop beyond this
        # seeded replica choice across route resolutions (ISSUE 13): fresh
        # clients spread over the replica set instead of all pinning the first
        # declared server, yet each client's choices replay deterministically
        self._route_rng = random.Random(zlib.crc32(f"{prefix}|{self.p2p.peer_id}".encode()))
        self._lock = threading.Lock()

    @property
    def retry_policy(self) -> RetryPolicy:
        """Every retry loop in this client shares one declared policy (ISSUE 3):
        short equal-jittered backoff — a replacement server needs a beat to
        re-declare the uid, and synchronized clients must not re-dial in
        lockstep. Derived lazily from ``max_retries`` so changing it (or tests
        building partial instances) stays honored."""
        policy = self.__dict__.get("_retry_policy")
        if policy is None or policy.max_attempts != self.max_retries + 1:
            policy = RetryPolicy(
                max_attempts=self.max_retries + 1,
                base_delay=0.25,
                backoff=2.0,
                max_delay=2.0,
                jitter="equal",
                name="remote_sequential",
            )
            self.__dict__["_retry_policy"] = policy
        return policy

    def __len__(self) -> int:
        return self.num_blocks

    def block_uid(self, index: int) -> str:
        return f"{self.prefix}{index}"

    def _resolve_info(self, index: int, force: bool = False) -> ExpertInfo:
        with self._lock:
            fresh_enough = time.monotonic() - self._resolved_at.get(index, -1e9) < self.update_period
            cached = self._infos.get(index)
            if not force and cached is not None and fresh_enough:
                return cached
        [info] = get_experts(self.dht, [self.block_uid(index)])
        if info is None:
            raise RuntimeError(f"no server declares block {self.block_uid(index)!r}")
        with self._lock:
            self._infos[index] = info
            self._resolved_at[index] = time.monotonic()
        return info

    def _block(self, index: int) -> _ResilientBlock:
        info = self._resolve_info(index)
        with self._lock:
            block = self._blocks.get(index)
            if block is None:
                block = self._blocks[index] = _ResilientBlock(self, index, info)
            elif block.expert_info != info:
                block.expert_info = info  # route refreshed by update_period
                with block._info_lock:
                    block._info = None
            return block

    def _call_block(self, index: int, x: jax.Array) -> jax.Array:
        return self._block(index)(x)

    def _peer_supports_spans(self, head: RemoteExpert) -> bool:
        """Capability negotiation for mixed swarms: a span-unaware server would run
        only the head block and silently return its output as the whole span's —
        so multi-block groups require the server to advertise span_support."""
        supported = self._span_support.get(head.peer_id)
        if supported is None:
            try:
                supported = bool(head.info.get("span_support"))
            except Exception:
                # transient info failure: assume no spans THIS grouping, but do not
                # cache the negative — a single failed fetch must not disable span
                # grouping for this peer for the process lifetime
                return False
            with self._lock:
                self._span_support[head.peer_id] = supported
        return supported

    def _select_block_replica(
        self, info: ExpertInfo, preferred: Optional[PeerID]
    ) -> ExpertInfo:
        """Pick this block's serving replica (ISSUE 13): breaker-open replicas
        are avoided (a killed server must drop out of fresh routes instantly),
        the PREVIOUS block's peer is kept when it also hosts this block (span
        grouping — one RPC per server, not per block), then the shared
        replica-health policy (expert.classify_replicas) decides, with a
        seeded-random pick while cold."""
        replicas = info.replica_set
        if len(replicas) == 1:
            return info
        from hivemind_tpu.moe.client.call_many import EXPERT_BREAKERS
        from hivemind_tpu.moe.client.expert import classify_replicas

        measured, cold, failing, banned = classify_replicas(
            info.uid, replicas, EXPERT_BREAKERS
        )
        live = [replica for _rate, _mean, replica in measured] + cold + failing
        pool = live if live else list(replicas)
        chosen = None
        if preferred is not None:
            for replica in pool:
                if replica.peer_id == preferred:
                    chosen = replica
                    break
        if chosen is None:
            if measured:
                chosen = measured[0][2]
            else:
                chosen = self._route_rng.choice(cold or pool)
        return ExpertInfo(info.uid, chosen.peer_id, chosen.compression, info.replicas)

    def _grouped_range(self, start: int, stop: int, force: bool = False):
        """Resolve blocks [start, stop) and group CONSECUTIVE same-peer blocks into
        spans: each group is one RPC (server chains the blocks — span execution).
        Replicated blocks prefer staying on the previous block's peer so spans
        survive replication (see _select_block_replica)."""
        blocks = []
        preferred: Optional[PeerID] = None
        for index in range(start, stop):
            chosen = self._select_block_replica(
                self._resolve_info(index, force=force), preferred
            )
            blocks.append(
                RemoteExpert(chosen, self.p2p, request_compression=self.request_compression)
            )
            preferred = chosen.peer_id
        groups = []
        for block in blocks:
            if (
                groups
                and groups[-1][0].peer_id == block.peer_id
                and self._peer_supports_spans(groups[-1][0])
            ):
                groups[-1][1].append(block.uid)
            else:
                groups.append((block, [block.uid]))
        for head, uids in groups:
            head.span = uids if len(uids) > 1 else None
        return groups

    def _span_forward(self, start: int, stop: int, x):
        """Each attempt restarts from the ORIGINAL input: a mid-chain failure would
        otherwise retry the whole range on a partially-advanced activation, silently
        double-applying the blocks that already ran (corrupting the custom_vjp
        primal on exactly the failover path the retry exists for)."""
        attempt_counter = [0]

        def one_attempt():
            force = attempt_counter[0] > 0
            attempt_counter[0] += 1
            current = x
            for head, _uids in self._grouped_range(start, stop, force=force):
                current = head.forward_np(current)[0]
            return current

        def on_retry(retry_index: int, error: BaseException) -> None:
            logger.warning(f"span forward [{start}, {stop}) failed (attempt {retry_index + 1}): {error!r}")

        try:
            return self.retry_policy.execute_sync(one_attempt, on_retry=on_retry)
        except Exception as last_error:
            raise RuntimeError(f"span forward [{start}, {stop}) failed after retries") from last_error

    def _span_backward(self, start: int, stop: int, x, grad):
        """Chained backward over the range. With one co-located span the server does
        everything in a single RPC; across several servers the boundary activations
        are recovered with one forward sweep first (the client keeps no residuals).

        Every backward RPC steps the serving blocks' optimizers, so a retry must
        NEVER replay a group whose backward already succeeded — progress is tracked
        as a shrinking [start, remaining) range and only the remainder is retried
        (forward sweeps are side-effect-free and safe to re-run)."""
        state = {"remaining": stop, "grad": grad, "attempt": 0}

        def one_attempt():
            force = state["attempt"] > 0
            state["attempt"] += 1
            if state["remaining"] <= start:
                return state["grad"]
            groups = self._grouped_range(start, state["remaining"], force=force)
            boundary_inputs, current = [], x
            for head, _uids in groups:
                boundary_inputs.append(current)
                if head is not groups[-1][0]:
                    current = head.forward_np(current)[0]
            for (head, uids), block_input in zip(reversed(groups), reversed(boundary_inputs)):
                state["grad"] = head.backward_np(block_input, state["grad"])[0]
                state["remaining"] -= len(uids)  # this group's optimizers have stepped
            return state["grad"]

        def on_retry(retry_index: int, error: BaseException) -> None:
            logger.warning(
                f"span backward [{start}, {state['remaining']}) failed (attempt {retry_index + 1}): {error!r}"
            )

        try:
            return self.retry_policy.execute_sync(one_attempt, on_retry=on_retry)
        except Exception as last_error:
            raise RuntimeError(f"span backward [{start}, {stop}) failed after retries") from last_error

    def __call__(self, x: jax.Array, start: int = 0, stop: Optional[int] = None) -> jax.Array:
        """Run blocks [start, stop) in order; differentiable end to end. Co-located
        consecutive blocks execute as server-side spans (one RPC per SERVER, not per
        block — both directions), with re-resolution retries inside the callbacks."""
        import numpy as np

        import jax.numpy as jnp

        stop = stop if stop is not None else self.num_blocks
        if start >= stop:
            return x
        out_schemas = self._block(stop - 1).info["outputs_schema"]
        assert len(out_schemas) == 1, "RemoteSequential chains single-tensor blocks"
        # blocks preserve batch and sequence dims; only the FEATURE dim follows the
        # server's schema (whose leading dims reflect its sample batch, not ours)
        out_struct = jax.ShapeDtypeStruct((*x.shape[:-1], out_schemas[0].shape[-1]), jnp.float32)
        sequential = self

        @jax.custom_vjp
        def remote_span(x):
            return jax.pure_callback(
                lambda a: np.asarray(
                    sequential._span_forward(start, stop, np.asarray(a)), np.float32
                ),
                out_struct,
                x,
            )

        def fwd(x):
            return remote_span(x), x

        def bwd(residual_x, g):
            grad_struct = jax.ShapeDtypeStruct(residual_x.shape, jnp.float32)
            grad = jax.pure_callback(
                lambda a, gg: np.asarray(
                    sequential._span_backward(start, stop, np.asarray(a), np.asarray(gg)),
                    np.float32,
                ),
                grad_struct,
                residual_x,
                g,
            )
            return (grad.astype(residual_x.dtype),)

        remote_span.defvjp(fwd, bwd)
        return remote_span(x)

    def decode_step(self, x, session_id: str, reset: bool = False):
        """Chain one KV-cache decode-session step through every block: the prefill
        call (``reset=True``) seeds each block's session with the prompt chunk
        [batch, prompt_len, hid], later calls advance a single token
        [batch, 1, hid] — O(context) per token vs the O(context²) right-padded
        ``__call__`` decode. Sessions are STICKY to the peers resolved at prefill
        (the periodic DHT re-resolution must not silently move a session to a
        cache-less peer), but a dead pinned peer fails over TRANSPARENTLY
        (VERDICT r3 #3, Petals-class behavior): the client retains each session's
        full input history, re-resolves the route, re-prefills every group on the
        replacement peers from that history, and continues the stream — the caller
        never sees a reset, and emitted positions are identical to an
        uninterrupted run (the re-prefill is deterministic)."""
        import numpy as np

        x = np.asarray(x, np.float32)
        if reset:
            # pin the route with FRESH immutable handles: _ResilientBlock objects
            # are shared and re-pointed in place by the periodic re-resolution, so
            # pinning them would let the route silently move to a cache-less peer.
            # Consecutive blocks on the SAME peer form a span served by one RPC
            # (Petals-style span execution): per-token round-trips = #servers.
            route = self._grouped_range(0, self.num_blocks)
            with self._lock:
                # a reset REUSES the prior state's lock (atomically, under the
                # global lock): an in-flight step on the old state then finishes
                # before this reset's server-side prefill runs, so a failed old
                # step cannot fail over AFTER the reset and clobber the fresh
                # server sessions with the stale history
                prior = self._decode_routes.get(session_id)
                state = {
                    "route": route,
                    "chunks": [],
                    "positions": 0,
                    "lock": prior["lock"] if prior is not None else threading.Lock(),
                }
                self._decode_routes[session_id] = state
                while len(self._decode_routes) > self.max_decode_routes:
                    self._decode_routes.pop(next(iter(self._decode_routes)))  # oldest
        else:
            with self._lock:
                state = self._decode_routes.get(session_id)
            if state is None:
                raise RuntimeError(
                    f"decode session {session_id!r} has no pinned route here; "
                    f"start it with reset=True"
                )
        # the per-session lock serializes concurrent decode_steps on the SAME
        # session (advisor r4: an unguarded concurrent step could fail over with a
        # half-appended chunk list); different sessions still decode in parallel.
        # KV positions are inherently ordered, so serializing is the only sound
        # semantics for same-session concurrency anyway.
        with state["lock"]:
            # history retention: a LIST of chunks (concatenated only at failover, so a
            # long generation costs O(1) per step, not an O(context) recopy), capped by
            # max_failover_history — past the cap, retention stops and a dead peer is
            # a hard error again (restart with reset=True), bounding client memory
            step_appended = False
            if reset:
                if self.max_failover_history and x.shape[1] <= self.max_failover_history:
                    state["chunks"], state["positions"] = [x], x.shape[1]
                else:  # retention disabled (cap 0) or the prompt alone exceeds the cap
                    state["chunks"], state["positions"] = None, 0
            elif state["chunks"] is not None:
                if state["positions"] + x.shape[1] <= self.max_failover_history:
                    state["chunks"].append(x)
                    state["positions"] += x.shape[1]
                    step_appended = True
                else:
                    state["chunks"] = None  # over the cap: failover disabled for this session
            try:
                out = x
                groups_advanced = 0
                for block, span in state["route"]:
                    out = block.decode_np(out, session_id, reset=reset, span=span)
                    groups_advanced += 1
            except Exception as e:
                from hivemind_tpu.telemetry.serving import is_overload_error

                if is_overload_error(e) and groups_advanced == 0:
                    # a typed shed (fair-share admission / bounded queue) is NOT
                    # a dead peer: the server session is intact and re-prefilling
                    # would only spend more of the very budget that ran out.
                    # Undo this step's history append so the caller can back off
                    # and retry the same step cleanly, and surface the shed.
                    # ONLY valid when no group advanced — a shed deeper in the
                    # pipeline means upstream groups already appended this step
                    # to their KV sessions, and a clean retry would double-feed
                    # them (silent divergence); that case falls through to the
                    # full re-prefill failover below, which rebuilds every
                    # group's cache consistently (or fails loudly).
                    if step_appended:
                        state["chunks"].pop()
                        state["positions"] -= x.shape[1]
                    raise
                if state["chunks"] is None:
                    raise  # history over the retention cap (or disabled): no failover
                history = np.concatenate(state["chunks"], axis=1)
                logger.warning(
                    f"decode session {session_id!r} lost a pinned peer ({e!r}); "
                    f"failing over: re-resolving the route and re-prefilling from "
                    f"{history.shape[1]} retained positions"
                )
                try:
                    out = self._decode_failover(session_id, state, history)
                except Exception:
                    # a FAILED failover leaves surviving servers' caches re-prefilled to
                    # an unknown point and this chunk already in the history: the
                    # session is unusable — forget it so a caller retry gets the
                    # explicit "start with reset=True" error instead of silent
                    # divergence
                    with self._lock:
                        self._decode_routes.pop(session_id, None)
                    raise
                if not reset:
                    out = out[:, -x.shape[1]:]  # the caller expects this step's positions only
        return out

    def _decode_failover(self, session_id: str, state: dict, history) -> "np.ndarray":
        """Re-resolve the pipeline and re-prefill EVERY group from the retained
        input history (surviving groups simply rebuild identical caches; the
        replacement peer builds its first). Each group's full-history prefill
        output is the next group's input history, so one sweep both recovers the
        caches and computes the current step. Retries with forced re-resolution
        (a replacement server may take a moment to re-declare the uid)."""
        import numpy as np

        def one_attempt():
            route = self._grouped_range(0, self.num_blocks, force=True)
            out = history
            for block, span in route:
                out = block.decode_np(out, session_id, reset=True, span=span)
            state["route"] = route
            return np.asarray(out, np.float32)

        def on_retry(retry_index: int, error: BaseException) -> None:
            logger.warning(
                f"decode failover for {session_id!r} failed (attempt {retry_index + 1}): {error!r}"
            )

        try:
            return self.retry_policy.execute_sync(one_attempt, on_retry=on_retry)
        except Exception as last_error:
            raise RuntimeError(
                f"decode session {session_id!r} could not fail over after retries"
            ) from last_error

    def close_decode_session(self, session_id: str) -> None:
        """Forget a pinned decode route and its retained history (the server side
        expires by TTL/LRU)."""
        with self._lock:
            self._decode_routes.pop(session_id, None)

    def block_scorecards(self) -> Dict[str, dict]:
        """Per-block serving scorecards (ISSUE 9): this client's observed
        success rate / latency quantiles / timeouts / sheds for each pipeline
        block it has called — which block (and therefore which server) is
        degrading the pipeline, from the caller's side."""
        from hivemind_tpu.telemetry.serving import SCORECARDS

        cards = SCORECARDS.export()
        return {
            uid: cards[uid]
            for uid in (self.block_uid(index) for index in range(self.num_blocks))
            if uid in cards
        }

    def decode_capacity(self) -> Optional[int]:
        """The tightest ``decode_max_len`` across the pipeline's current servers
        (each advertises it via rpc_info), or None if a block lacks sessions."""
        capacities = [
            self._block(index).info.get("decode_max_len") for index in range(self.num_blocks)
        ]
        return None if any(c is None for c in capacities) else min(capacities)

    def __getitem__(self, index: int):
        """A callable handle to one block (e.g. for partial pipelines)."""
        if not (0 <= index < self.num_blocks):
            raise IndexError(index)
        return lambda x: self._call_block(index, x)

    def __repr__(self):
        return f"RemoteSequential({self.prefix!r}, {self.num_blocks} blocks)"
