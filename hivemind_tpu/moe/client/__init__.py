from hivemind_tpu.moe.client.beam_search import MoEBeamSearcher
from hivemind_tpu.moe.client.expert import RemoteExpert, RemoteExpertWorker
from hivemind_tpu.moe.client.moe import RemoteMixtureOfExperts, RemoteSwitchMixtureOfExperts
from hivemind_tpu.moe.client.remote_sequential import RemoteSequential
