"""Expert UID grid naming (capability parity: reference hivemind/moe/expert_uid.py:8-37).

Experts live on a named grid: ``prefix.i.j.k`` — each dot-separated integer indexes one
grid dimension. Beam search walks prefixes left to right."""

from __future__ import annotations

import re
from typing import NamedTuple, Optional, Tuple

from hivemind_tpu.p2p import PeerID

ExpertUID = str
ExpertPrefix = str

UID_DELIMITER = "."
FLAT_EXPERT = -1

# The client/server contract for retry safety on ambiguous connection loss:
# rpc_info is a pure read and rpc_forward is side-effect-free (inference only);
# rpc_backward (steps the expert optimizer) and rpc_decode (advances a KV-cache
# session) must fail loudly instead of risking a double-applied side effect.
# Single source of truth for ConnectionHandler._idempotent_rpcs AND the direct
# call sites in client/expert.py.
IDEMPOTENT_CONNECTION_RPCS = frozenset({"rpc_info", "rpc_forward"})
UID_PATTERN = re.compile(r"^(([^.])+)([.](?:[0]|([1-9]([0-9]*))))+$")
PREFIX_PATTERN = re.compile(r"^(([^.])+)([.](?:[0]|([1-9]([0-9]*))))*[.]$")


def is_valid_uid(maybe_uid: str) -> bool:
    return bool(UID_PATTERN.fullmatch(maybe_uid))


def is_valid_prefix(maybe_prefix: str) -> bool:
    return bool(PREFIX_PATTERN.fullmatch(maybe_prefix))


def split_uid(uid_or_prefix: str) -> Tuple[ExpertPrefix, int]:
    """'ffn.5.12' -> ('ffn.5.', 12)"""
    uid_or_prefix = uid_or_prefix.rstrip(UID_DELIMITER)
    pivot = uid_or_prefix.rindex(UID_DELIMITER) + 1
    return uid_or_prefix[:pivot], int(uid_or_prefix[pivot:])


class ReplicaInfo(NamedTuple):
    """One server hosting an expert: its peer id plus the wire dtype that
    server's declaration advertised (None = unknown, negotiate via rpc_info)."""

    peer_id: PeerID
    compression: Optional[str] = None


class ExpertInfo(NamedTuple):
    uid: ExpertUID
    peer_id: PeerID
    # the server's advertised wire dtype for activations ("float16", "none", …)
    # when its DHT declaration carried one; None = unknown (the client falls
    # back to the rpc_info negotiation on first use)
    compression: Optional[str] = None
    # the FULL replica set declared for this uid (ISSUE 13), primary included;
    # None/empty = single-replica record (peer_id is the only server). peer_id
    # above is the *selected* primary — clients load-balance across `replicas`
    # by scorecard latency with breaker-aware failover (moe/client/expert.py)
    replicas: Optional[Tuple[ReplicaInfo, ...]] = None

    @property
    def replica_set(self) -> Tuple[ReplicaInfo, ...]:
        """Every known replica (always non-empty; falls back to the primary)."""
        if self.replicas:
            return self.replicas
        return (ReplicaInfo(self.peer_id, self.compression),)
