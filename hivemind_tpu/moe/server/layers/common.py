"""Built-in expert blocks + registry (capability parity: reference
hivemind/moe/server/layers/common.py:18-31 'ffn', transformer encoder block, 'nop';
custom_experts.py:35 register_expert_class)."""

from __future__ import annotations

from typing import Callable, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

name_to_block: Dict[str, Callable] = {}
name_to_input: Dict[str, Callable] = {}


def register_expert_class(name: str, sample_input: Callable[[int, int], np.ndarray]):
    """Register a flax module factory under ``name``; ``sample_input(batch, hid)``
    builds a schema-defining dummy input."""

    def decorator(factory):
        assert name not in name_to_block, f"expert class {name!r} already registered"
        name_to_block[name] = factory
        name_to_input[name] = sample_input
        return factory

    return decorator


class FeedforwardExpert(nn.Module):
    """hid -> 4*hid -> hid feedforward with layernorm (the reference's benchmark
    'ffn' expert shape)."""

    hidden_dim: int

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden_dim * 4, dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        h = jax.nn.gelu(h)
        h = nn.Dense(self.hidden_dim, dtype=jnp.bfloat16, param_dtype=jnp.float32)(h)
        return nn.LayerNorm(dtype=jnp.bfloat16)(x + h).astype(jnp.float32)


class TransformerExpert(nn.Module):
    """One post-norm transformer encoder block operating on [batch, seq, hid]."""

    hidden_dim: int
    num_heads: int = 8

    @nn.compact
    def __call__(self, x):
        from hivemind_tpu.ops.pallas_attention import attention_auto

        batch, seq, hid = x.shape
        head_dim = hid // self.num_heads
        dense = lambda n, name: nn.Dense(n, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name)
        q = dense(hid, "query")(x).reshape(batch, seq, self.num_heads, head_dim)
        k = dense(hid, "key")(x).reshape(batch, seq, self.num_heads, head_dim)
        v = dense(hid, "value")(x).reshape(batch, seq, self.num_heads, head_dim)
        attn = dense(hid, "attention_out")(attention_auto(q, k, v).reshape(batch, seq, hid))
        x = nn.LayerNorm(dtype=jnp.bfloat16)(x + attn)
        h = dense(4 * hid, "ffn_up")(x)
        h = dense(hid, "ffn_down")(jax.nn.gelu(h))
        return nn.LayerNorm(dtype=jnp.bfloat16)(x + h).astype(jnp.float32)


def _decode_attention(q, k_new, v_new, cache_k, cache_v, index, groups: int = 1):
    """Shared KV-cache attention step for decoder blocks.

    Writes ``k_new``/``v_new`` into the caches at ``index`` (dynamic), then attends
    the chunk's queries over every cached position the session has produced so far.
    Valid for the two session shapes: prefill (``index == 0``, chunk length L,
    causal within the chunk) and incremental (chunk length 1, attends everything
    ≤ index). ``groups`` > 1 repeats the (grouped-query) KV heads to match q at
    attention time — caches stay in the compact kv_heads layout.
    Returns (context, cache_k, cache_v)."""
    from hivemind_tpu.parallel.ring_attention import plain_attention

    batch, new_len = q.shape[0], q.shape[1]
    max_len = cache_k.shape[1]
    cache_k = jax.lax.dynamic_update_slice(cache_k, k_new.astype(cache_k.dtype), (0, index, 0, 0))
    cache_v = jax.lax.dynamic_update_slice(cache_v, v_new.astype(cache_v.dtype), (0, index, 0, 0))
    expand = (lambda t: jnp.repeat(t, groups, axis=2)) if groups > 1 else (lambda t: t)
    if new_len == 1:
        mask = (jnp.arange(max_len) <= index)[None, :]  # [1, max_len] key-validity
        context = plain_attention(
            q, expand(cache_k), expand(cache_v),
            mask=jnp.broadcast_to(mask, (batch, max_len)),
        )
    else:
        # prefill chunk at the session start: plain causal attention over the chunk
        # is exact (the cache holds nothing before index 0)
        context = plain_attention(q, expand(k_new), expand(v_new), causal=True)
    return context, cache_k, cache_v


class CausalTransformerExpert(nn.Module):
    """One pre-norm DECODER block on [batch, seq, hid]: causal attention + gelu ffn.
    The building block for pipelined autoregressive models over the swarm
    (RemoteSequential): causality means right-padded prefixes are exact — real
    positions never attend to the padding after them — so clients can decode with
    a fixed schema sequence length and read the logits at the true last position.

    Decode sessions: calling with ``(cache_k, cache_v, index)`` runs one KV-cache
    step — O(seq) per token instead of the O(seq²) right-padded recompute — and
    returns ``(y, cache_k, cache_v)``; see ``moe/server/decode_session.py``."""

    hidden_dim: int
    num_heads: int = 8

    def init_decode_cache(self, batch: int, max_len: int):
        head_dim = self.hidden_dim // self.num_heads
        shape = (batch, max_len, self.num_heads, head_dim)
        return jnp.zeros(shape, jnp.bfloat16), jnp.zeros(shape, jnp.bfloat16)

    @nn.compact
    def __call__(self, x, cache_k=None, cache_v=None, index=None):
        from hivemind_tpu.ops.pallas_attention import attention_auto

        batch, seq, hid = x.shape
        head_dim = hid // self.num_heads
        dense = lambda n, name: nn.Dense(n, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name)
        normed = nn.LayerNorm(dtype=jnp.bfloat16, name="attention_norm")(x)
        q = dense(hid, "query")(normed).reshape(batch, seq, self.num_heads, head_dim)
        k = dense(hid, "key")(normed).reshape(batch, seq, self.num_heads, head_dim)
        v = dense(hid, "value")(normed).reshape(batch, seq, self.num_heads, head_dim)
        if cache_k is None:
            attn = attention_auto(q, k, v, causal=True).reshape(batch, seq, hid)
        else:
            context, cache_k, cache_v = _decode_attention(q, k, v, cache_k, cache_v, index)
            attn = context.reshape(batch, seq, hid)
        x = x + dense(hid, "attention_out")(attn)
        normed = nn.LayerNorm(dtype=jnp.bfloat16, name="ffn_norm")(x)
        h = dense(4 * hid, "ffn_up")(normed)
        y = (x + dense(hid, "ffn_down")(jax.nn.gelu(h))).astype(jnp.float32)
        return y if cache_k is None else (y, cache_k, cache_v)


def _rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jax.Array, theta: float = 10000.0, offset=0) -> jax.Array:
    """Rotary position embedding over [batch, seq, heads, head_dim] (head_dim even).
    ``offset`` (may be traced) shifts positions — decode sessions rotate the new
    token at its absolute position in the sequence."""
    seq, dim = x.shape[1], x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    positions = offset + jnp.arange(seq, dtype=jnp.float32)
    angles = positions[:, None] * freqs[None, :]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [seq, dim]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    return x * cos + _rotate_half(x) * sin


class LlamaBlockExpert(nn.Module):
    """One Llama-family decoder block on [batch, seq, hid]: pre-RMSNorm, rotary
    position embeddings, causal attention with optional grouped-query KV heads, and
    a SwiGLU MLP. This is the block shape Petals serves for Llama models (the
    BASELINE 'Petals-style Llama-7B block server' config): stack N of these under
    ``RemoteSequential`` and decoding is exact with right-padded fixed schemas, same
    as ``CausalTransformerExpert``. RoPE makes positions intrinsic to the block, so
    the client does not ship position ids."""

    hidden_dim: int
    num_heads: int = 8
    num_kv_heads: int = 0  # 0 = multi-head (Llama-7B); set lower for GQA (Llama-70B style)
    rope_theta: float = 10000.0
    ffn_inner: int = 0  # 0 = the 8/3 rule below; real checkpoints set intermediate_size
    rms_eps: float = 1e-6  # real checkpoints set rms_norm_eps (Llama-2: 1e-5)

    def init_decode_cache(self, batch: int, max_len: int):
        kv_heads = self.num_kv_heads or self.num_heads
        shape = (batch, max_len, kv_heads, self.hidden_dim // self.num_heads)
        return jnp.zeros(shape, jnp.bfloat16), jnp.zeros(shape, jnp.bfloat16)

    @nn.compact
    def __call__(self, x, cache_k=None, cache_v=None, index=None):
        from hivemind_tpu.ops.pallas_attention import attention_auto

        batch, seq, hid = x.shape
        heads = self.num_heads
        kv_heads = self.num_kv_heads or heads
        assert heads % kv_heads == 0, (heads, kv_heads)
        head_dim = hid // heads
        dense = lambda n, name: nn.Dense(
            n, use_bias=False, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name
        )
        normed = nn.RMSNorm(epsilon=self.rms_eps, dtype=jnp.bfloat16, name="attention_norm")(x)
        q = dense(heads * head_dim, "query")(normed).reshape(batch, seq, heads, head_dim)
        k = dense(kv_heads * head_dim, "key")(normed).reshape(batch, seq, kv_heads, head_dim)
        v = dense(kv_heads * head_dim, "value")(normed).reshape(batch, seq, kv_heads, head_dim)
        offset = 0 if cache_k is None else index  # decode: rotate at absolute position
        q = apply_rope(q, self.rope_theta, offset)
        k = apply_rope(k, self.rope_theta, offset)
        if cache_k is None:
            if kv_heads != heads:  # grouped-query: each KV head serves heads/kv_heads queries
                k = jnp.repeat(k, heads // kv_heads, axis=2)
                v = jnp.repeat(v, heads // kv_heads, axis=2)
            attn = attention_auto(q, k, v, causal=True).reshape(batch, seq, hid)
        else:
            context, cache_k, cache_v = _decode_attention(
                q, k, v, cache_k, cache_v, index, groups=heads // kv_heads
            )
            attn = context.reshape(batch, seq, hid)
        x = x + dense(hid, "attention_out")(attn)
        normed = nn.RMSNorm(epsilon=self.rms_eps, dtype=jnp.bfloat16, name="ffn_norm")(x)
        inner = self.ffn_inner or -(-8 * hid // 3 // 8) * 8  # 8/3*hid rounded up to 8
        gate = dense(inner, "ffn_gate")(normed)
        up = dense(inner, "ffn_up")(normed)
        y = (x + dense(hid, "ffn_down")(jax.nn.silu(gate) * up)).astype(jnp.float32)
        return y if cache_k is None else (y, cache_k, cache_v)


class NopExpert(nn.Module):
    """Identity with a dummy parameter (reference 'nop' expert for transport tests)."""

    hidden_dim: int

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, ())
        return x * scale


register_expert_class("ffn", lambda batch, hid: np.zeros((batch, hid), np.float32))(FeedforwardExpert)
register_expert_class("transformer", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(TransformerExpert)
register_expert_class("causal_transformer", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(CausalTransformerExpert)
register_expert_class("llama_block", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(LlamaBlockExpert)
register_expert_class("nop", lambda batch, hid: np.zeros((batch, hid), np.float32))(NopExpert)
