"""Built-in expert blocks + registry (capability parity: reference
hivemind/moe/server/layers/common.py:18-31 'ffn', transformer encoder block, 'nop';
custom_experts.py:35 register_expert_class)."""

from __future__ import annotations

from typing import Callable, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

name_to_block: Dict[str, Callable] = {}
name_to_input: Dict[str, Callable] = {}


def register_expert_class(name: str, sample_input: Callable[[int, int], np.ndarray]):
    """Register a flax module factory under ``name``; ``sample_input(batch, hid)``
    builds a schema-defining dummy input."""

    def decorator(factory):
        assert name not in name_to_block, f"expert class {name!r} already registered"
        name_to_block[name] = factory
        name_to_input[name] = sample_input
        return factory

    return decorator


class FeedforwardExpert(nn.Module):
    """hid -> 4*hid -> hid feedforward with layernorm (the reference's benchmark
    'ffn' expert shape)."""

    hidden_dim: int

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden_dim * 4, dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        h = jax.nn.gelu(h)
        h = nn.Dense(self.hidden_dim, dtype=jnp.bfloat16, param_dtype=jnp.float32)(h)
        return nn.LayerNorm(dtype=jnp.bfloat16)(x + h).astype(jnp.float32)


class TransformerExpert(nn.Module):
    """One post-norm transformer encoder block operating on [batch, seq, hid]."""

    hidden_dim: int
    num_heads: int = 8

    @nn.compact
    def __call__(self, x):
        from hivemind_tpu.ops.pallas_attention import attention_auto

        batch, seq, hid = x.shape
        head_dim = hid // self.num_heads
        dense = lambda n, name: nn.Dense(n, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name)
        q = dense(hid, "query")(x).reshape(batch, seq, self.num_heads, head_dim)
        k = dense(hid, "key")(x).reshape(batch, seq, self.num_heads, head_dim)
        v = dense(hid, "value")(x).reshape(batch, seq, self.num_heads, head_dim)
        attn = dense(hid, "attention_out")(attention_auto(q, k, v).reshape(batch, seq, hid))
        x = nn.LayerNorm(dtype=jnp.bfloat16)(x + attn)
        h = dense(4 * hid, "ffn_up")(x)
        h = dense(hid, "ffn_down")(jax.nn.gelu(h))
        return nn.LayerNorm(dtype=jnp.bfloat16)(x + h).astype(jnp.float32)


class CausalTransformerExpert(nn.Module):
    """One pre-norm DECODER block on [batch, seq, hid]: causal attention + gelu ffn.
    The building block for pipelined autoregressive models over the swarm
    (RemoteSequential): causality means right-padded prefixes are exact — real
    positions never attend to the padding after them — so clients can decode with
    a fixed schema sequence length and read the logits at the true last position."""

    hidden_dim: int
    num_heads: int = 8

    @nn.compact
    def __call__(self, x):
        from hivemind_tpu.ops.pallas_attention import attention_auto

        batch, seq, hid = x.shape
        head_dim = hid // self.num_heads
        dense = lambda n, name: nn.Dense(n, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name)
        normed = nn.LayerNorm(dtype=jnp.bfloat16, name="attention_norm")(x)
        q = dense(hid, "query")(normed).reshape(batch, seq, self.num_heads, head_dim)
        k = dense(hid, "key")(normed).reshape(batch, seq, self.num_heads, head_dim)
        v = dense(hid, "value")(normed).reshape(batch, seq, self.num_heads, head_dim)
        attn = attention_auto(q, k, v, causal=True).reshape(batch, seq, hid)
        x = x + dense(hid, "attention_out")(attn)
        normed = nn.LayerNorm(dtype=jnp.bfloat16, name="ffn_norm")(x)
        h = dense(4 * hid, "ffn_up")(normed)
        return (x + dense(hid, "ffn_down")(jax.nn.gelu(h))).astype(jnp.float32)


def _rotate_half(x: jax.Array) -> jax.Array:
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rope(x: jax.Array, theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding over [batch, seq, heads, head_dim] (head_dim even)."""
    seq, dim = x.shape[1], x.shape[-1]
    freqs = theta ** (-jnp.arange(0, dim, 2, dtype=jnp.float32) / dim)
    angles = jnp.arange(seq, dtype=jnp.float32)[:, None] * freqs[None, :]
    angles = jnp.concatenate([angles, angles], axis=-1)  # [seq, dim]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    return x * cos + _rotate_half(x) * sin


class LlamaBlockExpert(nn.Module):
    """One Llama-family decoder block on [batch, seq, hid]: pre-RMSNorm, rotary
    position embeddings, causal attention with optional grouped-query KV heads, and
    a SwiGLU MLP. This is the block shape Petals serves for Llama models (the
    BASELINE 'Petals-style Llama-7B block server' config): stack N of these under
    ``RemoteSequential`` and decoding is exact with right-padded fixed schemas, same
    as ``CausalTransformerExpert``. RoPE makes positions intrinsic to the block, so
    the client does not ship position ids."""

    hidden_dim: int
    num_heads: int = 8
    num_kv_heads: int = 0  # 0 = multi-head (Llama-7B); set lower for GQA (Llama-70B style)
    rope_theta: float = 10000.0

    @nn.compact
    def __call__(self, x):
        from hivemind_tpu.ops.pallas_attention import attention_auto

        batch, seq, hid = x.shape
        heads = self.num_heads
        kv_heads = self.num_kv_heads or heads
        assert heads % kv_heads == 0, (heads, kv_heads)
        head_dim = hid // heads
        dense = lambda n, name: nn.Dense(
            n, use_bias=False, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name
        )
        normed = nn.RMSNorm(dtype=jnp.bfloat16, name="attention_norm")(x)
        q = dense(heads * head_dim, "query")(normed).reshape(batch, seq, heads, head_dim)
        k = dense(kv_heads * head_dim, "key")(normed).reshape(batch, seq, kv_heads, head_dim)
        v = dense(kv_heads * head_dim, "value")(normed).reshape(batch, seq, kv_heads, head_dim)
        q, k = apply_rope(q, self.rope_theta), apply_rope(k, self.rope_theta)
        if kv_heads != heads:  # grouped-query: each KV head serves heads/kv_heads queries
            k = jnp.repeat(k, heads // kv_heads, axis=2)
            v = jnp.repeat(v, heads // kv_heads, axis=2)
        attn = attention_auto(q, k, v, causal=True).reshape(batch, seq, hid)
        x = x + dense(hid, "attention_out")(attn)
        normed = nn.RMSNorm(dtype=jnp.bfloat16, name="ffn_norm")(x)
        inner = -(-8 * hid // 3 // 8) * 8  # 8/3 * hid rounded up to a multiple of 8
        gate = dense(inner, "ffn_gate")(normed)
        up = dense(inner, "ffn_up")(normed)
        return (x + dense(hid, "ffn_down")(jax.nn.silu(gate) * up)).astype(jnp.float32)


class NopExpert(nn.Module):
    """Identity with a dummy parameter (reference 'nop' expert for transport tests)."""

    hidden_dim: int

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, ())
        return x * scale


register_expert_class("ffn", lambda batch, hid: np.zeros((batch, hid), np.float32))(FeedforwardExpert)
register_expert_class("transformer", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(TransformerExpert)
register_expert_class("causal_transformer", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(CausalTransformerExpert)
register_expert_class("llama_block", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(LlamaBlockExpert)
register_expert_class("nop", lambda batch, hid: np.zeros((batch, hid), np.float32))(NopExpert)
