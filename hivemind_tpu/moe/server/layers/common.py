"""Built-in expert blocks + registry (capability parity: reference
hivemind/moe/server/layers/common.py:18-31 'ffn', transformer encoder block, 'nop';
custom_experts.py:35 register_expert_class)."""

from __future__ import annotations

from typing import Callable, Dict

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

name_to_block: Dict[str, Callable] = {}
name_to_input: Dict[str, Callable] = {}


def register_expert_class(name: str, sample_input: Callable[[int, int], np.ndarray]):
    """Register a flax module factory under ``name``; ``sample_input(batch, hid)``
    builds a schema-defining dummy input."""

    def decorator(factory):
        assert name not in name_to_block, f"expert class {name!r} already registered"
        name_to_block[name] = factory
        name_to_input[name] = sample_input
        return factory

    return decorator


class FeedforwardExpert(nn.Module):
    """hid -> 4*hid -> hid feedforward with layernorm (the reference's benchmark
    'ffn' expert shape)."""

    hidden_dim: int

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden_dim * 4, dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        h = jax.nn.gelu(h)
        h = nn.Dense(self.hidden_dim, dtype=jnp.bfloat16, param_dtype=jnp.float32)(h)
        return nn.LayerNorm(dtype=jnp.bfloat16)(x + h).astype(jnp.float32)


class TransformerExpert(nn.Module):
    """One post-norm transformer encoder block operating on [batch, seq, hid]."""

    hidden_dim: int
    num_heads: int = 8

    @nn.compact
    def __call__(self, x):
        from hivemind_tpu.parallel.ring_attention import plain_attention

        batch, seq, hid = x.shape
        head_dim = hid // self.num_heads
        dense = lambda n, name: nn.Dense(n, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name)
        q = dense(hid, "query")(x).reshape(batch, seq, self.num_heads, head_dim)
        k = dense(hid, "key")(x).reshape(batch, seq, self.num_heads, head_dim)
        v = dense(hid, "value")(x).reshape(batch, seq, self.num_heads, head_dim)
        attn = dense(hid, "attention_out")(plain_attention(q, k, v).reshape(batch, seq, hid))
        x = nn.LayerNorm(dtype=jnp.bfloat16)(x + attn)
        h = dense(4 * hid, "ffn_up")(x)
        h = dense(hid, "ffn_down")(jax.nn.gelu(h))
        return nn.LayerNorm(dtype=jnp.bfloat16)(x + h).astype(jnp.float32)


class CausalTransformerExpert(nn.Module):
    """One pre-norm DECODER block on [batch, seq, hid]: causal attention + gelu ffn.
    The building block for pipelined autoregressive models over the swarm
    (RemoteSequential): causality means right-padded prefixes are exact — real
    positions never attend to the padding after them — so clients can decode with
    a fixed schema sequence length and read the logits at the true last position."""

    hidden_dim: int
    num_heads: int = 8

    @nn.compact
    def __call__(self, x):
        from hivemind_tpu.parallel.ring_attention import plain_attention

        batch, seq, hid = x.shape
        head_dim = hid // self.num_heads
        dense = lambda n, name: nn.Dense(n, dtype=jnp.bfloat16, param_dtype=jnp.float32, name=name)
        normed = nn.LayerNorm(dtype=jnp.bfloat16, name="attention_norm")(x)
        q = dense(hid, "query")(normed).reshape(batch, seq, self.num_heads, head_dim)
        k = dense(hid, "key")(normed).reshape(batch, seq, self.num_heads, head_dim)
        v = dense(hid, "value")(normed).reshape(batch, seq, self.num_heads, head_dim)
        attn = plain_attention(q, k, v, causal=True).reshape(batch, seq, hid)
        x = x + dense(hid, "attention_out")(attn)
        normed = nn.LayerNorm(dtype=jnp.bfloat16, name="ffn_norm")(x)
        h = dense(4 * hid, "ffn_up")(normed)
        return (x + dense(hid, "ffn_down")(jax.nn.gelu(h))).astype(jnp.float32)


class NopExpert(nn.Module):
    """Identity with a dummy parameter (reference 'nop' expert for transport tests)."""

    hidden_dim: int

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, ())
        return x * scale


register_expert_class("ffn", lambda batch, hid: np.zeros((batch, hid), np.float32))(FeedforwardExpert)
register_expert_class("transformer", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(TransformerExpert)
register_expert_class("causal_transformer", lambda batch, hid: np.zeros((batch, 64, hid), np.float32))(CausalTransformerExpert)
register_expert_class("nop", lambda batch, hid: np.zeros((batch, hid), np.float32))(NopExpert)
