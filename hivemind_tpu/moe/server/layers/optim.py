"""Expert-side optimizer helpers (capability parity: reference
hivemind/moe/server/layers/optim.py ClippingWrapper + layers/lr_schedule.py) —
expressed as optax combinators rather than a torch optimizer wrapper."""

from __future__ import annotations

import optax


def clipped(optimizer: optax.GradientTransformation, clip_norm: float = 1.0) -> optax.GradientTransformation:
    """Global-norm gradient clipping around any optax optimizer (the reference's
    ClippingWrapper role)."""
    return optax.chain(optax.clip_by_global_norm(clip_norm), optimizer)


def linear_warmup_schedule(peak_lr: float, warmup_steps: int, total_steps: int) -> optax.Schedule:
    """Linear warmup then linear decay (the reference's get_linear_schedule_with_warmup)."""
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, peak_lr, warmup_steps),
            optax.linear_schedule(peak_lr, 0.0, max(total_steps - warmup_steps, 1)),
        ],
        boundaries=[warmup_steps],
    )


def lamb_with_warmup(peak_lr: float, warmup_steps: int, total_steps: int, clip_norm: float = 1.0):
    """The ALBERT-recipe optimizer: LAMB + warmup schedule + clipping (the reference
    trains ALBERT with Lamb, examples/albert/run_trainer.py)."""
    return clipped(optax.lamb(linear_warmup_schedule(peak_lr, warmup_steps, total_steps)), clip_norm)
