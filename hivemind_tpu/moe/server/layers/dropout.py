"""Deterministic dropout expert (capability parity: reference
hivemind/moe/server/layers/dropout.py).

The dropout MASK travels as a SECOND input tensor, so forward and backward apply the
exact same mask on the server even though they are separate RPCs — RNG-local dropout
cannot guarantee that across the wire. A natural fit for the multi-tensor expert
schema (``ModuleBackend(sample_inputs=...)``): the jax vjp of ``x * mask / keep``
reproduces the reference's custom autograd Function for free."""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.moe.server.layers.common import register_expert_class


class DeterministicDropout(nn.Module):
    """Dropout whose mask is an explicit input (reference dropout.py:19-34)."""

    drop_prob: float

    @nn.compact
    def __call__(self, x, mask):
        keep_prob = 1.0 - self.drop_prob
        return x * mask / keep_prob


def dropout_sample_input(batch_size: int, hid_dim: int):
    mask = (np.random.rand(batch_size, hid_dim) > 0.2).astype(np.float32)
    return np.zeros((batch_size, hid_dim), np.float32), mask


@register_expert_class("det_dropout", dropout_sample_input)
class DeterministicDropoutExpert(nn.Module):
    """linear -> deterministic dropout -> relu -> linear (reference dropout.py:42-53)."""

    hidden_dim: int
    dropout_prob: float = 0.2

    @nn.compact
    def __call__(self, x, mask):
        x = DeterministicDropout(self.dropout_prob)(x, mask)
        h = nn.Dense(2 * self.hidden_dim, dtype=jnp.bfloat16, param_dtype=jnp.float32)(x)
        h = jax.nn.relu(h)
        return nn.Dense(self.hidden_dim, dtype=jnp.bfloat16, param_dtype=jnp.float32)(h).astype(
            jnp.float32
        )
