"""Expert layer registry (capability parity: reference hivemind/moe/server/layers/).

``@register_expert_class(name, sample_input_fn)`` registers a flax module factory; the
sample input (batch-size-agnostic) defines the expert's I/O schema."""

from hivemind_tpu.moe.server.layers.common import (
    CausalTransformerExpert,
    FeedforwardExpert,
    NopExpert,
    TransformerExpert,
    name_to_block,
    name_to_input,
    register_expert_class,
)
from hivemind_tpu.moe.server.layers.dropout import (
    DeterministicDropout,
    DeterministicDropoutExpert,
)
from hivemind_tpu.moe.server.layers.optim import (
    clipped,
    lamb_with_warmup,
    linear_warmup_schedule,
)
