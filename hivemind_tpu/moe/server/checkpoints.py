"""Periodic expert checkpoints to disk (capability parity: reference
hivemind/moe/server/checkpoints.py:36-75 — torch.save + symlinks; here flax
serialization bytes with the same {dir}/{uid}/checkpoint_last layout)."""

from __future__ import annotations

import asyncio
import contextlib
import os
import time
from pathlib import Path
from typing import Dict

from hivemind_tpu.moe.server.module_backend import ModuleBackend
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn

logger = get_logger(__name__)


def store_experts(backends: Dict[str, ModuleBackend], checkpoint_dir: Path) -> None:
    timestamp = time.strftime("%Y%m%d_%H%M%S")
    for uid, backend in backends.items():
        expert_dir = Path(checkpoint_dir) / uid
        expert_dir.mkdir(parents=True, exist_ok=True)
        blob = backend.state_dict()
        checkpoint = expert_dir / f"checkpoint_{timestamp}.flax"
        checkpoint.write_bytes(blob)
        last = expert_dir / "checkpoint_last.flax"
        tmp = expert_dir / ".checkpoint_last.tmp"
        with contextlib.suppress(OSError):
            tmp.unlink()
        tmp.symlink_to(checkpoint.name)
        os.replace(tmp, last)


def load_experts(backends: Dict[str, ModuleBackend], checkpoint_dir: Path) -> int:
    """Restore every backend that has a checkpoint_last; returns how many loaded."""
    loaded = 0
    for uid, backend in backends.items():
        last = Path(checkpoint_dir) / uid / "checkpoint_last.flax"
        if last.exists():
            try:
                backend.load_state_dict(last.read_bytes())
                loaded += 1
            except Exception as e:
                logger.warning(f"could not load checkpoint for {uid}: {e!r}")
    return loaded


class CheckpointSaver:
    """Background task storing all experts every ``update_period`` seconds."""

    def __init__(self, backends: Dict[str, ModuleBackend], checkpoint_dir: Path, update_period: float = 300.0):
        self.backends, self.checkpoint_dir, self.update_period = backends, Path(checkpoint_dir), update_period
        self._task = None

    def start(self) -> None:
        self._task = spawn(self._loop(), name="checkpoints.loop")

    async def _loop(self) -> None:
        from hivemind_tpu.utils.asyncio_utils import run_in_executor

        while True:
            await asyncio.sleep(self.update_period)
            with contextlib.suppress(Exception):
                await run_in_executor(store_experts, self.backends, self.checkpoint_dir)

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
