"""The device executor: drains task pools by priority and runs their jitted
processing functions (capability parity: reference hivemind/moe/server/runtime.py:22-199
— there a thread juggling fork pipes; here an asyncio task + executor thread so device
dispatch never blocks the event loop)."""

from __future__ import annotations

import asyncio
import contextlib
import time
from collections import defaultdict
from typing import Dict, List, Optional, Sequence

from hivemind_tpu.moe.server.task_pool import TaskPool
from hivemind_tpu.utils.asyncio_utils import run_in_executor
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class Runtime:
    def __init__(self, pools: Sequence[TaskPool], stats_report_interval: Optional[float] = 60.0):
        self.pools = list(pools)
        self.stats_report_interval = stats_report_interval
        self._task: Optional[asyncio.Task] = None
        self._stats: Dict[str, List[float]] = defaultdict(lambda: [0.0, 0.0, 0.0])  # batches, samples, seconds
        self._last_report = time.perf_counter()

    def start(self) -> None:
        self._task = asyncio.create_task(self._run())

    async def _run(self) -> None:
        while True:
            waiters = [asyncio.create_task(pool.wait_for_tasks()) for pool in self.pools]
            try:
                await asyncio.wait(waiters, return_when=asyncio.FIRST_COMPLETED)
            finally:
                for waiter in waiters:
                    waiter.cancel()
            pool = min(self.pools, key=lambda p: p.priority)
            if pool.priority == float("inf"):
                await asyncio.sleep(0.001)
                continue
            batch = pool.pop_batch()
            if not batch:
                continue
            start = time.perf_counter()
            try:
                await run_in_executor(pool.process_batch, batch)
            except Exception as e:
                logger.warning(f"pool {pool.name}: batch failed with {e!r}")
                pool.fail_batch(batch, e)
                continue
            elapsed = time.perf_counter() - start
            stats = self._stats[pool.name]
            stats[0] += 1
            stats[1] += sum(t.batch_size for t in batch)
            stats[2] += elapsed
            self._maybe_report_stats()

    def _maybe_report_stats(self) -> None:
        """StatsReporter parity (reference runtime.py:161-199): periodic per-pool
        batch size / throughput logging."""
        if self.stats_report_interval is None:
            return
        now = time.perf_counter()
        if now - self._last_report < self.stats_report_interval:
            return
        self._last_report = now
        for name, (batches, samples, seconds) in sorted(self._stats.items()):
            if batches:
                logger.info(
                    f"[{name}] {int(batches)} batches, avg size {samples / batches:.1f}, "
                    f"{samples / max(seconds, 1e-9):.0f} samples/s device time"
                )
        self._stats.clear()
        try:
            from hivemind_tpu.utils.profiling import device_memory_stats

            memory = device_memory_stats()
            if memory.get("bytes_in_use"):
                used, limit = memory["bytes_in_use"], memory.get("bytes_limit", 0)
                logger.info(
                    f"[device] HBM {used / 2**30:.2f} GiB in use"
                    + (f" / {limit / 2**30:.2f} GiB" if limit else "")
                )
        except Exception:
            pass  # CPU backends expose no memory stats

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
