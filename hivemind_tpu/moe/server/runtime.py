"""The device executor: drains task pools by priority and runs their jitted
processing functions (capability parity: reference hivemind/moe/server/runtime.py:22-199
— there a thread juggling fork pipes; here an asyncio task + executor thread so device
dispatch never blocks the event loop)."""

from __future__ import annotations

import asyncio
import time
from typing import Dict, Optional, Sequence, Tuple

from hivemind_tpu.moe.server.task_pool import TaskPool
from hivemind_tpu.utils.asyncio_utils import run_in_executor
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn

logger = get_logger(__name__)

# layer-5 telemetry (docs/observability.md): per-pool throughput, batch latency
# and drain-loop utilization — the registry replaces the old private per-Runtime
# _stats dict, so one scrape sees the same numbers the periodic log line reports
# (queue depth/age gauges live in task_pool.py, sampled on submit AND drain)
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY

_BATCHES = _TELEMETRY.counter(
    "hivemind_moe_batches_total", "batches processed by the runtime", ("pool",)
)
_SAMPLES = _TELEMETRY.counter(
    "hivemind_moe_samples_total", "samples processed by the runtime", ("pool",)
)
_BATCH_FAILURES = _TELEMETRY.counter(
    "hivemind_moe_batch_failures_total", "batches whose processing function raised", ("pool",)
)
_BATCH_LATENCY = _TELEMETRY.histogram(
    "hivemind_moe_batch_latency_seconds", "device time of one batch", ("pool",)
)
_UTILIZATION = _TELEMETRY.gauge(
    "hivemind_moe_runtime_utilization",
    "fraction of the drain loop's recent wall time spent processing batches "
    "(1.0 = the device executor never idles; sampled over ~5 s windows)",
)


class Runtime:
    def __init__(self, pools: Sequence[TaskPool], stats_report_interval: Optional[float] = 60.0):
        self.pools = list(pools)
        self.stats_report_interval = stats_report_interval
        self._task: Optional[asyncio.Task] = None
        # set by add_pool so the drain loop's wait wakes for pools registered
        # mid-wait (ISSUE 13 replication) without any polling timeout
        self._pools_changed = asyncio.Event()
        self._last_report = time.perf_counter()
        # drain-loop utilization (ISSUE 9): busy seconds over a rolling window —
        # 1.0 with growing queues means the device executor is the bottleneck;
        # low utilization with deep queues points at dispatch, not compute
        self._utilization_window = 5.0
        self._busy_s = 0.0
        self._busy_anchor = time.perf_counter()
        # cached metric children: pool names are stable for the Runtime's lifetime
        self._children = {
            pool.name: (
                _BATCHES.labels(pool.name),
                _SAMPLES.labels(pool.name),
                _BATCH_LATENCY.labels(pool.name),
            )
            for pool in self.pools
        }
        # cumulative (batches, samples, seconds) at the last report, per pool —
        # the registry holds process-lifetime totals; the log line shows deltas.
        # Seeded from the CURRENT totals: the counters are process-global, so a
        # second Runtime reusing a pool name must not replay its predecessor's
        # work as one giant first interval.
        self._reported: Dict[str, Tuple[float, float, float]] = {
            name: (batches.value, samples.value, latency.sum)
            for name, (batches, samples, latency) in self._children.items()
        }

    def start(self) -> None:
        self._task = spawn(self._run(), name="runtime.run")

    def add_pool(self, pool: TaskPool) -> None:
        """Register a pool created after start() (ISSUE 13 expert replication:
        a server acquires a hot expert at runtime). Runs on the runtime's own
        loop; `_pools_changed` wakes the drain wait so the new pool is picked
        up immediately."""
        if pool in self.pools:
            return
        self.pools.append(pool)
        self._pools_changed.set()
        children = (
            _BATCHES.labels(pool.name),
            _SAMPLES.labels(pool.name),
            _BATCH_LATENCY.labels(pool.name),
        )
        self._children[pool.name] = children
        self._reported.setdefault(
            pool.name, (children[0].value, children[1].value, children[2].sum)
        )

    async def _run(self) -> None:
        while True:
            if not self.pools:
                # a replica-slot server starts empty and gains pools at runtime
                self._pools_changed.clear()  # lint: single-writer — loop clears its own wake event
                await self._pools_changed.wait()
                continue
            self._pools_changed.clear()
            waiters = [asyncio.create_task(pool.wait_for_tasks()) for pool in self.pools]
            # a pool added mid-wait (add_pool) has no waiter in this set — its
            # event wakes the wait so the next iteration picks the new pool up
            # immediately, with no polling timeout on idle servers
            waiters.append(asyncio.create_task(self._pools_changed.wait()))
            try:
                await asyncio.wait(waiters, return_when=asyncio.FIRST_COMPLETED)
            finally:
                for waiter in waiters:
                    waiter.cancel()
            pool = min(self.pools, key=lambda p: p.priority)
            if pool.priority == float("inf"):
                self._account_busy(0.0)  # idle windows drive the gauge to 0
                await asyncio.sleep(0.001)
                continue
            batch = pool.pop_batch()
            batches_c, samples_c, latency_h = self._children[pool.name]
            if not batch:
                continue
            start = time.perf_counter()
            try:
                await run_in_executor(pool.process_batch, batch)
            except Exception as e:
                logger.warning(f"pool {pool.name}: batch failed with {e!r}")
                _BATCH_FAILURES.inc(pool=pool.name)
                pool.fail_batch(batch, e)
                self._account_busy(time.perf_counter() - start)
                continue
            elapsed = time.perf_counter() - start
            batches_c.inc()
            samples_c.inc(sum(t.batch_size for t in batch))
            latency_h.observe(elapsed)
            self._account_busy(elapsed)
            self._maybe_report_stats()

    def _account_busy(self, elapsed: float) -> None:
        """Utilization gauge: busy seconds / wall seconds over ~5 s windows."""
        self._busy_s += elapsed
        now = time.perf_counter()
        window = now - self._busy_anchor
        if window >= self._utilization_window:
            _UTILIZATION.set(round(min(self._busy_s / window, 1.0), 4))
            self._busy_s = 0.0
            self._busy_anchor = now

    def _maybe_report_stats(self) -> None:
        """StatsReporter parity (reference runtime.py:161-199): periodic per-pool
        batch size / throughput logging, computed as deltas over the registry's
        cumulative counters."""
        if self.stats_report_interval is None:
            return
        now = time.perf_counter()
        if now - self._last_report < self.stats_report_interval:
            return
        self._last_report = now
        for name in sorted(self._children):
            batches_c, samples_c, latency_h = self._children[name]
            totals = (batches_c.value, samples_c.value, latency_h.sum)
            last = self._reported.get(name, (0.0, 0.0, 0.0))
            batches, samples, seconds = (t - l for t, l in zip(totals, last))
            self._reported[name] = totals
            if batches:
                logger.info(
                    f"[{name}] {int(batches)} batches, avg size {samples / batches:.1f}, "
                    f"{samples / max(seconds, 1e-9):.0f} samples/s device time"
                )
        try:
            from hivemind_tpu.utils.profiling import device_memory_stats

            memory = device_memory_stats()
            if memory.get("bytes_in_use"):
                used, limit = memory["bytes_in_use"], memory.get("bytes_limit", 0)
                logger.info(
                    f"[device] HBM {used / 2**30:.2f} GiB in use"
                    + (f" / {limit / 2**30:.2f} GiB" if limit else "")
                )
        except Exception:
            pass  # CPU backends expose no memory stats

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()
