"""Server-side KV-cache decode sessions for pipelined autoregressive inference.

Petals serves Llama blocks with per-client attention caches so each generated token
costs O(context) instead of the O(context²) right-padded recompute that
`RemoteSequential.__call__` implies. This is the session layer for the same
capability on the TPU stack: a client opens a session per block uid (a msgpack
`{"session_id", "reset"}` rides `ExpertRequest.metadata` — no proto change), the
first call prefills the prompt into fresh caches, and every later call advances one
token. Caches live on-device in the block's compact kv-heads layout
(`init_decode_cache` on the block class), the step function is jitted once per
(uid, batch, chunk-length) signature, and sessions expire by TTL / LRU cap so an
abandoned client cannot pin device memory.

No reference equivalent (the reference serves stateless experts; Petals is its
downstream project — README.md:35-40). Fault note: decode sessions are sticky to
the serving peer — if it dies, the client must re-prefill on a replacement
(`RemoteSequential.decode_step` raises rather than silently resuming with an empty
cache)."""

from __future__ import annotations

import threading
import time
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


def _next_pow2(n: int) -> int:
    power = 1
    while power < n:
        power *= 2
    return power


class _Session:
    __slots__ = ("cache_k", "cache_v", "index", "last_used", "lock")

    def __init__(self, cache_k, cache_v):
        self.cache_k, self.cache_v = cache_k, cache_v
        self.index = 0
        self.last_used = time.monotonic()
        self.lock = threading.Lock()


class DecodeSessionManager:
    """Per-(uid, session_id) KV caches + jitted decode steps for one server.

    :param max_len: cache capacity per session (prompt + generated tokens)
    :param session_ttl: seconds of inactivity before a session is evicted
    :param max_sessions: LRU cap across all uids
    """

    def __init__(self, backends, max_len: int = 256, session_ttl: float = 600.0,
                 max_sessions: int = 64):
        self.backends = backends
        self.max_len, self.session_ttl, self.max_sessions = max_len, session_ttl, max_sessions
        self._sessions: Dict[Tuple[str, str], _Session] = {}
        self._step_fns: Dict[Tuple[str, int, int], callable] = {}
        self._lock = threading.Lock()

    def supports(self, uid: str) -> bool:
        backend = self.backends.get(uid)
        return backend is not None and hasattr(backend.module, "init_decode_cache")

    def _evict_locked(self) -> None:
        now = time.monotonic()
        expired = [k for k, s in self._sessions.items() if now - s.last_used > self.session_ttl]
        for key in expired:
            del self._sessions[key]
        while len(self._sessions) > self.max_sessions:
            oldest = min(self._sessions, key=lambda k: self._sessions[k].last_used)
            del self._sessions[oldest]

    def _step_fn(self, uid: str, batch: int, new_len: int):
        key = (uid, batch, new_len)
        fn = self._step_fns.get(key)
        if fn is None:
            module = self.backends[uid].module

            def step(params, x, cache_k, cache_v, index):
                return module.apply({"params": params}, x, cache_k, cache_v, index)

            fn = self._step_fns[key] = jax.jit(step, donate_argnums=(2, 3))
        return fn

    def decode(self, uid: str, session_id: str, x: np.ndarray, reset: bool) -> np.ndarray:
        """One session step: prefill (``reset=True``, chunk = the prompt) or advance
        one token in an existing session. Returns the block output for the chunk.
        Raises ``KeyError`` for a continuation on an unknown/evicted session."""
        backend = self.backends.get(uid)
        if backend is None or not self.supports(uid):
            raise KeyError(f"expert {uid!r} does not support decode sessions")
        x = np.asarray(x, np.float32)
        assert x.ndim == 3, f"decode input must be [batch, chunk, hid], got {x.shape}"
        batch, new_len = x.shape[0], x.shape[1]
        if new_len > self.max_len:
            raise ValueError(f"chunk of {new_len} exceeds session max_len={self.max_len}")

        key = (uid, session_id)
        with self._lock:
            self._evict_locked()
            session = self._sessions.get(key)
            if reset:
                cache_k, cache_v = backend.module.init_decode_cache(batch, self.max_len)
                session = self._sessions[key] = _Session(cache_k, cache_v)
            elif session is None:
                # NEVER silently prefill a continuation: an evicted/expired/unknown
                # session would return semantically-garbage activations. The client
                # must restart generation with reset=True.
                raise KeyError(
                    f"unknown or expired decode session {session_id!r} for {uid!r}; "
                    f"restart generation with reset=True"
                )
            session.last_used = time.monotonic()

        with session.lock:
            if session.index == 0:
                pass  # prefill: any chunk length (causal within the chunk)
            elif new_len != 1:
                raise ValueError(
                    f"session {session_id!r} already holds {session.index} positions; "
                    f"only 1-token steps may follow the prefill (got chunk {new_len})"
                )
            if session.index + new_len > self.max_len:
                raise ValueError(
                    f"session {session_id!r} is full ({session.index}/{self.max_len})"
                )
            if session.cache_k.shape[0] != batch:
                raise ValueError(
                    f"session {session_id!r} batch is {session.cache_k.shape[0]}, got {batch}"
                )
            # bucket prefill lengths to powers of two so the jit cache stays at
            # O(log max_len) entries per (uid, batch) instead of one compile per
            # distinct prompt length. Padded tail slots of the cache are invisible
            # (the continuation mask stops at `index`) and are overwritten in place
            # by subsequent single-token steps; padded prefill OUTPUTS are sliced
            # off, and causal attention keeps real prefill positions exact.
            padded_len = new_len if new_len == 1 else min(_next_pow2(new_len), self.max_len)
            if padded_len != new_len:
                x = np.pad(x, ((0, 0), (0, padded_len - new_len), (0, 0)))
            step = self._step_fn(uid, batch, padded_len)
            y, session.cache_k, session.cache_v = step(
                backend.snapshot_params(), jnp.asarray(x), session.cache_k,
                session.cache_v, jnp.int32(session.index),
            )
            session.index += new_len
            return np.asarray(y)[:, :new_len]
