"""Server-side KV-cache decode sessions for pipelined autoregressive inference.

Petals serves Llama blocks with per-client attention caches so each generated token
costs O(context) instead of the O(context²) right-padded recompute that
`RemoteSequential.__call__` implies. This is the session layer for the same
capability on the TPU stack: a client opens a session per block uid (a msgpack
`{"session_id", "reset"}` rides `ExpertRequest.metadata` — no proto change), the
first call prefills the prompt into fresh caches, and every later call advances one
token. Caches live on-device in the block's compact kv-heads layout
(`init_decode_cache` on the block class), the step function is jitted once per
(uid, batch, chunk-length) signature, and sessions expire by TTL / LRU cap so an
abandoned client cannot pin device memory.

**Continuous batching** (`decode_async`): single-token steps from different
clients' sessions that arrive within a small window are merged into ONE device
call — the per-session step is `jax.vmap`-ed over a stacked session axis (params
broadcast; each row carries its own cache and per-row write index), with the
session count bucketed to powers of two so the jit cache stays small. One
dispatch serves every concurrent stream, which is what keeps a serving chip busy
when many clients decode one token at a time. Disable with
``HIVEMIND_TPU_DECODE_BATCHING=0`` for A/B runs.

No reference equivalent (the reference serves stateless experts; Petals is its
downstream project — README.md:35-40). Fault note: decode sessions are sticky to
the serving peer, and since r4 a dead peer fails over TRANSPARENTLY — the client
retains the session's input history and re-prefills a replacement
(`RemoteSequential.decode_step`; past the retention cap it degrades to raising,
and the caller restarts with ``reset=True``)."""

from __future__ import annotations

import asyncio
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.device import record_transfer
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.profiling import tracked_jit

logger = get_logger(__name__)

# KV-cache session saturation (ISSUE 9, docs/observability.md "Serving"): the
# session table is the serving peer's scarcest resource (each session pins
# device cache memory) and previously had zero visibility
_SESSIONS = _TELEMETRY.gauge(
    "hivemind_moe_decode_sessions", "live KV-cache decode sessions on this server"
)
_SESSION_OCCUPANCY = _TELEMETRY.gauge(
    "hivemind_moe_decode_session_occupancy",
    "live decode sessions / max_sessions (1.0 = the LRU cap is about to evict)",
)
_EVICTIONS = _TELEMETRY.counter(
    "hivemind_moe_decode_session_evictions_total",
    "decode sessions evicted, by reason (ttl = idle expiry, cap = LRU over max_sessions)",
    ("reason",),
)
_RESETS = _TELEMETRY.counter(
    "hivemind_moe_decode_session_resets_total",
    "decode sessions created or re-prefilled via reset=True",
)
_STEPS = _TELEMETRY.counter(
    "hivemind_moe_decode_steps_total",
    "decode session steps served, by path (direct = per-session call, "
    "batched = merged into a vmapped continuous batch)",
    ("path",),
)


def _next_pow2(n: int) -> int:
    power = 1
    while power < n:
        power *= 2
    return power


class _Session:
    __slots__ = ("cache_k", "cache_v", "index", "last_used", "lock")

    def __init__(self, cache_k, cache_v):
        self.cache_k, self.cache_v = cache_k, cache_v
        self.index = 0
        self.last_used = time.monotonic()
        self.lock = threading.Lock()


class DecodeSessionManager:
    """Per-(uid, session_id) KV caches + jitted decode steps for one server.

    :param max_len: cache capacity per session (prompt + generated tokens)
    :param session_ttl: seconds of inactivity before a session is evicted
    :param max_sessions: LRU cap across all uids
    """

    def __init__(self, backends, max_len: int = 256, session_ttl: float = 600.0,
                 max_sessions: int = 64, flush_window: float = 0.002,
                 merge_recency_s: Optional[float] = None):
        self.backends = backends
        self.max_len, self.session_ttl, self.max_sessions = max_len, session_ttl, max_sessions
        self.flush_window = flush_window  # how long a drainer waits for stragglers
        if merge_recency_s is None:
            merge_recency_s = float(os.environ.get("HIVEMIND_TPU_MERGE_RECENCY_S", "0.25"))
        self.merge_recency_s = merge_recency_s
        self._sessions: Dict[Tuple[str, str], _Session] = {}
        self._step_fns: Dict[Tuple[str, int, int], callable] = {}
        self._batched_fns: Dict[Tuple[str, int], callable] = {}
        self._dummy_caches: Dict[str, tuple] = {}  # per-uid padding rows for pow2 buckets
        self._lock = threading.Lock()
        self._pending: Dict[str, List] = {}  # uid -> [(future, session, x), ...]
        self._in_flight: Dict[int, int] = {}  # id(session) -> refcount, during _decode_batch
        self._drainers: Dict[str, asyncio.Task] = {}
        self.batching_enabled = os.environ.get("HIVEMIND_TPU_DECODE_BATCHING", "1") != "0"

    def supports(self, uid: str) -> bool:
        backend = self.backends.get(uid)
        return backend is not None and hasattr(backend.module, "init_decode_cache")

    def _evict_locked(self) -> None:
        now = time.monotonic()
        # sessions with an enqueued-but-unresolved batched step are pinned: evicting
        # one mid-flight would orphan its cache object — the step would "succeed"
        # against the orphan and the client's next continuation would KeyError.
        # _in_flight covers the window after _drain pops entries out of _pending but
        # before _decode_batch finishes (the device call itself).
        pinned = {
            id(session)
            for entries in self._pending.values()
            for (_future, session, _x) in entries
        } | set(self._in_flight)
        expired = [
            k for k, s in self._sessions.items()
            if now - s.last_used > self.session_ttl and id(s) not in pinned
        ]
        for key in expired:
            del self._sessions[key]
        if expired:
            _EVICTIONS.inc(len(expired), reason="ttl")
        evictable = [k for k in self._sessions if id(self._sessions[k]) not in pinned]
        while len(self._sessions) > self.max_sessions and evictable:
            oldest = min(evictable, key=lambda k: self._sessions[k].last_used)
            evictable.remove(oldest)
            del self._sessions[oldest]
            _EVICTIONS.inc(reason="cap")
        self._sample_gauges_locked()

    def _sample_gauges_locked(self) -> None:
        _SESSIONS.set(len(self._sessions))
        _SESSION_OCCUPANCY.set(round(len(self._sessions) / max(self.max_sessions, 1), 4))

    def _raw_step(self, uid: str):
        """The un-jitted per-session step; shared by the direct and batched paths so
        a signature change cannot silently diverge them."""
        module = self.backends[uid].module

        def step(params, x, cache_k, cache_v, index):
            from hivemind_tpu.ops.quantized_params import dequantize_tree

            # int8 weight-only backends: materialize dense weights inside the jit
            # (identity for plain fp32 trees)
            return module.apply({"params": dequantize_tree(params)}, x, cache_k, cache_v, index)

        return step

    def _step_fn(self, uid: str, batch: int, new_len: int):
        key = (uid, batch, new_len)
        fn = self._step_fns.get(key)
        if fn is None:
            # tracked_jit (ISSUE 19): every compile lands on the compile tracker
            # under one site — a client cycling prompt lengths past the pow2
            # buckets shows up as a recompile storm, not silent latency
            fn = self._step_fns[key] = tracked_jit(
                self._raw_step(uid), site="decode_session.step", donate_argnums=(2, 3)
            )
        return fn

    def decode(self, uid: str, session_id: str, x: np.ndarray, reset: bool) -> np.ndarray:
        """One session step: prefill (``reset=True``, chunk = the prompt) or advance
        one token in an existing session. Returns the block output for the chunk.
        Raises ``KeyError`` for a continuation on an unknown/evicted session."""
        backend = self.backends.get(uid)
        if backend is None or not self.supports(uid):
            raise KeyError(f"expert {uid!r} does not support decode sessions")
        x = np.asarray(x, np.float32)
        assert x.ndim == 3, f"decode input must be [batch, chunk, hid], got {x.shape}"
        batch, new_len = x.shape[0], x.shape[1]
        if new_len > self.max_len:
            raise ValueError(f"chunk of {new_len} exceeds session max_len={self.max_len}")

        key = (uid, session_id)
        with self._lock:
            self._evict_locked()
            session = self._sessions.get(key)
            if reset:
                cache_k, cache_v = backend.module.init_decode_cache(batch, self.max_len)
                if hasattr(backend, "shard_decode_cache"):
                    # mesh-sharded serving: the session's KV lives distributed
                    # over the backend's mesh (MeshModuleBackend), so a cache
                    # that exceeds one chip's HBM still fits the slice
                    cache_k, cache_v = backend.shard_decode_cache(cache_k, cache_v)
                session = self._sessions[key] = _Session(cache_k, cache_v)
                _RESETS.inc()
                self._sample_gauges_locked()
            elif session is None:
                # NEVER silently prefill a continuation: an evicted/expired/unknown
                # session would return semantically-garbage activations. The client
                # must restart generation with reset=True.
                raise KeyError(
                    f"unknown or expired decode session {session_id!r} for {uid!r}; "
                    f"restart generation with reset=True"
                )
            session.last_used = time.monotonic()

        with session.lock:
            if session.index == 0:
                pass  # prefill: any chunk length (causal within the chunk)
            elif new_len != 1:
                raise ValueError(
                    f"session {session_id!r} already holds {session.index} positions; "
                    f"only 1-token steps may follow the prefill (got chunk {new_len})"
                )
            if session.index + new_len > self.max_len:
                raise ValueError(
                    f"session {session_id!r} is full ({session.index}/{self.max_len})"
                )
            if session.cache_k.shape[0] != batch:
                raise ValueError(
                    f"session {session_id!r} batch is {session.cache_k.shape[0]}, got {batch}"
                )
            # bucket prefill lengths to powers of two so the jit cache stays at
            # O(log max_len) entries per (uid, batch) instead of one compile per
            # distinct prompt length. Padded tail slots of the cache are invisible
            # (the continuation mask stops at `index`) and are overwritten in place
            # by subsequent single-token steps; padded prefill OUTPUTS are sliced
            # off, and causal attention keeps real prefill positions exact.
            padded_len = new_len if new_len == 1 else min(_next_pow2(new_len), self.max_len)
            if padded_len != new_len:
                x = np.pad(x, ((0, 0), (0, padded_len - new_len), (0, 0)))
            step = self._step_fn(uid, batch, padded_len)
            record_transfer(x.nbytes, "host_to_device")
            y, session.cache_k, session.cache_v = step(
                backend.snapshot_params(), jnp.asarray(x), session.cache_k,
                session.cache_v, jnp.int32(session.index),
            )
            session.index += new_len
            # re-stamp AFTER the device step: a step that hits a jit compile can
            # outlast merge_recency_s, and a session stamped only at entry would
            # look stale to _concurrent_sessions the instant its own prefill
            # returns — so two freshly-prefilled streams never engage batching.
            # Bare float store; concurrent readers just see one of two recent stamps.
            session.last_used = time.monotonic()
            _STEPS.inc(path="direct")
            out = np.asarray(y)[:, :new_len]
            record_transfer(out.nbytes, "device_to_host")
            return out

    # ---- continuous batching of single-token steps across sessions ------------

    async def decode_async(self, uid: str, session_id: str, x: np.ndarray, reset: bool):
        """Asyncio entrypoint: batchable steps (continuation, chunk 1, session
        batch 1) are merged with other clients' concurrent steps into one vmapped
        device call; everything else takes the direct per-session path."""
        loop = asyncio.get_running_loop()
        x = np.asarray(x, np.float32)
        batchable = (
            self.batching_enabled and not reset
            and x.ndim == 3 and x.shape[0] == 1 and x.shape[1] == 1
        )
        if not batchable:
            return await loop.run_in_executor(None, self.decode, uid, session_id, x, reset)
        with self._lock:
            concurrent = self._concurrent_sessions(uid)
        if not concurrent:
            # single actively-decoding stream: the drainer/future/flush-window
            # machinery has nothing to merge and costs ~ms per token — take the
            # direct per-session path (same jitted step; same-session ordering
            # is still serialized by the session lock). ISSUE 10.
            return await loop.run_in_executor(None, self.decode, uid, session_id, x, reset)

        future = loop.create_future()
        with self._lock:
            # lookup + enqueue under ONE lock hold: releasing in between would let
            # _evict_locked delete the session while this step is pending, so the
            # step would update an orphaned cache and the next continuation KeyErrors
            self._evict_locked()  # the direct path evicts in decode(); mirror it here
            session = self._sessions.get((uid, session_id))
            if session is None:
                raise KeyError(
                    f"unknown or expired decode session {session_id!r} for {uid!r}; "
                    f"restart generation with reset=True"
                )
            session.last_used = time.monotonic()
            self._pending.setdefault(uid, []).append((future, session, x))
            if uid not in self._drainers or self._drainers[uid].done():
                self._drainers[uid] = spawn(self._drain(uid), name="decode_session.drain")
        return await future

    # NOTE on merge_recency_s (set in __init__; HIVEMIND_TPU_MERGE_RECENCY_S):
    # another session counts as a merge candidate only if it stepped within
    # this window — an actively decoding stream touches its session every
    # token (tens of ms on one serving hop), while an abandoned session would
    # otherwise tax every single-stream token with the full flush window until
    # TTL eviction. Tradeoff: in a DEEP pipeline each server sees a session
    # once per pipeline round, so with few concurrent streams and a round time
    # past this window, steps route direct and never merge — raise the env var
    # there (a rising `path="direct"` share of hivemind_moe_decode_steps_total
    # under concurrent load is the telltale).

    def _concurrent_sessions(self, uid: str) -> bool:
        """True when MORE THAN ONE recently-active session exists on this uid
        (so waiting the flush window could actually merge steps). Called under
        self._lock; the caller's own session is always recent."""
        now = time.monotonic()
        count = 0
        for key, session in self._sessions.items():
            if key[0] == uid and now - session.last_used < self.merge_recency_s:
                count += 1
                if count > 1:
                    return True
        return False

    async def _drain(self, uid: str) -> None:
        loop = asyncio.get_running_loop()
        try:
            # the flush window exists to merge OTHER clients' concurrent steps;
            # with a single actively-decoding session per uid it is pure
            # per-token latency (2 ms/step measured) — skip straight to the
            # drain (ISSUE 10)
            with self._lock:
                window = self.flush_window if self._concurrent_sessions(uid) else 0.0
            if window:
                await asyncio.sleep(window)  # let concurrent streams pile up
            else:
                await asyncio.sleep(0)  # one loop tick: same-tick submitters still merge
        except asyncio.CancelledError:
            # cancelled before the entries were even popped (server stop during the
            # flush window): no pins were taken yet, but the pending futures would
            # strand forever — cancel them so callers unblock
            with self._lock:
                stranded = self._pending.pop(uid, [])
            for future, _session, _x in stranded:
                if not future.done():
                    future.cancel()
            raise
        with self._lock:
            entries = self._pending.pop(uid, [])
            for _future, session, _x in entries:
                # keep the eviction pin through the device call: the entries leave
                # _pending now but their caches are updated until the batch resolves
                self._in_flight[id(session)] = self._in_flight.get(id(session), 0) + 1
        if not entries:
            return
        # one session must not appear twice in a batch (its cache would fork):
        # later duplicates roll over to the next drain round
        seen, batch_entries, rollover = set(), [], []
        for entry in entries:
            if id(entry[1]) in seen:
                rollover.append(entry)
            else:
                seen.add(id(entry[1]))
                batch_entries.append(entry)
        try:
            error = None
            try:
                results = await loop.run_in_executor(None, self._decode_batch, uid, batch_entries)
            except Exception as e:
                error = e
            for i, (future, _session, _x) in enumerate(batch_entries):
                if future.done():
                    continue
                result = error if error is not None else results[i]
                if isinstance(result, Exception):
                    future.set_exception(result)
                else:
                    future.set_result(result)
            # steps that arrived WHILE the batch was computing (decode_async saw a
            # live drainer and only enqueued) — and any same-session rollover — need
            # a fresh drainer now, or they would strand until some future call
            # happens to spawn one
            with self._lock:
                if rollover:
                    self._pending.setdefault(uid, []).extend(rollover)
                if self._pending.get(uid):
                    self._drainers[uid] = spawn(self._drain(uid), name="decode_session.drain")
        except asyncio.CancelledError:
            # drainer killed mid-batch (loop shutdown, server stop): nothing will
            # ever resolve these futures or re-drain the rollover — cancel them so
            # callers unblock instead of waiting forever. Steps that arrived WHILE
            # the batch was computing only enqueued into _pending (they saw a live
            # drainer), so they must be swept too or they strand and pin forever.
            with self._lock:
                stranded = self._pending.pop(uid, [])
            for future, _session, _x in batch_entries + rollover + stranded:
                if not future.done():
                    future.cancel()
            raise
        finally:
            # the eviction pins MUST drop on every exit path: a leaked pin makes the
            # session permanently unevictable
            with self._lock:
                for _future, session, _x in entries:
                    count = self._in_flight.get(id(session), 0) - 1
                    if count > 0:
                        self._in_flight[id(session)] = count
                    else:
                        self._in_flight.pop(id(session), None)

    def _batched_fn(self, uid: str, stack: int):
        key = (uid, stack)
        fn = self._batched_fns.get(key)
        if fn is None:
            fn = self._batched_fns[key] = tracked_jit(
                jax.vmap(self._raw_step(uid), in_axes=(None, 0, 0, 0, 0)),
                site="decode_session.batched_step",
                donate_argnums=(2, 3),
            )
        return fn

    def _dummy_rows(self, uid: str):
        """A throwaway (cache_k, cache_v) pair used to pad batches to the bucket
        size; its outputs and cache writes are discarded."""
        pair = self._dummy_caches.get(uid)
        if pair is None:
            pair = self._dummy_caches[uid] = self.backends[uid].module.init_decode_cache(
                1, self.max_len
            )
        return pair

    def _decode_batch(self, uid: str, entries: List) -> List:
        """Run one vmapped step over `entries` [(future, session, x)]; returns one
        result (ndarray or Exception) per entry, in order."""
        backend = self.backends[uid]
        # per-session locks in a fixed order so the direct path cannot deadlock us
        ordered = sorted(range(len(entries)), key=lambda i: id(entries[i][1]))
        for i in ordered:
            entries[i][1].lock.acquire()
        try:
            results: List = [None] * len(entries)
            live = []
            for i, (_future, session, x) in enumerate(entries):
                if session.index == 0:
                    results[i] = KeyError(f"decode session for {uid!r} has no prefill yet")
                elif session.index + 1 > self.max_len:
                    results[i] = ValueError(f"decode session is full ({session.index}/{self.max_len})")
                elif session.cache_k.shape[0] != 1:
                    results[i] = ValueError("batched decode requires session batch 1")
                else:
                    live.append(i)
            if not live:
                return results
            if len(live) == 1:
                # single-stream batch (one client decoding): the vmapped path
                # would stack-copy the session's multi-hundred-KB caches and
                # discard dummy-row work per token — use the per-session jitted
                # step directly (shared with decode(), so signatures can't
                # diverge); ISSUE 10 copy-free batching applied to decode
                [i] = live
                _future, session, x = entries[i]
                step = self._step_fn(uid, 1, 1)
                record_transfer(int(x.nbytes), "host_to_device")
                y, session.cache_k, session.cache_v = step(
                    backend.snapshot_params(), jnp.asarray(x), session.cache_k,
                    session.cache_v, jnp.int32(session.index),
                )
                session.index += 1
                session.last_used = time.monotonic()
                # counted "direct": nothing was merged/vmapped (the catalog row
                # defines `batched` as merged into a vmapped continuous batch)
                _STEPS.inc(path="direct")
                results[i] = np.asarray(y)[:, :1]
                record_transfer(results[i].nbytes, "device_to_host")
                return results
            stack = _next_pow2(len(live))
            dummy_k, dummy_v = self._dummy_rows(uid)
            xs, cks, cvs, idxs = [], [], [], []
            for i in live:
                _future, session, x = entries[i]
                xs.append(jnp.asarray(x))
                cks.append(session.cache_k)
                cvs.append(session.cache_v)
                idxs.append(session.index)
            for _ in range(stack - len(live)):
                xs.append(jnp.zeros_like(xs[0]))
                cks.append(dummy_k)
                cvs.append(dummy_v)
                idxs.append(1)  # a valid mid-cache position; output is discarded
            step = self._batched_fn(uid, stack)
            # xs rows originate host-side (one per live client step); caches are
            # already resident, so only the stacked activations count as h2d
            record_transfer(sum(int(x.nbytes) for x in xs), "host_to_device")
            y, new_k, new_v = step(
                backend.snapshot_params(), jnp.stack(xs), jnp.stack(cks), jnp.stack(cvs),
                jnp.asarray(idxs, jnp.int32),
            )
            y = np.asarray(y)
            record_transfer(y.nbytes, "device_to_host")
            _STEPS.inc(len(live), path="batched")
            now = time.monotonic()
            for row, i in enumerate(live):
                _future, session, _x = entries[i]
                session.cache_k = new_k[row]
                session.cache_v = new_v[row]
                session.index += 1
                session.last_used = now
                results[i] = y[row]
            # (the dummy rows survive: donation frees the STACKED buffer, not the
            # per-session/dummy constituents that were copied into it)
            return results
        finally:
            for i in ordered:
                entries[i][1].lock.release()
