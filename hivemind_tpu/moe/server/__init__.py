from hivemind_tpu.moe.server.checkpoints import CheckpointSaver, load_experts, store_experts
from hivemind_tpu.moe.server.connection_handler import ConnectionHandler
from hivemind_tpu.moe.server.dht_handler import declare_experts, get_experts
from hivemind_tpu.moe.server.layers import register_expert_class
from hivemind_tpu.moe.server.mesh_backend import MeshModuleBackend
from hivemind_tpu.moe.server.module_backend import ModuleBackend
from hivemind_tpu.moe.server.runtime import Runtime
from hivemind_tpu.moe.server.server import Server, background_server
from hivemind_tpu.moe.server.task_pool import TaskPool
