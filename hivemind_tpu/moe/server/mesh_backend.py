"""Mesh-sharded block serving: the serving unit is a MESH, not one chip.

The reference's device executor pins each expert to a single CUDA device
(reference hivemind/moe/server/runtime.py:22-199 — one process, one device, one
module queue). Re-designed TPU-first, a served block's parameters and KV decode
caches live as `jax.sharding.NamedSharding` global arrays over a device mesh:
XLA/GSPMD inserts the tensor-parallel collectives inside the already-jitted
forward/backward/decode steps, and the ENTIRE serving stack above (`Server`,
`ConnectionHandler`, task pools, decode sessions, `RemoteSequential` clients) is
unchanged — a client cannot tell whether one chip or a v4-32 slice answered its
RPC. This is what lets a 7B+ block whose weights exceed ONE chip's HBM be served
by a slice whose aggregate HBM holds it easily (see ``plan_block_capacity``'s
``mesh_devices``).

Sharding rule: every parameter kernel with ndim >= 2 is sharded over its LAST
axis (the output features — Megatron-style column parallel) when divisible by
the mesh axis size; 1-D leaves (biases, norm scales) replicate. Correctness
never depends on the rule — GSPMD resolves any placement — the rule just keeps
the big matmuls distributed. KV caches shard over the kv-heads axis the same
way (``shard_decode_cache``, consulted by the decode-session manager)."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from hivemind_tpu.moe.server.module_backend import ModuleBackend
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


class MeshModuleBackend(ModuleBackend):
    """A :class:`ModuleBackend` whose state is sharded over a device mesh.

    :param mesh: the serving mesh (possibly multi-host); all jitted entry points
        inherited from ModuleBackend consume the committed shardings directly.
    :param shard_axis: the mesh axis name to distribute parameters over.
    """

    def __init__(self, name: str, module, *, mesh: Mesh, shard_axis: str = "tp", **kwargs):
        self.mesh = mesh
        self.shard_axis = shard_axis
        super().__init__(name, module, **kwargs)

    def _init_state(self, samples, rng_seed: int):
        """Initialize DIRECTLY under the mesh shardings: a block bigger than one
        chip's HBM must never exist as a single-device array, not even
        transiently at init (jit out_shardings materializes each leaf sharded)."""

        def make():
            params = self.module.init(jax.random.PRNGKey(rng_seed), *samples)["params"]
            opt_state = (
                self.optimizer.init(params) if self.weight_quantization is None else None
            )
            return params, opt_state

        shapes = jax.eval_shape(make)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, self.leaf_spec(s)), shapes
        )
        # one-shot init jit, called once per backend — compile tracking would
        # only add noise to the per-site counters
        return jax.jit(make, out_shardings=shardings)()  # lint: allow(jit-in-hot-path)

    # ------------------------------------------------------------------ shardings

    def _axis_size(self) -> int:
        return int(self.mesh.shape[self.shard_axis])

    def leaf_spec(self, leaf) -> PartitionSpec:
        """Last-axis column-parallel for >=2-D kernels (when divisible), replicate
        the rest. 1-D optimizer statistics follow their parameter's rule via
        shape, not identity — a mu/nu leaf shaped like its kernel shards too."""
        shape = getattr(leaf, "shape", ())
        size = self._axis_size()
        if len(shape) >= 2 and shape[-1] % size == 0 and shape[-1] >= size:
            return PartitionSpec(*([None] * (len(shape) - 1)), self.shard_axis)
        return PartitionSpec()

    def tree_shardings(self, tree):
        return jax.tree_util.tree_map(
            lambda leaf: NamedSharding(self.mesh, self.leaf_spec(leaf)), tree
        )

    def shard_decode_cache(self, cache_k, cache_v):
        """Distribute a session's KV caches: shard the kv-heads axis (second to
        last in the compact [batch, len, kv_heads, head_dim] layout) when it
        divides the mesh axis, else the head_dim axis, else replicate."""

        def cache_sharding(cache):
            shape = cache.shape
            size = self._axis_size()
            if len(shape) >= 2 and shape[-2] % size == 0 and shape[-2] >= size:
                spec = [None] * len(shape)
                spec[-2] = self.shard_axis
            elif len(shape) >= 1 and shape[-1] % size == 0 and shape[-1] >= size:
                spec = [None] * len(shape)
                spec[-1] = self.shard_axis
            else:
                spec = [None] * len(shape)
            return NamedSharding(self.mesh, PartitionSpec(*spec))

        return (
            jax.device_put(cache_k, cache_sharding(cache_k)),
            jax.device_put(cache_v, cache_sharding(cache_v)),
        )

    def load_params(self, params) -> None:
        """Checkpoint loads land each (host) leaf DIRECTLY under its sharding —
        no single-device stopover, for the same too-big-for-one-chip reason as
        ``_init_state``. Optimizer statistics re-init from the sharded params,
        so they inherit the placement."""
        with self._state_lock:
            if self.weight_quantization is not None:
                from hivemind_tpu.ops.quantized_params import quantize_params

                quantized = quantize_params(params)
                self.params = jax.device_put(quantized, self.tree_shardings(quantized))
            else:
                self.params = jax.tree_util.tree_map(
                    lambda leaf: jax.device_put(
                        np.asarray(leaf), NamedSharding(self.mesh, self.leaf_spec(leaf))
                    ),
                    params,
                )
                self.opt_state = self.optimizer.init(self.params)

    # ------------------------------------------------------------------ accounting

    def param_bytes_per_device(self) -> int:
        """Resident parameter bytes on EACH device of the mesh — the number that
        must fit one chip's HBM (``param_bytes`` stays the global total)."""
        total = 0
        for leaf in jax.tree_util.tree_leaves(self.params):
            nbytes = int(np.prod(leaf.shape)) * leaf.dtype.itemsize
            if self.leaf_spec(leaf) != PartitionSpec():
                nbytes //= self._axis_size()
            total += nbytes
        return total

    def get_info(self):
        info = super().get_info()
        info["mesh_devices"] = int(np.prod(list(self.mesh.shape.values())))
        info["shard_axis"] = self.shard_axis
        return info

    def __repr__(self):
        return (
            f"MeshModuleBackend({self.name!r}, mesh={dict(self.mesh.shape)}, "
            f"axis={self.shard_axis!r})"
        )
