"""Batching queues between RPC handlers and the device runtime (capability parity:
reference hivemind/moe/server/task_pool.py:59-256 — there a fork with shared-memory
transfer; here an asyncio queue in the single-process runtime)."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)


@dataclass
class _Task:
    args: Tuple[np.ndarray, ...]
    future: asyncio.Future
    timestamp: float = field(default_factory=get_dht_time)

    @property
    def batch_size(self) -> int:
        return self.args[0].shape[0]


class TaskPool:
    """Collects tasks for one processing function; the Runtime drains the
    highest-priority pool (priority = oldest undispatched task, reference
    task_pool.py:169-176)."""

    def __init__(
        self,
        process_func: Callable[..., Sequence[np.ndarray]],
        name: str,
        *,
        max_batch_size: int = 4096,
        min_batch_size: int = 1,
        flush_timeout: float = 0.1,
    ):
        self.process_func = process_func
        self.name = name
        self.max_batch_size = max_batch_size
        self.min_batch_size = min_batch_size
        self.flush_timeout = flush_timeout  # sub-min batches run anyway after this age
        self._queue: List[_Task] = []
        self._task_added: Optional[asyncio.Event] = None

    def _event(self) -> asyncio.Event:
        if self._task_added is None:
            self._task_added = asyncio.Event()
        return self._task_added

    async def submit_task(self, *args: np.ndarray) -> Sequence[np.ndarray]:
        """Enqueue one task; resolves with its slice of the batched output."""
        batch_size = args[0].shape[0]
        if batch_size > self.max_batch_size:
            raise ValueError(f"task of {batch_size} items exceeds max_batch_size={self.max_batch_size}")
        task = _Task(tuple(np.asarray(a) for a in args), asyncio.get_event_loop().create_future())
        self._queue.append(task)
        self._event().set()
        return await task.future

    @property
    def queue_size(self) -> int:
        """Tasks currently waiting (telemetry: moe_pool_queue_depth)."""
        return len(self._queue)

    @property
    def priority(self) -> float:
        """Lower is more urgent: timestamp of the oldest queued task. A queue below
        min_batch_size is deprioritized only until its oldest task exceeds
        flush_timeout — never starved (the reference flushes partial batches too)."""
        if not self._queue:
            return float("inf")
        total = sum(t.batch_size for t in self._queue)
        oldest = self._queue[0].timestamp
        if total < self.min_batch_size and get_dht_time() - oldest < self.flush_timeout:
            return float("inf")
        return oldest

    def pop_batch(self) -> List[_Task]:
        """Remove up to max_batch_size samples' worth of tasks."""
        batch, total = [], 0
        while self._queue and total + self._queue[0].batch_size <= self.max_batch_size:
            task = self._queue.pop(0)
            batch.append(task)
            total += task.batch_size
        if self._task_added is not None and not self._queue:
            self._task_added.clear()
        return batch

    async def wait_for_tasks(self) -> None:
        await self._event().wait()

    def process_batch(self, tasks: List[_Task]) -> None:
        """Run process_func on the concatenated batch; split outputs per task.
        Called from the Runtime's executor thread via call_soon_threadsafe plumbing."""
        num_args = len(tasks[0].args)
        joined = [np.concatenate([t.args[i] for t in tasks], axis=0) for i in range(num_args)]
        outputs = self.process_func(*joined)
        if isinstance(outputs, np.ndarray):
            outputs = [outputs]
        offset = 0
        for task in tasks:
            size = task.batch_size
            task_out = [np.asarray(out[offset : offset + size]) for out in outputs]
            offset += size
            if not task.future.done():
                task.future.get_loop().call_soon_threadsafe(
                    lambda t=task, o=task_out: t.future.done() or t.future.set_result(o)
                )

    def fail_batch(self, tasks: List[_Task], exc: BaseException) -> None:
        for task in tasks:
            if not task.future.done():
                task.future.get_loop().call_soon_threadsafe(
                    lambda t=task: t.future.done() or t.future.set_exception(exc)
                )
