"""Batching queues between RPC handlers and the device runtime (capability parity:
reference hivemind/moe/server/task_pool.py:59-256 — there a fork with shared-memory
transfer; here an asyncio queue in the single-process runtime).

Saturation semantics (ISSUE 9): the queue is BOUNDED — past ``max_queue_size``
waiting tasks a submit is *shed* with a typed :class:`ServerOverloadedError`
(counted in ``hivemind_moe_shed_total{pool}``; the client's expert breakers
recognize the type across the RPC boundary), so an overloaded server answers
"no, now" instead of queueing unboundedly toward a timeout. Queue depth and
oldest-task age are gauged on submit AND drain, each task is stamped with its
queue-wait / batch-assembly / device-compute phases (accrued onto the active
``serving.request`` span for the ServingLedger), and every batch observes the
occupancy it ran at (samples ÷ max_batch_size)."""

from __future__ import annotations

import asyncio
import time
import weakref
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Deque, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.serving import accrue_span_phase
from hivemind_tpu.telemetry.tracing import current_span
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)

# saturation + phase metrics (docs/observability.md "Serving"): sampled on the
# submit/drain path, so the queue is visible while it GROWS, not only after a
# drain happens to run
_QUEUE_DEPTH = _TELEMETRY.gauge(
    "hivemind_moe_pool_queue_depth", "tasks waiting in a pool (sampled on submit and drain)",
    ("pool",),
)
_QUEUE_AGE = _TELEMETRY.gauge(
    "hivemind_moe_queue_age_seconds", "age of the oldest task waiting in a pool", ("pool",)
)
_QUEUE_WAIT = _TELEMETRY.histogram(
    "hivemind_moe_queue_wait_seconds", "submit-to-drain wait of one task", ("pool",)
)
_SHEDS = _TELEMETRY.counter(
    "hivemind_moe_shed_total",
    "tasks shed because the pool's bounded queue was full (ServerOverloadedError)",
    ("pool",),
)
_OCCUPANCY = _TELEMETRY.histogram(
    "hivemind_moe_batch_occupancy",
    "samples per device batch / max_batch_size (1.0 = the batch dimension is full)",
    ("pool",),
    buckets=(0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0),
)
_CANCELLED_SKIPPED = _TELEMETRY.counter(
    "hivemind_moe_pool_cancelled_skipped_total",
    "queued tasks dropped at drain time because their caller already gave up "
    "(hedge loser cancelled through the mux, abandoned deadline) — compute saved",
    ("pool",),
)


class ServerOverloadedError(RuntimeError):
    """The pool's bounded queue is full: this request was shed. Clients should
    back off (the expert's circuit breaker counts sheds as failures)."""


# every live pool, so read-time consumers (the serving ledger's saturation
# view) can refresh the gauges on demand: during a FULL stall nothing submits
# or drains, and event-driven sampling alone would freeze the age gauge at its
# last pre-stall value — exactly when the operator needs it most
_LIVE_POOLS: "weakref.WeakSet[TaskPool]" = weakref.WeakSet()


def sample_all_pool_gauges() -> None:
    """Refresh depth/age gauges for every live pool (thread-safe best effort)."""
    for pool in list(_LIVE_POOLS):
        pool._sample_gauges()


@dataclass
class _Task:
    args: Tuple[np.ndarray, ...]
    future: asyncio.Future
    timestamp: float = field(default_factory=get_dht_time)
    # phase stamps (perf_counter; ISSUE 9 attribution): queue-wait is
    # submitted->popped, assembly/compute/occupancy are shared per device batch
    submitted_pc: float = field(default_factory=time.perf_counter)
    popped_pc: Optional[float] = None
    assembly_s: Optional[float] = None
    compute_s: Optional[float] = None
    occupancy: Optional[float] = None

    @property
    def batch_size(self) -> int:
        return self.args[0].shape[0]


class TaskPool:
    """Collects tasks for one processing function; the Runtime drains the
    highest-priority pool (priority = oldest undispatched task, reference
    task_pool.py:169-176)."""

    def __init__(
        self,
        process_func: Callable[..., Sequence[np.ndarray]],
        name: str,
        *,
        max_batch_size: int = 4096,
        min_batch_size: int = 1,
        flush_timeout: float = 0.1,
        max_queue_size: int = 1024,
    ):
        self.process_func = process_func
        self.name = name
        self.max_batch_size = max_batch_size
        self.min_batch_size = min_batch_size
        self.flush_timeout = flush_timeout  # sub-min batches run anyway after this age
        self.max_queue_size = max_queue_size  # queued tasks beyond this are SHED
        # deque: submit appends right, drain pops left — O(1) per task where the
        # old list.pop(0) was O(n) under load; priority still reads [0] (oldest)
        self._queue: Deque[_Task] = deque()
        # reused batch-assembly buffers, keyed (arg index, bucket, trailing
        # shape, dtype) — see _batch_buffer
        self._batch_buffers: dict = {}
        self._task_added: Optional[asyncio.Event] = None
        # cached metric children (pool names are stable for the pool's lifetime)
        self._depth_gauge = _QUEUE_DEPTH.labels(name)
        self._age_gauge = _QUEUE_AGE.labels(name)
        self._wait_histogram = _QUEUE_WAIT.labels(name)
        self._shed_counter = _SHEDS.labels(name)
        self._occupancy_histogram = _OCCUPANCY.labels(name)
        self._cancelled_counter = _CANCELLED_SKIPPED.labels(name)
        _LIVE_POOLS.add(self)

    def _event(self) -> asyncio.Event:
        if self._task_added is None:
            self._task_added = asyncio.Event()
        return self._task_added

    def _sample_gauges(self) -> None:
        self._depth_gauge.set(len(self._queue))
        try:
            # may run off-loop (sample_all_pool_gauges): guard the popleft race
            oldest = self._queue[0].timestamp
        except IndexError:
            oldest = None
        self._age_gauge.set(max(get_dht_time() - oldest, 0.0) if oldest is not None else 0.0)

    async def submit_task(self, *args: np.ndarray) -> Sequence[np.ndarray]:
        """Enqueue one task; resolves with its slice of the batched output.
        Sheds (ServerOverloadedError) when the bounded queue is full."""
        batch_size = args[0].shape[0]
        if batch_size > self.max_batch_size:
            raise ValueError(f"task of {batch_size} items exceeds max_batch_size={self.max_batch_size}")
        if len(self._queue) >= self.max_queue_size:
            self._shed_counter.inc()
            self._sample_gauges()
            raise ServerOverloadedError(
                f"pool {self.name!r} is overloaded: {len(self._queue)} tasks queued "
                f"(max_queue_size={self.max_queue_size}); request shed"
            )
        task = _Task(tuple(np.asarray(a) for a in args), asyncio.get_event_loop().create_future())
        self._queue.append(task)
        self._sample_gauges()
        self._event().set()
        outputs = await task.future
        # phase attribution onto the active serving.request span (ISSUE 9)
        if task.popped_pc is not None:
            queue_wait = max(task.popped_pc - task.submitted_pc, 0.0)
            self._wait_histogram.observe(queue_wait)
            accrue_span_phase("queue_wait_s", queue_wait)
        if task.assembly_s is not None:
            accrue_span_phase("assembly_s", task.assembly_s)
        if task.compute_s is not None:
            accrue_span_phase("compute_s", task.compute_s)
        if task.occupancy is not None:
            span = current_span()
            if span is not None:
                # span-execution chains hit several pools: phases accumulate,
                # but occupancy/pool keep the WORST-occupancy hop (the
                # under-filled batch is the lever a reader wants named, and
                # last-write-wins would point at an arbitrary hop)
                previous = (span.attributes or {}).get("occupancy")
                if previous is None or task.occupancy < float(previous):
                    span.set("occupancy", task.occupancy)
                    span.set("pool", self.name)
        return outputs

    @property
    def queue_size(self) -> int:
        """Tasks currently waiting (telemetry: hivemind_moe_pool_queue_depth)."""
        return len(self._queue)

    @property
    def priority(self) -> float:
        """Lower is more urgent: timestamp of the oldest queued task. A queue below
        min_batch_size is deprioritized only until its oldest task exceeds
        flush_timeout — never starved (the reference flushes partial batches too)."""
        if not self._queue:
            return float("inf")
        total = sum(t.batch_size for t in self._queue)
        oldest = self._queue[0].timestamp
        if total < self.min_batch_size and get_dht_time() - oldest < self.flush_timeout:
            return float("inf")
        return oldest

    def pop_batch(self) -> List[_Task]:
        """Remove up to max_batch_size samples' worth of tasks. Tasks whose
        future is already done (the caller was cancelled — a hedge's losing
        request RESET through the mux, an abandoned deadline) are dropped here
        instead of burning a device-batch slot on an answer nobody will read."""
        batch, total = [], 0
        popped_at = time.perf_counter()
        while self._queue and total + self._queue[0].batch_size <= self.max_batch_size:
            task = self._queue.popleft()
            if task.future.done():
                self._cancelled_counter.inc()
                continue
            task.popped_pc = popped_at
            batch.append(task)
            total += task.batch_size
        self._sample_gauges()
        if self._task_added is not None and not self._queue:
            self._task_added.clear()
        return batch

    async def wait_for_tasks(self) -> None:
        await self._event().wait()

    def _batch_buffer(self, arg_index: int, bucket: int, sample: np.ndarray) -> np.ndarray:
        """The reusable batch-assembly buffer for one argument position at one
        power-of-two bucket size (ISSUE 10: the per-batch ``np.concatenate``
        allocated + copied every batch; now tasks write once into a buffer that
        matches the backend's one-executable-per-bucket jit cache, so the
        backend's own pad-to-bucket step becomes a no-op). Safe to reuse:
        batches run one at a time on the Runtime's executor, and process_func
        copies to device before the next batch overwrites it."""
        key = (arg_index, bucket, sample.shape[1:], sample.dtype.str)
        buffer = self._batch_buffers.get(key)
        if buffer is None:
            if len(self._batch_buffers) >= 32:
                # trailing shapes are request-controlled (e.g. per-client seq
                # lengths): bound retention — these are pure caches, so a clear
                # only costs the next batches one allocation each
                self._batch_buffers.clear()
            buffer = self._batch_buffers[key] = np.zeros(
                (bucket, *sample.shape[1:]), sample.dtype
            )
        return buffer

    def process_batch(self, tasks: List[_Task]) -> None:
        """Run process_func on the assembled batch; split outputs per task as
        zero-copy views. Called from the Runtime's executor thread via
        call_soon_threadsafe plumbing."""
        from hivemind_tpu.moe.server.module_backend import bucket_batch_size

        num_args = len(tasks[0].args)
        assembly_start = time.perf_counter()
        total = sum(t.batch_size for t in tasks)
        if len(tasks) == 1:
            # single-task batch (the per-token decode/forward common case):
            # pass the task's own arrays straight through — zero copies here
            joined: List[np.ndarray] = list(tasks[0].args)
            batch_len = total
        else:
            # copy-free batching: one write per task into the reused bucket
            # buffer (vs concatenate-allocate + the backend's pad copy)
            batch_len = bucket_batch_size(total, self.max_batch_size)
            joined = []
            for i in range(num_args):
                buffer = self._batch_buffer(i, batch_len, tasks[0].args[i])
                offset = 0
                for task in tasks:
                    buffer[offset : offset + task.batch_size] = task.args[i]
                    offset += task.batch_size
                if offset < batch_len:
                    # stale rows from the previous batch must not leak into the
                    # padding (a backward pool's optimizer update sums over them)
                    buffer[offset:batch_len] = 0
                joined.append(buffer)
        compute_start = time.perf_counter()
        outputs = self.process_func(*joined)
        compute_end = time.perf_counter()
        if isinstance(outputs, np.ndarray):
            outputs = [outputs]
        # a process_func returning the wrong leading dim used to mis-slice:
        # some tasks silently received truncated/empty outputs — fail the whole
        # batch loudly instead (the Runtime routes this into fail_batch).
        # Outputs must cover the submitted batch; bucket-padded rows beyond
        # `total` are sliced away below and never reach a task.
        for index, out in enumerate(outputs):
            out_len = np.asarray(out).shape[0] if np.ndim(out) else 0
            if out_len not in (total, batch_len):
                raise ValueError(
                    f"pool {self.name!r}: process_func output {index} has leading "
                    f"dim {out_len} but the batch holds {total} samples "
                    f"({len(tasks)} tasks, padded to {batch_len}) — refusing to "
                    f"mis-slice per-task outputs"
                )
        assembly_s = compute_start - assembly_start
        compute_s = compute_end - compute_start
        occupancy = round(total / max(self.max_batch_size, 1), 4)
        self._occupancy_histogram.observe(occupancy)
        offset = 0
        for task in tasks:
            size = task.batch_size
            task.assembly_s = assembly_s
            task.compute_s = compute_s
            task.occupancy = occupancy
            task_out = [np.asarray(out[offset : offset + size]) for out in outputs]
            offset += size
            if not task.future.done():
                task.future.get_loop().call_soon_threadsafe(
                    lambda t=task, o=task_out: t.future.done() or t.future.set_result(o)
                )

    def fail_batch(self, tasks: List[_Task], exc: BaseException) -> None:
        for task in tasks:
            if not task.future.done():
                task.future.get_loop().call_soon_threadsafe(
                    lambda t=task: t.future.done() or t.future.set_exception(exc)
                )
