"""Fair-share admission (ISSUE 13): per-client token buckets in front of the
bounded TaskPool queues.

The PR 8 saturation layer sheds when the QUEUE is full — correct for total
overload, but one greedy tenant can fill the queue and starve everyone before
the global backstop fires. Client ids are already attributed on every request
(the ``serving.request`` span's ``client``, stamped by the ConnectionHandler
from the P2P context), so admission can be *fair-share*: each client draws
request cost (samples) from its own token bucket; a client past its budget is
shed with the same **typed** answer contract as a queue shed — the error type
rides the mux ERROR frame, :func:`~hivemind_tpu.telemetry.serving.is_overload_error`
recognizes it on the caller, and the client's own breakers/scorecards react
exactly as they do to a pool shed — while every other client keeps flowing.

:class:`ClientOverBudgetError` subclasses
:class:`~hivemind_tpu.moe.server.task_pool.ServerOverloadedError` so every
existing "is this a shed?" isinstance check keeps working server-side too.

Cost model: one token per SAMPLE (the leading batch dim), so a hot client
cannot dodge its budget by batching harder. The bucket refills at
``rate_per_s`` with a burst ceiling of ``burst`` tokens; both are operator
knobs (``--client_rate`` / ``--client_burst`` in run_server). Disabled (the
default) when ``rate_per_s`` is None/0 — admission is opt-in capacity policy.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from hivemind_tpu.moe.server.task_pool import ServerOverloadedError
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)

_ADMISSION_SHEDS = _TELEMETRY.counter(
    "hivemind_moe_admission_shed_total",
    "requests shed by fair-share admission (a client over its own token budget; "
    "typed ClientOverBudgetError — other clients keep flowing)",
    ("kind",),
)
_ADMISSION_CLIENTS = _TELEMETRY.gauge(
    "hivemind_moe_admission_clients",
    "client token buckets currently tracked by fair-share admission",
)


class ClientOverBudgetError(ServerOverloadedError):
    """THIS client exhausted its fair-share token budget: the request was shed
    before touching any queue (it provably never executed — clients may fail
    over to another replica). Other clients are unaffected."""


class FairShareAdmission:
    """Per-client token buckets. Thread-safe; bucket count is bounded — client
    ids are remote-controlled, so an identity-cycling peer must not grow this
    map without bound (oldest-refilled buckets evicted; eviction only ever
    FORGIVES, granting a fresh burst, so cycling identities past the cap is
    equivalent to the admission layer being off for the attacker, never a way
    to starve honest clients)."""

    def __init__(
        self,
        rate_per_s: float,
        burst: Optional[float] = None,
        max_clients: int = 1024,
        clock: Callable[[], float] = time.monotonic,
    ):
        assert rate_per_s > 0, "use admission=None to disable fair-share admission"
        self.rate_per_s = float(rate_per_s)
        # default burst: two seconds of budget — enough to absorb a prefill
        # spike without letting a silent client bank minutes of credit
        self.burst = float(burst) if burst is not None else max(2.0 * rate_per_s, 1.0)
        self.max_clients = max_clients
        self._clock = clock
        self._lock = threading.Lock()
        # client -> [tokens, last_refill]; OrderedDict for LRU-ish eviction
        self._buckets: "OrderedDict[str, list]" = OrderedDict()

    def admit(self, client: str, cost: float = 1.0, kind: str = "request") -> None:
        """Draw ``cost`` tokens from ``client``'s bucket or raise the typed
        :class:`ClientOverBudgetError` shed."""
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(client)
            if bucket is None:
                while len(self._buckets) >= self.max_clients:
                    self._buckets.popitem(last=False)
                bucket = self._buckets[client] = [self.burst, now]
                _ADMISSION_CLIENTS.set(len(self._buckets))
            tokens, last = bucket
            tokens = min(tokens + (now - last) * self.rate_per_s, self.burst)
            bucket[1] = now
            if tokens < cost:
                bucket[0] = tokens
                self._buckets.move_to_end(client)
                _ADMISSION_SHEDS.inc(kind=kind)
                if cost > self.burst:
                    # a full bucket can never hold this much: no amount of
                    # waiting admits the request, so retrying is a silent
                    # starvation loop. Say so loudly — the classic trigger is a
                    # mid-session failover re-prefill (one draw of the WHOLE
                    # retained history) against a burst sized for single steps.
                    logger.warning(
                        f"admission: client {client} requested {cost:g} tokens but the "
                        f"burst ceiling is {self.burst:g} — permanently inadmissible at "
                        f"this budget; raise the burst to at least the largest single "
                        f"request (e.g. a failover re-prefill's full history)"
                    )
                    raise ClientOverBudgetError(
                        f"client {client} request costs {cost:g} tokens, over the burst "
                        f"ceiling {self.burst:g}: never admissible at this budget "
                        f"(rate {self.rate_per_s:g}/s); raise burst or shrink the request"
                    )
                raise ClientOverBudgetError(
                    f"client {client} is over its fair-share budget "
                    f"({cost:g} tokens requested, {tokens:.2f} available, "
                    f"rate {self.rate_per_s:g}/s burst {self.burst:g}); request shed"
                )
            bucket[0] = tokens - cost
            self._buckets.move_to_end(client)

    def tokens(self, client: str) -> Optional[float]:
        """Current balance (refilled to now) — observability/tests."""
        now = self._clock()
        with self._lock:
            bucket = self._buckets.get(client)
            if bucket is None:
                return None
            return min(bucket[0] + (now - bucket[1]) * self.rate_per_s, self.burst)

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)
