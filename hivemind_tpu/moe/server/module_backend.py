"""ModuleBackend: one expert = a flax module + optax optimizer behind jitted apply
functions (capability parity: reference hivemind/moe/server/module_backend.py:19-200).

TPU-first: instead of the reference's dynamic torch batches, inputs are padded to
power-of-two buckets so XLA compiles one executable per bucket; backward re-derives
the forward under jax.vjp and applies the optimizer update in the same jitted call
(the reference's on_backward semantics, module_backend.py:156-165)."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.compression import CompressionType
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.tensor_descr import BatchTensorDescriptor

logger = get_logger(__name__)


def bucket_batch_size(n: int, max_batch_size: int) -> int:
    """Next power of two ≥ n (capped): static shapes for XLA."""
    bucket = 1
    while bucket < n:
        bucket *= 2
    return min(bucket, max(max_batch_size, n))


class ModuleBackend:
    """See module docstring.

    :param module: a flax module whose __call__ takes one input array
    :param optimizer: optax transformation applied on every backward batch
    :param sample_input: schema-defining input WITH batch dim (any batch size)
    """

    def __init__(
        self,
        name: str,
        module,
        *,
        optimizer,
        sample_input: np.ndarray,
        max_batch_size: int = 4096,
        rng_seed: int = 0,
    ):
        self.name, self.module, self.optimizer = name, module, optimizer
        self.max_batch_size = max_batch_size
        sample = jnp.asarray(sample_input[:1])
        self.params = module.init(jax.random.PRNGKey(rng_seed), sample)["params"]
        self.opt_state = optimizer.init(self.params)
        self._state_lock = threading.Lock()
        self.update_count = 0

        sample_out = module.apply({"params": self.params}, sample)
        self.forward_schema = (BatchTensorDescriptor.from_array(np.asarray(sample_input)),)
        self.outputs_schema = (BatchTensorDescriptor.from_array(np.asarray(sample_out)),)

        @jax.jit
        def _forward(params, x):
            return module.apply({"params": params}, x)

        @jax.jit
        def _backward(params, opt_state, x, grad_out):
            import optax

            out, vjp = jax.vjp(lambda p, xx: module.apply({"params": p}, xx), params, x)
            grad_params, grad_x = vjp(grad_out)
            updates, new_opt_state = optimizer.update(grad_params, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return grad_x, new_params, new_opt_state

        self._jit_forward, self._jit_backward = _forward, _backward

    # ------------------------------------------------------------------ execution

    def _pad(self, batch: np.ndarray) -> Tuple[jnp.ndarray, int]:
        n = batch.shape[0]
        bucket = bucket_batch_size(n, self.max_batch_size)
        if bucket != n:
            pad_width = [(0, bucket - n)] + [(0, 0)] * (batch.ndim - 1)
            batch = np.pad(batch, pad_width)
        return jnp.asarray(batch), n

    def forward(self, inputs: np.ndarray) -> np.ndarray:
        """Inference on a concatenated batch (no parameter updates)."""
        padded, n = self._pad(np.asarray(inputs, np.float32))
        with self._state_lock:
            params = self.params
        out = self._jit_forward(params, padded)
        return np.asarray(out)[:n]

    def backward(self, inputs: np.ndarray, grad_outputs: np.ndarray) -> np.ndarray:
        """Gradient wrt inputs; ALSO applies one optimizer update to the expert
        (reference on_backward: the server trains on every backward call)."""
        padded_x, n = self._pad(np.asarray(inputs, np.float32))
        padded_g, _ = self._pad(np.asarray(grad_outputs, np.float32))
        with self._state_lock:
            grad_x, new_params, new_opt_state = self._jit_backward(
                self.params, self.opt_state, padded_x, padded_g
            )
            self.params, self.opt_state = new_params, new_opt_state
            self.update_count += 1
        return np.asarray(grad_x)[:n]

    # ------------------------------------------------------------------ metadata/state

    def get_info(self) -> Dict[str, Any]:
        return dict(
            forward_schema=list(self.forward_schema),
            outputs_schema=list(self.outputs_schema),
            max_batch_size=self.max_batch_size,
            updates=self.update_count,
        )

    def state_dict(self) -> bytes:
        import flax.serialization

        with self._state_lock:
            return flax.serialization.to_bytes(
                {"params": self.params, "opt_state": self.opt_state, "updates": self.update_count}
            )

    def load_state_dict(self, blob: bytes) -> None:
        import flax.serialization

        with self._state_lock:
            template = {"params": self.params, "opt_state": self.opt_state, "updates": 0}
            restored = flax.serialization.from_bytes(template, blob)
            self.params = restored["params"]
            self.opt_state = restored["opt_state"]
            self.update_count = int(restored["updates"])
