"""ModuleBackend: one expert = a flax module + optax optimizer behind jitted apply
functions (capability parity: reference hivemind/moe/server/module_backend.py:19-200).

TPU-first: instead of the reference's dynamic torch batches, inputs are padded to
power-of-two buckets so XLA compiles one executable per bucket; backward re-derives
the forward under jax.vjp and applies the optimizer update in the same jitted call
(the reference's on_backward semantics, module_backend.py:156-165)."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from hivemind_tpu.compression import CompressionType
from hivemind_tpu.telemetry.device import record_transfer
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.profiling import tracked_jit
from hivemind_tpu.utils.tensor_descr import BatchTensorDescriptor

logger = get_logger(__name__)


def bucket_batch_size(n: int, max_batch_size: int) -> int:
    """Next power of two ≥ n (capped): static shapes for XLA."""
    bucket = 1
    while bucket < n:
        bucket *= 2
    return min(bucket, max(max_batch_size, n))


class ModuleBackend:
    """See module docstring.

    :param module: a flax module; __call__ may take SEVERAL input arrays and return
        one array or a tuple of arrays (nested expert schemas, reference
        module_backend.py:68-74)
    :param optimizer: optax transformation applied on every backward batch
    :param sample_input: schema-defining input WITH batch dim (single-input experts)
    :param sample_inputs: schema-defining inputs for multi-input experts
    :param weight_quantization: ``"int8"`` stores the expert's weights with the
        repo's blockwise absmax codec (4x less resident memory; dense bf16/fp32
        weights are materialized transiently inside the jit). Serving-only: the
        backend refuses backward calls (the Petals-style Llama-7B block server of
        BASELINE config #5 serves frozen pretrained blocks).
    """

    def __init__(
        self,
        name: str,
        module,
        *,
        optimizer,
        sample_input: Optional[np.ndarray] = None,
        sample_inputs: Optional[Sequence[np.ndarray]] = None,
        max_batch_size: int = 4096,
        rng_seed: int = 0,
        weight_quantization: Optional[str] = None,
    ):
        assert (sample_input is None) != (sample_inputs is None), (
            "provide exactly one of sample_input / sample_inputs"
        )
        if sample_inputs is None:
            sample_inputs = (sample_input,)
        assert weight_quantization in (None, "int8"), weight_quantization
        self.name, self.module, self.optimizer = name, module, optimizer
        self.max_batch_size = max_batch_size
        self.weight_quantization = weight_quantization
        samples = tuple(jnp.asarray(np.asarray(s)[:1]) for s in sample_inputs)
        self.params, self.opt_state = self._init_state(samples, rng_seed)
        self._state_lock = threading.Lock()
        self.update_count = 0

        sample_out = module.apply({"params": self.params}, *samples)
        if weight_quantization is not None:
            from hivemind_tpu.ops.quantized_params import quantize_params

            self.params = quantize_params(self.params)
        outs = tuple(sample_out) if isinstance(sample_out, (tuple, list)) else (sample_out,)
        self.num_inputs, self.num_outputs = len(samples), len(outs)
        self._outputs_are_tuple = isinstance(sample_out, (tuple, list))
        self.forward_schema = tuple(
            BatchTensorDescriptor.from_array(np.asarray(s)) for s in sample_inputs
        )
        self.outputs_schema = tuple(BatchTensorDescriptor.from_array(np.asarray(o)) for o in outs)

        def _as_tuple(value):
            return tuple(value) if isinstance(value, (tuple, list)) else (value,)

        # tracked_jit (ISSUE 19): per-bucket compiles show up on the compile
        # tracker (sites are fixed strings — expert names would explode label
        # cardinality; the signature on the compile record carries the shape)
        @tracked_jit(site="module_backend.forward")
        def _forward(params, *xs):
            from hivemind_tpu.ops.quantized_params import dequantize_tree

            return _as_tuple(module.apply({"params": dequantize_tree(params)}, *xs))

        @tracked_jit(site="module_backend.backward")
        def _backward(params, opt_state, xs, grad_outs):
            import optax

            out, vjp = jax.vjp(lambda p, xx: module.apply({"params": p}, *xx), params, tuple(xs))
            cotangent = _as_tuple(grad_outs) if self._outputs_are_tuple else grad_outs[0]
            grad_params, grad_xs = vjp(cotangent)
            updates, new_opt_state = optimizer.update(grad_params, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            return grad_xs, new_params, new_opt_state

        self._jit_forward, self._jit_backward = _forward, _backward

    # ------------------------------------------------------------------ execution

    def _pad(self, batch: np.ndarray) -> Tuple[jnp.ndarray, int]:
        n = batch.shape[0]
        bucket = bucket_batch_size(n, self.max_batch_size)
        if bucket != n:
            pad_width = [(0, bucket - n)] + [(0, 0)] * (batch.ndim - 1)
            batch = np.pad(batch, pad_width)
        return jnp.asarray(batch), n

    def _init_state(self, samples, rng_seed: int):
        """Create (params, opt_state); subclasses control placement (the mesh
        backend lands state directly under its shardings)."""
        params = self.module.init(jax.random.PRNGKey(rng_seed), *samples)["params"]
        opt_state = self.optimizer.init(params) if self.weight_quantization is None else None
        return params, opt_state

    def snapshot_params(self):
        """The current parameter pytree under the state lock (for read-only use by
        auxiliary executors, e.g. decode sessions)."""
        with self._state_lock:
            return self.params

    def load_params(self, params) -> None:
        """Replace the expert's weights (e.g. with a pretrained checkpoint's). The
        tree must match the init schema. Quantized backends re-encode to int8;
        trainable ones restart optimizer statistics for the new weights."""
        with self._state_lock:
            if self.weight_quantization is not None:
                from hivemind_tpu.ops.quantized_params import quantize_params

                self.params = quantize_params(params)
            else:
                self.params = jax.tree_util.tree_map(jnp.asarray, params)
                self.opt_state = self.optimizer.init(self.params)

    def param_bytes(self) -> int:
        """Resident bytes of this expert's weights (int8 codes count, not the
        transient dense copies) — the HBM budgeting input."""
        from hivemind_tpu.ops.quantized_params import tree_param_bytes

        with self._state_lock:
            return tree_param_bytes(self.params)

    def forward(self, *inputs: np.ndarray) -> List[np.ndarray]:
        """Inference on a concatenated batch (no parameter updates)."""
        assert len(inputs) == self.num_inputs, (len(inputs), self.num_inputs)
        padded = [self._pad(np.asarray(x, np.float32)) for x in inputs]
        n = padded[0][1]
        record_transfer(sum(int(p.nbytes) for p, _ in padded), "host_to_device")
        outs = self._jit_forward(self.snapshot_params(), *(p for p, _ in padded))
        results = [np.asarray(out)[:n] for out in outs]
        record_transfer(sum(r.nbytes for r in results), "device_to_host")
        return results

    def backward(self, *tensors: np.ndarray) -> List[np.ndarray]:
        """Gradients wrt every input; ALSO applies one optimizer update to the expert
        (reference on_backward: the server trains on every backward call).
        ``tensors`` = the forward inputs followed by one grad per output."""
        if self.weight_quantization is not None:
            raise RuntimeError(
                f"expert {self.name!r} serves int8 weight-only (inference-only): "
                f"backward/training is not supported on quantized weights"
            )
        assert len(tensors) == self.num_inputs + self.num_outputs, (
            len(tensors), self.num_inputs, self.num_outputs,
        )
        padded_x = [self._pad(np.asarray(x, np.float32)) for x in tensors[: self.num_inputs]]
        padded_g = [self._pad(np.asarray(g, np.float32)) for g in tensors[self.num_inputs :]]
        n = padded_x[0][1]
        record_transfer(
            sum(int(p.nbytes) for p, _ in padded_x) + sum(int(p.nbytes) for p, _ in padded_g),
            "host_to_device",
        )
        with self._state_lock:
            grad_xs, new_params, new_opt_state = self._jit_backward(
                self.params,
                self.opt_state,
                tuple(p for p, _ in padded_x),
                tuple(p for p, _ in padded_g),
            )
            self.params, self.opt_state = new_params, new_opt_state
            self.update_count += 1
        grads_out = [np.asarray(g)[:n] for g in grad_xs]
        record_transfer(sum(g.nbytes for g in grads_out), "device_to_host")
        return grads_out

    # ------------------------------------------------------------------ metadata/state

    def get_info(self) -> Dict[str, Any]:
        return dict(
            forward_schema=list(self.forward_schema),
            outputs_schema=list(self.outputs_schema),
            max_batch_size=self.max_batch_size,
            updates=self.update_count,
        )

    def state_dict(self) -> bytes:
        import flax.serialization

        from hivemind_tpu.ops.quantized_params import dequantize_tree

        with self._state_lock:
            # quantized backends serialize the dense form (msgpack cannot carry the
            # QuantizedTensor nodes); load_state_dict re-encodes, so the round-trip
            # is exact for int8 serving
            return flax.serialization.to_bytes(
                {
                    "params": dequantize_tree(self.params),
                    "opt_state": self.opt_state if self.opt_state is not None else {},
                    "updates": self.update_count,
                }
            )

    def load_state_dict(self, blob: bytes) -> None:
        import flax.serialization

        from hivemind_tpu.ops.quantized_params import dequantize_tree, quantize_params

        with self._state_lock:
            template = {
                "params": dequantize_tree(self.params),
                "opt_state": self.opt_state if self.opt_state is not None else {},
                "updates": 0,
            }
            restored = flax.serialization.from_bytes(template, blob)
            if self.weight_quantization is not None:
                self.params = quantize_params(restored["params"])
            else:
                self.params = restored["params"]
                self.opt_state = restored["opt_state"]
            self.update_count = int(restored["updates"])
