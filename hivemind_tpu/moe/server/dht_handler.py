"""Expert discovery records (capability parity: reference
hivemind/moe/server/dht_handler.py:22-108): an expert's UID and EVERY prefix of it are
stored as dictionary subkeys, which is what makes left-to-right beam search over the
grid possible."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.expert_uid import (
    UID_DELIMITER,
    ExpertInfo,
    ExpertPrefix,
    ExpertUID,
    is_valid_uid,
    split_uid,
)
from hivemind_tpu.p2p import PeerID
from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time


def declare_experts(
    dht: DHT, uids: Sequence[ExpertUID], expiration_time: Optional[DHTExpiration] = None, wait: bool = True
):
    """Store this peer's experts: for 'ffn.5.12' store subkey 5 under 'ffn.' and
    subkey 12 under 'ffn.5.' plus the leaf record."""
    expiration_time = expiration_time if expiration_time is not None else get_dht_time() + 300
    peer_b58 = dht.peer_id.to_base58()

    async def _declare(dht_obj, node):
        keys, values, subkeys, expirations = [], [], [], []
        for uid in uids:
            assert is_valid_uid(uid), f"invalid expert uid {uid!r}"
            keys.append(uid)
            subkeys.append(None)
            values.append(peer_b58)
            expirations.append(expiration_time)
            prefix = uid
            while True:
                prefix, coord = split_uid(prefix)
                keys.append(prefix.rstrip(UID_DELIMITER))
                subkeys.append(coord)
                values.append(peer_b58)
                expirations.append(expiration_time)
                if UID_DELIMITER not in prefix.rstrip(UID_DELIMITER):
                    break  # reached the grid root (e.g. 'ffn_test')
        return await node.store_many(keys, values, expirations, subkeys=subkeys)

    result = dht.run_coroutine(_declare, return_future=not wait)
    return result


def get_experts(
    dht: DHT, uids: Sequence[ExpertUID], expiration_time: Optional[DHTExpiration] = None, wait: bool = True
):
    """Resolve expert UIDs to ExpertInfo(uid, peer_id) (or None if not found)."""

    async def _get(dht_obj, node) -> List[Optional[ExpertInfo]]:
        found = await node.get_many(list(uids))
        out: List[Optional[ExpertInfo]] = []
        for uid in uids:
            entry = found.get(uid)
            if entry is None or not isinstance(entry.value, str):
                out.append(None)
                continue
            try:
                out.append(ExpertInfo(uid, PeerID.from_base58(entry.value)))
            except Exception:
                out.append(None)
        return out

    return dht.run_coroutine(_get, return_future=not wait)
