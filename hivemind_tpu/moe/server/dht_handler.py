"""Expert discovery records (capability parity: reference
hivemind/moe/server/dht_handler.py:22-108): an expert's UID and EVERY prefix of it are
stored as dictionary subkeys, which is what makes left-to-right beam search over the
grid possible.

Record format: the stored value is ``<peer_b58>`` or ``<peer_b58>|<compression>``
— servers append their advertised activation wire dtype (ISSUE 10) so clients
learn the negotiated codec from discovery alone, without an extra ``rpc_info``
round-trip. Readers in THIS tree accept both forms, so upgraded clients resolve
legacy servers fine; the reverse is not true — a pre-ISSUE-10 client cannot
parse the suffixed record (its ``from_base58`` raises and the expert is skipped),
so serving peers must not upgrade ahead of the clients they serve."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.expert_uid import (
    UID_DELIMITER,
    ExpertInfo,
    ExpertPrefix,
    ExpertUID,
    is_valid_uid,
    split_uid,
)
from hivemind_tpu.p2p import PeerID
from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time

_RECORD_DELIMITER = "|"


def make_expert_record(peer_b58: str, compression: Optional[str] = None) -> str:
    """The stored declaration value; compression rides after a ``|``."""
    return f"{peer_b58}{_RECORD_DELIMITER}{compression}" if compression else peer_b58


def parse_expert_record(value) -> Optional[Tuple[PeerID, Optional[str]]]:
    """``(peer_id, compression_or_None)`` from a declaration value, or None if
    the value is malformed (DHT records are remote-supplied)."""
    if not isinstance(value, str):
        return None
    peer_b58, _, compression = value.partition(_RECORD_DELIMITER)
    try:
        return PeerID.from_base58(peer_b58), (compression or None)
    except Exception:
        return None


def declare_experts(
    dht: DHT,
    uids: Sequence[ExpertUID],
    expiration_time: Optional[DHTExpiration] = None,
    wait: bool = True,
    compression: Optional[str] = None,
):
    """Store this peer's experts: for 'ffn.5.12' store subkey 5 under 'ffn.' and
    subkey 12 under 'ffn.5.' plus the leaf record."""
    expiration_time = expiration_time if expiration_time is not None else get_dht_time() + 300
    record = make_expert_record(dht.peer_id.to_base58(), compression)

    async def _declare(dht_obj, node):
        keys, values, subkeys, expirations = [], [], [], []
        for uid in uids:
            assert is_valid_uid(uid), f"invalid expert uid {uid!r}"
            keys.append(uid)
            subkeys.append(None)
            values.append(record)
            expirations.append(expiration_time)
            prefix = uid
            while True:
                prefix, coord = split_uid(prefix)
                keys.append(prefix.rstrip(UID_DELIMITER))
                subkeys.append(coord)
                values.append(record)
                expirations.append(expiration_time)
                if UID_DELIMITER not in prefix.rstrip(UID_DELIMITER):
                    break  # reached the grid root (e.g. 'ffn_test')
        return await node.store_many(keys, values, expirations, subkeys=subkeys)

    result = dht.run_coroutine(_declare, return_future=not wait)
    return result


def get_experts(
    dht: DHT, uids: Sequence[ExpertUID], expiration_time: Optional[DHTExpiration] = None, wait: bool = True
):
    """Resolve expert UIDs to ExpertInfo(uid, peer_id, compression) (or None if
    not found)."""

    async def _get(dht_obj, node) -> List[Optional[ExpertInfo]]:
        found = await node.get_many(list(uids))
        out: List[Optional[ExpertInfo]] = []
        for uid in uids:
            entry = found.get(uid)
            parsed = parse_expert_record(entry.value) if entry is not None else None
            if parsed is None:
                out.append(None)
                continue
            peer_id, compression = parsed
            out.append(ExpertInfo(uid, peer_id, compression))
        return out

    return dht.run_coroutine(_get, return_future=not wait)
