"""Expert discovery records (capability parity: reference
hivemind/moe/server/dht_handler.py:22-108): an expert's UID and EVERY prefix of it are
stored as dictionary subkeys, which is what makes left-to-right beam search over the
grid possible.

Record format: each stored value is ``<peer_b58>`` or ``<peer_b58>|<compression>``
— servers append their advertised activation wire dtype (ISSUE 10) so clients
learn the negotiated codec from discovery alone, without an extra ``rpc_info``
round-trip. Since ISSUE 13 the *leaf* record is a **multi-value replica set**:
each server stores its record under its own peer-id subkey, so the DHT merges
concurrent declarations subkey-wise instead of newest-expiration-wins — the
key's value deserializes to ``{peer_b58: (record, expiration)}`` and resolution
returns the FULL replica set (``ExpertInfo.replicas``). Readers in THIS tree
accept every historical form (bare peer, ``peer|codec``, subkey dictionaries),
so upgraded clients resolve legacy servers fine; the reverse is not true — a
pre-ISSUE-13 client cannot parse the dictionary leaf (its value is not a
string), so serving peers must not upgrade ahead of the clients they serve.
Prefix records keep their coordinate subkeys unchanged (beam search only needs
coordinate existence; replica resolution happens at the leaf)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.expert_uid import (
    UID_DELIMITER,
    ExpertInfo,
    ExpertPrefix,
    ExpertUID,
    ReplicaInfo,
    is_valid_uid,
    split_uid,
)
from hivemind_tpu.p2p import PeerID
from hivemind_tpu.utils.timed_storage import DHTExpiration, get_dht_time

_RECORD_DELIMITER = "|"

# Replica-set leaf reads run the FULL merging traversal (get_many with an
# unreachable sufficient_expiration_time, i.e. `latest` semantics) instead of
# finishing at the first fresh value. Rationale: a replica-set leaf is
# MERGE-typed — any single node (the local get-cache especially, but also a
# storage node whose replica placement diverged from another declarer's) can
# hold a partial subkey dictionary, and a first-fresh read would return that
# partial set: a freshly-declared replica stays invisible, or worse, a dead
# server's dangling single-entry dict masks the live replicas. The traversal
# merges every visited node's dictionary subkey-wise (_SearchResult
# .add_candidate), so the resolved ExpertInfo carries the union. Used by
# `get_experts` ONLY — explicit route-building, where the extra hops are off
# the serving hot path. Beam-search leaf resolution runs per forward batch and
# deliberately stays first-fresh (moe/client/beam_search.py): a partial set
# there costs balancing quality for one call, not correctness.
REPLICA_SET_SUFFICIENCY = float("inf")


def make_expert_record(peer_b58: str, compression: Optional[str] = None) -> str:
    """The stored declaration value; compression rides after a ``|``."""
    return f"{peer_b58}{_RECORD_DELIMITER}{compression}" if compression else peer_b58


def parse_expert_record(value) -> Optional[Tuple[PeerID, Optional[str]]]:
    """``(peer_id, compression_or_None)`` from a declaration value, or None if
    the value is malformed (DHT records are remote-supplied)."""
    if not isinstance(value, str):
        return None
    peer_b58, _, compression = value.partition(_RECORD_DELIMITER)
    try:
        return PeerID.from_base58(peer_b58), (compression or None)
    except Exception:
        return None


def parse_expert_replicas(entry_value) -> List[ReplicaInfo]:
    """The replica set from one leaf declaration value, deterministically
    ordered (sorted by peer id). Accepts every wire form: a legacy plain
    ``peer|codec`` string (one replica) or the ISSUE-13 subkey dictionary
    ``{peer_b58: ValueWithExpiration(record)}``. Malformed members are skipped
    — DHT records are remote-supplied."""
    records: List[ReplicaInfo] = []
    if isinstance(entry_value, dict):
        seen = set()
        for _subkey, stored in entry_value.items():
            value = getattr(stored, "value", stored)
            parsed = parse_expert_record(value)
            if parsed is None or parsed[0] in seen:
                continue
            seen.add(parsed[0])
            records.append(ReplicaInfo(*parsed))
        records.sort(key=lambda replica: replica.peer_id.to_base58())
    else:
        parsed = parse_expert_record(entry_value)
        if parsed is not None:
            records.append(ReplicaInfo(*parsed))
    return records


def expert_info_from_entry(uid: ExpertUID, entry_value) -> Optional[ExpertInfo]:
    """Build the resolved :class:`ExpertInfo` (primary = first replica in the
    deterministic order; clients re-select by scorecard latency / seeded rng —
    moe/client/expert.py) from a leaf declaration value, or None if empty or
    malformed."""
    replicas = parse_expert_replicas(entry_value)
    if not replicas:
        return None
    primary = replicas[0]
    return ExpertInfo(uid, primary.peer_id, primary.compression, tuple(replicas))


def declare_experts(
    dht: DHT,
    uids: Sequence[ExpertUID],
    expiration_time: Optional[DHTExpiration] = None,
    wait: bool = True,
    compression: Optional[str] = None,
):
    """Store this peer's experts: for 'ffn.5.12' store subkey 5 under 'ffn.' and
    subkey 12 under 'ffn.5.' plus the leaf record."""
    expiration_time = expiration_time if expiration_time is not None else get_dht_time() + 300
    peer_b58 = dht.peer_id.to_base58()
    record = make_expert_record(peer_b58, compression)

    async def _declare(dht_obj, node):
        keys, values, subkeys, expirations = [], [], [], []
        for uid in uids:
            assert is_valid_uid(uid), f"invalid expert uid {uid!r}"
            # leaf record under this peer's OWN subkey (ISSUE 13): concurrent
            # declarations from several replicas merge subkey-wise into one
            # replica set instead of clobbering each other newest-wins
            keys.append(uid)
            subkeys.append(peer_b58)
            values.append(record)
            expirations.append(expiration_time)
            prefix = uid
            while True:
                prefix, coord = split_uid(prefix)
                keys.append(prefix.rstrip(UID_DELIMITER))
                subkeys.append(coord)
                values.append(record)
                expirations.append(expiration_time)
                if UID_DELIMITER not in prefix.rstrip(UID_DELIMITER):
                    break  # reached the grid root (e.g. 'ffn_test')
        return await node.store_many(keys, values, expirations, subkeys=subkeys)

    result = dht.run_coroutine(_declare, return_future=not wait)
    return result


def get_experts(
    dht: DHT, uids: Sequence[ExpertUID], expiration_time: Optional[DHTExpiration] = None, wait: bool = True
):
    """Resolve expert UIDs to ExpertInfo(uid, peer_id, compression) (or None if
    not found)."""

    async def _get(dht_obj, node) -> List[Optional[ExpertInfo]]:
        found = await node.get_many(
            list(uids), sufficient_expiration_time=REPLICA_SET_SUFFICIENCY
        )
        out: List[Optional[ExpertInfo]] = []
        for uid in uids:
            entry = found.get(uid)
            out.append(expert_info_from_entry(uid, entry.value) if entry is not None else None)
        return out

    return dht.run_coroutine(_get, return_future=not wait)
