"""Expert RPC endpoints (capability parity: reference
hivemind/moe/server/connection_handler.py:22-177 — there N forked handler processes;
here one asyncio servicer feeding the task pools directly).

Serving attribution (ISSUE 9): every expert RPC runs inside a ``serving.request``
span — a child of the ``p2p.handle:`` span, which already joined the remote
caller's trace via cross-peer propagation, so the request's phase decomposition
(queue-wait / batch-assembly / compute stamped by the TaskPool, serialize
stamped here) lands in the CALLER's trace and in the process-wide
:data:`~hivemind_tpu.telemetry.serving.SERVING_LEDGER`.

Serving data path (ISSUE 10, the PR 5 playbook applied to this layer):

- **Wire dtype**: responses are serialized with this server's configured
  activation codec (``--activation_compression``; default fp16, ``none`` =
  bit-identical). The choice is published in ``rpc_info`` (and on the DHT via
  the expert declarations), so clients negotiate the same dtype for requests.
- **Off-loop codecs**: request deserialization and response serialization run
  on the shared executor past a small inline threshold — the event-loop
  watchdog proved inline codecs stall RPC dispatch under load (the evidence
  was multi-MB payloads; a ~4 KB decode step stays inline, where the executor
  hop would dominate). The ``serialize_s`` phase accrues the executor
  round-trip when off-loop (queue time included; see docs/observability.md).
- **Scatter-gather responses**: responses leave as spliced
  :class:`~hivemind_tpu.utils.streaming.WireParts` frames — the tensor buffer
  rides into the AEAD as its own buffer instead of being copied into one
  ``SerializeToString`` blob; stream chunks are zero-copy memoryview slices,
  still serialized lazily one tensor at a time.
"""

from __future__ import annotations

import time
from typing import AsyncIterator, Dict, List, Optional

import numpy as np

from hivemind_tpu.compression import (
    CompressionType,
    codec_name,
    deserialize_tensor,
    deserialize_tensor_stream,
    expert_response_parts,
    resolve_activation_codec,
    serialize_tensor,
    split_response_for_wire,
)
from hivemind_tpu.moe.expert_uid import IDEMPOTENT_CONNECTION_RPCS
from hivemind_tpu.moe.server.module_backend import ModuleBackend
from hivemind_tpu.moe.server.task_pool import TaskPool
from hivemind_tpu.p2p import P2P, P2PContext, ServicerBase
from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.telemetry.serving import (
    SERVING_SPAN,
    WIRE_BYTES_RECEIVED,
    WIRE_BYTES_SENT,
    accrue_span_phase,
)
from hivemind_tpu.telemetry.tracing import trace as _trace
from hivemind_tpu.utils.asyncio_utils import run_in_executor
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.streaming import WireParts

logger = get_logger(__name__)

_STREAM_CHUNK = 2**20  # 1 MiB chunks inside stream replies

# payloads below this encode/decode inline: the executor hop would dominate a
# ~4 KB decode step (same rationale and threshold as the client's
# _OFF_LOOP_CODEC_BYTES in moe/client/expert.py — the loop-stall evidence that
# motivated off-loop codecs came from MULTI-MB payloads)
_OFF_LOOP_CODEC_BYTES = 256 * 1024

# cached metric children (one label value per role on this path)
_SERVER_BYTES_SENT = WIRE_BYTES_SENT.labels("server")
_SERVER_BYTES_RECEIVED = WIRE_BYTES_RECEIVED.labels("server")


class ConnectionHandler(ServicerBase):
    # which RPCs may be retried on ambiguous connection loss — shared with the
    # client's direct call sites (expert.py), see expert_uid.py for the rationale
    _idempotent_rpcs = IDEMPOTENT_CONNECTION_RPCS

    def __init__(self, backends: Dict[str, ModuleBackend], decode_max_len: int = 256,
                 decode_max_sessions: int = 64, max_queue_size: int = 1024,
                 activation_compression: str = "float16",
                 client_rate: Optional[float] = None,
                 client_burst: Optional[float] = None):
        from hivemind_tpu.moe.server.decode_session import DecodeSessionManager

        self.backends = backends
        self.activation_codec = resolve_activation_codec(activation_compression)
        self.forward_pools: Dict[str, TaskPool] = {}
        self.backward_pools: Dict[str, TaskPool] = {}
        self._max_queue_size = max_queue_size
        self.decode_sessions = DecodeSessionManager(
            backends, max_len=decode_max_len, max_sessions=decode_max_sessions
        )
        # fair-share admission (ISSUE 13): per-client token buckets ahead of the
        # bounded queues — one hot tenant sheds at its own budget, typed exactly
        # like a queue shed, while other clients keep flowing. Opt-in.
        self.admission = None
        if client_rate:
            from hivemind_tpu.moe.server.admission import FairShareAdmission

            self.admission = FairShareAdmission(client_rate, burst=client_burst)
        for name, backend in backends.items():
            self._register_pools(name, backend)

    def _register_pools(self, name: str, backend: ModuleBackend) -> None:
        self.forward_pools[name] = TaskPool(
            backend.forward, f"{name}_forward", max_batch_size=backend.max_batch_size,
            max_queue_size=self._max_queue_size,
        )
        self.backward_pools[name] = TaskPool(
            backend.backward, f"{name}_backward", max_batch_size=backend.max_batch_size,
            max_queue_size=self._max_queue_size,
        )

    def add_backend(self, uid: str, backend: ModuleBackend) -> List[TaskPool]:
        """Register a backend acquired at runtime (expert replication): pools
        are created here; the caller (Server.add_backend) hands them to the
        Runtime and re-declares. Returns the new pools."""
        if uid in self.backends and uid in self.forward_pools:
            return []
        self.backends[uid] = backend
        self._register_pools(uid, backend)
        return [self.forward_pools[uid], self.backward_pools[uid]]

    def _admit(self, context: P2PContext, tensors, kind: str) -> None:
        """Fair-share gate: draw this request's sample count from the calling
        client's token bucket (raises the typed ClientOverBudgetError shed).
        Runs inside the serving span so sheds stay attributed per client."""
        if self.admission is None:
            return
        cost = 1.0
        if tensors:
            first = tensors[0]
            if getattr(first, "ndim", 0):
                # samples, not requests: batching harder must not dodge the
                # budget. Decode steps are [batch, positions, hid] — charge
                # positions too (a prefill is prompt_len tokens of work).
                cost = float(first.shape[0])
                if getattr(first, "ndim", 0) >= 3:
                    cost *= float(first.shape[1])
        self.admission.admit(str(context.remote_id), cost, kind=kind)

    @property
    def activation_compression(self) -> str:
        """Canonical knob value of this server's wire dtype ("float16", "none", …)."""
        return codec_name(self.activation_codec)

    def all_pools(self) -> List[TaskPool]:
        return list(self.forward_pools.values()) + list(self.backward_pools.values())

    @staticmethod
    def _serving_trace(kind: str, uid: str, context: P2PContext, tensors=None) -> _trace:
        """The per-request serving span (ServingLedger assembles one record per
        finished span; see telemetry/serving.py). ``client`` is the remote
        caller — per-client attribution rides every record."""
        attributes = {
            "kind": kind,
            "expert": uid,
            "peer": str(context.local_id),
            "client": str(context.remote_id),
        }
        if tensors:
            first = tensors[0]
            if getattr(first, "ndim", 0):
                attributes["batch"] = int(first.shape[0])
        return _trace(SERVING_SPAN, **attributes)

    # ------------------------------------------------------------------ RPCs

    async def rpc_info(self, request: runtime_pb2.ExpertUID, context: P2PContext) -> runtime_pb2.ExpertInfoResponse:
        backend = self.backends.get(request.uid)
        if backend is None:
            raise KeyError(f"unknown expert {request.uid!r}")
        info = backend.get_info()
        info["span_support"] = True  # clients only group co-located blocks if set
        # wire-dtype negotiation (ISSUE 10): clients serialize their request
        # activations with the server's declared codec (NONE stays bit-identical)
        info["activation_compression"] = self.activation_compression
        if self.decode_sessions.supports(request.uid):
            info["decode_max_len"] = self.decode_sessions.max_len
        return runtime_pb2.ExpertInfoResponse(serialized_info=MSGPackSerializer.dumps(info))

    async def _run_forward(self, uid: str, tensors: List[np.ndarray]) -> List[np.ndarray]:
        pool = self.forward_pools.get(uid)
        if pool is None:
            raise KeyError(f"unknown expert {uid!r}")
        backend = self.backends[uid]
        assert len(tensors) == backend.num_inputs, (
            f"expert {uid!r} takes {backend.num_inputs} tensors, got {len(tensors)}"
        )
        return await pool.submit_task(*tensors)

    async def _run_backward(self, uid: str, tensors: List[np.ndarray]) -> List[np.ndarray]:
        pool = self.backward_pools.get(uid)
        if pool is None:
            raise KeyError(f"unknown expert {uid!r}")
        backend = self.backends[uid]
        expected = backend.num_inputs + backend.num_outputs
        assert len(tensors) == expected, (
            f"expert {uid!r} backward takes {expected} tensors (inputs + output grads), got {len(tensors)}"
        )
        return await pool.submit_task(*tensors)

    def _span_uids(self, uid: str, metadata: bytes) -> List[str]:
        """Span execution: request metadata may name CONSECUTIVE co-located blocks
        (``{"uids": [...]}`` starting with the request uid) to run as one chain —
        per-call round-trips for a pipeline drop from #blocks to #servers."""
        meta = MSGPackSerializer.loads(metadata) if metadata else {}
        uids = meta.get("uids") or [uid]
        if uids[0] != uid:
            raise ValueError(f"span uids must start with the request uid {uid!r}, got {uids!r}")
        for prev, nxt in zip(uids, uids[1:]):
            prev_backend, next_backend = self.backends.get(prev), self.backends.get(nxt)
            if prev_backend is None or next_backend is None:
                raise KeyError(f"unknown expert in span: {prev!r} or {nxt!r}")
            if prev_backend.num_outputs != next_backend.num_inputs:
                raise ValueError(
                    f"span chain mismatch: {prev!r} outputs {prev_backend.num_outputs} "
                    f"tensors but {nxt!r} takes {next_backend.num_inputs}"
                )
        return uids

    async def _run_forward_span(self, uids: List[str], tensors: List[np.ndarray]) -> List[np.ndarray]:
        for span_uid in uids:
            tensors = await self._run_forward(span_uid, tensors)
        return tensors

    async def _run_backward_span(self, uids: List[str], tensors: List[np.ndarray]) -> List[np.ndarray]:
        """Chained backward: recover each block's inputs with a forward sweep, then
        backpropagate block by block in reverse (every block's backward also steps
        its optimizer — same semantics as per-block RPCs)."""
        first = self.backends[uids[0]]
        block_inputs, current = [], tensors[: first.num_inputs]
        for span_uid in uids:
            block_inputs.append(current)
            if span_uid != uids[-1]:
                current = await self._run_forward(span_uid, current)
        grads = tensors[first.num_inputs:]
        for span_uid, inputs in zip(reversed(uids), reversed(block_inputs)):
            grads = await self._run_backward(span_uid, [*inputs, *grads])
        return grads

    # ------------------------------------------------------------------ codecs

    async def _deserialize_request(self, tensors) -> List[np.ndarray]:
        """Parse request tensors; big payloads decode off the event loop (the
        watchdog showed inline deserialization stalling dispatch under load),
        small ones inline (the executor hop would dominate them)."""
        if not tensors:
            return []
        tensor_list = list(tensors)
        if sum(len(t.buffer) for t in tensor_list) < _OFF_LOOP_CODEC_BYTES:
            return [deserialize_tensor(t) for t in tensor_list]
        return await run_in_executor(lambda: [deserialize_tensor(t) for t in tensor_list])

    def _serialize_outputs(self, outputs: List[np.ndarray]) -> List[runtime_pb2.Tensor]:
        # allow_inplace: each output row range is private to its task (views of
        # the fresh device-transfer batch), so the fp16 clip may reuse it
        return [serialize_tensor(o, self.activation_codec, None, True) for o in outputs]

    async def _respond(self, outputs: List[np.ndarray]) -> WireParts:
        """Serialize the response with the server's wire dtype (off-loop past
        the inline threshold), accrue the serialize phase onto the active
        serving span, and frame the tensors scatter-gather (buffers uncopied
        to the AEAD)."""
        start = time.perf_counter()
        if sum(int(getattr(o, "nbytes", 0)) for o in outputs) < _OFF_LOOP_CODEC_BYTES:
            serialized = self._serialize_outputs(outputs)
        else:
            serialized = await run_in_executor(self._serialize_outputs, outputs)
        accrue_span_phase("serialize_s", time.perf_counter() - start)
        response = expert_response_parts(serialized)
        _SERVER_BYTES_SENT.inc(response.nbytes)
        return response

    async def rpc_forward(self, request: runtime_pb2.ExpertRequest, context: P2PContext) -> runtime_pb2.ExpertResponse:
        _SERVER_BYTES_RECEIVED.inc(request.ByteSize())
        inputs = await self._deserialize_request(request.tensors)
        with self._serving_trace("forward", request.uid, context, inputs) as span:
            self._admit(context, inputs, "forward")
            uids = self._span_uids(request.uid, request.metadata)
            if span is not None and len(uids) > 1:
                span.set("span_len", len(uids))
            outputs = await self._run_forward_span(uids, inputs)
            return await self._respond(outputs)

    async def rpc_backward(self, request: runtime_pb2.ExpertRequest, context: P2PContext) -> runtime_pb2.ExpertResponse:
        _SERVER_BYTES_RECEIVED.inc(request.ByteSize())
        inputs = await self._deserialize_request(request.tensors)
        with self._serving_trace("backward", request.uid, context, inputs) as span:
            self._admit(context, inputs, "backward")
            uids = self._span_uids(request.uid, request.metadata)
            if span is not None and len(uids) > 1:
                span.set("span_len", len(uids))
            grads = await self._run_backward_span(uids, inputs)
            return await self._respond(grads)

    async def _run_decode(self, uid: str, metadata: bytes, tensors: List[np.ndarray]) -> np.ndarray:
        meta = MSGPackSerializer.loads(metadata) if metadata else {}
        session_id = meta.get("session_id")
        if not session_id:
            raise ValueError("rpc_decode requires a session_id in request metadata")
        [x] = tensors
        # span execution: chain consecutive co-located pipeline blocks' session
        # steps in ONE rpc; each per-uid step still goes through decode_async, so
        # cross-client continuous batching applies at every block of the span
        uids = self._span_uids(uid, metadata)
        reset = bool(meta.get("reset", False))
        for span_uid in uids:
            step_start = time.perf_counter()
            x = await self.decode_sessions.decode_async(span_uid, str(session_id), x, reset)
            # decode bypasses the pools: the whole session step (incl. the
            # continuous-batching flush window) is the compute phase
            accrue_span_phase("compute_s", time.perf_counter() - step_start)
        return x

    async def rpc_decode(self, request: runtime_pb2.ExpertRequest, context: P2PContext) -> runtime_pb2.ExpertResponse:
        """One KV-cache session step (decode_session.py). Metadata carries
        ``{"session_id": str, "reset": bool}``; sessions bypass the batching
        pools — each holds its own per-client device cache."""
        _SERVER_BYTES_RECEIVED.inc(request.ByteSize())
        tensors = await self._deserialize_request(request.tensors)
        with self._serving_trace("decode", request.uid, context, tensors):
            self._admit(context, tensors, "decode")
            output = await self._run_decode(request.uid, request.metadata, tensors)
            return await self._respond([output])

    async def rpc_replica_state(
        self, request: runtime_pb2.ExpertUID, context: P2PContext
    ) -> AsyncIterator[runtime_pb2.ExpertResponse]:
        """Expert replication transfer (ISSUE 13): stream this expert's
        construction spec + full ``state_dict`` blob to a peer acquiring a
        replica. First message carries msgpack metadata (spec, byte length,
        blake2b digest); the blob follows in 1 MiB chunks riding Tensor
        buffers. Backends without a ``replication_spec`` (e.g. checkpoint-
        loaded Llama blocks) refuse — they replicate by loading the same
        checkpoint, not over RPC."""
        import hashlib

        backend = self.backends.get(request.uid)
        if backend is None:
            raise KeyError(f"unknown expert {request.uid!r}")
        spec = getattr(backend, "replication_spec", None)
        if spec is None:
            raise ValueError(
                f"expert {request.uid!r} carries no replication spec; "
                f"replicate it from its source checkpoint instead"
            )
        blob = await run_in_executor(backend.state_dict)
        digest = hashlib.blake2b(blob, digest_size=16).hexdigest()
        yield runtime_pb2.ExpertResponse(
            metadata=MSGPackSerializer.dumps({
                "spec": dict(spec),
                "total_bytes": len(blob),
                "digest": digest,
            })
        )
        view = memoryview(blob)
        for offset in range(0, len(blob), _STREAM_CHUNK):
            chunk = bytes(view[offset:offset + _STREAM_CHUNK])
            _SERVER_BYTES_SENT.inc(len(chunk))
            yield runtime_pb2.ExpertResponse(
                tensors=[runtime_pb2.Tensor(buffer=chunk, dtype="uint8")]
            )

    # NOTE on the stream RPCs below: the serving span must not wrap a `yield`
    # (an async generator's body runs in its consumer's context), so it closes
    # after compute and the response chunks then serialize LAZILY, one tensor
    # at a time — a multi-hundred-MB streamed response must never be
    # materialized whole. Stream kinds therefore carry no `serialize_s` phase.

    async def rpc_decode_stream(
        self, requests: AsyncIterator[runtime_pb2.ExpertRequest], context: P2PContext
    ) -> AsyncIterator[runtime_pb2.ExpertResponse]:
        """Streaming variant for prefill chunks over the unary payload cap."""
        with self._serving_trace("decode_stream", "?", context) as span:
            uid, metadata, tensors = await self._collect_stream_with_metadata(requests)
            if span is not None:
                span.set("expert", uid)
                if tensors and getattr(tensors[0], "ndim", 0):
                    span.set("batch", int(tensors[0].shape[0]))
            self._admit(context, tensors, "decode")
            output = await self._run_decode(uid, metadata, tensors)
        async for message in self._stream_response([output]):
            yield message

    async def rpc_forward_stream(
        self, requests: AsyncIterator[runtime_pb2.ExpertRequest], context: P2PContext
    ) -> AsyncIterator[runtime_pb2.ExpertResponse]:
        with self._serving_trace("forward_stream", "?", context) as span:
            uid, metadata, tensors = await self._collect_stream_with_metadata(requests)
            if span is not None:
                span.set("expert", uid)
                if tensors and getattr(tensors[0], "ndim", 0):
                    span.set("batch", int(tensors[0].shape[0]))
            self._admit(context, tensors, "forward")
            outputs = await self._run_forward_span(self._span_uids(uid, metadata), tensors)
        async for message in self._stream_response(outputs):
            yield message

    async def rpc_backward_stream(
        self, requests: AsyncIterator[runtime_pb2.ExpertRequest], context: P2PContext
    ) -> AsyncIterator[runtime_pb2.ExpertResponse]:
        with self._serving_trace("backward_stream", "?", context) as span:
            uid, metadata, tensors = await self._collect_stream_with_metadata(requests)
            if span is not None:
                span.set("expert", uid)
                if tensors and getattr(tensors[0], "ndim", 0):
                    span.set("batch", int(tensors[0].shape[0]))
            self._admit(context, tensors, "backward")
            grads = await self._run_backward_span(self._span_uids(uid, metadata), tensors)
        async for message in self._stream_response(grads):
            yield message

    async def _collect_stream_with_metadata(self, requests: AsyncIterator[runtime_pb2.ExpertRequest]):
        """Collect a streamed request: uid + first message's metadata + tensors.
        Chunk reassembly/deserialization runs off-loop (one tensor at a time,
        as the chunks arrive)."""
        uid = None
        metadata = b""

        async def parts():
            nonlocal uid, metadata
            async for request in requests:
                _SERVER_BYTES_RECEIVED.inc(request.ByteSize())
                if uid is None and request.uid:
                    uid = request.uid
                if not metadata and request.metadata:
                    metadata = request.metadata
                yield list(request.tensors)

        tensors = await deserialize_tensor_stream(parts(), off_loop=True)
        if uid is None:
            # wire input from a remote peer: a proper error the client can read
            # (an assert would vanish under -O and crash as a bare AssertionError)
            raise ValueError("streamed expert request carried no expert uid")
        return uid, metadata, tensors

    async def _stream_response(self, outputs: List[np.ndarray]):
        """Lazy streamed response: each tensor serializes off-loop (with the
        server's wire dtype) only when its turn comes, and its chunks are
        zero-copy memoryview slices framed scatter-gather."""
        for out in outputs:
            if int(getattr(out, "nbytes", 0)) < _OFF_LOOP_CODEC_BYTES:
                serialized = serialize_tensor(out, self.activation_codec, None, True)
            else:
                serialized = await run_in_executor(
                    serialize_tensor, out, self.activation_codec, None, True
                )
            for chunk in split_response_for_wire(serialized, _STREAM_CHUNK):
                _SERVER_BYTES_SENT.inc(chunk.nbytes)
                yield chunk
