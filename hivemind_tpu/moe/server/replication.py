"""Hot-expert replication (ISSUE 13): the control loop that turns one-server
experts into replica sets.

The loop has two halves, both periodic and both driven by data the serving
stack already produces:

- **Advertise** (every server): experts whose recent ServingLedger QPS or
  batch occupancy crosses the policy thresholds — and whose DHT replica set is
  still below ``max_replicas`` — are advertised under the well-known key
  ``replica_wanted.<grid_root>`` (subkey = expert uid, value = the advertising
  server's ``peer|codec`` record, short expiration). The advert names exactly
  where a volunteer can fetch the weights.
- **Acquire** (servers started with ``replica_slots > 0``): watched grids'
  ``replica_wanted`` records are scanned; for each wanted uid this server does
  not already host (and whose replica set is still short), the expert's
  construction spec + ``state_dict`` blob stream over the source server's
  ``rpc_replica_state`` (digest-verified), a fresh ModuleBackend is built from
  the layer registry, registered live into the ConnectionHandler/Runtime
  (``Server.add_backend``), and declared — from that declaration on, clients
  resolve a multi-value replica set and start balancing/hedging across it.

Replication is *serving* capacity: an acquired replica answers rpc_forward /
rpc_decode with bit-equal weights at acquisition time. Backward traffic keeps
training whichever replica it lands on (replicas drift like any two
data-parallel workers between averaging rounds); training-grade consistency
remains the averager's job, not this loop's.
"""

from __future__ import annotations

import asyncio
import hashlib
import time
from typing import Dict, List, NamedTuple, Optional, Sequence

from hivemind_tpu.moe.expert_uid import UID_DELIMITER
from hivemind_tpu.proto import runtime_pb2
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.serving import SERVING_LEDGER
from hivemind_tpu.utils.asyncio_utils import aiter_with_timeout, run_in_executor, spawn
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)

REPLICA_WANTED_PREFIX = "replica_wanted."

_HOT_EXPERTS = _TELEMETRY.gauge(
    "hivemind_moe_replication_hot_experts",
    "locally served experts currently over the replication policy's QPS/occupancy thresholds",
)
_ADVERTS = _TELEMETRY.counter(
    "hivemind_moe_replication_adverts_total",
    "replica_wanted adverts stored for hot local experts",
)
_ACQUIRED = _TELEMETRY.counter(
    "hivemind_moe_replication_acquired_total",
    "expert replicas acquired over rpc_replica_state and registered live",
)


class ReplicationPolicy(NamedTuple):
    """When is an expert hot, and how far may it replicate."""

    qps_threshold: float = 4.0       # recent requests/s that make an expert hot
    occupancy_threshold: float = 0.5  # or: mean device-batch occupancy this hot
    max_replicas: int = 2            # replica-set ceiling (adverts stop here)
    period: float = 10.0             # control-loop cadence, seconds


def grid_root(uid: str) -> str:
    return uid.split(UID_DELIMITER, 1)[0]


def build_backend_from_spec(uid: str, spec: Dict, blob: bytes):
    """Reconstruct a donor's expert from its replication spec + state blob:
    module from the layer registry, weights/optimizer state from the verified
    ``state_dict`` stream (bit-equal to the donor at transfer time)."""
    import optax

    from hivemind_tpu.moe.server.layers import name_to_block, name_to_input
    from hivemind_tpu.moe.server.module_backend import ModuleBackend

    import flax.serialization

    expert_cls = spec["expert_cls"]
    hidden_dim = int(spec["hidden_dim"])
    module = name_to_block[expert_cls](hidden_dim, **(spec.get("expert_kwargs") or {}))
    sample = name_to_input[expert_cls](4, hidden_dim)
    sample_kwargs = (
        {"sample_inputs": sample} if isinstance(sample, tuple) else {"sample_input": sample}
    )
    backend = ModuleBackend(
        uid, module, optimizer=optax.adam(1e-3), **sample_kwargs,
        max_batch_size=int(spec.get("max_batch_size", 4096)),
    )
    # template-free restore: only the PARAMS move (serving capacity) — the
    # donor's optimizer state has whatever structure its optim_factory chose,
    # which this server cannot reconstruct; load_params restarts optimizer
    # statistics for the transferred weights (module_backend.py semantics)
    restored = flax.serialization.msgpack_restore(blob)
    backend.load_params(restored["params"])
    backend.update_count = int(restored.get("updates", 0))
    backend.replication_spec = dict(spec)
    return backend


async def fetch_replica_state(p2p, source_peer_id, uid: str, chunk_timeout: float = 30.0):
    """Stream ``rpc_replica_state`` from the donor; returns ``(spec, blob)``
    after digest verification (a truncated/corrupt transfer never builds a
    backend)."""
    stream = p2p.iterate_protobuf_handler(
        source_peer_id,
        "ConnectionHandler.rpc_replica_state",
        runtime_pb2.ExpertUID(uid=uid),
        runtime_pb2.ExpertResponse,
    )
    meta: Optional[Dict] = None
    chunks: List[bytes] = []
    # chunk_timeout bounds each INTER-CHUNK gap (a stalled donor must fail the
    # fetch, not wedge it forever) while leaving total transfer time unbounded
    async for message in aiter_with_timeout(stream, chunk_timeout):
        if meta is None:
            meta = MSGPackSerializer.loads(message.metadata)
            continue
        for tensor in message.tensors:
            chunks.append(tensor.buffer)
    if meta is None:
        raise ConnectionError(f"replica state stream for {uid!r} ended before metadata")
    blob = b"".join(chunks)
    if len(blob) != int(meta["total_bytes"]):
        raise ConnectionError(
            f"replica state for {uid!r} truncated: {len(blob)}/{meta['total_bytes']} bytes"
        )
    digest = hashlib.blake2b(blob, digest_size=16).hexdigest()
    if digest != meta["digest"]:
        raise ValueError(f"replica state for {uid!r} failed digest verification")
    return meta["spec"], blob


class ReplicationManager:
    """One per Server (started from ``Server._start`` when replication is on);
    runs on the server's event loop."""

    def __init__(
        self,
        server,
        *,
        replica_slots: int = 0,
        policy: Optional[ReplicationPolicy] = None,
        watch_grids: Optional[Sequence[str]] = None,
    ):
        self.server = server
        self.replica_slots = replica_slots
        self.policy = policy or ReplicationPolicy()
        self._explicit_watch = list(watch_grids) if watch_grids is not None else None
        self.acquired: List[str] = []
        self._last_requests: Dict[str, float] = {}
        self._last_check: Optional[float] = None
        self._task: Optional[asyncio.Task] = None

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._task = spawn(self._loop(), name="replication.loop")

    def shutdown(self) -> None:
        if self._task is not None:
            self._task.cancel()

    async def _loop(self) -> None:
        while True:
            try:
                await self.tick()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                logger.warning(f"replication tick failed: {e!r}")
            await asyncio.sleep(self.policy.period)

    def watched_grids(self) -> List[str]:
        if self._explicit_watch is not None:
            return self._explicit_watch
        return sorted({grid_root(uid) for uid in self.server.backends})

    # ------------------------------------------------------------ hot detection

    def hot_experts(self) -> List[str]:
        """Locally served experts over the policy thresholds, judged on the
        ServingLedger: QPS as the request-count delta since the last tick, and
        mean device-batch occupancy over the recent record window."""
        stats = SERVING_LEDGER.expert_stats()
        now = time.monotonic()
        interval = (now - self._last_check) if self._last_check is not None else None
        self._last_check = now
        occupancy: Dict[str, List[float]] = {}
        for record in SERVING_LEDGER.records(limit=128):
            if "occupancy" in record:
                occupancy.setdefault(record["expert"], []).append(float(record["occupancy"]))
        hot = []
        for uid, entry in stats.items():
            if uid not in self.server.backends:
                continue
            requests = float(entry.get("requests", 0))
            previous = self._last_requests.get(uid, requests if interval is None else 0.0)
            self._last_requests[uid] = requests
            if interval is None or interval <= 0:
                continue
            qps = max(requests - previous, 0.0) / interval
            mean_occupancy = 0.0
            if occupancy.get(uid):
                mean_occupancy = sum(occupancy[uid]) / len(occupancy[uid])
            if qps >= self.policy.qps_threshold or (
                qps > 0 and mean_occupancy >= self.policy.occupancy_threshold
            ):
                hot.append(uid)
        _HOT_EXPERTS.set(len(hot))
        return hot

    # ------------------------------------------------------------ control loop

    async def tick(self) -> None:
        hot = self.hot_experts()
        if hot:
            await self._advertise(hot)
        if self.replica_slots > len(self.acquired):
            await self._acquire_one()

    async def _replica_counts(self, uids: Sequence[str]) -> Dict[str, int]:
        from hivemind_tpu.moe.server.dht_handler import parse_expert_replicas

        async def _count(_dht, node):
            found = await node.get_many(list(uids))
            out = {}
            for uid in uids:
                entry = found.get(uid)
                out[uid] = len(parse_expert_replicas(entry.value)) if entry is not None else 0
            return out

        return await asyncio.wrap_future(
            self.server.dht.run_coroutine(_count, return_future=True)
        )

    async def _advertise(self, hot: Sequence[str]) -> None:
        """Store replica_wanted adverts for hot experts still short of
        max_replicas (the DHT read doubles as the replica-count check)."""
        from hivemind_tpu.moe.server.dht_handler import make_expert_record

        counts = await self._replica_counts(hot)
        wanted = [uid for uid in hot if counts.get(uid, 0) < self.policy.max_replicas]
        if not wanted:
            return
        record = make_expert_record(
            self.server.dht.peer_id.to_base58(),
            self.server.handler.activation_compression,
        )
        expiration = get_dht_time() + self.policy.period * 3
        keys = [REPLICA_WANTED_PREFIX + grid_root(uid) for uid in wanted]

        async def _store(_dht, node):
            return await node.store_many(
                keys, [record] * len(wanted), [expiration] * len(wanted),
                subkeys=list(wanted),
            )

        await asyncio.wrap_future(self.server.dht.run_coroutine(_store, return_future=True))
        _ADVERTS.inc(len(wanted))
        logger.info(f"advertised replica_wanted for hot experts: {wanted}")

    async def _acquire_one(self) -> None:
        """Scan watched grids' adverts; acquire the first wanted expert this
        server does not already host (one per tick — acquisition moves weights)."""
        from hivemind_tpu.moe.server.dht_handler import parse_expert_record

        grids = self.watched_grids()
        if not grids:
            return

        async def _scan(_dht, node):
            found = await node.get_many([REPLICA_WANTED_PREFIX + grid for grid in grids])
            wanted = {}
            for entry in found.values():
                if entry is None or not isinstance(entry.value, dict):
                    continue
                for subkey, stored in entry.value.items():
                    value = getattr(stored, "value", stored)
                    parsed = parse_expert_record(value)
                    if parsed is not None and isinstance(subkey, str):
                        wanted[subkey] = parsed
            return wanted

        wanted = await asyncio.wrap_future(self.server.dht.run_coroutine(_scan, return_future=True))
        candidates = {
            uid: source for uid, source in wanted.items()
            if uid not in self.server.backends and source[0] != self.server.dht.peer_id
        }
        if not candidates:
            return
        counts = await self._replica_counts(sorted(candidates))
        for uid in sorted(candidates):
            if counts.get(uid, 0) >= self.policy.max_replicas:
                continue
            source_peer, _compression = candidates[uid]
            try:
                p2p = await self.server.dht.replicate_p2p()
                spec, blob = await fetch_replica_state(p2p, source_peer, uid)
                backend = await run_in_executor(build_backend_from_spec, uid, spec, blob)
            except Exception as e:
                logger.warning(f"could not acquire replica of {uid!r} from {source_peer}: {e!r}")
                continue
            await self.server.add_backend(uid, backend)
            self.acquired.append(uid)  # lint: single-writer — only the replication loop appends
            _ACQUIRED.inc()
            logger.info(
                f"acquired replica of {uid!r} from {source_peer} "
                f"({len(blob)} state bytes, digest-verified); now serving + declared"
            )
            return
