"""The expert server (capability parity: reference hivemind/moe/server/server.py:35-411).

Owns: a DHT peer, ModuleBackends, the batching Runtime, the RPC handler, a periodic
expert-declaration task, and optionally a CheckpointSaver — all asyncio components in
one process (the reference forks handlers and pools; SURVEY §1 'process model')."""

from __future__ import annotations

import asyncio
import contextlib
import random
import threading
from pathlib import Path
from typing import Dict, List, Optional, Sequence

import numpy as np

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.expert_uid import UID_DELIMITER, is_valid_prefix, is_valid_uid
from hivemind_tpu.moe.server.checkpoints import CheckpointSaver, load_experts
from hivemind_tpu.moe.server.connection_handler import ConnectionHandler
from hivemind_tpu.moe.server.dht_handler import declare_experts, get_experts
from hivemind_tpu.moe.server.layers import name_to_block, name_to_input
from hivemind_tpu.moe.server.module_backend import ModuleBackend
from hivemind_tpu.moe.server.runtime import Runtime
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.loop import LoopRunner, get_loop_runner
from hivemind_tpu.utils.timed_storage import get_dht_time

logger = get_logger(__name__)


class Server:
    """Create with Server.create(...); call .run_in_background() / .shutdown()."""

    def __init__(
        self,
        dht: DHT,
        backends: Dict[str, ModuleBackend],
        *,
        update_period: float = 30.0,
        checkpoint_dir: Optional[Path] = None,
        decode_max_len: int = 256,
        decode_max_sessions: int = 64,
        max_queue_size: int = 1024,
        activation_compression: str = "float16",
        client_rate: Optional[float] = None,
        client_burst: Optional[float] = None,
        replica_slots: int = 0,
        replicate_hot_experts: bool = False,
        replication_policy=None,
        replication_watch_grids: Optional[Sequence[str]] = None,
        loop_runner: Optional[LoopRunner] = None,
    ):
        self.dht, self.backends = dht, backends
        self.update_period = update_period
        self.handler = ConnectionHandler(
            backends, decode_max_len=decode_max_len, decode_max_sessions=decode_max_sessions,
            max_queue_size=max_queue_size, activation_compression=activation_compression,
            client_rate=client_rate, client_burst=client_burst,
        )
        self.runtime = Runtime(self.handler.all_pools())
        self.checkpoint_saver = (
            CheckpointSaver(backends, checkpoint_dir) if checkpoint_dir is not None else None
        )
        # hot-expert replication (ISSUE 13): advertise hot local experts and/or
        # acquire other servers' hot experts into spare replica slots
        self.replication = None
        if replicate_hot_experts or replica_slots > 0:
            from hivemind_tpu.moe.server.replication import ReplicationManager

            self.replication = ReplicationManager(
                self, replica_slots=replica_slots, policy=replication_policy,
                watch_grids=replication_watch_grids,
            )
        self._runner = loop_runner if loop_runner is not None else get_loop_runner()
        self._declare_task: Optional[asyncio.Task] = None
        self._ready = threading.Event()

    @classmethod
    def create(
        cls,
        *,
        num_experts: Optional[int] = None,
        expert_uids: Optional[Sequence[str]] = None,
        expert_pattern: Optional[str] = None,
        expert_cls: str = "ffn",
        hidden_dim: int = 1024,
        expert_kwargs: Optional[dict] = None,
        optim_factory=None,
        max_batch_size: int = 4096,
        initial_peers: Sequence[str] = (),
        dht: Optional[DHT] = None,
        checkpoint_dir: Optional[Path] = None,
        decode_max_len: int = 256,
        decode_max_sessions: int = 64,
        max_queue_size: int = 1024,
        activation_compression: str = "float16",
        client_rate: Optional[float] = None,
        client_burst: Optional[float] = None,
        replica_slots: int = 0,
        replicate_hot_experts: bool = False,
        replication_policy=None,
        replication_watch_grids: Optional[Sequence[str]] = None,
        start: bool = False,
        **backend_kwargs,
    ) -> "Server":
        """Build a server with experts from the layer registry; UIDs are either given
        or sampled from ``expert_pattern`` (e.g. 'ffn.[0:256].[0:256]') and
        deduplicated against the DHT (reference server.py:351-411).

        ``expert_kwargs`` are forwarded to the expert class constructor — e.g.
        ``expert_cls='llama_block', expert_kwargs={'num_kv_heads': 2}`` serves
        grouped-query Llama blocks."""
        import optax

        if dht is None:
            dht = DHT(initial_peers=initial_peers, start=True)
        if expert_uids is None:
            if num_experts is None and replica_slots > 0:
                expert_uids = []  # replica-only volunteer: starts empty, acquires hot experts
            else:
                assert num_experts is not None, "provide either expert_uids or num_experts"
                expert_uids = _generate_uids(num_experts, expert_pattern or f"expert.[0:{2**30}]", dht)
        optim_factory = optim_factory or (lambda: optax.adam(1e-3))

        backends = {}
        for uid in expert_uids:
            module = name_to_block[expert_cls](hidden_dim, **(expert_kwargs or {}))
            sample = name_to_input[expert_cls](4, hidden_dim)
            # multi-tensor experts (e.g. det_dropout) declare a tuple of inputs
            sample_kwargs = (
                {"sample_inputs": sample} if isinstance(sample, tuple) else {"sample_input": sample}
            )
            backends[uid] = ModuleBackend(
                uid, module, optimizer=optim_factory(), **sample_kwargs,
                max_batch_size=max_batch_size, **backend_kwargs,
            )
            # registry-built experts are replicable over rpc_replica_state: the
            # spec lets an acquiring server reconstruct the module before
            # loading the transferred state_dict (moe/server/replication.py)
            backends[uid].replication_spec = {
                "expert_cls": expert_cls, "hidden_dim": hidden_dim,
                "expert_kwargs": dict(expert_kwargs or {}),
                "max_batch_size": max_batch_size,
            }
        if checkpoint_dir is not None:
            loaded = load_experts(backends, checkpoint_dir)
            if loaded:
                logger.info(f"restored {loaded} experts from {checkpoint_dir}")
        server = cls(dht, backends, checkpoint_dir=checkpoint_dir, decode_max_len=decode_max_len,
                     decode_max_sessions=decode_max_sessions, max_queue_size=max_queue_size,
                     activation_compression=activation_compression,
                     client_rate=client_rate, client_burst=client_burst,
                     replica_slots=replica_slots, replicate_hot_experts=replicate_hot_experts,
                     replication_policy=replication_policy,
                     replication_watch_grids=replication_watch_grids)
        if start:
            server.run_in_background(await_ready=True)
        return server

    # ------------------------------------------------------------------ lifecycle

    def run_in_background(self, await_ready: bool = True, timeout: Optional[float] = None) -> None:
        future = self._runner.run_coroutine(self._start(), return_future=True)
        if await_ready:
            future.result(timeout)

    async def _start(self) -> None:
        # a stalled loop stops expert RPC dispatch AND batch draining at once:
        # arm the watchdog with the server (idempotent; the DHT shares the loop)
        from hivemind_tpu.telemetry.watchdog import ensure_watchdog

        ensure_watchdog(asyncio.get_event_loop())
        await self.handler.add_p2p_handlers(await self.dht.replicate_p2p())
        self.runtime.start()
        if self.checkpoint_saver is not None:
            self.checkpoint_saver.start()
        if self.replication is not None:
            self.replication.start()
        self._declare_task = spawn(self._declare_periodically(), name="server.declare_periodically")
        self._ready.set()

    async def add_backend(self, uid: str, backend: ModuleBackend) -> None:
        """Register an expert acquired at runtime (replication): handler pools
        + runtime + an immediate declaration, so clients resolve the grown
        replica set without waiting a full update period. Runs on the server
        loop (the ReplicationManager's)."""
        pools = self.handler.add_backend(uid, backend)
        for pool in pools:
            self.runtime.add_pool(pool)
        declare_experts(
            self.dht, [uid],
            expiration_time=get_dht_time() + self.update_period * 3,
            wait=False,
            compression=self.handler.activation_compression,
        )

    async def _declare_periodically(self) -> None:
        while True:
            with contextlib.suppress(Exception):
                declare_experts(
                    self.dht, list(self.backends.keys()),
                    expiration_time=get_dht_time() + self.update_period * 3,
                    wait=False,
                    # the declaration carries the wire dtype, so clients learn
                    # the negotiated codec from discovery alone (ISSUE 10)
                    compression=self.handler.activation_compression,
                )
            await asyncio.sleep(self.update_period)

    def shutdown(self) -> None:
        async def _stop():
            if self._declare_task is not None:
                self._declare_task.cancel()
            if self.replication is not None:
                self.replication.shutdown()
            self.runtime.shutdown()
            if self.checkpoint_saver is not None:
                self.checkpoint_saver.shutdown()
            with contextlib.suppress(Exception):
                await self.handler.remove_p2p_handlers(await self.dht.replicate_p2p())

        with contextlib.suppress(Exception):
            self._runner.run_coroutine(_stop(), return_future=True).result(5.0)

    def __enter__(self):
        if not self._ready.is_set():
            self.run_in_background(await_ready=True)
        return self

    def __exit__(self, *args):
        self.shutdown()


def _generate_uids(num_experts: int, expert_pattern: str, dht: DHT, attempts_per_expert: int = 10) -> List[str]:
    """Sample unique UIDs matching 'prefix.[0:N].[0:M]'-style patterns, skipping UIDs
    already claimed in the DHT (reference server.py:351-411)."""
    import re

    def sample_uid() -> str:
        out = []
        for block in expert_pattern.split(UID_DELIMITER):
            match = re.fullmatch(r"\[(\d+):(\d+)\]", block)
            out.append(str(random.randint(int(match.group(1)), int(match.group(2)) - 1)) if match else block)
        return UID_DELIMITER.join(out)

    chosen: List[str] = []
    attempts = 0
    while len(chosen) < num_experts and attempts < num_experts * attempts_per_expert:
        attempts += 1
        candidates = list({sample_uid() for _ in range(num_experts - len(chosen))} - set(chosen))
        if not candidates:
            continue
        existing = get_experts(dht, candidates)
        for uid, info in zip(candidates, existing):
            if info is None and is_valid_uid(uid):
                chosen.append(uid)
    assert len(chosen) >= num_experts, f"could only allocate {len(chosen)}/{num_experts} unique uids"
    return chosen[:num_experts]


@contextlib.contextmanager
def background_server(**kwargs):
    """Spin up a server for tests/benchmarks; yields (dht, server)
    (reference server.py:308-348)."""
    server = Server.create(start=True, **kwargs)
    try:
        yield server.dht, server
    finally:
        server.shutdown()
        server.dht.shutdown()
