"""Load real (sharded) Llama-family checkpoints into ``llama_block`` serving
backends — the Petals-style block server of BASELINE config #5 (the reference has
no checkpoint loader of its own; Petals, its downstream, loads HF checkpoints into
per-layer block servers the same way).

- **Checkpoint format**: HuggingFace layout — ``config.json`` plus either a single
  ``model.safetensors`` or a sharded set with ``model.safetensors.index.json``.
  Tensors are read lazily per block (one decoder layer at a time), so host memory
  stays ~one block, never the whole model.
- **Weight mapping**: HF ``model.layers.N.self_attn.{q,k,v,o}_proj.weight`` /
  ``mlp.{gate,up,down}_proj.weight`` / ``{input,post_attention}_layernorm.weight``
  map onto :class:`LlamaBlockExpert`'s flax tree (Dense kernels transposed: HF
  stores [out, in]). HF's rotary convention (contiguous-half rotate) matches
  ``apply_rope``, so outputs agree with the original model.
- **Int8 serving**: pass ``weight_quantization="int8"`` to store blocks with the
  repo's blockwise absmax codec (4x less resident HBM; see ops/quantized_params).
- **HBM budgeting**: :func:`plan_block_capacity` decides how many blocks fit one
  chip from measured per-block bytes + decode-session KV budget + headroom.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from hivemind_tpu.utils.logging import get_logger

logger = get_logger(__name__)


@dataclass
class LlamaCheckpointConfig:
    hidden_size: int
    num_attention_heads: int
    num_key_value_heads: int
    intermediate_size: int
    num_hidden_layers: int
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-6  # HF LlamaConfig default; Llama-2 ships 1e-5

    @classmethod
    def load(cls, checkpoint_dir) -> "LlamaCheckpointConfig":
        with open(Path(checkpoint_dir) / "config.json") as f:
            raw = json.load(f)
        return cls(
            hidden_size=int(raw["hidden_size"]),
            num_attention_heads=int(raw["num_attention_heads"]),
            num_key_value_heads=int(raw.get("num_key_value_heads", raw["num_attention_heads"])),
            intermediate_size=int(raw["intermediate_size"]),
            num_hidden_layers=int(raw["num_hidden_layers"]),
            rope_theta=float(raw.get("rope_theta", 10000.0)),
            rms_norm_eps=float(raw.get("rms_norm_eps", 1e-6)),
        )


class ShardedSafetensorsReader:
    """Lazy tensor access over a single- or multi-file safetensors checkpoint."""

    def __init__(self, checkpoint_dir):
        self.dir = Path(checkpoint_dir)
        index_path = self.dir / "model.safetensors.index.json"
        if index_path.exists():
            with open(index_path) as f:
                self.weight_map: Dict[str, str] = json.load(f)["weight_map"]
        else:
            single = self.dir / "model.safetensors"
            if not single.exists():
                raise FileNotFoundError(
                    f"{self.dir} holds neither model.safetensors nor an index"
                )
            from safetensors import safe_open

            with safe_open(single, framework="np") as f:
                self.weight_map = {name: "model.safetensors" for name in f.keys()}
        self._open_files: dict = {}

    def names(self) -> Iterable[str]:
        return self.weight_map.keys()

    def get(self, name: str) -> np.ndarray:
        from safetensors import safe_open

        try:
            filename = self.weight_map[name]
        except KeyError:
            raise KeyError(f"checkpoint has no tensor {name!r}") from None
        handle = self._open_files.get(filename)
        if handle is None:
            handle = self._open_files[filename] = safe_open(
                self.dir / filename, framework="np"
            )
        return np.asarray(handle.get_tensor(name))


def _block_params_from_hf(reader: ShardedSafetensorsReader, layer: int) -> dict:
    """One decoder layer's HF tensors as a LlamaBlockExpert flax param tree."""
    prefix = f"model.layers.{layer}."

    def kernel(hf_name: str) -> dict:
        # HF Linear stores [out_features, in_features]; flax Dense wants [in, out]
        return {"kernel": np.ascontiguousarray(reader.get(prefix + hf_name).T.astype(np.float32))}

    return {
        "query": kernel("self_attn.q_proj.weight"),
        "key": kernel("self_attn.k_proj.weight"),
        "value": kernel("self_attn.v_proj.weight"),
        "attention_out": kernel("self_attn.o_proj.weight"),
        "ffn_gate": kernel("mlp.gate_proj.weight"),
        "ffn_up": kernel("mlp.up_proj.weight"),
        "ffn_down": kernel("mlp.down_proj.weight"),
        "attention_norm": {"scale": reader.get(prefix + "input_layernorm.weight").astype(np.float32)},
        "ffn_norm": {"scale": reader.get(prefix + "post_attention_layernorm.weight").astype(np.float32)},
    }


def load_llama_blocks(
    checkpoint_dir,
    *,
    layers: Optional[Sequence[int]] = None,
    uid_prefix: str = "llama.",
    weight_quantization: Optional[str] = None,
    max_batch_size: int = 64,
    optimizer=None,
    mesh=None,
    shard_axis: str = "tp",
) -> Tuple[Dict[str, "object"], LlamaCheckpointConfig]:
    """Build ``{uid: ModuleBackend}`` serving the checkpoint's decoder layers.

    ``layers`` defaults to all of them; uid = ``f"{uid_prefix}{layer}"`` so a
    ``RemoteSequential(dht, uid_prefix, n)`` client chains them in order. Blocks
    are loaded one at a time (host memory ~= one block). With ``mesh``, each
    block becomes a :class:`MeshModuleBackend` — params and KV caches sharded
    over ``shard_axis``, for blocks one chip cannot hold.
    """
    import optax

    from hivemind_tpu.moe.server.layers import name_to_block
    from hivemind_tpu.moe.server.mesh_backend import MeshModuleBackend
    from hivemind_tpu.moe.server.module_backend import ModuleBackend

    config = LlamaCheckpointConfig.load(checkpoint_dir)
    reader = ShardedSafetensorsReader(checkpoint_dir)
    layers = list(layers) if layers is not None else list(range(config.num_hidden_layers))

    backends: Dict[str, ModuleBackend] = {}
    for layer in layers:
        module = name_to_block["llama_block"](
            config.hidden_size,
            num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads,
            rope_theta=config.rope_theta,
            ffn_inner=config.intermediate_size,
            rms_eps=config.rms_norm_eps,
        )
        common_opts = dict(
            optimizer=optimizer or optax.sgd(0.0),
            sample_input=np.zeros((2, 8, config.hidden_size), np.float32),
            max_batch_size=max_batch_size,
            weight_quantization=weight_quantization,
        )
        if mesh is not None:
            backend = MeshModuleBackend(
                f"{uid_prefix}{layer}", module, mesh=mesh, shard_axis=shard_axis, **common_opts
            )
        else:
            backend = ModuleBackend(f"{uid_prefix}{layer}", module, **common_opts)
        backend.load_params(_block_params_from_hf(reader, layer))
        backends[backend.name] = backend
        logger.info(
            f"loaded block {layer} as {backend.name!r} "
            f"({backend.param_bytes() / 1e6:.1f} MB resident"
            f"{', int8' if weight_quantization else ''})"
        )
    return backends, config


# ---------------------------------------------------------------- HBM budgeting


def predict_block_param_bytes(
    config: LlamaCheckpointConfig, weight_quantization: Optional[str] = None
) -> int:
    """Resident bytes ONE decoder block should cost, from config arithmetic alone —
    the planning input for :func:`plan_block_capacity` BEFORE any weights load
    (VERDICT r3 #8: the prediction is asserted against measured bytes within 10%
    in tests/test_llama_loader.py). Exact model of the storage: fp32 kernels +
    norm scales, or blockwise int8 (codes padded to QUANT_BLOCK_SIZE + one fp32
    absmax per block; 1-D norm scales stay exact fp32)."""
    hid, inner = config.hidden_size, config.intermediate_size
    head_dim = hid // config.num_attention_heads
    kv = config.num_key_value_heads * head_dim
    matrices = [
        hid * hid,   # q_proj
        kv * hid,    # k_proj
        kv * hid,    # v_proj
        hid * hid,   # o_proj
        inner * hid,  # gate_proj
        inner * hid,  # up_proj
        hid * inner,  # down_proj
    ]
    norm_bytes = 2 * hid * 4  # input/post-attention RMSNorm scales, always fp32
    if weight_quantization == "int8":
        from hivemind_tpu.ops.quantized_params import QUANT_BLOCK_SIZE

        total = norm_bytes
        for size in matrices:
            blocks = -(-size // QUANT_BLOCK_SIZE)  # ceil
            total += blocks * QUANT_BLOCK_SIZE + blocks * 4  # int8 codes + fp32 absmax
        return total
    return sum(matrices) * 4 + norm_bytes


def decode_cache_bytes(config: LlamaCheckpointConfig, batch: int, max_len: int) -> int:
    """KV-cache bytes ONE session costs for ONE block (bf16 K + V in the compact
    kv-heads layout — see LlamaBlockExpert.init_decode_cache)."""
    head_dim = config.hidden_size // config.num_attention_heads
    return 2 * 2 * batch * max_len * config.num_key_value_heads * head_dim


def device_hbm_bytes(device=None) -> Optional[int]:
    """The accelerator's memory limit, when the platform reports one (TPU does;
    CPU jax does not — callers then pass an explicit budget)."""
    import jax

    device = device or jax.local_devices()[0]
    try:
        stats = device.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    return None


def plan_block_capacity(
    block_bytes: int,
    *,
    hbm_bytes: Optional[int] = None,
    device=None,
    decode_sessions: int = 0,
    cache_bytes_per_session_block: int = 0,
    reserve_fraction: float = 0.2,
    mesh_devices: int = 1,
) -> int:
    """How many blocks fit the serving unit:
    ``(HBM*devices*(1-reserve) - sessions*cache) / block``.

    ``mesh_devices`` > 1 plans a MESH-sharded server (``MeshModuleBackend``)
    from GLOBAL block bytes only — e.g. pre-load planning via
    ``predict_block_param_bytes`` — by assuming ideal ``1/mesh_devices``
    residency: ``hbm_bytes`` stays the PER-CHIP budget and the pooled budget
    scales with the mesh. When a probe block EXISTS, prefer passing its
    ``param_bytes_per_device()`` as ``block_bytes`` with the default
    ``mesh_devices=1`` instead (run_server does): measured residency also
    counts kernels that REPLICATE because their dims do not divide the mesh.
    Never combine per-device bytes with ``mesh_devices`` > 1 — that multiplies
    the budget while the cost is already divided, overcommitting ~N².

    ``reserve_fraction`` keeps headroom for activations, the transient dense
    weights of int8 serving, and XLA workspace. Returns at least 0.
    """
    if hbm_bytes is None:
        hbm_bytes = device_hbm_bytes(device)
    if hbm_bytes is None:
        raise ValueError(
            "platform does not report a memory limit; pass hbm_bytes explicitly"
        )
    usable = int(hbm_bytes * max(int(mesh_devices), 1) * (1.0 - reserve_fraction))
    per_block = block_bytes + decode_sessions * cache_bytes_per_session_block
    if per_block <= 0:
        return 0
    return max(usable // per_block, 0)


class LlamaClientHead:
    """The client-side ends of a Petals-style pipeline: token embedding in,
    final RMSNorm + LM head out (Petals keeps exactly these on the client while
    the decoder blocks run remotely). Loaded from the same HF checkpoint:
    ``model.embed_tokens.weight``, ``model.norm.weight``, and ``lm_head.weight``
    (absent ⇒ tied with the embedding, as Llama publishes it)."""

    def __init__(self, embed: np.ndarray, norm_scale: np.ndarray, lm_head: np.ndarray,
                 rms_eps: float = 1e-6):
        self.embed_matrix = embed  # [vocab, hid]
        self.norm_scale = norm_scale  # [hid]
        self.lm_head_matrix = lm_head  # [vocab, hid]
        self.rms_eps = rms_eps

    @classmethod
    def load(cls, checkpoint_dir) -> "LlamaClientHead":
        reader = ShardedSafetensorsReader(checkpoint_dir)
        config = LlamaCheckpointConfig.load(checkpoint_dir)
        embed = reader.get("model.embed_tokens.weight").astype(np.float32)
        norm = reader.get("model.norm.weight").astype(np.float32)
        try:
            lm_head = reader.get("lm_head.weight").astype(np.float32)
        except KeyError:
            lm_head = embed  # tied embeddings
        return cls(embed, norm, lm_head, rms_eps=config.rms_norm_eps)

    @property
    def vocab_size(self) -> int:
        return self.embed_matrix.shape[0]

    def embed(self, token_ids: np.ndarray) -> np.ndarray:
        """[batch, seq] int ids -> [batch, seq, hid] fp32 hidden states."""
        return self.embed_matrix[np.asarray(token_ids, np.int64)]

    def logits(self, hidden: np.ndarray) -> np.ndarray:
        """[batch, seq, hid] block-stack output -> [batch, seq, vocab] logits
        (RMSNorm then the LM projection, matching HF's LlamaForCausalLM tail)."""
        hidden = np.asarray(hidden, np.float32)
        rms = np.sqrt(np.mean(hidden**2, axis=-1, keepdims=True) + self.rms_eps)
        normed = hidden / rms * self.norm_scale
        return normed @ self.lm_head_matrix.T


def generate_greedy(
    head: LlamaClientHead,
    pipe,
    prompt_ids: np.ndarray,
    max_new_tokens: int,
    session_id: Optional[str] = None,
) -> np.ndarray:
    """Greedy decoding through a RemoteSequential block pipeline with KV-cache
    sessions: one prefill RPC chain, then one single-token chain per new token
    (the LAST token needs no trailing step — its cache entry would go unread).
    ``session_id`` defaults to a fresh unique id: the server keys sessions
    globally by (uid, session_id), so a shared constant would let concurrent
    generations silently clobber each other's KV caches.
    ``prompt_ids``: [batch, prompt_len]; returns [batch, prompt_len + new]."""
    import uuid

    if session_id is None:
        session_id = f"gen-{uuid.uuid4().hex}"
    prompt = np.asarray(prompt_ids, np.int64)
    # preallocate the full id buffer once: the old per-token
    # np.concatenate([ids, next_ids]) recopied the whole history every step,
    # making generation O(len²) in tokens (ISSUE 10 satellite)
    ids = np.empty((prompt.shape[0], prompt.shape[1] + max_new_tokens), np.int64)
    ids[:, : prompt.shape[1]] = prompt
    hidden = pipe.decode_step(head.embed(prompt), session_id, reset=True)
    for step in range(max_new_tokens):
        next_ids = np.argmax(head.logits(np.asarray(hidden)[:, -1:]), axis=-1)
        ids[:, prompt.shape[1] + step] = next_ids[:, 0]
        if step + 1 < max_new_tokens:
            hidden = pipe.decode_step(head.embed(next_ids), session_id)
    return ids
