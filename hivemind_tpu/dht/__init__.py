from hivemind_tpu.dht.crypto import Ed25519SignatureValidator
from hivemind_tpu.dht.dht import DHT
from hivemind_tpu.dht.node import Blacklist, DHTNode
from hivemind_tpu.dht.protocol import DHTProtocol
from hivemind_tpu.dht.routing import DHTID, DHTKey, PeerInfo, RoutingTable, Subkey
from hivemind_tpu.dht.schema import BytesWithEd25519PublicKey, SchemaValidator
from hivemind_tpu.dht.storage import DHTLocalStorage, DictionaryDHTValue
from hivemind_tpu.dht.validation import CompositeValidator, DHTRecord, RecordValidatorBase
