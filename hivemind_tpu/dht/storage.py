"""Local DHT record storage: plain values and sub-key dictionaries with per-subkey
expiration (capability parity: reference hivemind/dht/storage.py:10-69)."""

from __future__ import annotations

from typing import Optional, Union

from hivemind_tpu.dht.routing import BinaryDHTValue, DHTID, Subkey
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import DHTExpiration, TimedStorage, ValueWithExpiration


@MSGPackSerializer.ext_serializable(0x50)
class DictionaryDHTValue(TimedStorage[Subkey, BinaryDHTValue]):
    """A value that is itself a dictionary of subkey → (value, expiration). Stored
    under one DHT key; merged subkey-by-subkey on conflicting stores."""

    latest_expiration_time: DHTExpiration = -float("inf")

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self.latest_expiration_time = -float("inf")

    def store(self, key: Subkey, value: BinaryDHTValue, expiration_time: DHTExpiration) -> bool:
        self.latest_expiration_time = max(self.latest_expiration_time, expiration_time)
        return super().store(key, value, expiration_time)

    def packb(self) -> bytes:
        items = [[key, value, expiration] for key, (value, expiration) in self.items()]
        return MSGPackSerializer.dumps([self.maxsize, items])

    def packb_as_dict(self) -> bytes:
        """Wire form used by rpc_find: {subkey: (value, expiration)} via msgpack."""
        return MSGPackSerializer.dumps(
            {key: (value, expiration) for key, (value, expiration) in self.items()}
        )

    @classmethod
    def unpackb(cls, data: bytes) -> "DictionaryDHTValue":
        maxsize, items = MSGPackSerializer.loads(data)
        out = cls(maxsize=maxsize)
        for key, value, expiration in items:
            out.store(key, value, expiration)
        return out

    def __eq__(self, other) -> bool:
        if not isinstance(other, DictionaryDHTValue):
            return NotImplemented
        return dict(self.items()) == dict(other.items())


class DHTLocalStorage(TimedStorage[DHTID, Union[BinaryDHTValue, DictionaryDHTValue]]):
    """Storage of one DHT peer: plain binary values and subkey dictionaries
    (reference storage.py:44-69)."""

    def store(
        self, key: DHTID, value: BinaryDHTValue, expiration_time: DHTExpiration
    ) -> bool:
        """Store a plain value. Refuses to overwrite a dictionary with a plain value
        unless the plain value is fresher than everything in it."""
        existing = self.get(key)
        if existing is not None and isinstance(existing.value, DictionaryDHTValue):
            if expiration_time <= existing.value.latest_expiration_time:
                return False
        return super().store(key, value, expiration_time)

    def store_subkey(
        self, key: DHTID, subkey: Subkey, value: BinaryDHTValue, expiration_time: DHTExpiration
    ) -> bool:
        """Add/update one subkey of a dictionary value. A plain value under the same
        key is replaced only if this subkey is fresher (reference storage.py:44-62)."""
        existing = self.get(key)
        if existing is None or not isinstance(existing.value, DictionaryDHTValue):
            if existing is not None and existing.expiration_time >= expiration_time:
                return False  # a fresher plain value wins over the new dictionary entry
            dictionary = DictionaryDHTValue()
            dictionary.store(subkey, value, expiration_time)
            return super().store(key, dictionary, expiration_time)
        dictionary = existing.value
        stored = dictionary.store(subkey, value, expiration_time)
        if stored:
            # re-register the container so the outer expiration tracks the latest subkey
            super().store(key, dictionary, dictionary.latest_expiration_time)
        return stored
