"""The user-facing DHT facade (capability parity: reference hivemind/dht/dht.py:22-337).

The reference forks a daemon process and bridges it over pipes + MPFuture; here the
DHTNode runs on the process-wide event-loop thread (utils/loop.py) and sync callers get
blocking results or concurrent futures. ``run_coroutine`` keeps its role: execute an
arbitrary coroutine *on the DHT's loop* with direct access to the DHTNode (used by MoE
beam search to avoid shipping routing state across contexts).
"""

from __future__ import annotations

import asyncio
from concurrent.futures import Future as ConcurrentFuture
from typing import Any, Awaitable, Callable, Iterable, List, Optional, Sequence, TypeVar, Union

from hivemind_tpu.dht.node import DHTNode
from hivemind_tpu.dht.routing import DHTKey, Subkey
from hivemind_tpu.dht.validation import CompositeValidator, RecordValidatorBase
from hivemind_tpu.p2p import Multiaddr, P2P, PeerID
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.loop import EventLoopShutdownError, LoopRunner, get_loop_runner
from hivemind_tpu.utils.timed_storage import DHTExpiration, ValueWithExpiration, get_dht_time

logger = get_logger(__name__)

ReturnType = TypeVar("ReturnType")


class DHT:
    """Sync facade over an async DHTNode running on a background event loop.

    :param initial_peers: multiaddrs of existing swarm members (empty = start a swarm)
    :param start: if True, start immediately (else call ``.run_in_background()``)
    """

    def __init__(
        self,
        initial_peers: Sequence[Union[str, Multiaddr]] = (),
        *,
        start: bool = False,
        p2p: Optional[P2P] = None,
        record_validators: Iterable[RecordValidatorBase] = (),
        num_workers: int = 4,
        loop_runner: Optional[LoopRunner] = None,
        **kwargs,
    ):
        self.initial_peers = list(initial_peers)
        self.kwargs = kwargs
        self.num_workers = num_workers
        self._record_validator = CompositeValidator(record_validators)
        self._p2p_arg = p2p
        self._node: Optional[DHTNode] = None
        self._runner = loop_runner if loop_runner is not None else get_loop_runner()
        self.is_alive = False
        if start:
            self.run_in_background(await_ready=True)

    # ------------------------------------------------------------------ lifecycle

    def run_in_background(self, await_ready: bool = True, timeout: Optional[float] = None) -> None:
        future = self._runner.run_coroutine(self._create_node(), return_future=True)
        if await_ready:
            future.result(timeout)

    async def _create_node(self) -> None:
        if self._node is not None:
            return
        # a blocked event loop makes this peer look like a network straggler to
        # the whole swarm: watch for stalls from the moment the node exists
        from hivemind_tpu.telemetry.watchdog import ensure_watchdog

        ensure_watchdog(asyncio.get_event_loop())
        self._node = await DHTNode.create(
            p2p=self._p2p_arg,
            initial_peers=self.initial_peers,
            num_workers=self.num_workers,
            record_validator=self._record_validator,
            **self.kwargs,
        )
        self.is_alive = True

    @property
    def node(self) -> DHTNode:
        assert self._node is not None, "DHT is not started; pass start=True or call run_in_background()"
        return self._node

    def shutdown(self) -> None:
        if self._node is not None:
            node, self._node = self._node, None
            self.is_alive = False
            coro = node.shutdown()
            try:
                self._runner.run_coroutine(coro)
            except EventLoopShutdownError:
                coro.close()  # loop already gone: release the un-awaited coroutine
            except Exception as e:
                logger.warning(f"DHT node shutdown raised: {e!r}")

    def __enter__(self) -> "DHT":
        if self._node is None:
            self.run_in_background(await_ready=True)
        return self

    def __exit__(self, *args) -> None:
        self.shutdown()

    def __del__(self):
        try:
            if self.is_alive:
                self.shutdown()
        except Exception:
            pass

    # ------------------------------------------------------------------ API

    def get(
        self, key: DHTKey, latest: bool = False, return_future: bool = False, **kwargs
    ) -> Union[Optional[ValueWithExpiration], ConcurrentFuture]:
        future = self._runner.run_coroutine(self.node.get(key, latest, **kwargs), return_future=True)
        return future if return_future else future.result()

    def store(
        self,
        key: DHTKey,
        value: Any,
        expiration_time: DHTExpiration,
        subkey: Optional[Subkey] = None,
        return_future: bool = False,
        **kwargs,
    ) -> Union[bool, ConcurrentFuture]:
        future = self._runner.run_coroutine(
            self.node.store(key, value, expiration_time, subkey, **kwargs), return_future=True
        )
        return future if return_future else future.result()

    def run_coroutine(
        self,
        coro: Callable[["DHT", DHTNode], Awaitable[ReturnType]],
        return_future: bool = False,
    ) -> Union[ReturnType, ConcurrentFuture]:
        """Execute ``coro(dht, node)`` on the DHT's event loop (reference
        dht.py:240-268 runs it inside the forked daemon)."""

        async def _wrap() -> ReturnType:
            return await coro(self, self.node)

        future = self._runner.run_coroutine(_wrap(), return_future=True)
        return future if return_future else future.result()

    async def replicate_p2p(self) -> P2P:
        """The underlying transport, for components that share this peer's identity
        and connections (averagers, MoE). Async for drop-in parity with the reference
        API (dht.py:320-333 attaches a second daemon client and is awaited at every
        call site); in-process there is exactly one P2P to share."""
        return self.node.p2p

    def add_validators(self, record_validators: Iterable[RecordValidatorBase]) -> None:
        """Merge extra validators; must be called after start (parity with reference
        semantics where validators are extended post-init, dht.py add_validators)."""
        self._record_validator.extend(record_validators)

    def get_visible_maddrs(self, latest: bool = False) -> List[Multiaddr]:
        return self._runner.run_coroutine(self.node.get_visible_maddrs(latest))

    @property
    def peer_id(self) -> PeerID:
        return self.node.peer_id

    def __repr__(self):
        status = "alive" if self.is_alive else "not started"
        return f"DHT({status}, {len(self.initial_peers)} initial peers)"
