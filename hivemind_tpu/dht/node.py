"""DHTNode: one Kademlia participant (capability parity: reference hivemind/dht/node.py:45-937).

Implements bootstrap, beam-search get/store over the swarm, sub-key dictionary records,
response caching with refresh-before-expiry, in-flight request reuse, and a failure
blacklist with exponential backoff. Pure asyncio — runs inside whatever loop owns it
(the DHT facade puts it on the shared loop thread).
"""

from __future__ import annotations

import asyncio
import os
import random
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import (
    Any,
    Awaitable,
    Collection,
    Dict,
    List,
    Optional,
    Sequence,
    Tuple,
    Union,
)

from hivemind_tpu.dht.protocol import DHTProtocol
from hivemind_tpu.dht.routing import BinaryDHTValue, DHTID, DHTKey, PeerInfo, Subkey
from hivemind_tpu.dht.storage import DictionaryDHTValue
from hivemind_tpu.dht.traverse import traverse_dht
from hivemind_tpu.dht.validation import DHTRecord, RecordValidatorBase
from hivemind_tpu.p2p import Multiaddr, P2P, PeerID
from hivemind_tpu.resilience import BreakerBoard, Deadline
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import (
    DHTExpiration,
    TimedStorage,
    ValueWithExpiration,
    get_dht_time,
)

logger = get_logger(__name__)

DEFAULT_NUM_WORKERS = int(os.getenv("HIVEMIND_TPU_DHT_NUM_WORKERS", "4"))

# layer-2 telemetry (docs/observability.md): whole-operation (beam-search level)
# store/get latency as seen by DHT users — distinct from the per-RPC timings in
# dht/protocol.py, which measure single peer round-trips
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY
from hivemind_tpu.telemetry.tracing import (
    finish_span as _finish_span,
    install_span as _install_span,
    start_span as _start_span,
    trace as _tracing_span,
    uninstall_span as _uninstall_span,
)

_DHT_OP_LATENCY = _TELEMETRY.histogram(
    "hivemind_dht_operation_latency_seconds", "store_many/get_many wall time", ("op",)
)
_DHT_STORE_TRAVERSALS_SAVED = _TELEMETRY.counter(
    "hivemind_dht_store_traversals_saved_total",
    "store_many keys that reused another key's beam search because their local "
    "nearest-neighbor sets coincided (bulk republish path)",
)


class Blacklist(BreakerBoard):
    """Tracks unresponsive peers with exponential backoff (reference
    node.py:897-931) — now a thin parameterization of the shared cross-layer
    :class:`~hivemind_tpu.resilience.BreakerBoard` (ISSUE 3): one failure trips
    the peer's breaker open for ``base_time`` seconds, re-trips escalate by
    ``backoff_rate``, and a success after the window (the half-open probe)
    closes it. Trip/probe telemetry rides the shared breaker gauges."""

    def __init__(self, base_time: float = 5.0, backoff_rate: float = 2.0, maxsize: int = 10_000):
        super().__init__(
            "dht_blacklist",
            maxsize=maxsize,
            failure_threshold=1,
            recovery_time=base_time,
            backoff_rate=backoff_rate,
            clock=get_dht_time,
        )
        self.base_time, self.backoff_rate = base_time, backoff_rate


@dataclass
class _SearchResult:
    """Best value found for one key during a search, plus where it was/wasn't."""

    binary_value: Optional[Union[BinaryDHTValue, DictionaryDHTValue]] = None
    expiration_time: Optional[DHTExpiration] = None
    source_node_id: Optional[DHTID] = None
    nearest_without_value: List[DHTID] = field(default_factory=list)

    def add_candidate(
        self,
        candidate: Optional[ValueWithExpiration],
        source_node_id: Optional[DHTID],
    ) -> None:
        if candidate is None:
            return
        if isinstance(candidate.value, DictionaryDHTValue) and isinstance(self.binary_value, DictionaryDHTValue):
            # merge dictionaries entry-wise (subkey freshness decides)
            for subkey, (value, expiration) in candidate.value.items():
                self.binary_value.store(subkey, value, expiration)
        elif candidate.expiration_time > (self.expiration_time or -float("inf")):
            self.binary_value = candidate.value
        if candidate.expiration_time > (self.expiration_time or -float("inf")):
            self.expiration_time = candidate.expiration_time
            self.source_node_id = source_node_id


class DHTNode:
    """See module docstring. Create with ``await DHTNode.create(...)``."""

    def __init__(self):
        raise RuntimeError("use `await DHTNode.create(...)`")

    @classmethod
    async def create(
        cls,
        p2p: Optional[P2P] = None,
        node_id: Optional[DHTID] = None,
        initial_peers: Sequence[Union[str, Multiaddr]] = (),
        bucket_size: int = 20,
        num_replicas: int = 5,
        wait_timeout: float = 3.0,
        bootstrap_timeout: Optional[float] = None,
        num_workers: int = DEFAULT_NUM_WORKERS,
        beam_size: Optional[int] = None,
        queries_per_call: int = 3,
        cache_locally: bool = True,
        cache_nearest: int = 1,
        cache_size: int = 10_000,
        cache_on_store: bool = True,
        cache_refresh_before_expiry: float = 5.0,
        reuse_get_requests: bool = True,
        blacklist_time: float = 5.0,
        backoff_rate: float = 2.0,
        client_mode: bool = False,
        record_validator: Optional[RecordValidatorBase] = None,
        validate: bool = False,
        strict: bool = True,
        **p2p_kwargs,
    ) -> "DHTNode":
        self = object.__new__(cls)
        self.node_id = node_id if node_id is not None else DHTID.generate()
        self.num_replicas, self.num_workers = num_replicas, num_workers
        self.beam_size = beam_size if beam_size is not None else bucket_size
        self.queries_per_call = queries_per_call
        self.cache_locally, self.cache_nearest, self.cache_on_store = cache_locally, cache_nearest, cache_on_store
        self.cache_refresh_before_expiry = cache_refresh_before_expiry
        self.reuse_get_requests = reuse_get_requests
        self.blacklist = Blacklist(blacklist_time, backoff_rate)
        self.client_mode = client_mode
        self.record_validator = record_validator
        self._pending_get_requests: Dict[DHTID, List[Tuple[DHTExpiration, asyncio.Future]]] = defaultdict(list)
        self._cache_refresh_queue = TimedStorage[DHTID, DHTExpiration]()
        self._cache_refresh_available = asyncio.Event()
        self._refresh_task: Optional[asyncio.Task] = None
        # post-response background work (cache_nearest replication) that must
        # not outlive the node: cancelled in shutdown()
        self._background: set = set()
        self._owns_p2p = p2p is None

        if p2p is None:
            p2p = await P2P.create(**p2p_kwargs)
        self.p2p = p2p
        self.peer_id = p2p.peer_id
        self.protocol = await DHTProtocol.create(
            p2p, self.node_id, bucket_size, cache_size, client_mode, record_validator, wait_timeout
        )

        if initial_peers:
            initial_peers = [Multiaddr.parse(m) if isinstance(m, str) else m for m in initial_peers]
            # one Deadline budget for the whole bootstrap (resilience/policy.py):
            # stage 2's straggler wait gets whatever stage 1 left over
            bootstrap_budget = Deadline(bootstrap_timeout if bootstrap_timeout is not None else wait_timeout * 10)

            async def _ping_address(maddr: Multiaddr) -> Optional[DHTID]:
                try:
                    peer = await p2p.connect(maddr)
                    return await self.protocol.call_ping(peer, validate=validate, strict=strict)
                except Exception as e:
                    logger.debug(f"bootstrap peer {maddr} unreachable: {e!r}")
                    return None

            # stage 1: ping everyone; require at least one success (reference node.py:219-264)
            ping_tasks = [asyncio.create_task(_ping_address(m)) for m in initial_peers]
            finished, pending = await asyncio.wait(ping_tasks, return_when=asyncio.FIRST_COMPLETED)
            while pending and not any(t.result() is not None for t in finished if not t.cancelled()):
                extra_finished, pending = await asyncio.wait(pending, return_when=asyncio.FIRST_COMPLETED)
                finished |= extra_finished
            if not any(t.result() is not None for t in finished if not t.cancelled()):
                if pending:
                    await asyncio.gather(*pending, return_exceptions=True)
                if strict and not any(
                    t.result() is not None for t in ping_tasks if t.done() and not t.cancelled()
                ):
                    raise RuntimeError("DHTNode bootstrap failed: none of the initial peers responded")
            # stage 2: wait for stragglers until the deadline
            if pending:
                await asyncio.wait(pending, timeout=bootstrap_budget.remaining())
                for task in pending:
                    task.cancel()
            # stage 3: self-lookup to populate the routing table
            await self.find_nearest_nodes([self.node_id])

        return self

    # ------------------------------------------------------------------ traversal plumbing

    def _make_peer_resolver(self) -> Dict[DHTID, PeerInfo]:
        """Search-local map node_id → contact, seeded from the routing table and
        extended with every nearest-contact learned during traversal."""
        return dict(self.protocol.routing_table.iter_nodes())

    def _register_contacts(self, contacts: Dict[DHTID, PeerInfo]) -> None:
        """Make learned contacts dialable: their addresses must reach the transport
        peerstore before any stub call, since this build has no external daemon
        resolving PeerID → address."""
        for info in contacts.values():
            for maddr in info.maddrs:
                try:
                    self.p2p.add_peer_addr(info.peer_id, maddr)
                except Exception:
                    continue

    def _get_neighbors_fn(
        self,
        node_to_peer: Dict[DHTID, PeerInfo],
        search_results: Optional[Dict[DHTID, _SearchResult]] = None,
        sufficient_expiration: Optional[DHTExpiration] = None,
    ):
        async def get_neighbors(
            peer_node_id: DHTID, queries: Collection[DHTID]
        ) -> Dict[DHTID, Tuple[List[DHTID], bool]]:
            info = node_to_peer.get(peer_node_id)
            if info is None or info.peer_id in self.blacklist or info.peer_id == self.peer_id:
                return {q: ([], False) for q in queries}
            response = await self.protocol.call_find(info.peer_id, queries)
            if response is None:
                self.blacklist.register_failure(info.peer_id)
                return {q: ([], False) for q in queries}
            self.blacklist.register_success(info.peer_id)
            output: Dict[DHTID, Tuple[List[DHTID], bool]] = {}
            for query, (maybe_value, nearest) in response.items():
                node_to_peer.update(nearest)
                self._register_contacts(nearest)
                should_stop = False
                if search_results is not None:
                    state = search_results[query]
                    state.add_candidate(maybe_value, peer_node_id)
                    if maybe_value is None:
                        state.nearest_without_value.append(peer_node_id)
                    if (
                        sufficient_expiration is not None
                        and state.expiration_time is not None
                        and state.expiration_time >= sufficient_expiration
                    ):
                        should_stop = True
                output[query] = (list(nearest.keys()), should_stop)
            return output

        return get_neighbors

    async def find_nearest_nodes(
        self,
        queries: Collection[DHTID],
        k_nearest: Optional[int] = None,
        beam_size: Optional[int] = None,
        exclude_self: bool = False,
    ) -> Dict[DHTID, Dict[DHTID, PeerInfo]]:
        """Beam-search the swarm for each query id; returns nearest node contacts."""
        queries = list(queries)
        k_nearest = k_nearest if k_nearest is not None else self.beam_size
        beam_size = max(beam_size if beam_size is not None else self.beam_size, k_nearest)
        node_to_peer = self._make_peer_resolver()
        # seed each query's beam from its OWN neighborhood (cheap local op); distant
        # queries would otherwise converge from a random region, wasting round-trips
        initial_set = {}
        for query in queries:
            for nid, _info in self.protocol.routing_table.get_nearest_neighbors(query, beam_size):
                initial_set[nid] = None
        initial = list(initial_set)
        if not initial:
            # lone node (or empty table): the only known storage candidate is self
            if exclude_self:
                return {q: {} for q in queries}
            return {q: {self.node_id: PeerInfo(self.peer_id, ())} for q in queries}
        nearest_nodes, _visited = await traverse_dht(
            queries,
            initial,
            beam_size,
            self.num_workers,
            self.queries_per_call,
            self._get_neighbors_fn(node_to_peer),
        )
        output = {}
        for query in queries:
            ranked = nearest_nodes[query]
            if not exclude_self:
                ranked = sorted(set(ranked) | {self.node_id}, key=query.xor_distance)
            output[query] = {
                nid: (node_to_peer[nid] if nid != self.node_id else PeerInfo(self.peer_id, ()))
                for nid in ranked[:k_nearest]
                if nid == self.node_id or nid in node_to_peer
            }
        return output

    # bulk stores below this size keep the classic one-beam-per-key path;
    # grouping only pays when many keys share a neighborhood (ISSUE 12)
    _STORE_GROUPING_MIN_KEYS = 16

    async def _find_nearest_grouped(
        self, key_ids: List[DHTID], k_nearest: int, exclude_self: bool
    ) -> Dict[DHTID, Dict[DHTID, PeerInfo]]:
        """``find_nearest_nodes`` for bulk stores: keys whose local nearest-neighbor
        sets coincide share ONE beam search (run for a representative key with a
        widened beam), and each member re-ranks the shared contact pool by its own
        xor distance. At 10k expert declarations over a 1k-peer swarm the per-key
        traversal is the dominant republish cost; most keys land in one of ~N
        distinct neighborhoods, so this collapses the traversal count from
        O(keys) to O(distinct neighborhoods)."""
        if len(key_ids) < self._STORE_GROUPING_MIN_KEYS:
            return await self.find_nearest_nodes(key_ids, k_nearest=k_nearest, exclude_self=exclude_self)
        # replica placement must stay (near-)exact: divergent replica sets shard
        # subkey dictionaries across extra nodes, and readers that stop at the
        # first fresh value then see PARTIAL dicts (measured: beam-search recall
        # 0.71 vs 1.0 with a naive top-k signature). Three safeguards: keys
        # group only when their local neighborhoods coincide at DOUBLE the
        # replica count, the shared traversal returns that doubled pool for
        # per-member re-ranking, and any member whose OWN routing table knows a
        # node nearer than its chosen k-th replica that the pool lacks (a
        # witness that the pool is inadequate for this key) falls back to an
        # exact traversal.
        pool_size = max(2 * k_nearest, k_nearest + 4)
        groups: Dict[frozenset, List[DHTID]] = {}
        local_nearest: Dict[DHTID, List[DHTID]] = {}
        for key_id in key_ids:
            ordered = [
                node_id
                for node_id, _info in self.protocol.routing_table.get_nearest_neighbors(key_id, pool_size)
            ]
            # kept for the witness check below: its k_nearest-prefix is exactly
            # this scan's head, so each key pays ONE table scan, not two
            local_nearest[key_id] = ordered[:k_nearest]
            groups.setdefault(frozenset(ordered), []).append(key_id)
        if len(groups) > 0.75 * len(key_ids):
            # neighborhoods barely overlap: grouping buys nothing, keep exact placement
            return await self.find_nearest_nodes(key_ids, k_nearest=k_nearest, exclude_self=exclude_self)
        representatives = [members[0] for members in groups.values()]
        rep_nearest = await self.find_nearest_nodes(
            representatives, k_nearest=pool_size, exclude_self=exclude_self
        )
        output: Dict[DHTID, Dict[DHTID, PeerInfo]] = {}
        fallback: List[DHTID] = []
        for members in groups.values():
            pool = rep_nearest[members[0]]
            for key_id in members:
                ranked = sorted(pool, key=key_id.xor_distance)[:k_nearest]
                worst = key_id.xor_distance(ranked[-1]) if ranked else None
                inadequate = worst is None or any(
                    node_id not in pool and key_id.xor_distance(node_id) < worst
                    for node_id in local_nearest[key_id]
                )
                if inadequate and key_id != members[0]:
                    fallback.append(key_id)
                else:
                    output[key_id] = {node_id: pool[node_id] for node_id in ranked}
        if fallback:
            output.update(
                await self.find_nearest_nodes(fallback, k_nearest=k_nearest, exclude_self=exclude_self)
            )
        saved = len(key_ids) - len(representatives) - len(fallback)
        if saved > 0:
            _DHT_STORE_TRAVERSALS_SAVED.inc(saved)
        return output

    # ------------------------------------------------------------------ store

    async def store(
        self, key: DHTKey, value: Any, expiration_time: DHTExpiration, subkey: Optional[Subkey] = None, **kwargs
    ) -> bool:
        result = await self.store_many([key], [value], [expiration_time], subkeys=[subkey], **kwargs)
        return result[(key, subkey) if subkey is not None else key]

    async def store_many(
        self,
        keys: Sequence[DHTKey],
        values: Sequence[Any],
        expiration_time: Union[DHTExpiration, Sequence[DHTExpiration]],
        subkeys: Optional[Sequence[Optional[Subkey]]] = None,
        exclude_self: bool = False,
        await_all_replicas: bool = True,
    ) -> Dict[Any, bool]:
        """Serialize values, find ``num_replicas`` nearest nodes per key (possibly
        including self), and store with per-subkey records + validator signatures
        (reference node.py:351-503)."""
        started = time.perf_counter()
        with _tracing_span("dht.store", peer=str(self.protocol.p2p.peer_id), keys=len(keys)):
            return await self._store_many_traced(
                keys, values, expiration_time, subkeys, exclude_self, await_all_replicas, started
            )

    async def _store_many_traced(
        self, keys, values, expiration_time, subkeys, exclude_self, await_all_replicas, started
    ) -> Dict[Any, bool]:
        if isinstance(expiration_time, (int, float)):
            expiration_time = [expiration_time] * len(keys)
        if subkeys is None:
            subkeys = [None] * len(keys)
        assert len(keys) == len(values) == len(expiration_time) == len(subkeys)

        key_ids = [DHTID.generate(source=key) for key in keys]
        prepared: Dict[DHTID, List[Tuple[Optional[Subkey], bytes, DHTExpiration, Any]]] = defaultdict(list)
        for key, key_id, subkey, value, expiration in zip(keys, key_ids, subkeys, values, expiration_time):
            binary_value = MSGPackSerializer.dumps(value)
            if self.record_validator is not None:
                subkey_bytes = MSGPackSerializer.dumps(subkey) if subkey is not None else b""
                record = DHTRecord(key_id.to_bytes(), subkey_bytes, binary_value, expiration)
                binary_value = self.record_validator.sign_value(record)
            result_key = (key, subkey) if subkey is not None else key
            prepared[key_id].append((subkey, binary_value, expiration, result_key))

        nearest = await self._find_nearest_grouped(
            list(prepared.keys()), k_nearest=self.num_replicas, exclude_self=exclude_self or self.client_mode
        )

        output: Dict[Any, bool] = {}

        async def _store_one_key(key_id: DHTID) -> None:
            records = prepared[key_id]
            store_tasks = []
            for node_id, info in nearest[key_id].items():
                if node_id == self.node_id:
                    for subkey, binary_value, expiration, result_key in records:
                        if subkey is None:
                            ok = self.protocol._store_record(key_id, b"", binary_value, expiration, in_cache=False)
                        else:
                            ok = self.protocol._store_record(
                                key_id, MSGPackSerializer.dumps(subkey), binary_value, expiration, in_cache=False
                            )
                        output[result_key] = output.get(result_key, False) or ok
                else:
                    store_tasks.append(
                        asyncio.ensure_future(
                            self.protocol.call_store(
                                info.peer_id,
                                keys=[key_id] * len(records),
                                values=[r[1] for r in records],
                                expiration_time=[r[2] for r in records],
                                subkeys=[r[0] for r in records],
                            )
                        )
                    )

            def _register(reply) -> None:
                if reply is None:
                    return
                for (subkey, _bv, _exp, result_key), ok in zip(records, reply):
                    output[result_key] = output.get(result_key, False) or bool(ok)

            def _all_succeeded() -> bool:
                return all(output.get(r[3], False) for r in records)

            if await_all_replicas:
                for reply in await asyncio.gather(*store_tasks):
                    _register(reply)
            else:
                # return as soon as every record has one replica; stragglers finish
                # in the background (reference node.py await_all_replicas=False)
                pending = set(store_tasks)
                while pending and not _all_succeeded():
                    done, pending = await asyncio.wait(pending, return_when=asyncio.FIRST_COMPLETED)
                    for task in done:
                        _register(task.result())
            for _subkey, _bv, _exp, result_key in records:
                output.setdefault(result_key, False)

        await asyncio.gather(*(_store_one_key(key_id) for key_id in prepared))
        _DHT_OP_LATENCY.observe(time.perf_counter() - started, op="store")
        return output

    # ------------------------------------------------------------------ get

    async def get(self, key: DHTKey, latest: bool = False, **kwargs) -> Optional[ValueWithExpiration]:
        """Find the (freshest, if ``latest``) value for this key; deserialized."""
        result = await self.get_many([key], sufficient_expiration_time=float("inf") if latest else None, **kwargs)
        return result[key]

    async def get_many(
        self,
        keys: Collection[DHTKey],
        sufficient_expiration_time: Optional[DHTExpiration] = None,
        **kwargs,
    ) -> Dict[DHTKey, Optional[ValueWithExpiration]]:
        keys = list(keys)
        key_ids = [DHTID.generate(source=key) for key in keys]
        id_to_key = dict(zip(key_ids, keys))
        results_by_id = await self.get_many_by_id(key_ids, sufficient_expiration_time, **kwargs)
        return {id_to_key[key_id]: result for key_id, result in results_by_id.items()}

    async def get_many_by_id(
        self,
        key_ids: Collection[DHTID],
        sufficient_expiration_time: Optional[DHTExpiration] = None,
        num_workers: Optional[int] = None,
        beam_size: Optional[int] = None,
        return_futures: bool = False,
        _is_refresh: bool = False,
    ) -> Dict[DHTID, Union[Optional[ValueWithExpiration], Awaitable]]:
        """Beam search for each key; a key finishes as soon as a value fresh enough is
        found (sufficient_expiration_time defaults to 'valid now'). With
        ``return_futures``, each value is a future resolved when that key finishes
        (reference node.py:534-678)."""
        started = time.perf_counter()
        # manual span install: in futures mode the op outlives this coroutine,
        # so the span is finished from the same done-callback that feeds the
        # latency metric; traversal tasks created below inherit the span
        op_span = _start_span(
            "dht.get", peer=str(self.protocol.p2p.peer_id), keys=len(list(key_ids))
        )
        span_token = _install_span(op_span)
        try:
            return await self._get_many_by_id_traced(
                key_ids, sufficient_expiration_time, num_workers, beam_size,
                return_futures, _is_refresh, started, op_span,
            )
        finally:
            _uninstall_span(span_token)

    async def _get_many_by_id_traced(
        self, key_ids, sufficient_expiration_time, num_workers, beam_size,
        return_futures, _is_refresh, started, op_span,
    ) -> Dict[DHTID, Union[Optional[ValueWithExpiration], Awaitable]]:
        key_ids = list(key_ids)
        if sufficient_expiration_time is None:
            sufficient_expiration_time = get_dht_time()
        beam_size = beam_size if beam_size is not None else self.beam_size
        num_workers = num_workers if num_workers is not None else self.num_workers
        search_results: Dict[DHTID, _SearchResult] = {kid: _SearchResult() for kid in key_ids}
        futures: Dict[DHTID, asyncio.Future] = {kid: asyncio.get_event_loop().create_future() for kid in key_ids}

        # step 0: in-flight request reuse (reference reuse_get_requests)
        reused: Dict[DHTID, asyncio.Future] = {}
        if self.reuse_get_requests and not _is_refresh:
            for key_id in key_ids:
                for pending_sufficient, pending_future in self._pending_get_requests[key_id]:
                    if pending_sufficient >= sufficient_expiration_time and not pending_future.done():
                        reused[key_id] = pending_future
                        break

        # step 1: local storage / cache
        unfinished: List[DHTID] = []
        for key_id in key_ids:
            if key_id in reused:
                continue
            state = search_results[key_id]
            for storage in (self.protocol.storage, self.protocol.cache):
                maybe = storage.get(key_id)
                if maybe is not None:
                    state.add_candidate(maybe, source_node_id=self.node_id)
            if state.expiration_time is not None and state.expiration_time >= sufficient_expiration_time:
                self._finalize_get(key_id, state, futures[key_id], _is_refresh)
            else:
                unfinished.append(key_id)

        # step 2: network traversal for the rest
        if unfinished:
            for key_id in unfinished:
                self._pending_get_requests[key_id].append((sufficient_expiration_time, futures[key_id]))

            node_to_peer = self._make_peer_resolver()
            initial = [
                nid for nid, _ in self.protocol.routing_table.get_nearest_neighbors(unfinished[0], beam_size)
            ]

            async def found_callback(key_id: DHTID, _nearest: List[DHTID], _visited) -> None:
                self._finalize_get(key_id, search_results[key_id], futures[key_id], _is_refresh)

            if initial:
                traverse_task = asyncio.create_task(
                    traverse_dht(
                        unfinished,
                        initial,
                        beam_size,
                        num_workers,
                        self.queries_per_call,
                        self._get_neighbors_fn(node_to_peer, search_results, sufficient_expiration_time),
                        found_callback=found_callback,
                    )
                )
                # caching policies need the traversal results
                caching_task = spawn(self._apply_caching_policies(traverse_task, unfinished, search_results, node_to_peer), name="dht.apply_caching_policies")
                self._background.add(caching_task)
                caching_task.add_done_callback(self._background.discard)
            else:
                for key_id in unfinished:
                    self._finalize_get(key_id, search_results[key_id], futures[key_id], _is_refresh)

        output: Dict[DHTID, Any] = {}
        for key_id in key_ids:
            future = reused.get(key_id, futures[key_id])
            output[key_id] = future if return_futures else None
        if return_futures:
            # the op finishes when the LAST future resolves — observe from a
            # done-callback so futures-mode gets (the long beam searches) are
            # not invisible to the latency metric
            watcher = asyncio.gather(
                *(reused.get(kid, futures[kid]) for kid in key_ids), return_exceptions=True
            )
            def _observe_get(_w) -> None:
                _DHT_OP_LATENCY.observe(time.perf_counter() - started, op="get")
                _finish_span(op_span)

            watcher.add_done_callback(_observe_get)
            return output
        gathered = await asyncio.gather(*(reused.get(kid, futures[kid]) for kid in key_ids))
        _DHT_OP_LATENCY.observe(time.perf_counter() - started, op="get")
        _finish_span(op_span)
        return dict(zip(key_ids, gathered))

    def _finalize_get(
        self, key_id: DHTID, state: _SearchResult, future: asyncio.Future, is_refresh: bool
    ) -> None:
        if future.done():
            return
        self._pending_get_requests[key_id] = [
            (exp, fut) for exp, fut in self._pending_get_requests[key_id] if fut is not future and not fut.done()
        ]
        if state.binary_value is None:
            future.set_result(None)
            return
        # validate + strip + deserialize
        try:
            if isinstance(state.binary_value, DictionaryDHTValue):
                out_dict = {}
                for subkey, (value, expiration) in state.binary_value.items():
                    stripped = self._validate_and_strip(key_id, subkey, value, expiration)
                    if stripped is not None:
                        out_dict[subkey] = ValueWithExpiration(MSGPackSerializer.loads(stripped), expiration)
                if not out_dict:
                    future.set_result(None)
                    return
                result = ValueWithExpiration(out_dict, state.expiration_time)
            else:
                stripped = self._validate_and_strip(key_id, None, state.binary_value, state.expiration_time)
                if stripped is None:
                    future.set_result(None)
                    return
                result = ValueWithExpiration(MSGPackSerializer.loads(stripped), state.expiration_time)
        except Exception as e:
            logger.warning(f"failed to deserialize value for key {key_id!r}: {e!r}")
            future.set_result(None)
            return
        future.set_result(result)
        # local caching + refresh scheduling (refreshes always re-store, else the
        # refresh traversal would accomplish nothing)
        if (self.cache_locally or is_refresh) and state.source_node_id != self.node_id:
            self.protocol.cache.store(key_id, state.binary_value, state.expiration_time)
        if self.cache_refresh_before_expiry > 0 and key_id in self.protocol.cache:
            self._schedule_cache_refresh(key_id, state.expiration_time)

    def _validate_and_strip(
        self, key_id: DHTID, subkey: Optional[Subkey], value: bytes, expiration: DHTExpiration
    ) -> Optional[bytes]:
        if self.record_validator is None:
            return value
        subkey_bytes = MSGPackSerializer.dumps(subkey) if subkey is not None else b""
        record = DHTRecord(key_id.to_bytes(), subkey_bytes, value, expiration)
        if not self.record_validator.validate(record):
            logger.debug(f"record validation failed for key {key_id!r}")
            return None
        return self.record_validator.strip_value(record)

    async def _apply_caching_policies(
        self,
        traverse_task: asyncio.Task,
        key_ids: List[DHTID],
        search_results: Dict[DHTID, _SearchResult],
        node_to_peer: Dict[DHTID, PeerInfo],
    ) -> None:
        """cache_nearest: replicate found values to the nearest queried node that did
        not have them (reference node.py:651-653,763-794)."""
        try:
            await traverse_task
        except Exception:
            return
        if not self.cache_nearest:
            return
        for key_id in key_ids:
            state = search_results[key_id]
            if state.binary_value is None or isinstance(state.binary_value, DictionaryDHTValue):
                continue
            num_cached = 0
            for node_id in sorted(state.nearest_without_value, key=key_id.xor_distance):
                if num_cached >= self.cache_nearest:
                    break
                info = node_to_peer.get(node_id)
                if info is None:
                    continue
                await self.protocol.call_store(
                    info.peer_id, [key_id], [state.binary_value], state.expiration_time, in_cache=True
                )
                num_cached += 1

    # ------------------------------------------------------------------ cache refresh

    def _schedule_cache_refresh(self, key_id: DHTID, expiration_time: DHTExpiration) -> None:
        if self._refresh_task is None or self._refresh_task.done():
            self._refresh_task = spawn(self._refresh_stale_cache_entries(), name="dht.cache_refresh")
        refresh_time = expiration_time - self.cache_refresh_before_expiry
        self._cache_refresh_queue.store(key_id, expiration_time, refresh_time)
        self._cache_refresh_available.set()

    async def _refresh_stale_cache_entries(self) -> None:
        """Background task: re-fetch cached keys shortly before they expire
        (reference node.py:727-761)."""
        while True:
            while not self._cache_refresh_queue:
                self._cache_refresh_available.clear()  # lint: single-writer — sole refresh task
                await self._cache_refresh_available.wait()
            entry = self._cache_refresh_queue.top()
            if entry is None:
                continue
            key_id, (expiration_time, refresh_deadline) = entry[0], (entry[1].value, entry[1].expiration_time)
            wait_time = refresh_deadline - get_dht_time()
            if wait_time > 0:
                try:
                    await asyncio.wait_for(self._cache_refresh_available.wait(), timeout=wait_time)
                    self._cache_refresh_available.clear()
                    continue  # queue changed; re-evaluate the top entry
                except asyncio.TimeoutError:
                    pass
            if key_id in self._cache_refresh_queue:
                del self._cache_refresh_queue[key_id]  # lint: single-writer — sole refresh task
            if key_id not in self.protocol.cache:
                continue
            await self.get_many_by_id(
                [key_id], sufficient_expiration_time=expiration_time + self.cache_refresh_before_expiry,
                _is_refresh=True,
            )

    # ------------------------------------------------------------------ misc

    async def get_visible_maddrs(self, latest: bool = False) -> List[Multiaddr]:
        return self.p2p.get_visible_maddrs(latest)

    async def shutdown(self) -> None:
        if self._refresh_task is not None:
            self._refresh_task.cancel()
        for task in list(self._background):
            task.cancel()
        await self.protocol.shutdown()
        if self._owns_p2p:
            await self.p2p.shutdown()
