"""Concurrent multi-query Kademlia beam search (capability parity: reference
hivemind/dht/traverse.py:72-258).

``traverse_dht`` runs ``num_workers`` cooperative workers over a set of queries.
Each query keeps a candidate heap (unvisited nodes by xor distance) and a nearest
heap (visited nodes). A worker picks the query whose best candidate is relatively
closest (the reference's heuristic priority), visits that candidate via
``get_neighbors`` — batching up to ``queries_per_call`` other queries onto the same
RPC — and finishes a query once no candidate can improve its beam.
"""

from __future__ import annotations

import asyncio
import heapq
from collections import defaultdict
from typing import Awaitable, Callable, Collection, Dict, List, Optional, Set, Tuple

from hivemind_tpu.dht.routing import DHTID

# get_neighbors(peer, queries) -> {query: (neighbor_ids, should_stop)}
GetNeighborsFn = Callable[[DHTID, Collection[DHTID]], Awaitable[Dict[DHTID, Tuple[List[DHTID], bool]]]]


async def simple_traverse_dht(
    query_id: DHTID,
    initial_nodes: Collection[DHTID],
    beam_size: int,
    get_neighbors: GetNeighborsFn,
    visited_nodes: Collection[DHTID] = (),
) -> Tuple[List[DHTID], Set[DHTID]]:
    """Single-query, single-worker reference implementation (reference traverse.py:13-69);
    used in tests as ground truth for the concurrent version."""
    visited_nodes = set(visited_nodes)
    initial_nodes = list(dict.fromkeys(n for n in initial_nodes if n not in visited_nodes))
    candidates = [(query_id.xor_distance(node), node) for node in initial_nodes]
    heapq.heapify(candidates)
    nearest: List[Tuple[int, DHTID]] = [(-d, n) for d, n in candidates]
    heapq.heapify(nearest)
    known = set(initial_nodes)  # beam-membership dedup
    while len(nearest) > beam_size:
        heapq.heappop(nearest)

    while candidates:
        distance, peer = heapq.heappop(candidates)
        if len(nearest) == beam_size and distance > -nearest[0][0]:
            break
        if peer in visited_nodes:
            continue
        visited_nodes.add(peer)
        response = await get_neighbors(peer, [query_id])
        neighbors, should_stop = response.get(query_id, ([], False))
        for neighbor in neighbors:
            if neighbor in visited_nodes or neighbor in known:
                continue
            neighbor_distance = query_id.xor_distance(neighbor)
            if len(nearest) < beam_size or neighbor_distance < -nearest[0][0]:
                known.add(neighbor)
                heapq.heappush(candidates, (neighbor_distance, neighbor))
                heapq.heappush(nearest, (-neighbor_distance, neighbor))
                if len(nearest) > beam_size:
                    heapq.heappop(nearest)
        if should_stop:
            break
    return [node for _, node in sorted((-d, n) for d, n in nearest)], visited_nodes


class _QueryState:
    __slots__ = ("query", "candidates", "nearest", "in_beam", "visited", "finished", "stop_requested")

    def __init__(self, query: DHTID, initial_nodes: Collection[DHTID], visited: Set[DHTID]):
        self.query = query
        self.visited = visited  # shared across queries: global set of called peers
        self.candidates: List[Tuple[int, DHTID]] = [
            (query.xor_distance(node), node) for node in initial_nodes
        ]
        heapq.heapify(self.candidates)
        self.nearest: List[Tuple[int, DHTID]] = [(-d, n) for d, n in self.candidates]
        heapq.heapify(self.nearest)
        self.in_beam: Set[DHTID] = set(node for _, node in self.candidates)
        self.finished = False
        self.stop_requested = False

    def beam_size_now(self) -> int:
        return len(self.nearest)

    def upper_bound(self, beam_size: int) -> int:
        """Max distance within the current beam (or inf if beam not yet full)."""
        if len(self.nearest) < beam_size:
            return 1 << 300
        return -self.nearest[0][0]

    def add_neighbor(self, neighbor: DHTID, beam_size: int) -> None:
        if neighbor in self.in_beam:
            return
        distance = self.query.xor_distance(neighbor)
        if len(self.nearest) < beam_size or distance < -self.nearest[0][0]:
            self.in_beam.add(neighbor)
            heapq.heappush(self.nearest, (-distance, neighbor))
            if len(self.nearest) > beam_size:
                heapq.heappop(self.nearest)
            if neighbor not in self.visited:
                heapq.heappush(self.candidates, (distance, neighbor))

    def pop_best_candidate(self, beam_size: int) -> Optional[DHTID]:
        """Peek the best unvisited candidate that could still improve the beam, or None
        (the caller decides whether None means 'finished' — in-flight RPCs may still
        repopulate candidates)."""
        while self.candidates:
            distance, node = self.candidates[0]
            if node in self.visited:
                heapq.heappop(self.candidates)
                continue
            if distance > self.upper_bound(beam_size):
                return None
            return node
        return None

    def best_distance(self) -> int:
        while self.candidates and self.candidates[0][1] in self.visited:
            heapq.heappop(self.candidates)
        if not self.candidates:
            return 1 << 300
        return self.candidates[0][0]

    def result(self) -> List[DHTID]:
        return [node for _, node in sorted((-d, n) for d, n in self.nearest)]


async def traverse_dht(
    queries: Collection[DHTID],
    initial_nodes: List[DHTID],
    beam_size: int,
    num_workers: int,
    queries_per_call: int,
    get_neighbors: GetNeighborsFn,
    visited_nodes: Optional[Dict[DHTID, Set[DHTID]]] = None,
    found_callback: Optional[Callable[[DHTID, List[DHTID], Set[DHTID]], Awaitable]] = None,
    await_all_tasks: bool = True,
) -> Tuple[Dict[DHTID, List[DHTID]], Dict[DHTID, Set[DHTID]]]:
    """Concurrent beam search for multiple queries.

    :returns: ({query: nearest nodes, closest first}, {query: visited node set})
    """
    queries = list(dict.fromkeys(queries))
    if not queries or not initial_nodes:
        return {q: [] for q in queries}, {q: set(visited_nodes.get(q, ())) if visited_nodes else set() for q in queries}

    per_query_visited: Dict[DHTID, Set[DHTID]] = {
        q: set(visited_nodes.get(q, ())) if visited_nodes else set() for q in queries
    }
    states = {q: _QueryState(q, initial_nodes, per_query_visited[q]) for q in queries}
    active = set(queries)
    callback_tasks: List[asyncio.Task] = []
    search_finished = asyncio.Event()
    wakeup = asyncio.Event()
    in_flight = 0
    in_flight_per_query: Dict[DHTID, int] = defaultdict(int)

    def _finish_query(query: DHTID) -> None:
        state = states[query]
        if query in active:
            active.discard(query)
            state.finished = True
            if found_callback is not None:
                callback_tasks.append(
                    asyncio.create_task(found_callback(query, state.result(), per_query_visited[query]))
                )
        if not active:
            search_finished.set()

    async def worker() -> None:
        nonlocal in_flight
        while active:
            # pick the active query with the relatively closest unvisited candidate;
            # a query with no viable candidate finishes only once none of its RPCs
            # are in flight (an in-flight response may repopulate its heap)
            best_query, best_priority = None, None
            for query in list(active):
                state = states[query]
                if state.finished:
                    continue
                candidate = state.pop_best_candidate(beam_size)
                if candidate is None:
                    if in_flight_per_query[query] == 0:
                        _finish_query(query)
                    continue
                priority = state.best_distance()
                if best_priority is None or priority < best_priority:
                    best_query, best_priority = query, priority
            if best_query is None:
                if in_flight > 0 and active:
                    # someone else's RPC may add candidates; wait for it
                    wakeup.clear()
                    await wakeup.wait()
                    continue
                for query in list(active):
                    _finish_query(query)
                return

            state = states[best_query]
            peer = state.pop_best_candidate(beam_size)
            if peer is None:
                continue
            # batch other queries that still want to visit this peer
            batch = [best_query]
            for query in list(active):
                if len(batch) >= queries_per_call:
                    break
                if query == best_query or states[query].finished:
                    continue
                if peer not in per_query_visited[query]:
                    batch.append(query)
            for query in batch:
                per_query_visited[query].add(peer)
                in_flight_per_query[query] += 1

            in_flight += 1
            try:
                responses = await get_neighbors(peer, batch)
            except Exception:
                responses = {}
            finally:
                in_flight -= 1
                for query in batch:
                    in_flight_per_query[query] -= 1
                wakeup.set()

            for query in batch:
                neighbors, should_stop = responses.get(query, ([], False))
                q_state = states[query]
                for neighbor in neighbors:
                    q_state.add_neighbor(neighbor, beam_size)
                if should_stop:
                    q_state.stop_requested = True
                    _finish_query(query)

    workers = [asyncio.create_task(worker()) for i in range(max(1, num_workers))]
    try:
        await asyncio.wait_for(search_finished.wait(), timeout=None)
    finally:
        for task in workers:
            task.cancel()
        await asyncio.gather(*workers, return_exceptions=True)
    if await_all_tasks and callback_tasks:
        await asyncio.gather(*callback_tasks, return_exceptions=True)

    return {q: states[q].result() for q in queries}, per_query_visited
