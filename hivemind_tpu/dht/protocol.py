"""The three DHT RPCs — ping / store / find — plus routing-table maintenance and key
handoff (capability parity: reference hivemind/dht/protocol.py:25-430)."""

from __future__ import annotations

import asyncio
import time
from typing import Collection, Dict, List, Optional, Sequence, Tuple, Union

from hivemind_tpu.dht.routing import (
    BinaryDHTValue,
    DHTID,
    PeerInfo,
    RoutingTable,
    Subkey,
)
from hivemind_tpu.dht.storage import DHTLocalStorage, DictionaryDHTValue
from hivemind_tpu.dht.validation import DHTRecord, RecordValidatorBase
from hivemind_tpu.p2p import P2P, P2PContext, P2PError, PeerID, ServicerBase
from hivemind_tpu.proto import dht_pb2
from hivemind_tpu.resilience import CHAOS as _CHAOS
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.asyncio_utils import spawn
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import (
    MAX_DHT_TIME_DISCREPANCY_SECONDS,
    DHTExpiration,
    ValueWithExpiration,
    get_dht_time,
)

logger = get_logger(__name__)

# layer-2 telemetry (docs/observability.md): per-RPC outbound latency/failures
# and the live routing-table size of this node
from hivemind_tpu.telemetry import REGISTRY as _TELEMETRY

_DHT_RPC_LATENCY = _TELEMETRY.histogram(
    "hivemind_dht_rpc_latency_seconds", "outbound DHT RPC wall time", ("rpc",)
)
_DHT_RPC_FAILURES = _TELEMETRY.counter(
    "hivemind_dht_rpc_failures_total", "outbound DHT RPCs that returned no reply", ("rpc",)
)
_DHT_ROUTING_TABLE_SIZE = _TELEMETRY.gauge(
    "hivemind_dht_routing_table_size", "peers currently in this node's routing table"
)

# sentinel subkey meaning "this value is not a dictionary entry"
IS_REGULAR_VALUE = b""


class DHTProtocol(ServicerBase):
    """One per DHTNode. Wire behavior matches the reference: every request/response
    carries sender NodeInfo and updates the receiver's routing table; new routing-table
    entries trigger handoff of local keys that are closer to the newcomer."""

    # ping/find are reads; store has set semantics (storing the same record twice
    # yields the same state), so all three are safe to retry on an ambiguous
    # connection loss (see P2P.call_protobuf_handler idempotency gate)
    _idempotent_rpcs = frozenset({"rpc_ping", "rpc_find", "rpc_store"})

    @classmethod
    async def create(
        cls,
        p2p: P2P,
        node_id: DHTID,
        bucket_size: int,
        cache_size: Optional[int],
        client_mode: bool,
        record_validator: Optional[RecordValidatorBase] = None,
        wait_timeout: float = 3.0,
    ) -> "DHTProtocol":
        self = object.__new__(cls)
        self.p2p = p2p
        self.node_id = node_id
        self.bucket_size = bucket_size
        self.wait_timeout = wait_timeout
        self.client_mode = client_mode
        self.record_validator = record_validator
        self.storage = DHTLocalStorage()
        self.cache = DHTLocalStorage(maxsize=cache_size)
        self.routing_table = RoutingTable(node_id, bucket_size)
        self.node_info = dht_pb2.NodeInfo(node_id=node_id.to_bytes())
        self._handoff_tasks: set = set()
        if not client_mode:
            await self.add_p2p_handlers(p2p)
        return self

    def __init__(self):
        raise RuntimeError("use `await DHTProtocol.create(...)`")

    async def shutdown(self) -> None:
        if not self.client_mode:
            await self.remove_p2p_handlers(self.p2p)
        for task in list(self._handoff_tasks):
            task.cancel()

    def _make_node_info(self) -> dht_pb2.NodeInfo:
        if self.client_mode:
            # client-mode peers are unreachable: announce nothing so receivers never
            # register them in routing tables (reference protocol.py:36-81 skips
            # handler registration and peer info for clients)
            return dht_pb2.NodeInfo()
        return dht_pb2.NodeInfo(
            node_id=self.node_id.to_bytes(),
            maddrs=[str(m) for m in self.p2p.get_visible_maddrs()],
        )

    # ------------------------------------------------------------------ ping

    async def call_ping(
        self, peer: PeerID, validate: bool = False, strict: bool = True
    ) -> Optional[DHTID]:
        """Ping a peer; registers it in the routing table. Returns its node id, or
        None if unreachable. ``validate``: also check clock skew (reference
        protocol.py:97-162)."""
        started = time.perf_counter()
        try:
            if _CHAOS.enabled:  # injection point: lose/delay the whole ping
                await _CHAOS.inject("dht.rpc_ping", scope=str(self.p2p.peer_id))
            stub = self.get_stub(self.p2p, peer)
            response = await stub.rpc_ping(
                dht_pb2.PingRequest(peer=self._make_node_info(), validate=validate),
                timeout=self.wait_timeout,
            )
        except Exception as e:
            _DHT_RPC_FAILURES.inc(rpc="ping")
            logger.debug(f"ping to {peer} failed: {e!r}")
            return None
        _DHT_RPC_LATENCY.observe(time.perf_counter() - started, rpc="ping")
        peer_node_id = DHTID.from_bytes(response.peer.node_id)
        self.update_routing_table(peer_node_id, peer, response.peer.maddrs, responded=True)
        if validate:
            skew = abs(response.dht_time - get_dht_time())
            if skew > MAX_DHT_TIME_DISCREPANCY_SECONDS:
                message = f"clock skew with {peer} is {skew:.2f}s (max {MAX_DHT_TIME_DISCREPANCY_SECONDS}s)"
                if strict:
                    raise P2PError(message)
                logger.warning(message)
        return peer_node_id

    async def rpc_ping(self, request: dht_pb2.PingRequest, context: P2PContext) -> dht_pb2.PingResponse:
        self._register_sender(request.peer, context)
        return dht_pb2.PingResponse(
            peer=self._make_node_info(), dht_time=get_dht_time(), available=bool(request.peer.maddrs)
        )

    # ------------------------------------------------------------------ store

    async def call_store(
        self,
        peer: PeerID,
        keys: Sequence[DHTID],
        values: Sequence[Union[BinaryDHTValue, DictionaryDHTValue]],
        expiration_time: Union[DHTExpiration, Sequence[DHTExpiration]],
        subkeys: Optional[Sequence[Optional[Subkey]]] = None,
        in_cache: Optional[Union[bool, Sequence[bool]]] = None,
    ) -> Optional[List[bool]]:
        """Ask a peer to store the given records; dictionaries are decomposed into
        per-subkey stores. Returns per-record success flags or None if unreachable."""
        if isinstance(expiration_time, (int, float)):
            expiration_time = [expiration_time] * len(keys)
        if subkeys is None:
            subkeys = [None] * len(keys)
        if in_cache is None:
            in_cache = False
        if isinstance(in_cache, bool):
            in_cache = [in_cache] * len(keys)

        flat_keys, flat_subkeys, flat_values, flat_expirations, flat_in_cache = [], [], [], [], []
        for key, value, subkey, expiration, cached in zip(keys, values, subkeys, expiration_time, in_cache):
            if isinstance(value, DictionaryDHTValue):
                assert subkey is None, "cannot store a dictionary under a subkey"
                for inner_subkey, (inner_value, inner_expiration) in value.items():
                    flat_keys.append(key.to_bytes())
                    flat_subkeys.append(MSGPackSerializer.dumps(inner_subkey))
                    flat_values.append(inner_value)
                    flat_expirations.append(inner_expiration)
                    flat_in_cache.append(cached)
            else:
                flat_keys.append(key.to_bytes())
                flat_subkeys.append(IS_REGULAR_VALUE if subkey is None else MSGPackSerializer.dumps(subkey))
                flat_values.append(value)
                flat_expirations.append(expiration)
                flat_in_cache.append(cached)
        started = time.perf_counter()
        try:
            if _CHAOS.enabled:  # injection point: lose/delay the whole store
                await _CHAOS.inject("dht.rpc_store", scope=str(self.p2p.peer_id))
            stub = self.get_stub(self.p2p, peer)
            response = await stub.rpc_store(
                dht_pb2.StoreRequest(
                    keys=flat_keys,
                    subkeys=flat_subkeys,
                    values=flat_values,
                    expiration_time=flat_expirations,
                    in_cache=flat_in_cache,
                    peer=self._make_node_info(),
                ),
                timeout=self.wait_timeout,
            )
            _DHT_RPC_LATENCY.observe(time.perf_counter() - started, rpc="store")
            if response.peer.node_id:
                self.update_routing_table(
                    DHTID.from_bytes(response.peer.node_id), peer, response.peer.maddrs, responded=True
                )
            return list(response.store_ok)
        except Exception as e:
            _DHT_RPC_FAILURES.inc(rpc="store")
            logger.debug(f"store to {peer} failed: {e!r}")
            return None

    async def rpc_store(self, request: dht_pb2.StoreRequest, context: P2PContext) -> dht_pb2.StoreResponse:
        self._register_sender(request.peer, context)
        assert len(request.keys) == len(request.values) == len(request.expiration_time) == len(request.in_cache)
        response = dht_pb2.StoreResponse(peer=self._make_node_info())
        for key, subkey, value, expiration, in_cache in zip(
            request.keys, request.subkeys, request.values, request.expiration_time, request.in_cache
        ):
            response.store_ok.append(
                self._store_record(DHTID.from_bytes(key), subkey, value, expiration, in_cache)
            )
        return response

    def _store_record(
        self, key_id: DHTID, subkey: bytes, value: bytes, expiration: DHTExpiration, in_cache: bool
    ) -> bool:
        if expiration < get_dht_time():
            return False
        if self.record_validator is not None:
            record = DHTRecord(key_id.to_bytes(), subkey, value, expiration)
            if not self.record_validator.validate(record):
                return False
        storage = self.cache if in_cache else self.storage
        if subkey == IS_REGULAR_VALUE:
            return storage.store(key_id, value, expiration)
        return storage.store_subkey(key_id, MSGPackSerializer.loads(subkey), value, expiration)

    # ------------------------------------------------------------------ find

    async def call_find(
        self, peer: PeerID, keys: Collection[DHTID]
    ) -> Optional[
        Dict[
            DHTID,
            Tuple[
                Optional[ValueWithExpiration[Union[BinaryDHTValue, DictionaryDHTValue]]],
                Dict[DHTID, PeerInfo],
            ],
        ]
    ]:
        """Ask a peer for values and/or its nearest neighbors for each key
        (reference protocol.py:271-331)."""
        keys = list(keys)
        started = time.perf_counter()
        try:
            if _CHAOS.enabled:  # injection point: lose/delay the whole find
                await _CHAOS.inject("dht.rpc_find", scope=str(self.p2p.peer_id))
            stub = self.get_stub(self.p2p, peer)
            response = await stub.rpc_find(
                dht_pb2.FindRequest(keys=[k.to_bytes() for k in keys], peer=self._make_node_info()),
                timeout=self.wait_timeout,
            )
            _DHT_RPC_LATENCY.observe(time.perf_counter() - started, rpc="find")
            if response.peer.node_id:
                self.update_routing_table(
                    DHTID.from_bytes(response.peer.node_id), peer, response.peer.maddrs, responded=True
                )
            assert len(response.results) == len(keys)
            output = {}
            for key_id, result in zip(keys, response.results):
                nearest = {}
                for node_id_bytes, contact in zip(result.nearest_node_ids, result.nearest_contacts):
                    nearest[DHTID.from_bytes(node_id_bytes)] = PeerInfo(
                        PeerID(contact.peer_id), tuple(contact.maddrs)
                    )
                if result.type == dht_pb2.NOT_FOUND:
                    output[key_id] = None, nearest
                elif result.type == dht_pb2.FOUND_REGULAR:
                    output[key_id] = ValueWithExpiration(result.value, result.expiration_time), nearest
                elif result.type == dht_pb2.FOUND_DICTIONARY:
                    loaded = MSGPackSerializer.loads(result.value)
                    dictionary = DictionaryDHTValue()
                    for inner_subkey, (inner_value, inner_expiration) in loaded.items():
                        dictionary.store(inner_subkey, inner_value, inner_expiration)
                    output[key_id] = ValueWithExpiration(dictionary, result.expiration_time), nearest
                else:
                    logger.warning(f"unknown find result type {result.type}")
                    output[key_id] = None, nearest
            return output
        except Exception as e:
            _DHT_RPC_FAILURES.inc(rpc="find")
            logger.debug(f"find to {peer} failed: {e!r}")
            return None

    async def rpc_find(self, request: dht_pb2.FindRequest, context: P2PContext) -> dht_pb2.FindResponse:
        self._register_sender(request.peer, context)
        sender_node_id = DHTID.from_bytes(request.peer.node_id) if request.peer.node_id else None
        response = dht_pb2.FindResponse(peer=self._make_node_info())
        for key_bytes in request.keys:
            key_id = DHTID.from_bytes(key_bytes)
            result = dht_pb2.FindResult(type=dht_pb2.NOT_FOUND)
            maybe_item = self.storage.get(key_id)
            cached_item = self.cache.get(key_id)
            if cached_item is not None and (
                maybe_item is None or cached_item.expiration_time > maybe_item.expiration_time
            ):
                maybe_item = cached_item
            if maybe_item is not None:
                if isinstance(maybe_item.value, DictionaryDHTValue):
                    result.type = dht_pb2.FOUND_DICTIONARY
                    result.value = maybe_item.value.packb_as_dict()
                else:
                    result.type = dht_pb2.FOUND_REGULAR
                    result.value = maybe_item.value
                result.expiration_time = maybe_item.expiration_time
            for node_id, info in self.routing_table.get_nearest_neighbors(
                key_id, self.bucket_size, exclude=sender_node_id
            ):
                result.nearest_node_ids.append(node_id.to_bytes())
                result.nearest_contacts.append(
                    dht_pb2.PeerContact(peer_id=info.peer_id.to_bytes(), maddrs=list(info.maddrs))
                )
            response.results.append(result)
        return response

    # ------------------------------------------------------------------ routing upkeep

    def _register_sender(self, peer_info: dht_pb2.NodeInfo, context: P2PContext) -> None:
        if peer_info.node_id:
            self.update_routing_table(
                DHTID.from_bytes(peer_info.node_id), context.remote_id, peer_info.maddrs, responded=True
            )

    def update_routing_table(
        self, node_id: DHTID, peer_id: PeerID, maddrs: Sequence[str], responded: bool
    ) -> None:
        """Register contact success/failure with the routing table; newly-added nodes receive
        local keys that are closer to them than to us (reference protocol.py:371-405)."""
        if node_id is None or node_id == self.node_id:
            return
        for maddr in maddrs:
            try:
                self.p2p.add_peer_addr(peer_id, maddr)
            except Exception:
                continue
        if not responded:
            self.routing_table.remove_node(node_id)
            _DHT_ROUTING_TABLE_SIZE.set(len(self.routing_table))
            return
        is_new = node_id not in self.routing_table
        ping_candidate = self.routing_table.add_or_update_node(node_id, PeerInfo(peer_id, tuple(maddrs)))
        _DHT_ROUTING_TABLE_SIZE.set(len(self.routing_table))
        if ping_candidate is not None:
            # bucket full: ping the stalest entry; evict it if dead (Kademlia §4.1)
            task = spawn(self._check_stale_node(*ping_candidate), name="dht.check_stale_node")
            self._handoff_tasks.add(task)
            task.add_done_callback(self._handoff_tasks.discard)
        if is_new and node_id in self.routing_table and self.storage:
            task = spawn(self._handoff_keys(node_id), name="dht.handoff_keys")
            self._handoff_tasks.add(task)
            task.add_done_callback(self._handoff_tasks.discard)

    async def _check_stale_node(self, node_id: DHTID, info: PeerInfo) -> None:
        result = await self.call_ping(info.peer_id, strict=False)
        bucket = self.routing_table.buckets[self.routing_table.get_bucket_index(node_id)]
        bucket.nodes_requested_for_ping.discard(node_id)
        if result is None:
            self.routing_table.remove_node(node_id)

    async def _handoff_keys(self, new_node_id: DHTID) -> None:
        """Replicate to a newcomer every local key that is closer to it than to us."""
        info = self.routing_table.get_info(new_node_id)
        if info is None:
            return
        keys, values, expirations = [], [], []
        with self.storage.freeze():
            for key_id, (value, expiration) in self.storage.items():
                if key_id.xor_distance(new_node_id) < key_id.xor_distance(self.node_id):
                    keys.append(key_id)
                    values.append(value)
                    expirations.append(expiration)
        if keys:
            await self.call_store(info.peer_id, keys, values, expirations)
