"""Kademlia routing primitives: DHTID, k-buckets, and the routing table.

Capability parity: reference hivemind/dht/routing.py (DHTID 252-303, RoutingTable
109-157, KBucket 167-248). Deviation: IDs are 256-bit SHA-256 (the reference uses
160-bit SHA1); the xor metric and bucket math are unchanged by width.
"""

from __future__ import annotations

import hashlib
import os
import random
from collections import OrderedDict
from itertools import chain
from typing import Any, Dict, Iterator, List, NamedTuple, Optional, Sequence, Tuple, Union

from hivemind_tpu.p2p.peer_id import Multiaddr, PeerID
from hivemind_tpu.utils.serializer import MSGPackSerializer

DHTKey = Any
Subkey = Any
BinaryDHTValue = bytes

ID_NBITS = 256
ID_NBYTES = ID_NBITS // 8


class DHTID(int):
    MIN = 0
    MAX = 2**ID_NBITS

    @classmethod
    def generate(cls, source: Optional[Any] = None, nbits: int = ID_NBITS) -> "DHTID":
        """Random id, or the hash of ``source`` (used to map keys into id space)."""
        if source is None:
            return cls(int.from_bytes(os.urandom(ID_NBYTES), "big"))
        if not isinstance(source, bytes):
            source = MSGPackSerializer.dumps(source)
        return cls(int.from_bytes(hashlib.sha256(source).digest(), "big"))

    def xor_distance(self, other: Union[int, Sequence[int]]) -> Union[int, List[int]]:
        if isinstance(other, (list, tuple)):
            return [int(self) ^ int(o) for o in other]
        return int(self) ^ int(other)

    def to_bytes(self) -> bytes:
        return int(self).to_bytes(ID_NBYTES, "big")

    @classmethod
    def from_bytes(cls, data: bytes) -> "DHTID":
        return cls(int.from_bytes(data, "big"))

    def __repr__(self) -> str:
        return f"DHTID({hex(int(self))[:18]}…)"


class PeerInfo(NamedTuple):
    """Contact info kept per routing-table entry: identity + dialable addresses.
    (The reference resolves PeerID→addr in the libp2p daemon's peerstore; this build
    carries addresses through the protocol instead.)"""

    peer_id: PeerID
    maddrs: Tuple[str, ...]


class KBucket:
    """Nodes with ids in [lower, upper); at most ``size`` live entries plus a
    replacement queue (reference routing.py:167-248)."""

    def __init__(self, lower: int, upper: int, size: int):
        assert lower < upper
        self.lower, self.upper, self.size = lower, upper, size
        self.nodes_to_peers: "OrderedDict[DHTID, PeerInfo]" = OrderedDict()
        self.replacement_nodes: "OrderedDict[DHTID, PeerInfo]" = OrderedDict()
        self.nodes_requested_for_ping: set = set()
        self.last_updated = 0.0

    def has_in_range(self, node_id: DHTID) -> bool:
        return self.lower <= node_id < self.upper

    def add_or_update_node(self, node_id: DHTID, info: PeerInfo) -> bool:
        """Move to fresh end if known, insert if space, else queue as replacement.
        Returns True unless the bucket was full (caller may then try to split)."""
        from hivemind_tpu.utils.timed_storage import get_dht_time

        self.last_updated = get_dht_time()
        if node_id in self.nodes_to_peers:
            self.nodes_to_peers.move_to_end(node_id)
            self.nodes_to_peers[node_id] = info
            return True
        if len(self.nodes_to_peers) < self.size:
            self.nodes_to_peers[node_id] = info
            return True
        if node_id in self.replacement_nodes:
            self.replacement_nodes.move_to_end(node_id)
        self.replacement_nodes[node_id] = info
        return False

    def request_ping_node(self) -> Optional[Tuple[DHTID, PeerInfo]]:
        """The stalest node not already being pinged (liveness check candidate)."""
        for node_id, info in self.nodes_to_peers.items():
            if node_id not in self.nodes_requested_for_ping:
                self.nodes_requested_for_ping.add(node_id)
                return node_id, info
        return None

    def remove_node(self, node_id: DHTID) -> Optional[Tuple[DHTID, PeerInfo]]:
        """Drop a node; promote the oldest replacement into the freed live slot.
        Returns the promoted (id, info) so the owning table can register it."""
        self.nodes_requested_for_ping.discard(node_id)
        promoted = None
        if node_id in self.nodes_to_peers:
            del self.nodes_to_peers[node_id]
            if self.replacement_nodes:
                replacement_id, info = self.replacement_nodes.popitem(last=False)
                self.nodes_to_peers[replacement_id] = info
                promoted = (replacement_id, info)
        self.replacement_nodes.pop(node_id, None)
        return promoted

    def split(self) -> Tuple["KBucket", "KBucket"]:
        midpoint = (self.lower + self.upper) // 2
        left, right = KBucket(self.lower, midpoint, self.size), KBucket(midpoint, self.upper, self.size)
        for node_id, info in chain(self.nodes_to_peers.items(), self.replacement_nodes.items()):
            bucket = left if node_id < midpoint else right
            bucket.add_or_update_node(node_id, info)
        left.last_updated = right.last_updated = self.last_updated
        return left, right

    def __repr__(self) -> str:
        return (
            f"KBucket({hex(self.lower)[:10]}…{hex(self.upper)[:10]}, "
            f"{len(self.nodes_to_peers)} nodes, {len(self.replacement_nodes)} replacements)"
        )


class RoutingTable:
    """All known peers bucketed by xor distance from our node id
    (reference routing.py:109-157)."""

    def __init__(self, node_id: DHTID, bucket_size: int = 20, depth_modulo: int = 5):
        self.node_id = node_id
        self.bucket_size = bucket_size
        self.depth_modulo = depth_modulo
        self.buckets: List[KBucket] = [KBucket(DHTID.MIN, DHTID.MAX, bucket_size)]
        self.peer_to_uid: Dict[PeerID, DHTID] = {}
        self.uid_to_info: Dict[DHTID, PeerInfo] = {}

    def get_bucket_index(self, node_id: DHTID) -> int:
        lo, hi = 0, len(self.buckets)
        while hi - lo > 1:
            mid = (lo + hi) // 2
            if self.buckets[mid].lower <= node_id:
                lo = mid
            else:
                hi = mid
        return lo

    def add_or_update_node(self, node_id: DHTID, info: PeerInfo) -> Optional[Tuple[DHTID, PeerInfo]]:
        """Register a live contact. Returns a (node_id, info) that should be pinged for
        liveness if the relevant bucket is full (Kademlia §2.2/4.1 eviction check)."""
        if node_id == self.node_id:
            return None
        bucket_index = self.get_bucket_index(node_id)
        bucket = self.buckets[bucket_index]
        store_success = bucket.add_or_update_node(node_id, info)
        if store_success:
            self._register_live(node_id, info)
            return None
        # full bucket: split if it covers our own id (or depth rule), else request ping
        if bucket.has_in_range(self.node_id) or self._bucket_depth(bucket) % self.depth_modulo != 0:
            self.split_bucket(bucket_index)
            return self.add_or_update_node(node_id, info)
        return bucket.request_ping_node()

    def _bucket_depth(self, bucket: KBucket) -> int:
        return ID_NBITS - (bucket.upper - bucket.lower - 1).bit_length()

    def split_bucket(self, index: int) -> None:
        left, right = self.buckets[index].split()
        self.buckets[index : index + 1] = [left, right]
        # replacements may have been promoted into the new buckets' live slots;
        # register every live node so lookups can see them
        for bucket in (left, right):
            for node_id, info in bucket.nodes_to_peers.items():
                self._register_live(node_id, info)

    def _register_live(self, node_id: DHTID, info: PeerInfo) -> None:
        self.peer_to_uid[info.peer_id] = node_id
        self.uid_to_info[node_id] = info

    def remove_node(self, node_id: DHTID) -> None:
        bucket = self.buckets[self.get_bucket_index(node_id)]
        info = self.uid_to_info.pop(node_id, None)
        if info is not None:
            self.peer_to_uid.pop(info.peer_id, None)
        promoted = bucket.remove_node(node_id)
        if promoted is not None:
            self._register_live(*promoted)

    def get_info(self, node_id: DHTID) -> Optional[PeerInfo]:
        return self.uid_to_info.get(node_id)

    def get_nearest_neighbors(
        self, query_id: DHTID, k: int, exclude: Optional[DHTID] = None
    ) -> List[Tuple[DHTID, PeerInfo]]:
        candidates = (
            (query_id.xor_distance(node_id), node_id, info)
            for node_id, info in self.uid_to_info.items()
            if node_id != exclude
        )
        import heapq

        nearest = heapq.nsmallest(k, candidates)
        return [(node_id, info) for _, node_id, info in nearest]

    def __contains__(self, item: Union[DHTID, PeerID]) -> bool:
        if isinstance(item, PeerID):
            return item in self.peer_to_uid
        return item in self.uid_to_info

    def __len__(self) -> int:
        return len(self.uid_to_info)

    def iter_nodes(self) -> Iterator[Tuple[DHTID, PeerInfo]]:
        return iter(list(self.uid_to_info.items()))

    def get_stale_buckets(self, staleness_seconds: float) -> List[KBucket]:
        from hivemind_tpu.utils.timed_storage import get_dht_time

        now = get_dht_time()
        return [b for b in self.buckets if now - b.last_updated > staleness_seconds]

    def sample_refresh_id(self, bucket: KBucket) -> DHTID:
        return DHTID(random.randint(bucket.lower, bucket.upper - 1))

    def __repr__(self) -> str:
        return f"RoutingTable({len(self)} nodes, {len(self.buckets)} buckets)"
