"""Schema-validated DHT records via pydantic v2 models (capability parity: reference
hivemind/dht/schema.py:15-155, which uses pydantic v1; this build is v2-native).

Each field of the schema model describes one DHT key: the field's type constrains the
values (and, for dict-typed fields, the subkey and value types). Keys not covered by
any schema are accepted or rejected according to ``allow_extra_keys``.
"""

from __future__ import annotations

import re
import typing
from typing import Any, Dict, Optional, Type

import pydantic

from hivemind_tpu.dht.routing import DHTID
from hivemind_tpu.dht.validation import DHTRecord, RecordValidatorBase
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)


class SchemaValidator(RecordValidatorBase):
    def __init__(
        self,
        schema: Type[pydantic.BaseModel],
        allow_extra_keys: bool = True,
        prefix: Optional[str] = None,
    ):
        self._patterns_to_models: Dict[re.Pattern, tuple] = {}
        self._allow_extra_keys = allow_extra_keys
        self._add_schema(schema, prefix)

    def _add_schema(self, schema: Type[pydantic.BaseModel], prefix: Optional[str]) -> None:
        for field_name, field_info in schema.model_fields.items():
            key_name = f"{prefix}_{field_name}" if prefix is not None else field_name
            key_id = DHTID.generate(source=key_name).to_bytes()
            annotation = field_info.annotation
            is_dict = typing.get_origin(annotation) in (dict, Dict)
            # a single-field model for validating one record's value
            field_model = pydantic.create_model(
                f"_Field_{key_name}",
                __config__=pydantic.ConfigDict(strict=False, arbitrary_types_allowed=True),
                value=(annotation, ...),
            )
            # a protected key may carry an [owner:…] suffix appended to the hashed id
            # (reference schema.py allows the same optional public-key tail)
            pattern = re.compile(re.escape(key_id.hex()) + r"(.*)?")
            self._patterns_to_models[pattern] = (field_model, is_dict, key_name)

    def validate(self, record: DHTRecord) -> bool:
        models = [
            (model, is_dict, name)
            for pattern, (model, is_dict, name) in self._patterns_to_models.items()
            if pattern.fullmatch(record.key.hex())
        ]
        if not models:
            if not self._allow_extra_keys:
                logger.debug(f"record key {record.key.hex()[:16]}… matches no schema")
            return self._allow_extra_keys
        try:
            value = MSGPackSerializer.loads(record.value)
        except Exception:
            logger.debug("schema validation: value is not valid msgpack")
            return False
        for model, is_dict, name in models:
            try:
                if is_dict and record.subkey:
                    subkey = MSGPackSerializer.loads(record.subkey)
                    model(value={subkey: value})
                else:
                    model(value=value)
                return True
            except pydantic.ValidationError as e:
                logger.debug(f"schema validation failed for key {name}: {e}")
        return False

    @property
    def priority(self) -> int:
        return 1  # runs beneath signature validators (on already-stripped values)

    def merge_with(self, other: RecordValidatorBase) -> bool:
        if not isinstance(other, SchemaValidator):
            return False
        self._patterns_to_models.update(other._patterns_to_models)
        self._allow_extra_keys = self._allow_extra_keys or other._allow_extra_keys
        return True


def conbytes(*, regex: Optional[bytes] = None) -> Any:
    """A bytes type constrained by a regex (parity helper for schemas like
    BytesWithPublicKey, reference schema.py:179)."""
    pattern = re.compile(regex) if regex is not None else None

    def _validate(value: Any) -> bytes:
        if not isinstance(value, bytes):
            raise ValueError(f"expected bytes, got {type(value)}")
        if pattern is not None and not pattern.fullmatch(value):
            raise ValueError("bytes do not match the required pattern")
        return value

    return typing.Annotated[bytes, pydantic.BeforeValidator(_validate)]


BytesWithEd25519PublicKey = conbytes(regex=rb".*\[owner:.+\].*")
