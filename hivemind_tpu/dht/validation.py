"""Record validation framework (capability parity: reference hivemind/dht/validation.py:6-123).

Validators inspect/transform records on store (sign, type-check) and on retrieval
(verify, strip signatures). ``CompositeValidator`` chains several in priority order.
"""

from __future__ import annotations

import dataclasses
import threading
from abc import ABC, abstractmethod
from typing import Iterable, List


@dataclasses.dataclass(init=True, repr=True, frozen=True)
class DHTRecord:
    key: bytes
    subkey: bytes
    value: bytes
    expiration_time: float


class DHTRecordRequestType:
    POST = "post"  # this node initiates the store
    GET = "get"  # record received from another node


class RecordValidatorBase(ABC):
    """Before storing, ``sign_value`` may extend the value; on every store (local or
    remote), ``validate`` accepts/rejects; ``strip_value`` removes any additions
    before handing values back to the caller."""

    @abstractmethod
    def validate(self, record: DHTRecord) -> bool: ...

    def sign_value(self, record: DHTRecord) -> bytes:
        return record.value

    def strip_value(self, record: DHTRecord) -> bytes:
        return record.value

    @property
    def priority(self) -> int:
        """Validators are applied on store in ascending priority; on strip in
        descending (reference validation.py:66-78)."""
        return 0

    def merge_with(self, other: "RecordValidatorBase") -> bool:
        """Try absorbing another validator of the same kind; True if merged."""
        return False


class CompositeValidator(RecordValidatorBase):
    def __init__(self, validators: Iterable[RecordValidatorBase] = ()):
        self._validators: List[RecordValidatorBase] = []
        self._lock = threading.Lock()
        self.extend(validators)

    def extend(self, validators: Iterable[RecordValidatorBase]) -> None:
        with self._lock:
            for new_validator in validators:
                for existing in self._validators:
                    if existing.merge_with(new_validator):
                        break
                else:
                    self._validators.append(new_validator)
            self._validators.sort(key=lambda v: -v.priority)

    def validate(self, record: DHTRecord) -> bool:
        # validators see the record progressively stripped of higher-priority layers
        for i, validator in enumerate(self._validators):
            if not validator.validate(record):
                return False
            record = dataclasses.replace(record, value=validator.strip_value(record))
        return True

    def sign_value(self, record: DHTRecord) -> bytes:
        for validator in reversed(self._validators):
            record = dataclasses.replace(record, value=validator.sign_value(record))
        return record.value

    def strip_value(self, record: DHTRecord) -> bytes:
        for validator in self._validators:
            record = dataclasses.replace(record, value=validator.strip_value(record))
        return record.value
