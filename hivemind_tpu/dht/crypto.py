"""Signature-protected DHT records (capability parity: reference hivemind/dht/crypto.py:12-91).

A key or subkey containing ``[owner:<pubkey>]`` may only be stored with a matching
``[signature:<sig>]`` suffix on the value, signed by that owner. Uses Ed25519 (the
reference uses RSA; see utils/crypto.py for the rationale).
"""

from __future__ import annotations

import base64
import dataclasses
import re
from typing import Optional

from hivemind_tpu.dht.validation import DHTRecord, RecordValidatorBase
from hivemind_tpu.utils.crypto import Ed25519PrivateKey, Ed25519PublicKey
from hivemind_tpu.utils.logging import get_logger
from hivemind_tpu.utils.serializer import MSGPackSerializer

logger = get_logger(__name__)


class Ed25519SignatureValidator(RecordValidatorBase):
    """Makes protected records editable only by their owner."""

    _owner_marker = b"[owner:"
    _signature_re = re.compile(rb"\[signature:(.*?)\]")

    def __init__(self, private_key: Optional[Ed25519PrivateKey] = None):
        self._private_key = private_key if private_key is not None else Ed25519PrivateKey.process_wide()
        # base64: raw key bytes could contain ']' and break marker extraction
        serialized_public = base64.b64encode(self._private_key.get_public_key().to_bytes())
        self._local_public_key = self._owner_marker + serialized_public + b"]"

    @property
    def local_public_key(self) -> bytes:
        """The marker blob callers embed in keys/subkeys they want to protect."""
        return self._local_public_key

    def validate(self, record: DHTRecord) -> bool:
        public_keys = self._extract_owner_keys(record.key) + self._extract_owner_keys(record.subkey)
        if not public_keys:
            return True  # unprotected record
        signature_match = self._signature_re.search(record.value)
        if signature_match is None:
            logger.debug("protected record has no signature")
            return False
        signature = signature_match.group(1)
        stripped = dataclasses.replace(record, value=self._signature_re.sub(b"", record.value))
        payload = self._record_payload(stripped)
        for serialized_key in public_keys:
            try:
                public_key = Ed25519PublicKey.from_bytes(base64.b64decode(serialized_key))
            except Exception:
                continue
            if public_key.verify(payload, signature):
                return True
        logger.debug("signature verification failed for protected record")
        return False

    def sign_value(self, record: DHTRecord) -> bytes:
        if self._local_public_key not in record.key and self._local_public_key not in record.subkey:
            return record.value
        signature = self._private_key.sign(self._record_payload(record))
        return record.value + b"[signature:" + signature + b"]"

    def strip_value(self, record: DHTRecord) -> bytes:
        return self._signature_re.sub(b"", record.value)

    def _record_payload(self, record: DHTRecord) -> bytes:
        return MSGPackSerializer.dumps(
            [record.key, record.subkey, record.value, record.expiration_time]
        )

    def _extract_owner_keys(self, field: bytes) -> list:
        if not field or self._owner_marker not in field:
            return []
        out = []
        start = 0
        while True:
            idx = field.find(self._owner_marker, start)
            if idx < 0:
                break
            end = field.find(b"]", idx)
            if end < 0:
                break
            out.append(field[idx + len(self._owner_marker) : end])
            start = end + 1
        return out

    @property
    def priority(self) -> int:
        return 10  # signatures wrap everything else (applied last on sign, first on strip)

    def merge_with(self, other: RecordValidatorBase) -> bool:
        # signature validators with different keys coexist: validation tries each owner
        return isinstance(other, Ed25519SignatureValidator) and other._local_public_key == self._local_public_key
