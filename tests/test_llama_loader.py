"""Llama-7B block-server readiness (VERDICT r2 next-round #8; BASELINE config #5):
real sharded HF-layout checkpoints load into llama_block backends, serve int8
weight-only through decode sessions, and per-block HBM accounting plans chip
capacity."""

import json
import time

import numpy as np
import optax
import pytest
from safetensors.numpy import save_file

from hivemind_tpu.dht import DHT
from hivemind_tpu.moe.server.llama_loader import (
    LlamaCheckpointConfig,
    ShardedSafetensorsReader,
    _block_params_from_hf,
    decode_cache_bytes,
    load_llama_blocks,
    plan_block_capacity,
)
from hivemind_tpu.moe.server.server import Server

HID, HEADS, KV_HEADS, INNER, LAYERS = 128, 4, 2, 352, 2


def _write_checkpoint(tmp_path, seed=0):
    """A tiny sharded HF-layout Llama checkpoint: 2 layers across 2 shard files."""
    rng = np.random.RandomState(seed)
    cfg = {
        "hidden_size": HID, "num_attention_heads": HEADS,
        "num_key_value_heads": KV_HEADS, "intermediate_size": INNER,
        "num_hidden_layers": LAYERS, "rope_theta": 10000.0,
        "rms_norm_eps": 1e-5,  # Llama-2's value; must thread through to the blocks
    }
    (tmp_path / "config.json").write_text(json.dumps(cfg))
    head_dim = HID // HEADS
    weight_map = {}
    for layer in range(LAYERS):
        prefix = f"model.layers.{layer}."
        scale = 1.0 / np.sqrt(HID)
        tensors = {
            prefix + "self_attn.q_proj.weight": rng.randn(HEADS * head_dim, HID) * scale,
            prefix + "self_attn.k_proj.weight": rng.randn(KV_HEADS * head_dim, HID) * scale,
            prefix + "self_attn.v_proj.weight": rng.randn(KV_HEADS * head_dim, HID) * scale,
            prefix + "self_attn.o_proj.weight": rng.randn(HID, HID) * scale,
            prefix + "mlp.gate_proj.weight": rng.randn(INNER, HID) * scale,
            prefix + "mlp.up_proj.weight": rng.randn(INNER, HID) * scale,
            prefix + "mlp.down_proj.weight": rng.randn(HID, INNER) * scale,
            prefix + "input_layernorm.weight": np.ones(HID),
            prefix + "post_attention_layernorm.weight": np.ones(HID),
        }
        shard = f"model-{layer:05d}-of-{LAYERS:05d}.safetensors"
        save_file({k: v.astype(np.float32) for k, v in tensors.items()}, tmp_path / shard)
        weight_map.update({name: shard for name in tensors})
    (tmp_path / "model.safetensors.index.json").write_text(
        json.dumps({"weight_map": weight_map})
    )


def _local_reference(checkpoint_dir, x):
    """Apply the checkpoint's blocks directly in flax (the ground truth)."""
    import jax.numpy as jnp

    from hivemind_tpu.moe.server.layers import name_to_block

    config = LlamaCheckpointConfig.load(checkpoint_dir)
    reader = ShardedSafetensorsReader(checkpoint_dir)
    out = jnp.asarray(x)
    for layer in range(config.num_hidden_layers):
        module = name_to_block["llama_block"](
            config.hidden_size, num_heads=config.num_attention_heads,
            num_kv_heads=config.num_key_value_heads, rope_theta=config.rope_theta,
            ffn_inner=config.intermediate_size, rms_eps=config.rms_norm_eps,
        )
        params = _block_params_from_hf(reader, layer)
        out = module.apply({"params": params}, out)
    return np.asarray(out)


def test_sharded_checkpoint_loads_exactly(tmp_path):
    _write_checkpoint(tmp_path)
    backends, config = load_llama_blocks(tmp_path, uid_prefix="lt.")
    assert config.num_hidden_layers == LAYERS and set(backends) == {"lt.0", "lt.1"}

    x = np.random.RandomState(3).randn(2, 16, HID).astype(np.float32)
    served = x
    for layer in range(LAYERS):
        served = backends[f"lt.{layer}"].forward(served)[0]
    # weights load exactly; the block COMPUTES in bf16, so jitted-vs-eager
    # reduction orderings differ at bf16 epsilon (elementwise rtol is meaningless
    # for near-zero outputs — compare in relative L2)
    truth = _local_reference(tmp_path, x)
    rel_err = np.linalg.norm(served - truth) / np.linalg.norm(truth)
    assert rel_err < 5e-3, rel_err


def test_int8_serving_close_smaller_and_frozen(tmp_path):
    _write_checkpoint(tmp_path)
    fp32, _ = load_llama_blocks(tmp_path, uid_prefix="f.")
    int8, _ = load_llama_blocks(tmp_path, uid_prefix="q.", weight_quantization="int8")

    # 4x smaller residency (norm scales stay exact, so slightly above 1/4)
    fp32_bytes = sum(b.param_bytes() for b in fp32.values())
    int8_bytes = sum(b.param_bytes() for b in int8.values())
    assert int8_bytes < 0.30 * fp32_bytes, (int8_bytes, fp32_bytes)

    x = np.random.RandomState(5).randn(2, 16, HID).astype(np.float32)
    exact, quant = x, x
    for layer in range(LAYERS):
        exact = fp32[f"f.{layer}"].forward(exact)[0]
        quant = int8[f"q.{layer}"].forward(quant)[0]
    rel_err = np.linalg.norm(quant - exact) / np.linalg.norm(exact)
    assert rel_err < 0.05, rel_err

    # weight-only serving is frozen: training calls must refuse loudly
    grads = np.ones_like(x)
    with pytest.raises(RuntimeError, match="inference-only"):
        int8["q.0"].backward(x, grads)

    # state_dict round-trips through the dense form and re-encodes exactly
    before = int8["q.0"].forward(x)[0]
    blob = int8["q.0"].state_dict()
    int8["q.0"].load_state_dict(blob)
    np.testing.assert_allclose(int8["q.0"].forward(x)[0], before)


def test_int8_blocks_serve_decode_sessions_over_rpc(tmp_path):
    """The full BASELINE #5 shape: checkpoint -> int8 blocks -> Server ->
    RemoteSequential KV-cache decode; outputs match local fp32 ground truth and
    tok/s is recorded."""
    from hivemind_tpu.moe import RemoteSequential

    _write_checkpoint(tmp_path)
    backends, _config = load_llama_blocks(tmp_path, uid_prefix="ls.", weight_quantization="int8")
    dht = DHT(start=True)
    server = Server(dht, backends, decode_max_len=64)
    client_dht = None
    try:
        server.run_in_background(await_ready=True)
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "ls.", LAYERS)

        rng = np.random.RandomState(11)
        prompt_len, steps = 8, 8
        hidden = rng.randn(1, prompt_len + steps, HID).astype(np.float32)

        start = time.perf_counter()
        out = pipe.decode_step(hidden[:, :prompt_len], "sess", reset=True)
        step_outs = [
            pipe.decode_step(hidden[:, prompt_len + t : prompt_len + t + 1], "sess")
            for t in range(steps)
        ]
        elapsed = time.perf_counter() - start
        toks_per_s = (prompt_len + steps) / elapsed
        print(f"\nint8 llama decode over RPC: {toks_per_s:.1f} tok/s ({LAYERS} blocks)")

        served = np.concatenate([np.asarray(out)] + [np.asarray(s) for s in step_outs], axis=1)
        truth = _local_reference(tmp_path, hidden)
        rel_err = np.linalg.norm(served - truth) / np.linalg.norm(truth)
        assert rel_err < 0.05, rel_err
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        dht.shutdown()


def test_hbm_planning_7b_shapes():
    """At real Llama-7B shapes, int8 fits the whole model on a 16 GB chip with
    decode sessions; fp32 does not — the accounting that picks block counts."""
    config = LlamaCheckpointConfig(
        hidden_size=4096, num_attention_heads=32, num_key_value_heads=32,
        intermediate_size=11008, num_hidden_layers=32,
    )
    params_per_block = 4 * 4096 * 4096 + 3 * 4096 * 11008 + 2 * 4096
    fp32_block = params_per_block * 4
    int8_block = params_per_block * 1.03  # + per-4096-block fp32 absmax overhead

    cache = decode_cache_bytes(config, batch=1, max_len=2048)
    assert cache == 2 * 2 * 2048 * 4096  # bf16 K+V, full kv heads

    hbm = 16 * 1024**3
    fp32_fit = plan_block_capacity(
        int(fp32_block), hbm_bytes=hbm, decode_sessions=8, cache_bytes_per_session_block=cache
    )
    int8_fit = plan_block_capacity(
        int(int8_block), hbm_bytes=hbm, decode_sessions=8, cache_bytes_per_session_block=cache
    )
    # fp32 7B + 8×2048-token sessions: ~1.08 GB/block → a third of the model/chip;
    # int8 more than doubles capacity, and at 4 sessions the WHOLE model fits
    assert fp32_fit < config.num_hidden_layers // 2
    assert int8_fit > 2 * fp32_fit
    int8_fit_light = plan_block_capacity(
        int(int8_block), hbm_bytes=hbm, decode_sessions=4, cache_bytes_per_session_block=cache
    )
    assert int8_fit_light >= config.num_hidden_layers

    with pytest.raises(ValueError):
        plan_block_capacity(1, hbm_bytes=None, device=None)  # CPU reports no limit


def test_single_file_checkpoint_and_missing_tensor(tmp_path):
    """Single-file model.safetensors checkpoints load identically to sharded ones,
    and a truncated checkpoint fails with a clear KeyError naming the tensor."""
    from safetensors.numpy import save_file

    _write_checkpoint(tmp_path)
    sharded = ShardedSafetensorsReader(tmp_path)

    single_dir = tmp_path / "single"
    single_dir.mkdir()
    (single_dir / "config.json").write_text((tmp_path / "config.json").read_text())
    save_file({name: sharded.get(name) for name in sharded.names()},
              single_dir / "model.safetensors")

    backends, config = load_llama_blocks(single_dir, uid_prefix="sf.")
    assert len(backends) == LAYERS and config.hidden_size == HID
    x = np.random.RandomState(9).randn(1, 8, HID).astype(np.float32)
    out = x
    for layer in range(LAYERS):
        out = backends[f"sf.{layer}"].forward(out)[0]
    ref = _local_reference(tmp_path, x)
    assert np.linalg.norm(out - ref) / np.linalg.norm(ref) < 1e-2  # bf16 compute noise

    truncated = tmp_path / "truncated"
    truncated.mkdir()
    (truncated / "config.json").write_text((tmp_path / "config.json").read_text())
    partial = {n: sharded.get(n) for n in sharded.names() if "mlp.down_proj" not in n}
    save_file(partial, truncated / "model.safetensors")
    with pytest.raises(KeyError, match="mlp.down_proj"):
        load_llama_blocks(truncated, uid_prefix="tr.")

    with pytest.raises(FileNotFoundError):
        ShardedSafetensorsReader(tmp_path / "nowhere")


def test_greedy_generation_from_checkpoint_over_rpc(tmp_path):
    """BASELINE #5 end-to-end: token ids in, token ids out. The client loads the
    checkpoint's embedding/final-norm/LM-head; the decoder blocks serve remotely
    with KV-cache sessions; greedy generation matches a local full-model replay
    of the same sequence."""
    from safetensors.numpy import save_file

    from hivemind_tpu.moe import RemoteSequential
    from hivemind_tpu.moe.server.llama_loader import LlamaClientHead, generate_greedy

    VOCAB = 96
    _write_checkpoint(tmp_path)
    rng = np.random.RandomState(21)
    head_tensors = {
        "model.embed_tokens.weight": (rng.randn(VOCAB, HID) / np.sqrt(HID)).astype(np.float32),
        "model.norm.weight": np.ones(HID, np.float32),
        # separate (untied) head so the tied-fallback path is NOT what's tested here
        "lm_head.weight": (rng.randn(VOCAB, HID) / np.sqrt(HID)).astype(np.float32),
    }
    shard = "model-head.safetensors"
    save_file(head_tensors, tmp_path / shard)
    index_path = tmp_path / "model.safetensors.index.json"
    index = json.loads(index_path.read_text())
    index["weight_map"].update({name: shard for name in head_tensors})
    index_path.write_text(json.dumps(index))

    backends, _config = load_llama_blocks(tmp_path, uid_prefix="gen.")
    head = LlamaClientHead.load(tmp_path)
    assert head.vocab_size == VOCAB
    assert not np.array_equal(head.lm_head_matrix, head.embed_matrix)

    dht = DHT(start=True)
    server = Server(dht, backends, decode_max_len=64)
    client_dht = None
    try:
        server.run_in_background(await_ready=True)
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in dht.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "gen.", LAYERS)

        prompt = rng.randint(0, VOCAB, size=(1, 6))
        generated = generate_greedy(head, pipe, prompt, max_new_tokens=8)
        assert generated.shape == (1, 14)
        assert np.array_equal(generated[:, :6], prompt)

        # local ground truth: full forward of the SERVED sequence through the
        # checkpoint blocks + head (teacher-forced replay, so positions check
        # independently). The served path computes in bf16 through a different
        # jit than the local one — a near-tied top-2 may flip, so accept the
        # generated token when its local logit is within bf16 noise of the max.
        hidden = _local_reference(tmp_path, head.embed(generated))
        local_logits = head.logits(hidden)
        for t in range(6, 14):
            position = local_logits[0, t - 1]
            best = float(np.max(position))
            chosen = float(position[int(generated[0, t])])
            tolerance = 2e-2 * max(abs(best), 1.0)
            assert best - chosen <= tolerance, (
                t, int(generated[0, t]), int(np.argmax(position)), best - chosen
            )
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server.shutdown()
        dht.shutdown()


def test_generation_across_two_servers(tmp_path):
    """The multi-server BASELINE #5 topology: each server hosts a layer RANGE of
    the same checkpoint (quickstart's --llama_layers story); the client chains
    them by uid and generates across both."""
    from safetensors.numpy import save_file

    from hivemind_tpu.moe import RemoteSequential
    from hivemind_tpu.moe.server.llama_loader import LlamaClientHead, generate_greedy

    VOCAB = 64
    _write_checkpoint(tmp_path)
    rng = np.random.RandomState(33)
    head_tensors = {
        "model.embed_tokens.weight": (rng.randn(VOCAB, HID) / np.sqrt(HID)).astype(np.float32),
        "model.norm.weight": np.ones(HID, np.float32),
    }
    save_file(head_tensors, tmp_path / "model-head.safetensors")
    index_path = tmp_path / "model.safetensors.index.json"
    index = json.loads(index_path.read_text())
    index["weight_map"].update({n: "model-head.safetensors" for n in head_tensors})
    index_path.write_text(json.dumps(index))

    backends_a, _config = load_llama_blocks(tmp_path, layers=[0], uid_prefix="sp.")
    backends_b, _config = load_llama_blocks(
        tmp_path, layers=[1], uid_prefix="sp.", weight_quantization="int8"
    )
    dht_a = DHT(start=True)
    server_a = Server(dht_a, backends_a, decode_max_len=64)
    dht_b = DHT(initial_peers=[str(m) for m in dht_a.get_visible_maddrs()], start=True)
    server_b = Server(dht_b, backends_b, decode_max_len=64)
    client_dht = None
    try:
        server_a.run_in_background(await_ready=True)
        server_b.run_in_background(await_ready=True)
        time.sleep(1.0)
        client_dht = DHT(initial_peers=[str(m) for m in dht_a.get_visible_maddrs()], start=True)
        pipe = RemoteSequential(client_dht, "sp.", LAYERS)
        head = LlamaClientHead.load(tmp_path)
        assert np.array_equal(head.lm_head_matrix, head.embed_matrix)  # tied fallback

        prompt = rng.randint(0, VOCAB, size=(1, 4))
        generated = generate_greedy(head, pipe, prompt, max_new_tokens=5)
        assert generated.shape == (1, 9)
        assert np.array_equal(generated[:, :4], prompt)
        assert (generated >= 0).all() and (generated < VOCAB).all()
    finally:
        if client_dht is not None:
            client_dht.shutdown()
        server_b.shutdown()
        server_a.shutdown()
        dht_b.shutdown()
        dht_a.shutdown()


def test_predicted_block_bytes_match_measured_gqa(tmp_path):
    """plan_block_capacity's planning input must be trustworthy BEFORE weights
    load (VERDICT r3 #8): predict_block_param_bytes from config arithmetic alone
    must match the measured resident bytes of a loaded block within 10%, for both
    fp32 and int8, at a GQA shape (hidden 1024, 4 layers, kv_heads < heads,
    sharded index)."""
    import json as json_module

    from benchmarks.benchmark_llama_serving import synthesize_checkpoint
    from hivemind_tpu.moe.server.llama_loader import (
        LlamaCheckpointConfig,
        load_llama_blocks,
        predict_block_param_bytes,
    )

    synthesize_checkpoint(tmp_path, hidden=1024, heads=8, kv_heads=2, inner=2816, layers=4)
    index = json_module.loads((tmp_path / "model.safetensors.index.json").read_text())
    assert len(set(index["weight_map"].values())) == 4  # genuinely sharded
    config = LlamaCheckpointConfig.load(tmp_path)
    assert config.num_key_value_heads < config.num_attention_heads  # GQA

    for quantization in (None, "int8"):
        predicted = predict_block_param_bytes(config, quantization)
        backends, _ = load_llama_blocks(
            tmp_path, uid_prefix="pb.", weight_quantization=quantization, layers=[0]
        )
        measured = backends["pb.0"].param_bytes()
        assert abs(predicted - measured) <= 0.10 * measured, (
            f"{quantization}: predicted {predicted} vs measured {measured}"
        )
    # int8 must actually shrink the block ~4x
    assert predict_block_param_bytes(config, "int8") < 0.3 * predict_block_param_bytes(config)
