"""The flagship recipe's monitor (examples/albert/run_training_monitor.py):
joins the swarm as an observer, aggregates signed progress records, and exports
wandb-style metrics to the offline JSONL sink (VERDICT r2 next-round #9)."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import optax

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import Optimizer

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_MONITOR = os.path.join(_REPO, "examples", "albert", "run_training_monitor.py")


def test_monitor_reports_and_exports_metrics(tmp_path):
    dht = DHT(start=True)
    opt = Optimizer(
        dht=dht, run_id="monitor_test", target_batch_size=1024,
        params={"w": np.zeros(4, np.float32)}, optimizer=optax.sgd(0.1),
        batch_size_per_step=8, matchmaking_time=1.0,
    )
    monitor = None
    try:
        # report progress a few times so the tracker publishes signed records
        for _ in range(5):
            opt.step({"w": np.ones(4, np.float32)})
            time.sleep(0.2)

        sink = tmp_path / "metrics.jsonl"
        env = dict(os.environ, PYTHONPATH=os.pathsep.join(
            [_REPO] + [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep) if p]
        ))
        monitor = subprocess.Popen(
            [sys.executable, _MONITOR, "--run_id", "monitor_test",
             "--initial_peers", str(dht.get_visible_maddrs()[0]),
             "--refresh_period", "1.0", "--max_reports", "2",
             "--metrics_jsonl", str(sink)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
        )
        # keep reporting while the monitor watches
        deadline = time.monotonic() + 90
        while monitor.poll() is None and time.monotonic() < deadline:
            opt.step({"w": np.ones(4, np.float32)})
            time.sleep(0.3)
        out, _ = monitor.communicate(timeout=30)
        assert monitor.returncode == 0, out[-3000:]

        records = [json.loads(line) for line in sink.read_text().splitlines()]
        assert len(records) >= 2, records
        for record in records:
            assert record["num_peers"] >= 1
            assert record["samples_per_second"] >= 0
            assert "epoch" in record and "time" in record
    finally:
        if monitor is not None and monitor.poll() is None:
            monitor.kill()
        opt.shutdown()
        dht.shutdown()
