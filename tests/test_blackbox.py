"""Black-box flight recorder (ISSUE 17): spool durability, post-mortem
reconstruction, skew-corrected merge, and sim virtual-time determinism.

The durability tests attack the on-disk format the way crashes do — torn
tails, bit flips, concurrent writers, restarts over a corpse — and assert the
reader degrades frame-by-frame instead of losing the spool. The sim test pins
the headline ISSUE 17 property: two same-seed scenario runs leave
bit-identical ``ledger_round`` frame streams in every peer's spool.
"""

import json
import struct
import threading

import pytest

from hivemind_tpu.hivemind_cli.run_blackbox import (
    estimate_skew,
    load_spools,
    main as blackbox_main,
    merge_timeline,
    reconstruct_final_round,
    render_spool_chrome_trace,
    spool_snapshot,
)
from hivemind_tpu.hivemind_cli.run_top import render_frame
from hivemind_tpu.sim import run_scenario
from hivemind_tpu.telemetry.blackbox import (
    READ_SKIPPED,
    BlackBox,
    SpoolWriter,
    arm_blackbox,
    disarm_blackbox,
    read_spool,
)
from hivemind_tpu.telemetry.ledger import RoundLedger
from hivemind_tpu.telemetry.registry import MetricsRegistry
from hivemind_tpu.telemetry.tracing import finish_span, start_span, trace

_FRAME_HEADER = struct.Struct(">II")


# ------------------------------------------------------------- spool durability


def test_rotation_under_concurrent_writers(tmp_path):
    """Many threads hammering one writer: every frame lands exactly once, in a
    frame-aligned segment, across however many rotations that forces."""
    writer = SpoolWriter(tmp_path, peer="p0", segment_bytes=4096, retention_segments=64)
    n_threads, per_thread = 8, 200

    def _pound(worker: int) -> None:
        for i in range(per_thread):
            writer.append("span", {"name": f"w{worker}", "i": i})

    threads = [threading.Thread(target=_pound, args=(w,)) for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    writer.close()

    frames, stats = read_spool(tmp_path)
    assert stats["torn_tail"] == 0 and stats["corrupt"] == 0
    assert stats["segments"] > 1, "4KiB segments must have rotated"
    assert len(list(tmp_path.glob("spool-*.open"))) == 0, "close() publishes the tail"
    spans = [f for f in frames if f["k"] == "span"]
    assert len(spans) == n_threads * per_thread
    # exactly-once per (worker, i): no frame lost or duplicated by rotation races
    seen = {(f["d"]["name"], f["d"]["i"]) for f in spans}
    assert len(seen) == n_threads * per_thread
    headers = [f for f in frames if f["k"] == "header"]
    assert len(headers) == stats["segments"], "every segment starts with a header"


def test_torn_tail_is_truncated_and_counted(tmp_path):
    """A kill-9 mid-frame leaves a half-written tail: the reader keeps every
    complete frame and counts the tear instead of exploding."""
    writer = SpoolWriter(tmp_path, peer="p0")
    for i in range(5):
        writer.append("span", {"i": i})
    # simulate the crash: close the fd without publishing, then tear the tail
    with writer._lock:
        writer._file.close()
        writer._file = None
    (open_seg,) = tmp_path.glob("spool-*.open")
    open_seg.write_bytes(open_seg.read_bytes()[:-7])  # mid-payload tear

    frames, stats = read_spool(tmp_path)
    assert stats["torn_tail"] == 1
    assert stats["corrupt"] == 0
    spans = [f["d"]["i"] for f in frames if f["k"] == "span"]
    assert spans == [0, 1, 2, 3], "all complete frames survive; only the torn one is lost"


def test_retention_cap_bounds_the_spool(tmp_path):
    writer = SpoolWriter(tmp_path, peer="p0", segment_bytes=2048, retention_segments=2)
    for i in range(400):
        writer.append("span", {"i": i, "pad": "x" * 64})
    writer.close()
    segments = sorted(tmp_path.glob("spool-*.seg"))
    assert len(segments) == 2, "oldest segments must be deleted past the cap"
    frames, _stats = read_spool(tmp_path)
    spans = [f["d"]["i"] for f in frames if f["k"] == "span"]
    # the survivors are the NEWEST frames, still contiguous and in order
    assert spans == list(range(spans[0], 400))


def test_corrupt_frame_is_skipped_frame_aligned(tmp_path):
    """A bit flip inside one payload: that frame dies (crc), every later frame
    still reads — the length header kept the stream aligned."""
    writer = SpoolWriter(tmp_path, peer="p0")
    for i in range(6):
        writer.append("span", {"i": i})
    writer.close()
    (seg,) = tmp_path.glob("spool-*.seg")
    raw = bytearray(seg.read_bytes())
    # walk to the 3rd frame (header frame + spans 0,1) and flip a payload byte
    offset = 0
    for _ in range(3):
        length, _crc = _FRAME_HEADER.unpack_from(raw, offset)
        offset += _FRAME_HEADER.size + length
    length, _crc = _FRAME_HEADER.unpack_from(raw, offset)
    raw[offset + _FRAME_HEADER.size + 2] ^= 0xFF
    seg.write_bytes(bytes(raw))

    skipped_before = READ_SKIPPED.value(reason="crc")
    frames, stats = read_spool(tmp_path)
    assert stats["corrupt"] == 1 and stats["torn_tail"] == 0
    assert READ_SKIPPED.value(reason="crc") == skipped_before + 1
    spans = [f["d"]["i"] for f in frames if f["k"] == "span"]
    assert spans == [0, 1, 3, 4, 5], "only the flipped frame is lost"


def test_restart_publishes_the_previous_incarnations_open_segment(tmp_path):
    """A restarted peer spooling into the same directory must not clobber its
    pre-crash evidence: the leftover .open is promoted to .seg and segment
    numbering continues past it."""
    first = SpoolWriter(tmp_path, peer="p0")
    first.append("span", {"life": 1})
    with first._lock:  # die without publishing
        first._file.close()
        first._file = None
    assert len(list(tmp_path.glob("spool-*.open"))) == 1

    second = SpoolWriter(tmp_path, peer="p0")
    second.append("span", {"life": 2})
    second.close()

    assert len(list(tmp_path.glob("spool-*.open"))) == 0
    frames, stats = read_spool(tmp_path)
    assert stats["segments"] == 2 and stats["torn_tail"] == 0
    lives = [f["d"]["life"] for f in frames if f["k"] == "span"]
    assert lives == [1, 2]


# ------------------------------------------------- listeners and post-mortem


def test_blackbox_spools_spans_and_reconstructs_the_crash_site(tmp_path):
    box = BlackBox(tmp_path, peer="p0", metrics_interval=None)
    with trace("optimizer.step", peer="p0"):
        pass
    # the operation the peer "dies inside": started, never finished
    start_span("averaging.allreduce", peer="p0")
    box.writer.append("ledger_round", {"round": 7, "slowest_peer": "pX", "peer": "p0"})
    box.abandon()  # kill-9 semantics: .open stays behind, unpublished

    assert len(list(tmp_path.glob("spool-*.open"))) == 1
    frames, stats = read_spool(tmp_path)
    kinds = [f["k"] for f in frames]
    assert kinds.count("span_start") == 2 and kinds.count("span") == 1

    post = reconstruct_final_round(frames, stats)
    assert post["reconstructed"] is True
    assert post["final_round"]["round"] == 7
    assert post["last_in_flight"]["name"] == "averaging.allreduce"
    assert post["open_spans"] == 1
    assert post["last_span"]["name"] == "optimizer.step"


def test_peer_filter_scopes_a_shared_telemetry_plane(tmp_path):
    """Multi-peer harnesses (soak, sim) arm one box per peer on one process:
    only frames attributable to the filtered peer may land in its spool."""
    box = BlackBox(tmp_path, peer_filter="pA", metrics_interval=None)
    try:
        with trace("dht.store", peer="pA"):
            pass
        with trace("dht.store", peer="pB"):
            pass
        with trace("dht.store"):  # no peer attribute at all
            pass
    finally:
        box.close()
    frames, _stats = read_spool(tmp_path)
    spans = [f for f in frames if f["k"] in ("span", "span_start")]
    assert spans, "the filtered peer's spans must spool"
    assert all(f["d"]["attrs"]["peer"] == "pA" for f in spans)


def test_arm_blackbox_is_idempotent_per_directory(tmp_path):
    try:
        box = arm_blackbox(tmp_path / "a", peer="p0", metrics_interval=None)
        assert arm_blackbox(tmp_path / "a", metrics_interval=None) is box
        other = arm_blackbox(tmp_path / "b", peer="p0", metrics_interval=None)
        assert other is not box
        assert box._closed, "re-arming a new directory closes the old box"
    finally:
        disarm_blackbox()


def test_closed_writer_swallows_late_listener_fires(tmp_path):
    box = BlackBox(tmp_path, peer="p0", metrics_interval=None)
    box.close()
    box.writer.append("span", {"late": True})  # must be a no-op, not a crash
    frames, _stats = read_spool(tmp_path)
    assert all(f["k"] == "header" for f in frames)


# --------------------------------------------------------- cross-peer merging


def _spoolset(*peers):
    """Synthetic load_spools() shape: {peer: {"frames", "stats", "header"}}."""
    return {
        peer: {"frames": frames, "stats": {"frames": len(frames), "segments": 1,
                                           "torn_tail": 0, "corrupt": 0},
               "header": {"peer": peer, "clock": "wall"}}
        for peer, frames in peers
    }


def test_skew_estimate_restores_cross_peer_causality():
    """Peer B's clock runs 10s behind: its child span 'starts before' the
    remote parent that caused it. The estimator must shift B forward until
    causality holds again."""
    parent = {"t": 100.0, "k": "span", "d": {"name": "rpc", "trace": "t1",
                                             "span": "aaaa", "start": 100.0, "dur_s": 1.0}}
    child = {"t": 90.2, "k": "span", "d": {"name": "handle", "trace": "t1", "span": "bbbb",
                                           "parent": "aaaa", "start": 90.2, "dur_s": 0.5}}
    spools = _spoolset(("A", [parent]), ("B", [child]))
    offsets = estimate_skew(spools)
    assert offsets["A"] == 0.0
    assert offsets["B"] == pytest.approx(9.8)

    merged = merge_timeline(spools, offsets)
    times = {f["peer"]: f["t"] for f in merged}
    assert times["B"] >= times["A"], "corrected child may not precede its parent"


def test_merge_timeline_last_window_anchors_on_the_victim():
    frames_a = [{"t": t, "k": "span", "d": {"span": f"a{t}", "start": t}} for t in (10.0, 50.0)]
    frames_b = [{"t": t, "k": "span", "d": {"span": f"b{t}", "start": t}} for t in (12.0, 30.0)]
    spools = _spoolset(("A", frames_a), ("B", frames_b))
    # victim B died at t=30: the window must end there, not at A's t=50
    merged = merge_timeline(spools, {"A": 0.0, "B": 0.0}, last_s=20.0, victim="B")
    assert [f["t"] for f in merged] == [10.0, 12.0, 30.0]


def test_chrome_export_marks_the_crash_site_in_flight():
    merged = [
        {"t": 1.0, "peer": "A", "k": "span",
         "d": {"name": "step", "trace": "t1", "span": "s1", "start": 1.0, "dur_s": 0.25}},
        {"t": 1.5, "peer": "A", "k": "span_start",
         "d": {"name": "allreduce", "trace": "t1", "span": "s2", "start": 1.5}},
    ]
    doc = render_spool_chrome_trace(merged)
    events = {e.get("name"): e for e in doc["traceEvents"]}
    assert events["step"]["ph"] == "X" and events["step"]["dur"] > 0
    assert events["allreduce"]["ph"] == "i", "unfinished span renders as an instant"
    assert events["allreduce"]["args"]["in_flight"] is True
    assert events["process_name"]["args"]["name"] == "peer A"


def test_spool_snapshot_feeds_the_dashboard(tmp_path):
    """hivemind-top --from-spool: a spool renders as a dashboard frame with
    straggler attribution recomputed from the spooled rounds."""
    box = BlackBox(tmp_path, peer="p0", metrics_interval=None)
    box.writer.append("ledger_round", {
        "round": 1, "peer": "p0", "slowest_peer": "pSlow",
        "exchanges": [{"peer": "pSlow", "dur_s": 2.0}, {"peer": "pFast", "dur_s": 0.5},
                      {"peer": "pMid", "dur_s": 0.6}],
    })
    with trace("optimizer.step", peer="p0"):
        pass
    box.snapshot_metrics()
    box.close()

    spools = load_spools([tmp_path])
    snapshot = spool_snapshot(spools["p0"])
    assert snapshot["ledger"]["records"][0]["round"] == 1
    scores = snapshot["ledger"]["stragglers"]["pSlow"]
    assert scores["rounds_slowest"] == 1 and scores["excess_s"] == pytest.approx(1.4)
    assert "metrics" in snapshot and snapshot["slow_spans"]

    frame, _samples = render_frame({"p0": snapshot}, now=snapshot["time"], ansi=False)
    assert "p0" in frame


def test_cli_end_to_end(tmp_path, capsys):
    spool_dir = tmp_path / "peerA"
    box = BlackBox(spool_dir, peer="peerA", metrics_interval=None)
    with trace("dht.store", peer="peerA"):
        pass
    start_span("averaging.allreduce", peer="peerA")
    box.writer.append("ledger_round", {"round": 3, "peer": "peerA", "slowest_peer": "pX"})
    box.abandon()

    assert blackbox_main([str(spool_dir), "--victim", "peerA", "--format", "json"]) == 0
    report = json.loads(capsys.readouterr().out)
    post = report["postmortem"]["peerA"]
    assert post["final_round"]["round"] == 3
    assert post["last_in_flight"]["name"] == "averaging.allreduce"

    out = tmp_path / "trace.json"
    assert blackbox_main([str(spool_dir), "--format", "chrome", "--out", str(out)]) == 0
    doc = json.loads(out.read_text())
    names = {e.get("name") for e in doc["traceEvents"]}
    assert "averaging.allreduce" in names and "dht.store" in names


# --------------------------------------------------- sim virtual-time spools


def test_sim_same_seed_spools_are_bit_identical(tmp_path):
    """ISSUE 17 acceptance: a seeded sim scenario with per-peer spools leaves
    bit-identical virtual-time ``ledger_round`` frame streams (straggler
    attribution included) across two same-seed runs."""
    params = dict(peers=24, regions=2, keys=40, churn_fraction=0.15, probe_samples=10,
                  matchmaking_peers=8, matchmaking_rounds=1)
    first = run_scenario("dht_churn", seed=33, blackbox_root=str(tmp_path / "one"), **params)
    second = run_scenario("dht_churn", seed=33, blackbox_root=str(tmp_path / "two"), **params)

    ledger = first.summary["matchmaking"]["ledger"]
    assert ledger["rounds"] > 0, "the cohort must have produced virtual-time rounds"
    assert first.digest() == second.digest(), "the ledger summary rides the digest"

    one = sorted(p.name for p in (tmp_path / "one").iterdir())
    two = sorted(p.name for p in (tmp_path / "two").iterdir())
    assert one == two and len(one) == 8, "one spool per cohort peer"
    compared_rounds = 0
    for name in one:
        frames_one, stats_one = read_spool(tmp_path / "one" / name)
        frames_two, stats_two = read_spool(tmp_path / "two" / name)
        assert stats_one["torn_tail"] == 0 and stats_one["corrupt"] == 0
        rounds_one = [f for f in frames_one if f["k"] == "ledger_round"]
        rounds_two = [f for f in frames_two if f["k"] == "ledger_round"]
        # full frames — virtual timestamps included — must match bit for bit
        assert rounds_one == rounds_two
        compared_rounds += len(rounds_one)
        # virtual clock: frame timestamps are sim-time (epoch-magnitude anchor)
        assert all(f["t"] >= 1e9 for f in rounds_one)
    assert compared_rounds > 0, "at least one peer must have spooled its rounds"
