"""Integration tests: DHTProtocol RPCs against live nodes, DHTNode swarm store/get,
caching, blacklist (scope: reference tests/test_dht_protocol.py + test_dht_node.py).
All swarms are real localhost TCP — no fake network."""

import asyncio
import random

import pytest

from hivemind_tpu.dht.node import DHTNode
from hivemind_tpu.dht.protocol import DHTProtocol
from hivemind_tpu.dht.routing import DHTID
from hivemind_tpu.dht.storage import DictionaryDHTValue
from hivemind_tpu.p2p import P2P
from hivemind_tpu.utils.serializer import MSGPackSerializer
from hivemind_tpu.utils.timed_storage import get_dht_time


async def make_protocol_pair():
    p2p_a, p2p_b = await P2P.create(), await P2P.create()
    proto_a = await DHTProtocol.create(p2p_a, DHTID.generate(), bucket_size=20, cache_size=100, client_mode=False)
    proto_b = await DHTProtocol.create(p2p_b, DHTID.generate(), bucket_size=20, cache_size=100, client_mode=False)
    await p2p_a.connect(p2p_b.get_visible_maddrs()[0])
    return (p2p_a, proto_a), (p2p_b, proto_b)


async def test_protocol_ping_store_find():
    (p2p_a, proto_a), (p2p_b, proto_b) = await make_protocol_pair()
    try:
        # ping registers both directions
        peer_node_id = await proto_a.call_ping(p2p_b.peer_id)
        assert peer_node_id == proto_b.node_id
        assert proto_b.node_id in proto_a.routing_table
        assert proto_a.node_id in proto_b.routing_table

        # plain store + find
        key_id = DHTID.generate(source=b"key")
        now = get_dht_time()
        ok = await proto_a.call_store(p2p_b.peer_id, [key_id], [b"value"], now + 30)
        assert ok == [True]
        found = await proto_a.call_find(p2p_b.peer_id, [key_id])
        value, nearest = found[key_id]
        assert value.value == b"value" and abs(value.expiration_time - (now + 30)) < 1e-6

        # stale store rejected
        ok = await proto_a.call_store(p2p_b.peer_id, [key_id], [b"stale"], now + 10)
        assert ok == [False]

        # subkey (dictionary) store + find
        dict_key = DHTID.generate(source=b"dict")
        ok = await proto_a.call_store(
            p2p_b.peer_id, [dict_key], [b"v1"], now + 30, subkeys=["sub1"]
        )
        assert ok == [True]
        ok = await proto_a.call_store(
            p2p_b.peer_id, [dict_key], [b"v2"], now + 40, subkeys=["sub2"]
        )
        assert ok == [True]
        found = await proto_a.call_find(p2p_b.peer_id, [dict_key])
        value, _ = found[dict_key]
        assert isinstance(value.value, DictionaryDHTValue)
        assert value.value.get("sub1").value == b"v1"
        assert value.value.get("sub2").value == b"v2"

        # find for a missing key: no value; nearest excludes the requester itself,
        # so in a 2-node swarm the neighbor list is empty
        missing = DHTID.generate()
        found = await proto_a.call_find(p2p_b.peer_id, [missing])
        value, nearest = found[missing]
        assert value is None and proto_a.node_id not in nearest
    finally:
        for proto, p2p in ((proto_a, p2p_a), (proto_b, p2p_b)):
            await proto.shutdown()
            await p2p.shutdown()


async def test_protocol_unreachable_peer():
    p2p = await P2P.create()
    proto = await DHTProtocol.create(p2p, DHTID.generate(), 20, 100, client_mode=False)
    try:
        from hivemind_tpu.utils.crypto import Ed25519PrivateKey
        from hivemind_tpu.p2p import PeerID

        ghost = PeerID.from_private_key(Ed25519PrivateKey())
        assert await proto.call_ping(ghost) is None
        assert await proto.call_store(ghost, [DHTID.generate()], [b"x"], get_dht_time() + 9) is None
        assert await proto.call_find(ghost, [DHTID.generate()]) is None
    finally:
        await proto.shutdown()
        await p2p.shutdown()


async def launch_swarm(n_peers: int, **kwargs):
    """A real localhost swarm of DHTNodes, each bootstrapping off the first."""
    nodes = [await DHTNode.create(**kwargs)]
    first_maddrs = await nodes[0].get_visible_maddrs()
    rest = await asyncio.gather(
        *(DHTNode.create(initial_peers=[str(m) for m in first_maddrs], **kwargs) for _ in range(n_peers - 1))
    )
    nodes.extend(rest)
    return nodes


async def shutdown_swarm(nodes):
    await asyncio.gather(*(node.shutdown() for node in nodes))


async def test_dht_node_swarm_store_get():
    nodes = await launch_swarm(8)
    try:
        now = get_dht_time()
        # store via one node, get via another
        assert await nodes[1].store("communism", "ok", now + 60)
        result = await nodes[-1].get("communism")
        assert result is not None and result.value == "ok"

        # complex values survive serialization
        payload = {"tensors": [1, 2, 3], "meta": ("tuple", b"bytes")}
        assert await nodes[2].store("payload", payload, now + 60)
        result = await nodes[5].get("payload")
        assert result.value == payload

        # missing key
        assert await nodes[3].get("no_such_key") is None

        # freshest value wins with latest=True
        assert await nodes[0].store("versioned", "old", now + 30)
        assert await nodes[4].store("versioned", "new", now + 50)
        result = await nodes[6].get("versioned", latest=True)
        assert result.value == "new"
    finally:
        await shutdown_swarm(nodes)


async def test_dht_node_subkeys_across_swarm():
    nodes = await launch_swarm(6)
    try:
        now = get_dht_time()
        assert await nodes[0].store("grid", value=b"expert0", expiration_time=now + 60, subkey="e0")
        assert await nodes[2].store("grid", value=b"expert1", expiration_time=now + 61, subkey="e1")
        result = await nodes[4].get("grid", latest=True)
        assert isinstance(result.value, dict)
        assert result.value["e0"].value == b"expert0"
        assert result.value["e1"].value == b"expert1"
    finally:
        await shutdown_swarm(nodes)


async def test_dht_node_caching():
    # num_replicas=1 so most nodes do NOT hold the value in storage and must cache it
    nodes = await launch_swarm(5, cache_refresh_before_expiry=0, num_replicas=1)
    try:
        now = get_dht_time()
        await nodes[0].store("hot_key", 42, now + 60)
        key_id = DHTID.generate(source="hot_key")
        reader = next(n for n in nodes if n.protocol.storage.get(key_id) is None)
        result = await reader.get("hot_key")
        assert result.value == 42
        # second read must be servable from the local cache
        assert reader.protocol.cache.get(key_id) is not None
    finally:
        await shutdown_swarm(nodes)


async def test_dht_node_blacklist_and_recovery():
    nodes = await launch_swarm(4)
    try:
        victim = nodes[2]
        victim_peer = victim.peer_id
        await victim.shutdown()
        # trigger failures so survivors blacklist the dead peer
        now = get_dht_time()
        for i in range(3):
            await nodes[0].store(f"k{i}", i, now + 30)
        for node in (nodes[0],):
            # peer may or may not have been contacted, but if it failed it must be banned
            if node.blacklist.ban_counter.get(victim_peer, 0) > 0:
                assert victim_peer in node.blacklist
        # the swarm still functions
        assert await nodes[1].get("k0") is not None or await nodes[0].get("k0") is not None
    finally:
        await shutdown_swarm([n for n in nodes if n is not nodes[2]])


async def test_dht_node_client_mode():
    nodes = await launch_swarm(3)
    try:
        maddrs = [str(m) for m in await nodes[0].get_visible_maddrs()]
        client = await DHTNode.create(initial_peers=maddrs, client_mode=True)
        now = get_dht_time()
        assert await client.store("from_client", "hello", now + 30)
        assert (await nodes[1].get("from_client")).value == "hello"
        # client must not appear in anyone's routing table
        for node in nodes:
            assert client.node_id not in node.protocol.routing_table
        await client.shutdown()
    finally:
        await shutdown_swarm(nodes)


async def test_dht_node_beam_search_matches_direct():
    """Every value stored anywhere must be retrievable from every node."""
    nodes = await launch_swarm(10)
    try:
        now = get_dht_time()
        keys = [f"key{i}" for i in range(12)]
        for i, key in enumerate(keys):
            assert await nodes[i % len(nodes)].store(key, i, now + 120)
        random.shuffle(keys)
        getters = random.choices(nodes, k=len(keys))
        results = await asyncio.gather(*(node.get(key) for node, key in zip(getters, keys)))
        for key, result in zip(keys, results):
            assert result is not None, f"lost {key}"
            assert result.value == int(key[3:])
    finally:
        await shutdown_swarm(nodes)
