"""ICI tier: MeshTensorBridge collectives and MeshAverager in a real swarm
(SURVEY §5 two-tier communication backend; VERDICT r1 item 3).

A peer whose state is sharded over the 8-device virtual CPU mesh joins a swarm round
with a plain host-resident peer; the averages must match the numpy path exactly."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from hivemind_tpu.averaging import DecentralizedAverager, MeshAverager
from hivemind_tpu.dht import DHT
from hivemind_tpu.parallel import MeshTensorBridge, make_mesh

from swarm_utils import launch_dht_swarm


def test_bridge_gather_scatter_roundtrip():
    mesh = make_mesh(dp=2, tp=2, sp=2)
    bridge = MeshTensorBridge(mesh)
    rng = np.random.RandomState(0)
    host = {
        "a": rng.randn(8, 16).astype(np.float32),
        "b": rng.randn(4, 4, 4).astype(np.float32),
    }
    tree = {
        "a": jax.device_put(host["a"], NamedSharding(mesh, P("dp", "tp"))),
        "b": jax.device_put(host["b"], NamedSharding(mesh, P("sp", None, None))),
    }
    gathered = bridge.gather_to_host(tree)
    flat_host = [host["a"], host["b"]]  # tree_flatten orders dict leaves by sorted key
    for got, expected in zip(gathered, flat_host):
        np.testing.assert_array_equal(got, expected)

    # scatter modified values back; shardings must be preserved
    modified = [t + 1.0 for t in gathered]
    new_tree = bridge.scatter_from_host(tree, modified)
    np.testing.assert_array_equal(np.asarray(new_tree["a"]), host["a"] + 1.0)
    assert new_tree["a"].sharding.spec == P("dp", "tp")


def test_staging_runs_no_xla_and_pulls_each_region_once():
    """VERDICT r2 weak #3 regression: host staging must be pure shard pulls — no
    jit/XLA computation (the old replicated-gather cost a full model replica of
    device memory PER DEVICE), and each distinct region must be fetched from
    exactly one device even when the sharding replicates it across many."""
    mesh = make_mesh(dp=2, tp=2, sp=2)
    bridge = MeshTensorBridge(mesh)
    rng = np.random.RandomState(2)
    host = {
        "sharded": rng.randn(8, 16).astype(np.float32),
        "replicated": rng.randn(5, 3).astype(np.float32),  # every device holds it all
        "mixed": rng.randn(4, 6).astype(np.float32),  # sharded over dp, replicated over tp/sp
    }
    tree = {
        "sharded": jax.device_put(host["sharded"], NamedSharding(mesh, P("dp", "tp"))),
        "replicated": jax.device_put(host["replicated"], NamedSharding(mesh, P())),
        "mixed": jax.device_put(host["mixed"], NamedSharding(mesh, P("dp", None))),
    }

    # every distinct region exactly once: 4 for P(dp, tp), 1 for replicated, 2 for P(dp)
    assert len(bridge._unique_shards(tree["sharded"])) == 4
    assert len(bridge._unique_shards(tree["replicated"])) == 1
    assert len(bridge._unique_shards(tree["mixed"])) == 2

    import unittest.mock

    mirrors = bridge.allocate_mirrors(tree)
    with unittest.mock.patch.object(
        jax, "jit", side_effect=AssertionError("staging must not launch XLA computations")
    ):
        bridge.stage_into_mirrors(tree, mirrors)
    flat_host = [jax.tree_util.tree_flatten(host)[0][i] for i in range(3)]
    for got, expected in zip(mirrors, flat_host):
        np.testing.assert_array_equal(got, expected)

    # bf16 leaves are upcast into the fp32 mirrors shard-by-shard
    bf16 = jax.device_put(
        jnp.asarray(host["sharded"], jnp.bfloat16), NamedSharding(mesh, P("dp", None))
    )
    [mirror] = bridge.gather_to_host([bf16])
    assert mirror.dtype == np.float32
    np.testing.assert_allclose(mirror, host["sharded"], atol=0.01, rtol=0.01)


def test_bridge_mesh_mean_is_psum_mean():
    """Per-replica stacks reduce on-device (pmean under shard_map) to the numpy mean."""
    mesh = make_mesh(dp=4, tp=2)
    bridge = MeshTensorBridge(mesh)
    rng = np.random.RandomState(1)
    stacked_host = rng.randn(4, 6, 8).astype(np.float32)  # leading dim = dp replicas
    stacked = jax.device_put(stacked_host, NamedSharding(mesh, P("dp", "tp", None)))
    reduced = bridge.mesh_mean({"g": stacked}, axis="dp")["g"]
    assert reduced.shape == (6, 8)
    np.testing.assert_allclose(np.asarray(reduced), stacked_host.mean(axis=0), rtol=1e-6)


def _launch_swarm_pair(mesh_tree, host_tensors, prefix, **mesh_kwargs):
    first, second = launch_dht_swarm(2)
    common = dict(
        prefix=prefix, start=True, target_group_size=2,
        min_matchmaking_time=1.0, request_timeout=1.0,
        sender_timeout=5.0, reducer_timeout=10.0,
    )
    mesh = mesh_kwargs.pop("mesh")
    mesh_peer = MeshAverager(mesh_tree, mesh, first, **mesh_kwargs, **common)
    host_peer = DecentralizedAverager(host_tensors, second, **common)
    return first, second, mesh_peer, host_peer


def test_mesh_peer_joins_swarm_round():
    """8-device mesh peer + host peer: post-round device shards hold the exact
    cross-peer average and the host peer sees the mesh peer's contribution."""
    mesh = make_mesh(dp=2, tp=2, sp=2)
    rng = np.random.RandomState(2)
    w_host = rng.randn(8, 32).astype(np.float32)
    b_host = rng.randn(64).astype(np.float32)
    tree = {
        "w": jax.device_put(w_host, NamedSharding(mesh, P("dp", "tp"))),
        "b": jax.device_put(b_host, NamedSharding(mesh, P("sp"))),
    }
    peer_w = rng.randn(8, 32).astype(np.float32)
    peer_b = rng.randn(64).astype(np.float32)

    first = second = mesh_peer = host_peer = None
    try:
        # host list must follow the mesh peer's flatten order (dict keys sorted: b, w)
        first, second, mesh_peer, host_peer = _launch_swarm_pair(
            tree, [peer_b, peer_w], "ici_round", mesh=mesh
        )
        controls = [a.step(wait=False, timeout=30) for a in (mesh_peer, host_peer)]
        for control in controls:
            assert control.result(timeout=60) is not None

        expected_w = (w_host + peer_w) / 2.0
        expected_b = (b_host + peer_b) / 2.0
        averaged = mesh_peer.device_tree
        assert averaged["w"].sharding.spec == P("dp", "tp")
        # the ICI staging path adds ZERO error: device shards are bit-identical to
        # the peer's own post-round host mirrors (the numpy path)
        with mesh_peer.get_tensors() as mirrors:
            np.testing.assert_array_equal(np.asarray(averaged["b"]), mirrors[0])
            np.testing.assert_array_equal(np.asarray(averaged["w"]), mirrors[1])
        # and the round itself converged to the cross-peer mean (delta application
        # costs at most 1 ulp, same as host-resident peers)
        np.testing.assert_allclose(np.asarray(averaged["w"]), expected_w, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(averaged["b"]), expected_b, rtol=1e-6, atol=1e-7)
        with host_peer.get_tensors() as tensors:
            np.testing.assert_allclose(tensors[0], expected_b, rtol=1e-6, atol=1e-7)
            np.testing.assert_allclose(tensors[1], expected_w, rtol=1e-6, atol=1e-7)
    finally:
        for obj in (mesh_peer, host_peer, first, second):
            if obj is not None:
                obj.shutdown()


def test_mesh_peer_local_reduce_axis():
    """Per-dp-replica gradients: the swarm sees the ICI mean; afterwards every
    replica adopts the swarm average (broadcast scatter)."""
    mesh = make_mesh(dp=2, tp=2, sp=2)
    rng = np.random.RandomState(3)
    stacked_host = rng.randn(2, 12, 4).astype(np.float32)  # [dp, ...]
    tree = {"g": jax.device_put(stacked_host, NamedSharding(mesh, P("dp", "tp", None)))}
    ici_mean = stacked_host.mean(axis=0)
    peer_g = rng.randn(12, 4).astype(np.float32)

    first = second = mesh_peer = host_peer = None
    try:
        first, second, mesh_peer, host_peer = _launch_swarm_pair(
            tree, [peer_g], "ici_grad", mesh=mesh, local_reduce_axis="dp"
        )
        controls = [a.step(wait=False, timeout=30) for a in (mesh_peer, host_peer)]
        for control in controls:
            assert control.result(timeout=60) is not None

        expected = (ici_mean + peer_g) / 2.0
        averaged = np.asarray(mesh_peer.device_tree["g"])
        assert averaged.shape == (2, 12, 4)
        for replica in range(2):
            np.testing.assert_allclose(averaged[replica], expected, rtol=1e-6, atol=1e-7)
        with host_peer.get_tensors() as tensors:
            np.testing.assert_allclose(tensors[0], expected, rtol=1e-6, atol=1e-7)
    finally:
        for obj in (mesh_peer, host_peer, first, second):
            if obj is not None:
                obj.shutdown()


def test_mesh_peer_fresh_state_staged_per_round():
    """The mesh tree can change between rounds; _pre_allreduce must stage the CURRENT
    device values, not the construction-time snapshot."""
    mesh = make_mesh(dp=2, tp=2, sp=2)
    zeros = np.zeros((16,), np.float32)
    tree = {"x": jax.device_put(zeros, NamedSharding(mesh, P("dp")))}
    peer_x = np.full((16,), 4.0, np.float32)

    first = second = mesh_peer = host_peer = None
    try:
        first, second, mesh_peer, host_peer = _launch_swarm_pair(
            tree, [peer_x], "ici_fresh", mesh=mesh
        )
        # user updates the device tree after construction (e.g. a local train step)
        ones = np.full((16,), 2.0, np.float32)
        mesh_peer.device_tree = {"x": jax.device_put(ones, NamedSharding(mesh, P("dp")))}

        controls = [a.step(wait=False, timeout=30) for a in (mesh_peer, host_peer)]
        for control in controls:
            assert control.result(timeout=60) is not None
        np.testing.assert_array_equal(
            np.asarray(mesh_peer.device_tree["x"]), np.full((16,), 3.0, np.float32)
        )
    finally:
        for obj in (mesh_peer, host_peer, first, second):
            if obj is not None:
                obj.shutdown()


@pytest.mark.slow  # ~60 s subprocess benchmark; the staging LOGIC is covered
# sub-second by the test_mesh_peer_* tests above — this only re-measures RSS
def test_streaming_staging_memory_bar_100m_params():
    """The 100M-param ICI staging round must grow RSS by at most 1.5x the model
    size (VERDICT r3 #4): per-leaf streaming reduce+stage never materializes the
    reduced tree whole, and steady-state rounds reuse persistent mirrors. Run in a
    fresh subprocess so this process's earlier high-water mark cannot mask (or
    fake) the measurement — asserted against the same benchmark artifact RESULTS.md
    records (benchmarks/benchmark_ici.py)."""
    import json
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # the benchmark sets its own device-count flag
    result = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks", "benchmark_ici.py"),
         "--num_params", "100000000", "--num_rounds", "2", "--platform", "cpu"],
        capture_output=True, text=True, timeout=420, env=env,
    )
    assert result.returncode == 0, result.stderr[-2000:]
    record = json.loads(result.stdout.strip().splitlines()[-1])
    model_gb = record["extra"]["model_gb"]
    growth_gb = record["extra"]["rss_growth_during_rounds_gb"]
    assert growth_gb <= 1.5 * model_gb, (
        f"staging rounds grew RSS by {growth_gb} GB against a {model_gb} GB model "
        f"(> 1.5x bar): whole-tree transients are back"
    )
