"""Transport robustness fuzzing: a live P2P server fed garbage at every protocol
layer — raw TCP bytes, valid-handshake-then-garbage-ciphertext, and authenticated
mux frames with malformed headers/flags/stream ids — must drop the offender and
keep serving legitimate clients (the reference inherits this hardening from
go-libp2p; here the wire stack is ours, so the evidence must be too)."""

import asyncio
import os
import struct

import numpy as np

from hivemind_tpu.p2p import P2P
from hivemind_tpu.p2p.crypto_channel import handshake
from hivemind_tpu.proto import test_pb2
from hivemind_tpu.utils.crypto import Ed25519PrivateKey


async def _echo_server():
    server = await P2P.create()

    async def echo(request: test_pb2.TestRequest, context) -> test_pb2.TestResponse:
        return test_pb2.TestResponse(number=request.number * 2)

    await server.add_protobuf_handler("echo", echo, test_pb2.TestRequest)
    return server


async def _assert_still_serving(server):
    client = await P2P.create()
    try:
        await client.connect(server.get_visible_maddrs()[0])
        response = await asyncio.wait_for(
            client.call_protobuf_handler(
                server.peer_id, "echo", test_pb2.TestRequest(number=21), test_pb2.TestResponse
            ),
            timeout=15,
        )
        assert response.number == 42
    finally:
        await client.shutdown()


def test_raw_garbage_and_oversize_headers_do_not_kill_the_server():
    async def scenario():
        server = await _echo_server()
        host, port = "127.0.0.1", server.listen_port
        rng = np.random.RandomState(0)
        try:
            for attempt in range(20):
                reader, writer = await asyncio.open_connection(host, port)
                if attempt % 4 == 0:
                    payload = rng.bytes(rng.randint(1, 2000))  # raw noise
                elif attempt % 4 == 1:
                    payload = struct.pack(">I", 0xFFFFFFFF)  # absurd length prefix
                elif attempt % 4 == 2:
                    payload = struct.pack(">I", 64) + rng.bytes(10)  # truncated frame
                else:
                    payload = b""  # connect-and-vanish
                try:
                    writer.write(payload)
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass
                writer.close()
            await _assert_still_serving(server)
        finally:
            await server.shutdown()

    asyncio.run(asyncio.wait_for(scenario(), timeout=90))


def test_garbage_ciphertext_after_real_handshake():
    """An AUTHENTICATED peer that then sends undecryptable frames only kills its
    own connection."""

    async def scenario():
        server = await _echo_server()
        rng = np.random.RandomState(1)
        for _ in range(5):
            reader, writer = await asyncio.open_connection("127.0.0.1", server.listen_port)
            channel, _extras = await handshake(
                reader, writer, Ed25519PrivateKey(), is_initiator=True
            )
            garbage = rng.bytes(300)
            writer.write(struct.pack(">I", len(garbage)) + garbage)
            try:
                await writer.drain()
            except (ConnectionError, OSError):
                pass
            channel.close()
        await _assert_still_serving(server)
        await server.shutdown()

    asyncio.run(asyncio.wait_for(scenario(), timeout=90))


def test_malformed_mux_frames_over_authenticated_channel():
    """Valid AEAD framing carrying hostile MUX payloads: bogus flags, duplicate and
    local-parity OPEN ids, DATA for unknown streams, short frames."""

    async def scenario():
        server = await _echo_server()
        rng = np.random.RandomState(2)
        for round_index in range(3):
            reader, writer = await asyncio.open_connection("127.0.0.1", server.listen_port)
            channel, _extras = await handshake(
                reader, writer, Ed25519PrivateKey(), is_initiator=True
            )
            header = struct.Struct(">QB")
            hostile = [
                header.pack(2, 1) + b"echo",  # OPEN with the SERVER's id parity
                header.pack(1, 1) + b"echo",  # legitimate OPEN ...
                header.pack(1, 1) + b"echo",  # ... duplicated (must be rejected)
                header.pack(999, 2) + b"data-for-nobody",  # DATA on unknown stream
                header.pack(1, 0xFF) + b"all-flags-set",
                header.pack(1, 16) + b"not-msgpack-error-payload",
                b"\x00",  # shorter than the mux header itself
                header.pack(1, 2) + rng.bytes(1000),  # garbage DATA on a live stream
            ]
            for frame in hostile:
                try:
                    await channel.send(frame)
                except (ConnectionError, OSError):
                    break
            await asyncio.sleep(0.2)
            channel.close()
        await _assert_still_serving(server)
        await server.shutdown()

    asyncio.run(asyncio.wait_for(scenario(), timeout=90))
