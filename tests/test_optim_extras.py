"""PowerSGD averaging (two chained phases, error feedback), GradScaler shim,
TrainingAverager legacy, math utils."""

import time

import numpy as np
import optax
import pytest

import jax
import jax.numpy as jnp

from hivemind_tpu.dht import DHT
from hivemind_tpu.optim import GradScaler, PowerSGDGradientAverager, TrainingAverager
from hivemind_tpu.utils.math_utils import get_flatten_greedy_dims, orthogonalize

from swarm_utils import launch_dht_swarm


def test_math_utils():
    m = np.random.RandomState(0).randn(16, 4).astype(np.float32)
    orthogonalize(m)
    gram = m.T @ m
    assert np.allclose(gram, np.eye(4), atol=1e-4)
    assert get_flatten_greedy_dims((128, 64)) == (128, 64)
    assert get_flatten_greedy_dims((4, 4, 16)) == (16, 16)
    assert get_flatten_greedy_dims((100,)) == (100, 1)


def test_powersgd_two_peer_average():
    dhts = launch_dht_swarm(2)
    try:
        shapes = [(64, 32), (8,)]  # one compressible matrix + one raw vector
        averagers = []
        grads = {}
        for i, dht in enumerate(dhts):
            rng = np.random.RandomState(i)
            # low-rank "gradients" (rank 2): a rank-4 factorization should capture them
            low_rank = (rng.randn(64, 2) @ rng.randn(2, 32)).astype(np.float32)
            grads[i] = [low_rank, rng.randn(8).astype(np.float32)]
            averagers.append(
                PowerSGDGradientAverager(
                    [np.zeros(s, np.float32) for s in shapes],
                    averager_rank=4,
                    dht=dht, prefix="psgd", start=True,
                    target_group_size=2, min_matchmaking_time=1.0, request_timeout=1.0,
                )
            )
        assert averagers[0]._compressed_idx == [0] and averagers[0]._uncompressed_idx == [1]
        for i, averager in enumerate(averagers):
            averager.accumulate_grads_(grads[i], batch_size=1)
        controls = [a.step(wait=False, timeout=40) for a in averagers]
        for control in controls:
            control.result(timeout=60)

        expected_raw = (grads[0][1] + grads[1][1]) / 2
        expected_matrix = (grads[0][0] + grads[1][0]) / 2
        for averager in averagers:
            with averager.use_averaged_gradients() as out:
                # raw tensors are averaged exactly
                assert np.allclose(out[1], expected_raw, atol=1e-4)
                # rank-8 of a 32x16 matrix: good but approximate; direction must match
                cos = np.sum(out[0] * expected_matrix) / (
                    np.linalg.norm(out[0]) * np.linalg.norm(expected_matrix) + 1e-9
                )
                assert cos > 0.95, f"cosine similarity {cos}"
                # error feedback holds the dropped residual
                assert np.linalg.norm(averager._error_feedback[0]) > 0
        for averager in averagers:
            averager.shutdown()
    finally:
        for dht in dhts:
            dht.shutdown()


def test_grad_scaler_shim():
    scaler = GradScaler()
    grads = {"w": jnp.ones(4)}
    assert scaler.unscale_(grads)
    called = []
    scaler.step(lambda: called.append(1))
    assert called == [1]
    bad = {"w": jnp.asarray([1.0, np.inf, 0, 0])}
    assert not scaler.unscale_(bad)
    scaler.step(lambda: called.append(2))  # skipped
    assert called == [1]
    scaler.update()
    assert not scaler.found_inf


def test_training_averager_legacy():
    dhts = launch_dht_swarm(2)
    try:
        states = [
            {"params": [np.full(10, float(i + 1), np.float32)]} for i in range(2)
        ]
        averagers = []
        for i, dht in enumerate(dhts):
            def getter(i=i):
                return states[i]["params"]

            def setter(tensors, i=i):
                states[i]["params"] = tensors

            averagers.append(
                TrainingAverager(
                    dht=dht, get_tensors_fn=getter, set_tensors_fn=setter,
                    prefix="legacy", start=True, target_group_size=2,
                    min_matchmaking_time=1.0,
                )
            )
        import threading

        threads = [
            threading.Thread(target=lambda a=a: a.average_step(timeout=40)) for a in averagers
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for i in range(2):
            assert np.allclose(states[i]["params"][0], 1.5, atol=1e-4)
        for a in averagers:
            a.shutdown()
    finally:
        for dht in dhts:
            dht.shutdown()


@pytest.mark.slow  # ~30 s; PowerSGD averaging is covered in ~1 s by
# test_powersgd_two_peer_average above, and the optimizer integration by
# test_optimizer_dpu.py::test_powersgd_with_dpu_convergence
def test_optimizer_with_powersgd_factory():
    """The collaborative Optimizer with PowerSGD gradient compression (the albert
    recipe's --powersgd_rank path): two peers converge through low-rank averaged
    gradients (scope: reference test_optimizer.py grad_averager_factory case)."""
    import threading

    from hivemind_tpu.optim import Optimizer, PowerSGDGradientAverager

    rng = np.random.RandomState(0)
    true_w = rng.randn(8, 4).astype(np.float32)
    features = rng.randn(256, 8).astype(np.float32)
    targets = features @ true_w

    @jax.jit
    def loss_and_grad(params, x, y):
        return jax.value_and_grad(lambda p: jnp.mean((x @ p["w"] - y) ** 2))(params)

    dhts = launch_dht_swarm(2)
    results, errors = {}, []

    def run_peer(index, dht):
        opt = None
        try:
            opt = Optimizer(
                dht=dht, run_id="powersgd_opt", target_batch_size=64,
                params={"w": jnp.zeros((8, 4), jnp.float32)}, optimizer=optax.sgd(0.3),
                batch_size_per_step=16, matchmaking_time=1.5, averaging_timeout=30,
                target_group_size=2,
                grad_averager_factory=PowerSGDGradientAverager,
                grad_averager_opts={"averager_rank": 2},
                tracker_opts=dict(min_refresh_period=0.3, default_refresh_period=0.5),
            )
            rng_local = np.random.RandomState(index)
            first_loss = last_loss = None
            for _ in range(60):
                if opt.local_epoch >= 10:
                    break
                idx = rng_local.choice(len(features), 16)
                loss, grads = loss_and_grad(opt.params, features[idx], targets[idx])
                first_loss = first_loss if first_loss is not None else float(loss)
                last_loss = float(loss)
                opt.step(grads)
                time.sleep(0.25)
            results[index] = (first_loss, last_loss, opt.local_epoch)
        except Exception:
            import traceback

            errors.append((index, traceback.format_exc()))
        finally:
            if opt is not None:
                opt.shutdown()

    threads = [threading.Thread(target=run_peer, args=(i, d)) for i, d in enumerate(dhts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=240)
    try:
        assert not errors, f"peer failures: {errors}"
        assert len(results) == 2
        for index, (first_loss, last_loss, epoch) in results.items():
            assert epoch >= 2, f"peer {index} stuck at epoch {epoch}"
            assert last_loss < first_loss / 2, (index, first_loss, last_loss)
    finally:
        for dht in dhts:
            dht.shutdown()


def test_chronic_dpu_failure_counter_and_backoff():
    """VERDICT r2 weak #4: consecutive degraded epochs must be counted, escalate
    past the threshold, and back off matchmaking — never silently train local SGD."""
    from concurrent.futures import Future

    from hivemind_tpu.optim.optimizer import Optimizer

    opt = Optimizer.__new__(Optimizer)
    opt.matchmaking_time = 5.0
    opt.chronic_failure_threshold = 3
    opt._consecutive_failed_rounds = 0
    opt._pending_update = None

    assert not opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 5.0

    for i in range(1, 3):
        opt._record_round_outcome(False)
        assert opt.consecutive_failed_averaging_rounds == i
        assert not opt.chronic_averaging_failure
        assert opt._matchmaking_delay() == 5.0  # no backoff before the threshold

    opt._record_round_outcome(False)  # crosses the threshold -> ERROR log
    assert opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 10.0  # 2x
    opt._record_round_outcome(False)
    assert opt._matchmaking_delay() == 20.0  # 4x
    for _ in range(5):
        opt._record_round_outcome(False)
    assert opt._matchmaking_delay() == 40.0  # capped at 8x

    # a failed BACKGROUND transition future counts too
    failed = Future()
    failed.set_exception(RuntimeError("swarm unreachable"))
    opt._pending_update = failed
    before = opt.consecutive_failed_averaging_rounds
    opt._finish_pending_update()
    assert opt.consecutive_failed_averaging_rounds == before + 1

    # a solo-swarm epoch (no round attempted) is neither a failure nor a recovery
    before = opt.consecutive_failed_averaging_rounds
    opt._record_round_outcome(None)
    assert opt.consecutive_failed_averaging_rounds == before
    assert opt.chronic_averaging_failure

    # one successful round fully recovers
    opt._record_round_outcome(True)
    assert opt.consecutive_failed_averaging_rounds == 0
    assert not opt.chronic_averaging_failure
    assert opt._matchmaking_delay() == 5.0
