"""Shared swarm builders for the test suite (layout parity: reference
tests/test_utils/dht_swarms.py). All tests launch REAL localhost swarms — there is
no fake network backend, so test and production code paths are identical."""

from hivemind_tpu.dht import DHT


def launch_dht_swarm(n: int):
    """n DHT peers on real localhost sockets; the first is everyone's bootstrap."""
    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    return [first] + [DHT(initial_peers=maddrs, start=True) for _ in range(n - 1)]


def shutdown_all(components, dhts):
    """Tear down averagers/optimizers first, then their DHTs."""
    for component in components:
        component.shutdown()
    for dht in dhts:
        dht.shutdown()
