"""Per-link wire-codec negotiation + adaptive straggler demotion (ISSUE 11):
the pure negotiation function, the advert wire format (incl. legacy gather
blobs), the ledger-driven demote/promote policy, and the acceptance demo — a
chaos-delayed link negotiates down to 8-bit while fast links stay at fp16."""

import numpy as np

from hivemind_tpu.averaging.wire_codec import (
    EF_TIERS,
    WIRE_TIERS,
    LinkCodecPolicy,
    WireLink,
    make_advert,
    negotiate_link,
    parse_advert,
    tier_of_codec,
    tier_rank,
)
from hivemind_tpu.compression import (
    BlockwiseQuantization,
    Float16Compression,
    NoCompression,
    ScaledFloat16Compression,
    Uniform8BitQuantization,
)


# ------------------------------------------------------------------ negotiation


def test_tier_ladder_and_codec_mapping():
    assert WIRE_TIERS == ("none", "float16", "uniform8", "blockwise8")
    assert tier_of_codec(NoCompression()) == "none"
    assert tier_of_codec(Float16Compression()) == "float16"
    assert tier_of_codec(Uniform8BitQuantization()) == "uniform8"
    assert tier_of_codec(BlockwiseQuantization()) == "blockwise8"
    # codecs off the ladder disable negotiation rather than breaking it
    assert tier_of_codec(ScaledFloat16Compression()) is None
    for tier in WIRE_TIERS:
        link = WireLink.for_tier(tier)
        assert link.error_feedback == (tier in EF_TIERS)
        assert tier_of_codec(link.codec) == tier


def test_negotiate_defaults_match_configured_codec():
    """No demotions → the link runs at the shared default tier (the exact
    pre-negotiation behavior, which the bit-identity suite relies on)."""
    a = parse_advert(make_advert(WIRE_TIERS, "float16", {}))
    b = parse_advert(make_advert(WIRE_TIERS, "float16", {}))
    assert negotiate_link(a, b, "peerA", "peerB") == "float16"


def test_negotiate_demotion_is_symmetric():
    """A demoting B (or vice versa) lands BOTH directions on the demoted tier:
    each endpoint evaluates the same pure function over the same two adverts."""
    demoting = parse_advert(make_advert(WIRE_TIERS, "float16", {"peerB": "uniform8"}))
    plain = parse_advert(make_advert(WIRE_TIERS, "float16", {}))
    # A's view of the A<->B link and B's view of the same link must agree
    assert negotiate_link(demoting, plain, "peerA", "peerB") == "uniform8"
    assert negotiate_link(plain, demoting, "peerB", "peerA") == "uniform8"
    # the demotion names peerB specifically: a third peer is unaffected
    assert negotiate_link(demoting, plain, "peerA", "peerC") == "float16"


def test_negotiate_clamps_to_common_tiers():
    """A proposal the other side does not support clamps down to the best
    mutually supported tier at or below the proposal."""
    wants_q8 = parse_advert(make_advert(WIRE_TIERS, "float16", {"peerB": "uniform8"}))
    only_fp = parse_advert(make_advert(("none", "float16"), "float16", {}))
    assert negotiate_link(wants_q8, only_fp, "peerA", "peerB") == "float16"


def test_negotiate_requires_both_adverts():
    advert = parse_advert(make_advert(WIRE_TIERS, "float16", {}))
    assert negotiate_link(advert, None, "a", "b") is None
    assert negotiate_link(None, advert, "a", "b") is None


def test_parse_advert_rejects_malformed():
    """Adverts are remote-controlled: anything malformed parses to None (the
    link falls back to the configured codec), never an exception."""
    assert parse_advert(None) is None
    assert parse_advert("float16") is None
    assert parse_advert({"t": "float16", "d": "float16"}) is None  # t not a list
    assert parse_advert({"t": ["float16"], "d": "uniform8"}) is None  # default unsupported
    assert parse_advert({"t": ["bogus"], "d": "bogus"}) is None
    parsed = parse_advert({"t": ["float16", "bogus"], "d": "float16", "m": {"p": "nope", 3: "x"}})
    assert parsed == {"t": ("float16",), "d": "float16", "m": {}}


def test_advert_survives_msgpack_roundtrip():
    from hivemind_tpu.utils.serializer import MSGPackSerializer

    advert = make_advert(WIRE_TIERS, "float16", {"peerS": "uniform8"})
    blob = MSGPackSerializer.dumps([1.0e8, 0, None, advert])
    decoded = MSGPackSerializer.loads(blob)
    assert parse_advert(decoded[3])["m"] == {"peerS": "uniform8"}
    # legacy 3-slot gather blobs (pre-ISSUE-11 peers) simply carry no advert
    legacy = MSGPackSerializer.loads(MSGPackSerializer.dumps([1.0e8, 0, None]))
    assert len(legacy) == 3


# ------------------------------------------------------------------ policy


class _ScriptedLedger:
    """Stands in for the RoundLedger: scripted cumulative straggler scores."""

    def __init__(self):
        self.scores = {}
        self.events = []

    def bump(self, peer, slowest=0, excess=0.0):
        entry = self.scores.setdefault(peer, {"rounds_slowest": 0, "excess_s": 0.0})
        entry["rounds_slowest"] += slowest
        entry["excess_s"] += excess

    def straggler_scores(self):
        return {peer: dict(score) for peer, score in self.scores.items()}

    def record_codec_event(self, peer, action, tier=None):
        self.events.append((peer, action, tier))


def test_policy_demotes_chronic_straggler_and_promotes_after_clean_streak():
    ledger = _ScriptedLedger()
    policy = LinkCodecPolicy(
        ledger, demote_rounds=3, min_excess_s=0.1, promote_after=4
    )
    # noise: slowest sometimes but with negligible excess — never demoted
    for _ in range(6):
        ledger.bump("noisy", slowest=1, excess=0.01)
        assert policy.refresh() == {}
    # chronic: three slowest rounds with real excess
    for _ in range(2):
        ledger.bump("slow", slowest=1, excess=0.4)
        assert "slow" not in policy.refresh()
    ledger.bump("slow", slowest=1, excess=0.4)
    assert policy.refresh() == {"slow": "uniform8"}
    assert ("slow", "demote", "uniform8") in ledger.events
    # stays demoted while evidence keeps arriving
    ledger.bump("slow", slowest=1, excess=0.4)
    assert "slow" in policy.refresh()
    # promotion: promote_after consecutive refreshes with no slow+excess rounds
    for i in range(4):
        demoted = policy.refresh()
    assert demoted == {}
    assert ("slow", "promote", None) in ledger.events


def test_policy_retro_attribution_deltas_clamped_and_forget_drops_state():
    ledger = _ScriptedLedger()
    policy = LinkCodecPolicy(ledger, demote_rounds=2, min_excess_s=0.1)
    ledger.bump("p", slowest=2, excess=0.5)
    policy.refresh()
    # ledger retro-attribution MOVED credit away: totals decreased
    ledger.scores["p"]["rounds_slowest"] = 1
    ledger.scores["p"]["excess_s"] = 0.1
    policy.refresh()  # negative deltas clamp to zero, no crash
    policy.forget("p")
    assert policy.demotions() == {}


def test_policy_bounds_tracked_peers():
    ledger = _ScriptedLedger()
    policy = LinkCodecPolicy(ledger, max_peers=8)
    for index in range(50):
        ledger.bump(f"peer{index}", slowest=1, excess=0.0)
        policy.refresh()
    assert len(policy._last_seen) <= 8


# ------------------------------------------------------------------ acceptance demo


def test_chaos_slow_link_negotiates_down_to_8bit():
    """The acceptance criterion end-to-end: a chaos `delay` rule on one peer's
    delta leg makes every exchange WITH that peer chronically slow; the other
    peers' straggler policies demote it, and the next rounds' ledger records
    show that link at uniform8 while the fast link stays at float16."""
    from hivemind_tpu.averaging import DecentralizedAverager
    from hivemind_tpu.dht import DHT
    from hivemind_tpu.resilience import CHAOS
    from hivemind_tpu.telemetry.ledger import LEDGER

    first = DHT(start=True)
    maddrs = [str(m) for m in first.get_visible_maddrs()]
    dhts = [first] + [DHT(initial_peers=maddrs, start=True) for _ in range(2)]
    averagers = []
    try:
        for i, dht in enumerate(dhts):
            rng = np.random.RandomState(i)
            averagers.append(
                DecentralizedAverager(
                    [rng.randn(2000).astype(np.float32)], dht, prefix="adaptive",
                    start=True, target_group_size=3, min_matchmaking_time=1.0,
                    compression=Float16Compression(),
                    link_policy=LinkCodecPolicy(
                        demote_rounds=2, min_excess_s=0.1, promote_after=50
                    ),
                )
            )
        slow = averagers[2]
        slow_id = str(slow.peer_id)
        fast_ids = {str(a.peer_id) for a in averagers[:2]}
        # the slow peer serves its reduction deltas slowly — a bandwidth-starved
        # WAN reducer; every exchange WITH it stretches, fast links don't
        CHAOS.add_rule("allreduce.reduce", "delay", delay=0.4, scope=slow_id)

        demoted_record = None
        for _round in range(8):
            controls = [a.step(wait=False, timeout=30) for a in averagers]
            for control in controls:
                control.result(timeout=45)
            for record in LEDGER.records():
                codecs = record.get("link_codecs") or {}
                if record["peer"] in fast_ids and codecs.get(slow_id) == "uniform8":
                    demoted_record = record
            if demoted_record is not None:
                break
        assert demoted_record is not None, (
            f"slow link never negotiated down; records: {LEDGER.records()}"
        )
        # the fast<->fast link in the same record stayed at fp16
        fast_remote = next(pid for pid in fast_ids if pid != demoted_record["peer"])
        assert demoted_record["link_codecs"].get(fast_remote) == "float16"
        # and the decision itself is on the ledger's event ring
        assert any(
            event["action"] == "demote" and event["peer"] == slow_id
            for event in LEDGER.codec_events()
        )
    finally:
        CHAOS.clear()
        for averager in averagers:
            averager.shutdown()
        for dht in dhts:
            dht.shutdown()
