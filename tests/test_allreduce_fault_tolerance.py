"""Fault-injection matrix: a faulty peer dies/stalls at each stage of the all-reduce;
surviving peers must still complete with consistent averages
(scope: reference tests/test_allreduce_fault_tolerance.py:22-120)."""

import asyncio
from enum import Enum, auto

import numpy as np
import pytest

from hivemind_tpu.averaging import AllReduceRunner, DecentralizedAverager
from hivemind_tpu.averaging.allreduce import AveragingMode
from hivemind_tpu.dht import DHT
from hivemind_tpu.proto import averaging_pb2

from swarm_utils import launch_dht_swarm


class Fault(Enum):
    NONE = auto()
    FAIL_BEFORE = auto()  # dies after matchmaking, before sending anything
    FAIL_SENDING = auto()  # sends the first part, then closes its streams
    SLOW_SENDING = auto()  # stalls while sending
    FAIL_REDUCING = auto()  # returns one delta, then stops reducing
    SLOW_REDUCING = auto()  # stalls while reducing
    CANCEL = auto()  # cancels its own step right after scheduling it


class FaultyAllReduceRunner(AllReduceRunner):
    def __init__(self, *args, fault: Fault, **kwargs):
        self.fault = fault
        super().__init__(*args, **kwargs)

    async def _communicate_with_peer(self, peer_index):
        if self.fault in (Fault.FAIL_SENDING, Fault.SLOW_SENDING):
            peer_id = self.ordered_peer_ids[peer_index]
            stub = self.get_stub(peer_id)

            async def _requests():
                first = True
                async for serialized in self.container.iterate_input_parts_for(peer_index):
                    if not first:
                        if self.fault == Fault.SLOW_SENDING:
                            await asyncio.sleep(30)
                        return  # FAIL_SENDING: close stream after one part
                    yield averaging_pb2.AveragingData(
                        code=averaging_pb2.PART_DATA,
                        group_id=self.group_id,
                        tensor_part=serialized,
                        weight=self.weight,
                    )
                    first = False

            try:
                async for _response in stub.rpc_aggregate_part(_requests()):
                    pass
            except Exception:
                pass
            self.container.register_failed_reducer(peer_index)
            return
        await super()._communicate_with_peer(peer_index)

    async def handle_aggregate_stream(self, first_message, requests, context):
        if self.fault in (Fault.FAIL_REDUCING, Fault.SLOW_REDUCING):
            count = 0
            async for message in super().handle_aggregate_stream(first_message, requests, context):
                yield message
                count += 1
                if count >= 1:
                    if self.fault == Fault.SLOW_REDUCING:
                        await asyncio.sleep(30)
                    return  # close the response stream early
            return
        async for message in super().handle_aggregate_stream(first_message, requests, context):
            yield message


class FaultyAverager(DecentralizedAverager):
    def __init__(self, *args, fault: Fault = Fault.NONE, **kwargs):
        self.fault = fault
        super().__init__(*args, **kwargs)

    def _make_allreduce_runner(self, group_info, peer_element_counts, modes, weight):
        if self.fault == Fault.FAIL_BEFORE:
            raise RuntimeError("injected failure before allreduce")
        if self.fault == Fault.NONE:
            return super()._make_allreduce_runner(group_info, peer_element_counts, modes, weight)
        return FaultyAllReduceRunner(
            fault=self.fault,
            p2p=self.p2p,
            group_id=group_info.group_id,
            tensors=self._snapshot_tensors(),
            ordered_peer_ids=group_info.peer_ids,
            peer_element_counts=peer_element_counts,
            modes=modes,
            get_stub=self._get_peer_stub,
            weight=weight,
            compression=self.compression,
            part_size_bytes=self.part_size_bytes,
            sender_timeout=self.sender_timeout,
            reducer_timeout=self.reducer_timeout,
        )


def launch_faulty_swarm(n_peers: int, fault_index: int, fault: Fault, part_size_bytes=64):
    dhts = launch_dht_swarm(n_peers)
    averagers = []
    for i, dht in enumerate(dhts):
        rng = np.random.RandomState(100 + i)
        tensors = [rng.randn(256).astype(np.float32)]
        averagers.append(
            FaultyAverager(
                tensors, dht, prefix="faulttest", start=True,
                target_group_size=n_peers,
                min_matchmaking_time=1.0, request_timeout=1.0,
                sender_timeout=2.0, reducer_timeout=4.0,
                part_size_bytes=part_size_bytes,  # small parts: faults hit mid-stream
                fault=fault if i == fault_index else Fault.NONE,
            )
        )
    return dhts, averagers


@pytest.mark.parametrize(
    "fault",
    [Fault.NONE, Fault.FAIL_BEFORE, Fault.FAIL_SENDING, Fault.SLOW_SENDING, Fault.FAIL_REDUCING, Fault.SLOW_REDUCING, Fault.CANCEL],
    ids=lambda f: f.name,
)
def test_allreduce_fault_tolerance(fault):
    n_peers, fault_index = 4, 1
    dhts, averagers = launch_faulty_swarm(n_peers, fault_index, fault)
    try:
        controls = [a.step(wait=False, timeout=25, allow_retries=False) for a in averagers]
        if fault == Fault.CANCEL:
            # reference test_allreduce_fault_tolerance.py:22-120 CANCEL case: the
            # faulty peer withdraws by cancelling its own step mid-matchmaking
            import time

            time.sleep(0.5)
            controls[fault_index].cancel()
        survivor_results = {}
        for i, control in enumerate(controls):
            try:
                result = control.result(timeout=40)
                survivor_results[i] = result
            except Exception:
                assert i == fault_index or fault in (Fault.SLOW_SENDING, Fault.SLOW_REDUCING, Fault.CANCEL), (
                    f"healthy peer {i} failed under fault {fault.name}"
                )
        survivors = [i for i in survivor_results if i != fault_index]
        assert len(survivors) >= n_peers - 2, f"too many casualties under {fault.name}: {survivors}"

        values = {}
        for i in survivors:
            with averagers[i].get_tensors() as tensors:
                values[i] = tensors[0].copy()
        if fault == Fault.NONE:
            # everyone (incl. peer 1) must hold the exact same average
            reference_value = values[survivors[0]]
            for i in survivors[1:]:
                assert np.allclose(values[i], reference_value, atol=1e-4)
        else:
            # spans reduced by surviving reducers must agree across all survivors;
            # at least half of the vector must have been successfully averaged
            agreement = np.mean(
                [np.isclose(values[survivors[0]], values[i], atol=1e-4) for i in survivors[1:]],
                axis=0,
            )
            assert agreement.mean() >= 0.5, f"{fault.name}: survivors agree on only {agreement.mean():.0%}"
    finally:
        for averager in averagers:
            averager.shutdown()
        for dht in dhts:
            dht.shutdown()
