"""Fault-injection matrix: a faulty peer dies/stalls at each stage of the all-reduce;
surviving peers must still complete with consistent averages
(scope: reference tests/test_allreduce_fault_tolerance.py:22-120).

ISSUE 3: the fault matrix now runs on the first-class chaos engine
(hivemind_tpu/resilience/chaos.py) — seeded rules scoped to the faulty peer's id
at the named ``allreduce.setup`` / ``allreduce.load`` / ``allreduce.reduce``
injection points replace the old ``FaultyAllReduceRunner`` / ``FaultyAverager``
test-local subclasses, so the code under test is EXACTLY the production code."""

from enum import Enum, auto

import numpy as np
import pytest

from hivemind_tpu.averaging import DecentralizedAverager
from hivemind_tpu.resilience import CHAOS

from swarm_utils import launch_dht_swarm


class Fault(Enum):
    NONE = auto()
    FAIL_BEFORE = auto()  # dies after matchmaking, before sending anything
    FAIL_SENDING = auto()  # sends the first part, then its sends abort
    SLOW_SENDING = auto()  # stalls while sending
    FAIL_REDUCING = auto()  # returns one delta, then its reduces abort
    SLOW_REDUCING = auto()  # stalls while reducing
    CANCEL = auto()  # cancels its own step right after scheduling it


def arm_fault(fault: Fault, faulty_scope: str) -> None:
    """Translate one matrix entry into seeded chaos rules scoped to the faulty
    peer (every peer shares the process-wide engine; scope isolates the victim)."""
    CHAOS.clear()
    CHAOS.reseed(1234)
    if fault == Fault.FAIL_BEFORE:
        CHAOS.add_rule("allreduce.setup", "abort", scope=faulty_scope)
    elif fault == Fault.FAIL_SENDING:
        CHAOS.add_rule("allreduce.load", "abort", after=1, scope=faulty_scope)
    elif fault == Fault.SLOW_SENDING:
        # delay >> sender_timeout: indistinguishable from a stall to the group
        CHAOS.add_rule("allreduce.load", "delay", delay=8.0, after=1, scope=faulty_scope)
    elif fault == Fault.FAIL_REDUCING:
        CHAOS.add_rule("allreduce.reduce", "abort", after=1, scope=faulty_scope)
    elif fault == Fault.SLOW_REDUCING:
        CHAOS.add_rule("allreduce.reduce", "delay", delay=8.0, after=1, scope=faulty_scope)
    # NONE / CANCEL need no injected faults


def launch_swarm_of_averagers(n_peers: int, part_size_bytes=64):
    dhts = launch_dht_swarm(n_peers)
    averagers = []
    for i, dht in enumerate(dhts):
        rng = np.random.RandomState(100 + i)
        tensors = [rng.randn(256).astype(np.float32)]
        averagers.append(
            DecentralizedAverager(
                tensors, dht, prefix="faulttest", start=True,
                target_group_size=n_peers,
                min_matchmaking_time=1.0, request_timeout=1.0,
                sender_timeout=1.5, reducer_timeout=2.0,
                part_size_bytes=part_size_bytes,  # small parts: faults hit mid-stream
            )
        )
    return dhts, averagers


@pytest.mark.chaos
@pytest.mark.parametrize(
    "fault",
    [Fault.NONE, Fault.FAIL_BEFORE, Fault.FAIL_SENDING, Fault.SLOW_SENDING, Fault.FAIL_REDUCING, Fault.SLOW_REDUCING, Fault.CANCEL],
    ids=lambda f: f.name,
)
def test_allreduce_fault_tolerance(fault):
    n_peers, fault_index = 4, 1
    dhts, averagers = launch_swarm_of_averagers(n_peers)
    try:
        arm_fault(fault, faulty_scope=str(averagers[fault_index].peer_id))
        controls = [a.step(wait=False, timeout=25, allow_retries=False) for a in averagers]
        if fault == Fault.CANCEL:
            # reference test_allreduce_fault_tolerance.py:22-120 CANCEL case: the
            # faulty peer withdraws by cancelling its own step mid-matchmaking
            import time

            time.sleep(0.5)
            controls[fault_index].cancel()
        survivor_results = {}
        for i, control in enumerate(controls):
            try:
                result = control.result(timeout=40)
                survivor_results[i] = result
            except Exception:
                assert i == fault_index or fault in (Fault.SLOW_SENDING, Fault.SLOW_REDUCING, Fault.CANCEL), (
                    f"healthy peer {i} failed under fault {fault.name}"
                )
        survivors = [i for i in survivor_results if i != fault_index]
        assert len(survivors) >= n_peers - 2, f"too many casualties under {fault.name}: {survivors}"

        values = {}
        for i in survivors:
            with averagers[i].get_tensors() as tensors:
                values[i] = tensors[0].copy()
        if fault == Fault.NONE:
            # everyone (incl. peer 1) must hold the exact same average
            reference_value = values[survivors[0]]
            for i in survivors[1:]:
                assert np.allclose(values[i], reference_value, atol=1e-4)
        else:
            # spans reduced by surviving reducers must agree across all survivors;
            # at least half of the vector must have been successfully averaged
            agreement = np.mean(
                [np.isclose(values[survivors[0]], values[i], atol=1e-4) for i in survivors[1:]],
                axis=0,
            )
            assert agreement.mean() >= 0.5, f"{fault.name}: survivors agree on only {agreement.mean():.0%}"
        if fault not in (Fault.NONE, Fault.CANCEL):
            injected = sum(CHAOS.stats().values())
            assert injected >= 1, f"{fault.name}: chaos rules armed but nothing injected"
    finally:
        CHAOS.clear()
        for averager in averagers:
            averager.shutdown()
        for dht in dhts:
            dht.shutdown()
